.PHONY: test test-unit test-integration doctest bench clean

test: test-unit test-integration

test-unit:
	python -m pytest tests/unittests -q

test-integration:
	python -m pytest tests/integrations -q

# every docstring example runs as a test (pyproject --doctest-modules covers the package)
doctest:
	python -m pytest torchmetrics_tpu -q

bench:
	python bench.py

clean:
	find . -type d -name __pycache__ -prune -exec rm -rf {} +
	rm -rf .pytest_cache
