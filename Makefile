.PHONY: test test-unit test-integration doctest bench telemetry-smoke clean

test: test-unit test-integration

test-unit:
	python -m pytest tests/unittests -q

test-integration:
	python -m pytest tests/integrations -q

# every docstring example runs as a test (pyproject --doctest-modules covers the package)
doctest:
	python -m pytest torchmetrics_tpu -q

bench:
	python bench.py

# tier-1 guard for the observability exporter: one fused-sweep iteration with telemetry on,
# trace exported and schema-checked (also runs as part of test-integration / the tier-1 lane)
telemetry-smoke:
	TM_TPU_TELEMETRY=1 python -m pytest tests/integrations/test_telemetry_smoke.py -q

clean:
	find . -type d -name __pycache__ -prune -exec rm -rf {} +
	rm -rf .pytest_cache
