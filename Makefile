.PHONY: test test-unit test-integration doctest bench telemetry-smoke jaxlint clean

test: jaxlint test-unit test-integration

test-unit:
	python -m pytest tests/unittests -q

test-integration:
	python -m pytest tests/integrations -q

# every docstring example runs as a test (pyproject --doctest-modules covers the package)
doctest:
	python -m pytest torchmetrics_tpu -q

bench:
	python bench.py

# static JAX/TPU hazard analysis (rules TPU001-TPU006, docs/static-analysis.md): exits
# nonzero on any non-baselined finding OR stale baseline entry; regenerate the baseline
# with `python -m torchmetrics_tpu._lint torchmetrics_tpu --write-baseline`
jaxlint:
	python -m torchmetrics_tpu._lint torchmetrics_tpu --strict-baseline

# tier-1 guard for the observability exporter: one fused-sweep iteration with telemetry on,
# trace exported and schema-checked (also runs as part of test-integration / the tier-1 lane)
telemetry-smoke:
	TM_TPU_TELEMETRY=1 python -m pytest tests/integrations/test_telemetry_smoke.py -q

clean:
	find . -type d -name __pycache__ -prune -exec rm -rf {} +
	rm -rf .pytest_cache
