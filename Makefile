.PHONY: test test-unit test-integration doctest bench bench-smoke keyed-smoke shard-smoke sketch-smoke compress-smoke serve-smoke control-smoke obs-smoke online-smoke bundle-smoke fleet-smoke explain-smoke telemetry-smoke jaxlint jaxlint-fast jaxlint-race jaxlint-sarif jaxlint-ir chaos chaos-matrix perf-gate perf-baseline clean

test: jaxlint jaxlint-race test-unit test-integration bench-smoke keyed-smoke shard-smoke sketch-smoke compress-smoke serve-smoke control-smoke obs-smoke online-smoke bundle-smoke fleet-smoke explain-smoke chaos chaos-matrix perf-gate

test-unit:
	python -m pytest tests/unittests -q

test-integration:
	python -m pytest tests/integrations -q

# every docstring example runs as a test (pyproject --doctest-modules covers the package)
doctest:
	python -m pytest torchmetrics_tpu -q

bench:
	python bench.py

# tiny-N bench lane: same code paths and JSON schema as the real bench, seconds of wall
# time; fails the build if bench.py exits nonzero or stops emitting parseable JSON
bench-smoke:
	python bench.py --smoke > /tmp/tm_bench_smoke.json
	python -c "import json; d=[l for l in open('/tmp/tm_bench_smoke.json').read().strip().splitlines() if l][-1]; p=json.loads(d); assert 'metric' in p and 'extras' in p, p; print('bench-smoke ok:', p['metric'])"

# keyed multi-tenant lane (docs/keyed.md): tiny-N mixed-tenant bench asserting the
# acceptance bar — KeyedMetric at N=10k keys >= 50x a 10k-instance Python loop, with
# bit-identical per-key results across the jit / AOT+donation / buffered tiers
keyed-smoke:
	python bench.py --keyed --smoke > /tmp/tm_keyed_smoke.json
	python -c "import json; p=json.loads([l for l in open('/tmp/tm_keyed_smoke.json').read().strip().splitlines() if l][-1]); ex=p['extras']; s=ex['keyed_vs_instance_loop_n10000']; assert s is not None and s >= 50, ex; bits=[v for k,v in ex.items() if k.startswith('keyed_bit_identical')]; assert bits and all(bits), ex; print('keyed-smoke ok: %.0fx vs instance loop @ N=10k' % s)"

# sharded-state lane (docs/distributed.md "Sharded state"): keyed tenant table on a forced
# 8-device host mesh — asserts the acceptance bar: reduce-once sync bytes strictly below
# the replicated allgather baseline, per-key values bit-identical across placements and
# dispatch tiers, and the lazy reduce firing at most once per (update-epoch, compute) pair
shard-smoke:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 python bench.py --sharded --smoke > /tmp/tm_shard_smoke.json
	python -c "import json; p=json.loads([l for l in open('/tmp/tm_shard_smoke.json').read().strip().splitlines() if l][-1]); ex=p['extras']; rep=ex['sync_bytes_per_compute_replicated']; shd=ex['sync_bytes_per_compute_sharded']; assert shd < rep, (shd, rep); bits=[v for k,v in ex.items() if k.startswith('sharded_bit_identical')]; assert bits and all(bits), ex; assert ex['lazy_reduce_fires'] <= ex['sharded_compute_epochs'] and ex['lazy_reduce_reuses'] >= 1, ex; print('shard-smoke ok: %dB sharded vs %dB allgather per compute (%.1fx), bit-identical' % (shd, rep, rep/shd))"

# compressed-collective lane (docs/distributed.md "Compressed collectives"): 4-rank
# simulated world asserting the acceptance bar — int8/bf16 modes ship strictly fewer
# bytes than compression="none" at the pinned shapes (sketch states >= 2x saved via the
# packed-blob fast path), exact modes (min/max/count/int/sketch-merge) BIT-identical to
# the uncompressed sync, and sum error under error-feedback within the documented
# block-scale bound across repeated sync epochs (no drift)
compress-smoke:
	python bench.py --sync-compress --smoke > /tmp/tm_compress_smoke.json
	python -c "import json; p=json.loads([l for l in open('/tmp/tm_compress_smoke.json').read().strip().splitlines() if l][-1]); ex=p['extras']; base=ex['compress_bytes_received_none']; assert ex['compress_bytes_received_int8'] < base and ex['compress_bytes_received_bf16'] < base, ex; assert ex['compress_sketch_saved_ratio_int8'] >= 2 and ex['compress_sketch_saved_ratio_bf16'] >= 2, ex; assert ex['compress_exact_bit_identical_int8'] and ex['compress_exact_bit_identical_bf16'], ex; assert ex['compress_sum_abs_err_int8'] <= ex['compress_sum_err_bound_int8'] and ex['compress_sum_abs_err_bf16'] <= ex['compress_sum_err_bound_bf16'], ex; assert ex['compress_mean_abs_err_int8'] <= ex['compress_mean_err_bound_int8'] and ex['compress_mean_abs_err_bf16'] <= ex['compress_mean_err_bound_bf16'], ex; assert ex['compress_ef_max_err_int8'] <= ex['compress_ef_err_bound_int8'] and ex['compress_ef_max_err_bf16'] <= ex['compress_ef_err_bound_bf16'], ex; print('compress-smoke ok: int8 %dB vs none %dB per sync (%.2fx), sketch %.1fx saved, EF err %.2e <= %.2e' % (ex['compress_bytes_received_int8'], base, base/ex['compress_bytes_received_int8'], ex['compress_sketch_saved_ratio_int8'], ex['compress_ef_max_err_int8'], ex['compress_ef_err_bound_int8']))"

# serving lane (docs/serving.md): tiny-N async-ingestion bench asserting the acceptance
# bar — async completion throughput >= the synchronous loop at smoke shapes (drain-side
# coalescing: k dispatches -> 1 update_batches scan), ZERO sheds and zero backpressure
# stalls in block mode, exact shed accounting under forced overflow, and bit-identity of
# the async value vs the synchronous run AND vs a preempted-mid-overlap journal replay
serve-smoke:
	python bench.py --serve --smoke > /tmp/tm_serve_smoke.json
	python -c "import json; p=json.loads([l for l in open('/tmp/tm_serve_smoke.json').read().strip().splitlines() if l][-1]); ex=p['extras']; r=ex['serve_async_vs_sync_completion']; assert r >= 1.0, ('async completion fell below sync', ex); assert ex['serve_block_mode_sheds'] == 0 and ex['serve_block_mode_stalls'] == 0, ex; bits=[v for k,v in ex.items() if k.startswith('serve_bit_identical')]; assert bits and all(bits), ex; assert ex['serve_overload_sheds_exact'], ex; print('serve-smoke ok: async %.2fx sync, sustained %.2fx @1.2x offered, enqueue p99 %sus' % (r, ex['serve_sustained_vs_sync'], ex['serve_enqueue_p99_us']))"

# adaptive-control lane (docs/serving.md "Control loop"): oscillating square-wave
# offered load through the ServeController, asserting the acceptance bar — the adaptive
# admission ladder sheds no more than the best static on_full config under the same
# drive, actuator toggles stay under the min_hold_ticks decision-rate cap (zero thrash),
# every decision lands as a flight-recorder event, and adaptive_recover() replays the
# WAL minus the journaled sheds to a bit-identical state
control-smoke:
	python bench.py --serve --smoke > /tmp/tm_control_smoke.json
	python -c "import json; p=json.loads([l for l in open('/tmp/tm_control_smoke.json').read().strip().splitlines() if l][-1]); ex=p['extras']; assert ex['adaptive_shed_ratio'] <= 1.0, ('adaptive shed worse than static', ex['adaptive_shed_ratio']); assert ex['serve_adaptive_thrash_free'], ('actuator toggles exceeded the decision-rate cap', ex); assert ex['serve_adaptive_replay_identical'], ('adaptive replay not bit-identical', ex); assert ex['controller_decisions'] > 0, ex; print('control-smoke ok: shed ratio %.3f (adaptive %d vs static %d), %d decisions / %d transitions (%d escalations), thrash-free, replay bit-identical' % (ex['adaptive_shed_ratio'], ex['serve_adaptive_sheds'], ex['serve_static_sheds'], ex['controller_decisions'], ex['controller_transitions'], ex['controller_escalations']))"

# serving-observability lane (docs/observability.md "Serving traces, live series &
# SLOs"): traced serve burst -> exported Perfetto trace with VALID flow pairing (every
# ph:"s" has its ph:"f", committed flows land on the drain track), OpenMetrics
# exposition round-tripped through the strict parser AND fetched over the localhost
# scrape endpoint, the SLO shed-ratio alarm quiet on a healthy run and FIRING on an
# injected shed storm, and the tracing-disabled enqueue hook chain <= 2us/enqueue
obs-smoke:
	python bench.py --obs --smoke > /tmp/tm_obs_smoke.json
	python -c "import json; p=json.loads([l for l in open('/tmp/tm_obs_smoke.json').read().strip().splitlines() if l][-1]); ex=p['extras']; assert ex['obs_trace_flows_valid'] and ex['obs_trace_flows'] > 0, ex; assert ex['obs_trace_committed_cross_thread'] == ex['obs_trace_flows'], ex; assert ex['obs_openmetrics_valid'] and ex['obs_scrape_valid'], ex; assert ex['obs_slo_quiet_when_healthy'] and ex['obs_slo_alarm_fired'], ex; assert ex['obs_disabled_overhead_ok'], ('disabled-path enqueue hooks above the 2us bound', ex['obs_disabled_hook_overhead_us']); print('obs-smoke ok: %d flows valid, %dB OpenMetrics (%d families), SLO burn %.0fx on %d sheds, disabled-path %.2fus' % (ex['obs_trace_flows'], ex['obs_openmetrics_bytes'], ex['obs_openmetrics_families'], ex['obs_slo_burn_rate'], ex['obs_slo_storm_sheds'], ex['obs_disabled_hook_overhead_us']))"

# online windowed-monitoring lane (docs/online.md): tiny-N windowed bench asserting the
# acceptance bar — windowed per-update cost <= 1.5x the plain template, sliding
# compute() bit-identical to the direct twin across the AOT/jit/buffered/scan tiers,
# and the KS drift alarm firing its one-shot warn EXACTLY once on an injected
# distribution shift while staying silent on the stationary segment
online-smoke:
	python bench.py --online --smoke > /tmp/tm_online_smoke.json
	python -c "import json; p=json.loads([l for l in open('/tmp/tm_online_smoke.json').read().strip().splitlines() if l][-1]); ex=p['extras']; r=ex['online_windowed_vs_plain_overhead']; assert r <= ex['online_overhead_bound'], ('windowed overhead above bound', ex); bits=[v for k,v in ex.items() if k.startswith('online_bit_identical')]; assert bits and all(bits), ex; assert ex['online_drift_quiet_stationary'] and ex['online_drift_alarm_fired_once'], ex; print('online-smoke ok: %.2fx windowed overhead, advance %sus, detector %sus, drift one-shot on shift' % (r, ex['online_advance_cost_us'], ex['online_detector_eval_us']))"

# flight-recorder & post-mortem-bundle lane (docs/observability.md "Flight recorder &
# post-mortem bundles"): asserts the acceptance bar — the ALWAYS-ON flight-ring record
# path <= 2us/event (best-of-3), a captured bundle passes strict per-section-CRC
# validation, obs.memory_ledger() resident bytes match nbytes ground truth within 1%
# for keyed tables / window rings / sketch states, and the MemoryBudget alarm fires its
# one-shot warn EXACTLY once on an injected over-budget keyed table (quiet under budget)
bundle-smoke:
	python bench.py --flight --smoke > /tmp/tm_bundle_smoke.json
	python -c "import json; p=json.loads([l for l in open('/tmp/tm_bundle_smoke.json').read().strip().splitlines() if l][-1]); ex=p['extras']; assert ex['flight_record_ok'], ('flight-ring record path above the 2us bound', ex['flight_record_us_per_event']); assert ex['bundle_validates'], ex; assert ex['memory_ledger_ok'], ('memory ledger off nbytes truth', ex['memory_ledger_max_rel_err']); assert ex['memory_budget_quiet_under_budget'] and ex['memory_budget_fires_over_budget'] and ex['memory_budget_warned_exactly_once'], ex; assert set(ex['memory_ledger_kinds']) >= {'tenant_table','window_ring','sketch'}, ex; print('bundle-smoke ok: record %.2fus/event (<=2us), capture %.1fms, ledger err %.1e, budget one-shot' % (ex['flight_record_us_per_event'], ex['bundle_capture_ms'], ex['memory_ledger_max_rel_err']))"

# fleet federation lane (docs/observability.md "Fleet federation & incident
# correlation"): live localhost peers -> fleet-tier Federator, asserting the acceptance
# bar -- merged scrape strict-parses, counters sum exactly, the fleet p99 is a true
# pooled quantile within the KLL rank-error bound, and a peer killed mid-fleet degrades
# to an unhealthy count without failing the scrape
fleet-smoke:
	python bench.py --fleet --smoke > /tmp/tm_fleet_smoke.json
	python -c "import json; p=json.loads([l for l in open('/tmp/tm_fleet_smoke.json').read().strip().splitlines() if l][-1]); ex=p['extras']; assert ex['merged_scrape_parses'], ex; assert ex['fleet_counter_sum_ok'], ('fleet counter aggregate wrong', ex['fleet_counter_sum']); assert ex['fleet_p99_ok'], ('fleet p99 outside the pooled-quantile bound', ex['fleet_p99']); assert ex['incident_minted'] and ex['incident_in_federated_scrape'], ('incident id did not gossip into the scrape', ex); assert ex['fleet_bundle_validates'] and ex['fleet_bundle_incident_matches'], ('merge-fleet bundle invalid', ex); assert ex['degrade_ok'], ('peer death failed the scrape', ex); assert ex['fleet_unhealthy'] == 0, ex; print('fleet-smoke ok: %d peers polled in %.1fms, %dB merged scrape, pooled p99 %.0f, peer-death degrades cleanly' % (ex['fleet_peers'], ex['fleet_poll_ms'], ex['merged_scrape_bytes'], ex['fleet_p99']))"

# compile-plane lane (docs/observability.md "Compile plane"): a burst across the jit and
# AOT dispatch tiers must land ledger rows under BOTH tiers, the one forced dtype-flip
# retrace must be attributed to its exact culprit leaf, the seam matrix must survive the
# strict OpenMetrics parse and bundle validation, and the disabled-path decision note
# must stay under 2us/dispatch
explain-smoke:
	python bench.py --explain --smoke > /tmp/tm_explain_smoke.json
	python -c "import json; p=json.loads([l for l in open('/tmp/tm_explain_smoke.json').read().strip().splitlines() if l][-1]); ex=p['extras']; assert ex['compile_both_tiers'], ('ledger missing a dispatch tier', ex['compile_tiers_seen']); assert ex['retraces_attributed'] >= 1 and ex['retrace_culprits_exact'], ('retrace not attributed to the exact leaf', ex); assert ex['retrace_flight_events'] >= 1, ex; assert ex['seam_matrix_full_axis'] and ex['seam_matrix_openmetrics_ok'] and ex['seam_matrix_bundle_ok'], ('seam matrix failed validation', ex); assert ex['explain_decision_ok'], ('decision note above the 2us bound', ex['explain_decision_us_per_dispatch']); assert ex['explain_has_flags'] and ex['explain_has_tiers'] and ex['explain_has_decisions'] and ex['explain_has_compiles'], ex; print('explain-smoke ok: %d ledger rows across %s, %d retraces attributed (args[1] dtype), decision note %.2fus (<=2us)' % (ex['compile_ledger_rows'], '+'.join(ex['compile_tiers_seen']), ex['retraces_attributed'], ex['explain_decision_us_per_dispatch']))"

# streaming-sketch lane (docs/sketches.md): tiny-N sketch-vs-cat bench asserting the
# acceptance bar — sketch-mode AUROC/quantile state is FIXED-size (identical bytes after
# 1 batch and the full stream, well under the cat footprint), measured quantile/AUC error
# within the documented bounds, and the exact (cat) mode bit-identical to the functional
# path (the sketch subsystem must not perturb it)
sketch-smoke:
	python bench.py --sketch --smoke > /tmp/tm_sketch_smoke.json
	python -c "import json; p=json.loads([l for l in open('/tmp/tm_sketch_smoke.json').read().strip().splitlines() if l][-1]); ex=p['extras']; assert ex['sketch_auc_abs_error'] <= ex['sketch_auc_error_bound'], ex; assert ex['quantile_rank_error'] <= ex['quantile_error_bound'], ex; assert ex['sketch_auroc_state_bytes'] == ex['sketch_auroc_state_bytes_short_stream'], ex; assert ex['sketch_auroc_state_bytes'] < ex['cat_auroc_state_bytes'], ex; assert ex['sketch_auroc_state_bytes'] <= 65536 and ex['sketch_quantile_state_bytes'] <= 65536, ex; assert ex['sketch_exact_mode_bit_identical'], ex; print('sketch-smoke ok: %dB sketch vs %dB cat state (%.0fx), AUC err %.2e <= %.2e' % (ex['sketch_auroc_state_bytes'], ex['cat_auroc_state_bytes'], ex['cat_auroc_state_bytes']/ex['sketch_auroc_state_bytes'], ex['sketch_auc_abs_error'], ex['sketch_auc_error_bound']))"

# static JAX/TPU hazard analysis (rules TPU000-TPU024, docs/static-analysis.md): exits
# nonzero on any non-baselined finding OR stale baseline entry; regenerate the baseline
# with `python -m torchmetrics_tpu._lint torchmetrics_tpu --write-baseline`. Whole-program
# pass over the package PLUS examples/ and bench.py, with the content-fingerprint
# incremental cache (unchanged reruns skip rule execution entirely).
jaxlint:
	python -m torchmetrics_tpu._lint torchmetrics_tpu examples bench.py --strict-baseline --cache

# pre-push inner loop: same whole-program analysis (cross-module rules stay sound), but
# only findings in files changed vs. origin/main are REPORTED — with a warm cache this is
# sub-second. Override the ref with `make jaxlint-fast REF=HEAD~1`.
REF ?= origin/main
jaxlint-fast:
	python -m torchmetrics_tpu._lint torchmetrics_tpu examples bench.py --cache --changed-only $(REF)

# deterministic schedule sanitizer (docs/static-analysis.md "Concurrency rules & the
# schedule sanitizer"): replays the shipped concurrency suppressions' named scenarios —
# engine enqueue-vs-quiesce, flight-ring append-vs-snapshot, federation
# poll-vs-shutdown, health-ledger evict-vs-probe — under seeded interleaving
# permutations; exits nonzero if ANY explored schedule breaks an invariant. The seed is
# pinned so CI failures replay locally with the printed schedule trace.
jaxlint-race:
	JAX_PLATFORMS=cpu TM_TPU_CHAOS_SEED=1234 python -m torchmetrics_tpu._lint.racerun --seed 1234

# SARIF artifact for CI code-scanning upload (same finding set as `make jaxlint`)
jaxlint-sarif:
	python -m torchmetrics_tpu._lint torchmetrics_tpu examples bench.py --cache --format sarif --output jaxlint.sarif

# opt-in jaxpr IR cross-check: lowers the registered aggregation kernels and verifies the
# AST layer agrees with the compiler's ground truth (imports jax; see docs/static-analysis.md)
jaxlint-ir:
	python -m torchmetrics_tpu._lint torchmetrics_tpu examples bench.py --cache --ir

# tier-1 guard for the observability exporter: one fused-sweep iteration with telemetry on,
# trace exported and schema-checked (also runs as part of test-integration / the tier-1 lane)
telemetry-smoke:
	TM_TPU_TELEMETRY=1 python -m pytest tests/integrations/test_telemetry_smoke.py -q

# fault-injection lane (docs/robustness.md): drives every recovery latch — forced AOT
# compile failure, post-donation dispatch death, collective timeout, preemption,
# NaN-poisoned batches — under a FIXED seed and asserts recovery to bit-identical state
chaos:
	TM_TPU_CHAOS_SEED=1234 python -m pytest tests/unittests/robust -q

# composite multi-fault sweep (docs/robustness.md "Chaos matrix"): seeded combinations of
# rank death mid-gather → quorum → rejoin+reconciliation, preemption mid-buffered-flush →
# journal replay, and flapping rank → eviction → re-admission, asserting bit-identical
# convergence with the unfaulted world for sum/mean/max/min/cat across dispatch tiers
chaos-matrix:
	TM_TPU_CHAOS_SEED=1234 python -m pytest tests/unittests/robust/test_chaos_matrix.py -q

# perf regression gate (docs/observability.md "Cost profiling & perf gate"): re-captures
# the XLA cost ledger for the fixed aggregation workload and diffs it — plus the latest
# BENCH_*.json headline numbers — against the committed PERF_LEDGER.json baseline. Exits
# nonzero on regression (1) or a missing baseline (2); skips with a notice on backends
# without cost_analysis(). For an INTENTIONAL change, run `make perf-baseline` and commit
# the refreshed PERF_LEDGER.json alongside the change that moved the numbers.
perf-gate:
	python -m torchmetrics_tpu.obs.gate

perf-baseline:
	python -m torchmetrics_tpu.obs.gate --update-baseline

clean:
	find . -type d -name __pycache__ -prune -exec rm -rf {} +
	rm -rf .pytest_cache
