"""Benchmark: metric-updates/sec/chip on a 1M-sample classification sweep.

BASELINE.md north star, config #1/#4: ``MetricCollection([Accuracy, Precision, Recall, F1])``
(multiclass, num_classes=5) update/compute loop over 1M samples. Prints ONE JSON line:
``{"metric", "value", "unit", "vs_baseline"}`` where ``vs_baseline`` is our throughput divided
by the reference's (oguz-hanoglu/torchmetrics, torch backend) measured on the same host.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

TOTAL_SAMPLES = 1_000_000
BATCH = 10_000
NUM_CLASSES = 5
N_BATCHES = TOTAL_SAMPLES // BATCH


def _gen_data():
    rng = np.random.RandomState(7)
    preds = rng.randint(0, NUM_CLASSES, size=(N_BATCHES, BATCH)).astype(np.int32)
    target = rng.randint(0, NUM_CLASSES, size=(N_BATCHES, BATCH)).astype(np.int32)
    return preds, target


def bench_ours(preds: np.ndarray, target: np.ndarray) -> float:
    """updates/sec through the stateful MetricCollection API (compute groups fused)."""
    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu import MetricCollection
    from torchmetrics_tpu.classification import (
        MulticlassAccuracy,
        MulticlassF1Score,
        MulticlassPrecision,
        MulticlassRecall,
    )

    def make():
        return MetricCollection(
            [
                MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False),
                MulticlassPrecision(num_classes=NUM_CLASSES, average="macro", validate_args=False),
                MulticlassRecall(num_classes=NUM_CLASSES, average="macro", validate_args=False),
                MulticlassF1Score(num_classes=NUM_CLASSES, average="macro", validate_args=False),
            ]
        )

    stack_preds = jnp.asarray(preds)
    stack_target = jnp.asarray(target)
    jax.block_until_ready((stack_preds, stack_target))

    # warmup: build compute groups + compile the scanned update kernel (jit caches are
    # per-instance; reset() clears state but keeps the compiled kernels)
    mc = make()
    for _ in range(2):  # 1st pass forms groups (scan sees N-1 batches), 2nd compiles the N shape
        mc.update_batches(stack_preds, stack_target)
        jax.block_until_ready(list(mc.compute().values()))
        mc.reset()

    # steady-state throughput: K pipelined sweeps (dispatch is async; one sync at the end so a
    # host<->device round-trip isn't billed to every sweep)
    K = 50
    t0 = time.perf_counter()
    results = []
    for _ in range(K):
        mc.reset()
        mc.update_batches(stack_preds, stack_target)
        results.append(mc.compute())
    jax.block_until_ready(results)
    elapsed = time.perf_counter() - t0
    res = results[-1]
    print(
        f"ours (fused scan): {K}x{N_BATCHES} updates in {elapsed:.4f}s,"
        f" result={ {k: float(v) for k, v in res.items()} }",
        file=sys.stderr,
    )
    return K * N_BATCHES / elapsed


def bench_reference(preds: np.ndarray, target: np.ndarray) -> float:
    """Same sweep through the reference torchmetrics (torch backend)."""
    import types

    # minimal lightning_utilities shim (not installed in this image)
    if "lightning_utilities" not in sys.modules:
        lu = types.ModuleType("lightning_utilities")
        core = types.ModuleType("lightning_utilities.core")
        imports_mod = types.ModuleType("lightning_utilities.core.imports")
        enums_mod = types.ModuleType("lightning_utilities.core.enums")

        import importlib.util
        from enum import Enum

        def package_available(name: str) -> bool:
            try:
                return importlib.util.find_spec(name) is not None
            except Exception:
                return False

        def compare_version(package: str, op, version: str, use_base_version: bool = False) -> bool:
            try:
                from packaging.version import Version

                mod = __import__(package)
                return op(Version(mod.__version__), Version(version))
            except Exception:
                return False

        class StrEnum(str, Enum):
            @classmethod
            def from_str(cls, value, source="key"):
                for st in cls:
                    if st.value.lower() == str(value).lower() or st.name.lower() == str(value).lower():
                        return st
                return None

            @classmethod
            def try_from_str(cls, value, source="key"):
                return cls.from_str(value, source)

            def __eq__(self, other):
                if isinstance(other, str):
                    return self.value.lower() == other.lower()
                return super().__eq__(other)

            def __hash__(self):
                return hash(self.value.lower())

        def apply_to_collection(data, dtype, function, *args, **kwargs):
            if isinstance(data, dtype):
                return function(data, *args, **kwargs)
            if isinstance(data, dict):
                return {k: apply_to_collection(v, dtype, function, *args, **kwargs) for k, v in data.items()}
            if isinstance(data, (list, tuple)):
                out = [apply_to_collection(v, dtype, function, *args, **kwargs) for v in data]
                return type(data)(out) if isinstance(data, tuple) else out
            return data

        imports_mod.package_available = package_available
        imports_mod.compare_version = compare_version
        enums_mod.StrEnum = StrEnum
        lu.apply_to_collection = apply_to_collection
        core.imports = imports_mod
        core.enums = enums_mod
        lu.core = core
        sys.modules["lightning_utilities"] = lu
        sys.modules["lightning_utilities.core"] = core
        sys.modules["lightning_utilities.core.imports"] = imports_mod
        sys.modules["lightning_utilities.core.enums"] = enums_mod

    sys.path.insert(0, "/root/reference/src")
    import torch
    from torchmetrics import MetricCollection as RefCollection
    from torchmetrics.classification import (
        MulticlassAccuracy,
        MulticlassF1Score,
        MulticlassPrecision,
        MulticlassRecall,
    )

    def make():
        return RefCollection(
            [
                MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False),
                MulticlassPrecision(num_classes=NUM_CLASSES, average="macro", validate_args=False),
                MulticlassRecall(num_classes=NUM_CLASSES, average="macro", validate_args=False),
                MulticlassF1Score(num_classes=NUM_CLASSES, average="macro", validate_args=False),
            ]
        )

    dev_preds = [torch.from_numpy(p).long() for p in preds]
    dev_target = [torch.from_numpy(t).long() for t in target]

    # measure a slice and extrapolate (reference torch-CPU path is slow)
    n_meas = min(N_BATCHES, 30)
    mc = make()
    mc.update(dev_preds[0], dev_target[0])  # group formation
    t0 = time.perf_counter()
    for i in range(1, n_meas):
        mc.update(dev_preds[i], dev_target[i])
    _ = mc.compute()
    elapsed = time.perf_counter() - t0
    print(f"reference: {n_meas - 1} updates in {elapsed:.3f}s", file=sys.stderr)
    return (n_meas - 1) / elapsed


def main() -> None:
    preds, target = _gen_data()
    ours = bench_ours(preds, target)
    try:
        ref = bench_reference(preds, target)
        vs = ours / ref
    except Exception as err:  # reference unavailable -> report absolute number only
        print(f"reference bench failed: {err!r}", file=sys.stderr)
        vs = float("nan")
    print(
        json.dumps(
            {
                "metric": "metric_updates_per_sec_1M_sample_multiclass_sweep",
                "value": round(ours, 2),
                "unit": "updates/s (batch=10k, MetricCollection[Acc,P,R,F1] fused)",
                "vs_baseline": round(vs, 3) if vs == vs else None,
            }
        )
    )


if __name__ == "__main__":
    main()
