"""Benchmark: metric-updates/sec/chip on a 1M-sample classification sweep.

BASELINE.md north star, config #1/#4: ``MetricCollection([Accuracy, Precision, Recall, F1])``
(multiclass, num_classes=5) update/compute loop over 1M samples. Prints ONE JSON line:
``{"metric", "value", "unit", "vs_baseline"}`` where ``vs_baseline`` is our throughput divided
by the reference's (oguz-hanoglu/torchmetrics, torch backend) measured on the same host.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

TOTAL_SAMPLES = 1_000_000
BATCH = 10_000
NUM_CLASSES = 5
N_BATCHES = TOTAL_SAMPLES // BATCH

#: --smoke: tiny-N CI mode (make bench-smoke) — same code paths and JSON schema, seconds of
#: wall time, no reference/mesh subprocesses. Guards against bench.py rotting between rounds.
SMOKE = False


def _apply_smoke_sizes() -> None:
    global TOTAL_SAMPLES, BATCH, N_BATCHES, SMOKE
    SMOKE = True
    TOTAL_SAMPLES = 20_000
    BATCH = 1_000
    N_BATCHES = TOTAL_SAMPLES // BATCH


def _gen_data():
    rng = np.random.RandomState(7)
    preds = rng.randint(0, NUM_CLASSES, size=(N_BATCHES, BATCH)).astype(np.int32)
    target = rng.randint(0, NUM_CLASSES, size=(N_BATCHES, BATCH)).astype(np.int32)
    return preds, target


def bench_ours(preds: np.ndarray, target: np.ndarray) -> dict:
    """Fused-sweep numbers through ``MetricCollection.sweep_fn`` (one launch per K sweeps).

    The tunneled chip adds a large constant per-launch cost (see ``bench_dispatch_latency``) —
    ~4ms host dispatch pipelined, ~134ms for a blocking round-trip — which swamps any per-sweep
    protocol that launches from the host (this is what collapsed the r02→r03 headline: same
    code, higher tunnel latency). So the headline DEVICE RATE is measured as a two-point slope:
    time a K1-sweep and a K2-sweep single-launch program (sweeps scanned on device, each sweep
    salted so XLA cannot CSE them) and divide the extra work by the extra time — constant
    dispatch+latency cancels. End-to-end wall time for ONE 1M-sample sweep (latency included)
    is reported alongside, and is the like-for-like number against the reference's wall time.
    """
    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu import MetricCollection
    from torchmetrics_tpu.classification import (
        MulticlassAccuracy,
        MulticlassF1Score,
        MulticlassPrecision,
        MulticlassRecall,
    )

    mc = MetricCollection(
        [
            MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False),
            MulticlassPrecision(num_classes=NUM_CLASSES, average="macro", validate_args=False),
            MulticlassRecall(num_classes=NUM_CLASSES, average="macro", validate_args=False),
            MulticlassF1Score(num_classes=NUM_CLASSES, average="macro", validate_args=False),
        ]
    )
    stack_preds = jnp.asarray(preds)
    stack_target = jnp.asarray(target)
    jax.block_until_ready((stack_preds, stack_target))
    mc(stack_preds[0], stack_target[0])  # form compute groups
    mc.reset()
    fn = mc.sweep_fn()

    # ONE jitted program with a RUNTIME sweep count (fori_loop): no per-K recompiles, and the
    # k2-k1 slope cancels every constant cost (dispatch, tunnel latency, result fetch)
    def run(k):
        def body(i, acc):
            vals = fn((stack_preds + i) % NUM_CLASSES, stack_target)
            return acc + sum(jnp.asarray(v) for v in vals.values())

        return jax.lax.fori_loop(0, k, body, jnp.zeros(()))

    run_j = jax.jit(run)
    jax.block_until_ready(run_j(2))  # compile
    res = {k: float(v) for k, v in fn(stack_preds, stack_target).items()}  # sanity values

    device_rate, t1, t2, k1, k2 = _slope_rate(run_j, per_call=N_BATCHES)
    wall_one_sweep = _best_of(lambda: jax.block_until_ready(run_j(1)), windows=3)

    # the host-API protocol (one update_batches + compute launch set per sweep) for context
    mc.reset()
    mc.update_batches(stack_preds, stack_target)
    jax.block_until_ready(list(mc.compute().values()))

    def _host_window():
        results = []
        for _ in range(5):
            mc.reset()
            mc.update_batches(stack_preds, stack_target)
            results.append(mc.compute())
        jax.block_until_ready(results)

    host_api_rate = 5 * N_BATCHES / _best_of(_host_window, windows=3)
    print(
        f"ours (fused sweep): slope rate {device_rate:.0f} updates/s"
        f" (t@{k1}={t1:.4f}s t@{k2}={t2:.4f}s), one-sweep wall {wall_one_sweep:.4f}s,"
        f" host-API {host_api_rate:.0f} updates/s, result={res}",
        file=sys.stderr,
    )
    return {
        "device_rate": device_rate,
        "wall_one_sweep_s": wall_one_sweep,
        "host_api_rate": host_api_rate,
    }


def _make_collection():
    from torchmetrics_tpu import MetricCollection
    from torchmetrics_tpu.classification import (
        MulticlassAccuracy,
        MulticlassF1Score,
        MulticlassPrecision,
        MulticlassRecall,
    )

    return MetricCollection(
        [
            MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False),
            MulticlassPrecision(num_classes=NUM_CLASSES, average="macro", validate_args=False),
            MulticlassRecall(num_classes=NUM_CLASSES, average="macro", validate_args=False),
            MulticlassF1Score(num_classes=NUM_CLASSES, average="macro", validate_args=False),
        ]
    )


def _presplit_batches(preds: np.ndarray, target: np.ndarray):
    """Per-batch device arrays, materialised OUTSIDE the timed window.

    Protocol parity with the reference bench, which iterates a pre-built list of per-batch
    torch tensors: slicing ``stack[i]`` inside the loop is an extra eager device op per
    step (two per batch — it was ~2/3 of the measured per-step cost on CPU) that a real
    training loop, receiving each batch as its own array, never pays.
    """
    import jax
    import jax.numpy as jnp

    stack_p, stack_t = jnp.asarray(preds), jnp.asarray(target)
    plist = [stack_p[i] for i in range(N_BATCHES)]
    tlist = [stack_t[i] for i in range(N_BATCHES)]
    jax.block_until_ready((plist, tlist))
    return plist, tlist


def bench_ours_per_step(preds: np.ndarray, target: np.ndarray, n_meas: int = 100) -> dict:
    """updates/sec through per-batch ``forward`` — the SAME protocol the reference loop uses
    (one dispatch per batch, batch value returned, per-batch arrays pre-built like the
    reference's tensor list), so `vs_baseline` compares like with like.

    Also reports ``per_step_host_overhead_us``: the ``dispatch.host_overhead`` timer mean
    from a short telemetry-enabled window — the wall time a fast-dispatch step spends
    OUTSIDE the compiled executable (the quantity the AOT tier exists to minimise).
    """
    import jax

    from torchmetrics_tpu import obs

    mc = _make_collection()
    plist, tlist = _presplit_batches(preds, target)
    for i in range(2):  # group formation + compile
        mc(plist[i], tlist[i])
    mc.reset()

    n_meas = min(n_meas, N_BATCHES)

    def _window():
        mc.reset()
        out = [mc(plist[i % N_BATCHES], tlist[i % N_BATCHES]) for i in range(n_meas)]
        jax.block_until_ready(list(out[-1].values()))

    # the tunnel occasionally stalls a whole window (~100ms hiccups); more windows give the
    # best-of a real chance to see an unstalled pass
    best = _best_of(_window, windows=6)
    print(f"ours (per-step forward): {n_meas} updates in {best:.4f}s", file=sys.stderr)

    host_overhead_us = None
    with obs.enabled():
        mc.reset()
        out = [mc(plist[i % N_BATCHES], tlist[i % N_BATCHES]) for i in range(min(50, n_meas))]
        jax.block_until_ready(list(out[-1].values()))
        timer = obs.telemetry._timers.get("dispatch.host_overhead")
        if timer is not None and timer.count:
            host_overhead_us = round(timer.mean_s * 1e6, 2)
    return {"rate": n_meas / best, "host_overhead_us": host_overhead_us}


def bench_buffered_updates(preds: np.ndarray, target: np.ndarray, k: int = 16) -> float:
    """updates/sec through ``MetricCollection.buffered(k)`` — the deferred micro-batch
    accumulator: k host-side appends, then ONE stacked ``update_scan`` launch. This is the
    update-only-loop protocol (no per-batch value), the shape where the accumulator turns
    k dispatches into one."""
    import jax

    mc = _make_collection()
    plist, tlist = _presplit_batches(preds, target)
    mc(plist[0], tlist[0])  # group formation + compile
    mc.reset()
    buf = mc.buffered(k)
    # compile both stacked-scan signatures (full-k flush + the N%k remainder) out of window
    for i in range(k):
        buf.update(plist[i % N_BATCHES], tlist[i % N_BATCHES])
    for i in range(N_BATCHES % k):
        buf.update(plist[i], tlist[i])
    buf.flush()
    buf.reset()

    def _window():
        buf.reset()
        for i in range(N_BATCHES):
            buf.update(plist[i], tlist[i])
        buf.flush()
        jax.block_until_ready(list(mc.compute().values()))

    best = _best_of(_window, windows=4)
    print(f"ours (buffered k={k} updates): {N_BATCHES} updates in {best:.4f}s", file=sys.stderr)
    return N_BATCHES / best


def _keyed_instance_loop_rate(cls, ids_batches, val_batches, n_keys: int) -> tuple:
    """The loop the keyed engine replaces: a dict of per-key instances, one update per
    key present in each batch (group-by on the host, charitable to the loop — the naive
    per-ELEMENT loop is far worse). Returns (batches/sec, per-key values array)."""
    import jax

    insts = [cls(nan_strategy="ignore") for _ in range(n_keys)]
    # warm the per-group-size jit cache out of window (ragged group shapes retrace)
    ids0, vals0 = np.asarray(ids_batches[0]), np.asarray(val_batches[0])
    for k in np.unique(ids0):
        insts[k].update(vals0[ids0 == k])
    for m in insts:
        m.reset()
    t0 = time.perf_counter()
    for ids, vals in zip(ids_batches, val_batches):
        ids, vals = np.asarray(ids), np.asarray(vals)
        for k in np.unique(ids):
            insts[k].update(vals[ids == k])
    values = [m.compute() for m in insts]
    jax.block_until_ready(values)
    elapsed = time.perf_counter() - t0
    return len(ids_batches) / elapsed, np.asarray([np.asarray(v) for v in values])


def bench_keyed(n_keys_list, batch: int, n_batches: int, loop_batches: int) -> dict:
    """``--keyed`` scenario: mixed-tenant batches through ONE KeyedMetric vs a dict of
    per-key instances (docs/keyed.md). Emits per-N ``keyed_updates_per_sec`` (update
    launches per second, each launch folding a full mixed-tenant batch), the speedup over
    the instance loop on the SAME batches, and bit-identity of every per-key value across
    the jit / AOT+donation / buffered dispatch tiers AND vs the instance loop.

    Values are integer-valued float32 so float accumulation is exact — "bit-identical"
    means bit-identical, not within-epsilon, regardless of reduction order.
    """
    import jax

    from torchmetrics_tpu import obs
    from torchmetrics_tpu.aggregation import SumMetric
    from torchmetrics_tpu.keyed import KeyedMetric
    from torchmetrics_tpu.ops.dispatch import ENV_FAST_DISPATCH

    rng = np.random.RandomState(11)
    out: dict = {}
    for n_keys in n_keys_list:
        ids_np = rng.randint(0, n_keys, size=(n_batches, batch)).astype(np.int32)
        vals_np = rng.randint(0, 64, size=(n_batches, batch)).astype(np.float32)
        import jax.numpy as jnp

        ids = [jnp.asarray(ids_np[i]) for i in range(n_batches)]
        vals = [jnp.asarray(vals_np[i]) for i in range(n_batches)]
        jax.block_until_ready((ids, vals))

        km = KeyedMetric(SumMetric(nan_strategy="ignore"), n_keys)
        km.update(ids[0], vals[0])  # compile out of window
        km.reset()

        def _window():
            km.reset()
            for i in range(n_batches):
                km.update(ids[i], vals[i])
            jax.block_until_ready(km.compute())

        best = _best_of(_window, windows=3)
        keyed_rate = n_batches / best
        out[f"keyed_updates_per_sec_n{n_keys}"] = round(keyed_rate, 2)
        print(
            f"keyed N={n_keys}: {n_batches} mixed-tenant updates in {best:.4f}s"
            f" ({keyed_rate:.0f} updates/s)",
            file=sys.stderr,
        )

        # the instance loop on a PREFIX of the same stream (it is orders of magnitude
        # slower; the rate extrapolates per batch, the values anchor bit-identity)
        lb = min(loop_batches, n_batches)
        loop_rate, loop_vals = _keyed_instance_loop_rate(
            SumMetric, ids_np[:lb], vals_np[:lb], n_keys
        )
        out[f"instance_loop_updates_per_sec_n{n_keys}"] = round(loop_rate, 2)
        out[f"keyed_vs_instance_loop_n{n_keys}"] = round(keyed_rate / loop_rate, 1)

        # bit-identity of every per-key value, across all three dispatch tiers
        def run_tier(tier: str) -> np.ndarray:
            prior = os.environ.get(ENV_FAST_DISPATCH)
            if tier == "jit":
                os.environ[ENV_FAST_DISPATCH] = "0"
            try:
                m = KeyedMetric(SumMetric(nan_strategy="ignore"), n_keys)
                if tier == "buffered":
                    with m.buffered(4) as buf:
                        for i in range(lb):
                            buf.update(ids[i], vals[i])
                else:
                    for i in range(lb):
                        m.update(ids[i], vals[i])
                return np.asarray(m.compute())
            finally:
                if prior is None:
                    os.environ.pop(ENV_FAST_DISPATCH, None)
                else:
                    os.environ[ENV_FAST_DISPATCH] = prior

        tiers = {tier: run_tier(tier) for tier in ("aot", "jit", "buffered")}
        identical = all(v.tobytes() == loop_vals.tobytes() for v in tiers.values())
        out[f"keyed_bit_identical_n{n_keys}"] = bool(identical)
    out["keyed_batch"] = batch
    out["keyed_n_batches"] = n_batches
    out["keyed_telemetry"] = {
        k: obs.telemetry.counter(k).value
        for k in ("keyed.updates", "keyed.active_keys", "keyed.fanout")
    }
    return out


def keyed_main(smoke: bool) -> None:
    """``bench.py --keyed [--smoke]``: one JSON line with the keyed scenario numbers.

    Full mode sweeps N in {1e3, 1e4, 1e5}; smoke keeps {1e3, 1e4} at tiny batch counts
    (the acceptance point — 50x over the instance loop at N=10k — must hold even there).
    """
    if smoke:
        n_keys_list, batch, n_batches, loop_batches = (1_000, 10_000), 2048, 8, 2
    else:
        n_keys_list, batch, n_batches, loop_batches = (1_000, 10_000, 100_000), 8192, 50, 3
    extras = bench_keyed(n_keys_list, batch=batch, n_batches=n_batches, loop_batches=loop_batches)
    extras.update(_contention_report())
    try:
        from torchmetrics_tpu import obs

        extras["telemetry"] = obs.bench_extras()
        # per-key cost-ledger rows: the keyed kernels' compiler-level FLOPs/bytes
        # (resolved outside every timed window), diffable by the perf gate
        extras["cost_ledger"] = [
            {k: r[k] for k in ("key", "metric", "kernel", "tier", "flops",
                               "bytes_accessed", "temp_bytes", "argument_bytes", "available")}
            for r in obs.cost_ledger()
            if r["metric"] == "KeyedMetric"
        ]
    except Exception as err:  # pragma: no cover - extras are best-effort
        extras["telemetry_error"] = repr(err)
    headline = extras.get("keyed_updates_per_sec_n10000")
    print(
        json.dumps(
            {
                "metric": "keyed_updates_per_sec",
                "value": headline,
                "unit": ("[SMOKE tiny-N lane — not a recordable perf number] " if smoke else "") + (
                    "mixed-tenant update launches/s at N=10k keys (KeyedMetric[Sum], one"
                    " fused segment-reduce launch per batch; per-N rates, instance-loop"
                    " speedups, tier bit-identity, and keyed cost-ledger rows in extras)"
                ),
                "vs_baseline": extras.get("keyed_vs_instance_loop_n10000"),
                "extras": extras,
            }
        )
    )


def bench_sharded(n_keys: int, batch: int, n_batches: int, world: int) -> dict:
    """``--sharded`` scenario (docs/distributed.md "Sharded state"): a keyed tenant table
    replicated vs ``shard()``-ed over the forced multi-device host mesh.

    Measures (a) mixed-tenant update throughput in both placements (sharded accumulation
    must not cost throughput), (b) bit-identity of every per-key value sharded-vs-
    replicated across the AOT / jit / buffered dispatch tiers AND through a simulated
    ``world``-rank sync, and (c) the sync byte ledger: received bytes for one compute's
    sync through the replicated full allgather vs the sharded reduce-scatter + slab
    assembly, plus the lazy reduce-once cache behaviour (fires once per update epoch,
    reuses on recompute). Values are integer-valued float32 — bit-identical means
    bit-identical.
    """
    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu import obs
    from torchmetrics_tpu.aggregation import SumMetric
    from torchmetrics_tpu.keyed import KeyedMetric
    from torchmetrics_tpu.ops.dispatch import ENV_FAST_DISPATCH
    from torchmetrics_tpu.parallel import sync as sync_mod
    from torchmetrics_tpu.parallel.mesh import MeshContext, is_partitioned

    ctx = MeshContext()
    out: dict = {
        "mesh_devices": jax.device_count(),
        "mesh_axis_size": ctx.size,
        "sync_world": world,
        "sharded_n_keys": n_keys,
        "sharded_batch": batch,
        "sharded_n_batches": n_batches,
    }
    rng = np.random.RandomState(17)
    ids_np = rng.randint(0, n_keys, size=(n_batches, batch)).astype(np.int32)
    vals_np = rng.randint(0, 64, size=(n_batches, batch)).astype(np.float32)
    ids = [jnp.asarray(ids_np[i]) for i in range(n_batches)]
    vals = [jnp.asarray(vals_np[i]) for i in range(n_batches)]
    jax.block_until_ready((ids, vals))

    def throughput(mode: str) -> float:
        km = KeyedMetric(SumMetric(nan_strategy="ignore"), n_keys)
        if mode == "sharded":
            km.shard(ctx)
        km.update(ids[0], vals[0])  # compile out of window
        km.reset()

        def _window():
            km.reset()
            for i in range(n_batches):
                km.update(ids[i], vals[i])
            jax.block_until_ready(km.compute())

        return n_batches / _best_of(_window, windows=3)

    for mode in ("replicated", "sharded"):
        rate = throughput(mode)
        out[f"keyed_updates_per_sec_{mode}"] = round(rate, 2)
        print(f"sharded lane [{mode}]: {rate:.0f} mixed-tenant updates/s at N={n_keys}", file=sys.stderr)

    # tier bit-identity: sharded vs replicated per-key values must match BYTE for byte
    def run_tier(tier: str, sharded: bool) -> np.ndarray:
        prior = os.environ.get(ENV_FAST_DISPATCH)
        if tier == "jit":
            os.environ[ENV_FAST_DISPATCH] = "0"
        try:
            m = KeyedMetric(SumMetric(nan_strategy="ignore"), n_keys)
            if sharded:
                m.shard(ctx)
            if tier == "buffered":
                with m.buffered(4) as buf:
                    for i in range(n_batches):
                        buf.update(ids[i], vals[i])
            else:
                for i in range(n_batches):
                    m.update(ids[i], vals[i])
            return np.asarray(m.compute())
        finally:
            if prior is None:
                os.environ.pop(ENV_FAST_DISPATCH, None)
            else:
                os.environ[ENV_FAST_DISPATCH] = prior

    for tier in ("aot", "jit", "buffered"):
        rep, shd = run_tier(tier, False), run_tier(tier, True)
        out[f"sharded_bit_identical_{tier}"] = bool(rep.tobytes() == shd.tobytes())

    # sync byte ledger over a simulated world: rank replicas with disjoint streams
    ranks = [KeyedMetric(SumMetric(nan_strategy="ignore"), n_keys) for _ in range(world)]
    for m in ranks:
        for _ in range(2):
            i = rng.randint(0, n_keys, size=(batch,)).astype(np.int32)
            v = rng.randint(0, 64, size=(batch,)).astype(np.float32)
            m.update(i, v)  # jaxlint: disable=TPU010 — rank replicas of a simulated world, one per rank (not per-key streams)
    states = [dict(m._state.tensors) for m in ranks]
    reds = {n: ranks[0]._reductions[n] for n in states[0]}
    opts = sync_mod.SyncOptions(world=world)
    gather = sync_mod.simulate_mesh_world(states, reds, opts)
    rep_sync = sync_mod.process_sync(states[0], reds, gather_fn=gather, options=opts)
    km0 = ranks[0].shard(ctx)
    sharded_names = [n for n, s in km0.shard_specs.items() if is_partitioned(s)]
    states[0] = dict(km0._state.tensors)
    shd_sync = sync_mod.process_sync(
        states[0], reds, gather_fn=gather, options=opts, sharded_states=sharded_names
    )
    out["sync_bytes_per_compute_replicated"] = int(rep_sync.bytes_received)
    out["sync_bytes_per_compute_sharded"] = int(shd_sync.bytes_received)
    out["sync_sharded_states"] = list(shd_sync.sharded_states)
    out["sharded_bit_identical_sync"] = all(
        np.asarray(rep_sync[n]).tobytes() == np.asarray(shd_sync[n]).tobytes() for n in states[0]
    )

    # lazy reduce-once: one fire per update epoch, reuse on recompute, refire after update
    km0.compute_with_cache = False  # force each compute through the sync seam
    km0.dist_sync_fn = gather
    km0.distributed_available_fn = lambda: True
    km0.sync_options = opts
    f0 = obs.telemetry.counter("sync.lazy_reduce.fires").value
    r0 = obs.telemetry.counter("sync.lazy_reduce.reuses").value
    km0.compute()
    km0.compute()  # same epoch: must reuse, zero new bytes
    km0.update(ids[0], vals[0])  # new epoch
    states[0] = dict(km0._state.tensors)
    km0.compute()
    out["sharded_compute_epochs"] = 2
    out["lazy_reduce_fires"] = obs.telemetry.counter("sync.lazy_reduce.fires").value - f0
    out["lazy_reduce_reuses"] = obs.telemetry.counter("sync.lazy_reduce.reuses").value - r0
    out["sync_bytes_saved_total"] = obs.telemetry.counter("sync.bytes_saved").value
    return out


def sharded_main(smoke: bool) -> None:
    """``bench.py --sharded [--smoke]``: one JSON line with the sharded-state numbers.

    Runs on a forced multi-device host mesh (``--xla_force_host_platform_device_count``,
    set by ``make shard-smoke``/this entry point). The acceptance point (``make
    shard-smoke``): per-compute sync bytes in sharded mode strictly below the allgather
    baseline, per-key values bit-identical across tiers and placements, and the lazy
    reduce firing at most once per (update-epoch, compute) pair.
    """
    if smoke:
        n_keys, batch, n_batches, world = 1024, 2048, 8, 4
    else:
        n_keys, batch, n_batches, world = 65536, 8192, 50, 8
    extras = bench_sharded(n_keys, batch=batch, n_batches=n_batches, world=world)
    extras.update(_contention_report())
    try:
        from torchmetrics_tpu import obs

        extras["telemetry"] = obs.bench_extras()
    except Exception as err:  # pragma: no cover - extras are best-effort
        extras["telemetry_error"] = repr(err)
    rep, shd = extras["sync_bytes_per_compute_replicated"], extras["sync_bytes_per_compute_sharded"]
    print(
        json.dumps(
            {
                "metric": "sharded_sync_bytes_per_compute",
                "value": shd,
                "unit": ("[SMOKE tiny-N lane — not a recordable perf number] " if smoke else "") + (
                    "bytes received per sync of the keyed tenant table through the sharded"
                    " reduce-scatter path (vs_baseline = replicated-allgather bytes / sharded"
                    " bytes; throughput, tier/sync bit-identity, and lazy reduce-once"
                    " behaviour in extras — docs/distributed.md 'Sharded state')"
                ),
                "vs_baseline": round(rep / shd, 2) if shd else None,
                "extras": extras,
            }
        )
    )


def bench_sync_compress(n: int, world: int, epochs: int) -> dict:
    """``--sync-compress`` scenario (docs/distributed.md "Compressed collectives"):
    every ``SyncOptions(compression=...)`` mode over a ``world``-rank simulated mesh.

    Measures, per mode at pinned shapes: (a) true wire bytes shipped/received/saved
    for a state dict covering every codec lane (f32 sum + mean slabs, f32 max/min,
    int32 counts, a KLL quantile sketch and a threshold-histogram pair); (b) exact-mode
    bit-identity flags — min/max/count/int and both sketch merges must match the
    ``compression="none"`` sync byte for byte; (c) sum/mean error vs full precision
    within the documented block-scale bounds; (d) the sketch-blob fast path's saved
    ratio (packed wire vs raw arrays, ≥ 2x gated); and (e) error-feedback behaviour
    across repeated sync EPOCHS of a growing sum — the max error must stay within the
    single-sync bound (no drift), which is the whole point of the residual store.
    """
    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu import obs
    from torchmetrics_tpu.parallel import compress as compress_mod
    from torchmetrics_tpu.parallel import sync as sync_mod
    from torchmetrics_tpu.sketch import kll

    rng = np.random.RandomState(23)
    kinds = {"q": "kll", "hist": "hist"}

    def make_states() -> list:
        states = []
        for _ in range(world):
            sketch = kll.kll_init(64, 16)
            sketch = kll.kll_update(sketch, jnp.asarray(rng.randn(512).astype(np.float32)))
            states.append({
                "slab": jnp.asarray((rng.randn(n) * 16).astype(np.float32)),
                "meanv": jnp.asarray(rng.randn(n).astype(np.float32)),
                "vmax": jnp.asarray(rng.randn(n).astype(np.float32)),
                "vmin": jnp.asarray(rng.randn(n).astype(np.float32)),
                "count": jnp.asarray(rng.randint(0, 1 << 20, size=(n,)).astype(np.int32)),
                "q": sketch,
                "hist": jnp.asarray(rng.randint(0, 4096, size=(2, 512)).astype(np.float32)),
            })
        return states

    reds = {"slab": "sum", "meanv": "mean", "vmax": "max", "vmin": "min",
            "count": "sum", "q": kll.kll_merge_stacked, "hist": "sum"}
    states = make_states()
    jax.block_until_ready([s["slab"] for s in states])
    out: dict = {"compress_world": world, "compress_n": n, "compress_ef_epochs": epochs}
    synced: dict = {}
    exact_states = ("vmax", "vmin", "count", "q", "hist")
    slab_max = max(float(np.max(np.abs(np.asarray(s["slab"])))) for s in states)
    mean_max = max(float(np.max(np.abs(np.asarray(s["meanv"])))) for s in states)
    for mode in ("none", "bf16", "int8"):
        opts = sync_mod.SyncOptions(world=world, compression=mode)
        gather = sync_mod.simulate_mesh_world(states, reds, opts, sketch_kinds=kinds)
        t0 = time.perf_counter()
        res = sync_mod.process_sync(
            dict(states[0]), reds, gather_fn=gather, options=opts,
            sketch_wire=kinds, residuals={},
        )
        out[f"compress_sync_wall_ms_{mode}"] = round((time.perf_counter() - t0) * 1e3, 2)
        synced[mode] = res
        out[f"compress_bytes_shipped_{mode}"] = int(res.bytes_shipped)
        out[f"compress_bytes_received_{mode}"] = int(res.bytes_received)
        out[f"compress_bytes_saved_{mode}"] = int(res.bytes_saved)
        out[f"compress_compressed_states_{mode}"] = list(res.compressed_states)
        # sketch-blob fast path in isolation: packed wire vs the raw arrays
        sk_states = [{k: s[k] for k in ("q", "hist")} for s in states]
        sk_gather = sync_mod.simulate_mesh_world(sk_states, reds, opts, sketch_kinds=kinds)
        sk = sync_mod.process_sync(
            dict(sk_states[0]), {k: reds[k] for k in ("q", "hist")},
            gather_fn=sk_gather, options=opts, sketch_wire=kinds,
        )
        raw_sk = sum(int(np.asarray(s[k]).nbytes) for s in sk_states for k in ("q", "hist"))
        raw_sk_wire = raw_sk + int(np.asarray(sk_states[0]["q"]).nbytes
                                   + np.asarray(sk_states[0]["hist"]).nbytes)
        out[f"compress_sketch_wire_bytes_{mode}"] = int(sk.bytes_shipped + sk.bytes_received)
        out[f"compress_sketch_saved_ratio_{mode}"] = round(
            raw_sk_wire / max(1, sk.bytes_shipped + sk.bytes_received), 2
        )
    base = synced["none"]
    for mode in ("bf16", "int8"):
        res = synced[mode]
        out[f"compress_exact_bit_identical_{mode}"] = all(
            np.asarray(res[k]).tobytes() == np.asarray(base[k]).tobytes() for k in exact_states
        )
        sum_err = float(np.max(np.abs(np.asarray(res["slab"], np.float64) - np.asarray(base["slab"], np.float64))))
        mean_err = float(np.max(np.abs(np.asarray(res["meanv"], np.float64) - np.asarray(base["meanv"], np.float64))))
        out[f"compress_sum_abs_err_{mode}"] = sum_err
        out[f"compress_sum_err_bound_{mode}"] = compress_mod.sum_error_bound(mode, slab_max, world)
        out[f"compress_mean_abs_err_{mode}"] = mean_err
        # mean over w ranks averages w per-rank quantization errors — same bound / w
        out[f"compress_mean_err_bound_{mode}"] = compress_mod.sum_error_bound(mode, mean_max, world) / world

    # error-feedback across repeated sync epochs: a growing sum, one sync per epoch,
    # rank 0's residual store persistent (as Metric._sync_dist keeps it) — max error
    # must stay within the single-sync bound at the FINAL magnitudes (no drift)
    for mode in ("bf16", "int8"):
        ef_states = [{"acc": np.zeros(n, np.float32)} for _ in range(world)]
        ef_reds = {"acc": "sum"}
        opts = sync_mod.SyncOptions(world=world, compression=mode)
        gather = sync_mod.simulate_mesh_world(ef_states, ef_reds, opts)
        store: dict = {}
        max_err = 0.0
        for _ in range(epochs):
            for r in range(world):
                ef_states[r]["acc"] = ef_states[r]["acc"] + rng.randn(n).astype(np.float32)
            exact = np.sum([np.asarray(s["acc"], np.float64) for s in ef_states], axis=0)
            res = sync_mod.process_sync(
                dict(ef_states[0]), ef_reds, gather_fn=gather, options=opts, residuals=store,
            )
            max_err = max(max_err, float(np.max(np.abs(np.asarray(res["acc"], np.float64) - exact))))
        acc_max = max(float(np.max(np.abs(s["acc"]))) for s in ef_states)
        out[f"compress_ef_max_err_{mode}"] = max_err
        out[f"compress_ef_err_bound_{mode}"] = compress_mod.sum_error_bound(mode, acc_max, world)
    out["compress_bytes_saved_total"] = obs.telemetry.counter("sync.bytes_saved.compression").value
    out["compress_compressed_syncs_total"] = obs.telemetry.counter("sync.compressed_syncs").value
    return out


def sync_compress_main(smoke: bool) -> None:
    """``bench.py --sync-compress [--smoke]``: one JSON line with the codec numbers.

    The acceptance point (``make compress-smoke``): int8/bf16 modes ship strictly fewer
    bytes than ``compression="none"`` at the pinned shapes (sketch states ≥ 2x saved),
    exact modes bit-identical to the uncompressed sync, and sum error under
    error-feedback within the documented bound across repeated sync epochs.
    """
    if smoke:
        n, world, epochs = 4096, 4, 4
    else:
        n, world, epochs = 262144, 8, 8
    extras = bench_sync_compress(n, world=world, epochs=epochs)
    extras.update(_contention_report())
    try:
        from torchmetrics_tpu import obs

        extras["telemetry"] = obs.bench_extras()
    except Exception as err:  # pragma: no cover - extras are best-effort
        extras["telemetry_error"] = repr(err)
    none_b = extras["compress_bytes_received_none"]
    int8_b = extras["compress_bytes_received_int8"]
    print(
        json.dumps(
            {
                "metric": "sync_compress_bytes_per_sync",
                "value": int8_b,
                "unit": ("[SMOKE tiny-N lane — not a recordable perf number] " if smoke else "") + (
                    "bytes received per process_sync of the mixed state dict under"
                    " compression='int8' (vs_baseline = compression='none' bytes /"
                    " int8 bytes; per-mode wire bytes, exact-mode bit-identity flags,"
                    " error-feedback drift bounds and sketch-blob ratios in extras —"
                    " docs/distributed.md 'Compressed collectives')"
                ),
                "vs_baseline": round(none_b / int8_b, 2) if int8_b else None,
                "extras": extras,
            }
        )
    )


def bench_sketch(batch: int, n_batches: int) -> dict:
    """``--sketch`` scenario (docs/sketches.md): O(1) streaming sketch states vs the
    unbounded-cat exact mode, at pinned shapes.

    Measures, for the AUROC family and the quantile path: updates+compute throughput
    (sketch folds per batch and finalises O(bins) vs cat's append-then-sort-the-world),
    resident state bytes (fixed vs linear in samples), and the measured approximation
    error against the documented bound. Also asserts the exact mode is UNTOUCHED: the
    cat-state metric's value must be bit-identical to the direct functional computation.
    """
    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu import obs
    from torchmetrics_tpu.classification import BinaryAUROC
    from torchmetrics_tpu.functional.classification.auroc import binary_auroc
    from torchmetrics_tpu.sketch import StreamingQuantile, auroc_error_bound, kll
    from torchmetrics_tpu.sketch.state import sketch_state_bytes

    rng = np.random.RandomState(17)
    bins = 2048
    preds_np = rng.uniform(0.0, 1.0, (n_batches, batch)).astype(np.float32)
    target_np = (rng.uniform(0, 1, (n_batches, batch)) < np.clip(preds_np * 0.8 + 0.1, 0, 1)).astype(np.int32)
    preds = [jnp.asarray(preds_np[i]) for i in range(n_batches)]
    target = [jnp.asarray(target_np[i]) for i in range(n_batches)]
    jax.block_until_ready((preds, target))
    out: dict = {"sketch_bins": bins, "sketch_batch": batch, "sketch_n_batches": n_batches}

    def auroc_window(metric) -> float:
        metric.reset()
        for i in range(n_batches):
            metric.update(preds[i], target[i])
        jax.block_until_ready(metric.compute())
        return 0.0

    sk = BinaryAUROC(approx="sketch", sketch_bins=bins)
    auroc_window(sk)  # compile out of window
    best = _best_of(lambda: auroc_window(sk), windows=3)
    out["sketch_auroc_samples_per_sec"] = round(batch * n_batches / best, 2)
    ex = BinaryAUROC()  # exact cat mode
    auroc_window(ex)
    best_ex = _best_of(lambda: auroc_window(ex), windows=3)
    out["cat_auroc_samples_per_sec"] = round(batch * n_batches / best_ex, 2)
    out["sketch_vs_cat_auroc_speedup"] = round(best_ex / best, 2)

    # state bytes: resident accumulator footprint after the full stream
    out["sketch_auroc_state_bytes"] = sketch_state_bytes(sk)
    out["cat_auroc_state_bytes"] = int(sum(
        e.size * e.dtype.itemsize for entries in ex._state.lists.values() for e in entries
    ))
    # fixed-size proof: the sketch footprint after 1 batch equals the full-stream one
    sk_short = BinaryAUROC(approx="sketch", sketch_bins=bins)
    sk_short.update(preds[0], target[0])
    out["sketch_auroc_state_bytes_short_stream"] = sketch_state_bytes(sk_short)

    # measured error vs the documented discretisation bound
    auc_sketch = float(sk.compute())
    auc_exact = float(ex.compute())
    out["sketch_auc_abs_error"] = round(abs(auc_sketch - auc_exact), 8)
    out["sketch_auc_error_bound"] = auroc_error_bound(bins)
    # exact mode untouched: the stateful cat path == the direct functional computation
    direct = float(binary_auroc(
        jnp.concatenate(preds), jnp.concatenate(target), validate_args=False
    ))
    out["sketch_exact_mode_bit_identical"] = bool(
        np.float32(auc_exact).tobytes() == np.float32(direct).tobytes()
    )

    # quantile sketch: rank error vs the sorted stream + throughput vs cat-and-sort
    vals_np = rng.normal(0.0, 1.0, (n_batches, batch)).astype(np.float32)
    vals = [jnp.asarray(vals_np[i]) for i in range(n_batches)]
    jax.block_until_ready(vals)
    sq = StreamingQuantile(q=(0.1, 0.5, 0.99))

    def q_window():
        sq.reset()
        for i in range(n_batches):
            sq.update(vals[i])
        jax.block_until_ready(sq.compute())

    q_window()
    best_q = _best_of(q_window, windows=3)
    out["sketch_quantile_samples_per_sec"] = round(batch * n_batches / best_q, 2)
    sorted_all = np.sort(vals_np.reshape(-1))
    n = sorted_all.size
    est = np.asarray(sq.compute())
    out["quantile_rank_error"] = round(max(
        abs(np.searchsorted(sorted_all, est[i]) / n - q)
        for i, q in enumerate((0.1, 0.5, 0.99))
    ), 6)
    out["quantile_error_bound"] = kll.DEFAULT_RANK_ERROR
    out["sketch_quantile_state_bytes"] = sketch_state_bytes(sq)

    def cat_q_window():
        buf = [np.asarray(v) for v in vals]
        return np.quantile(np.concatenate(buf), (0.1, 0.5, 0.99))

    best_cq = _best_of(cat_q_window, windows=3)
    out["cat_quantile_samples_per_sec"] = round(batch * n_batches / best_cq, 2)

    out["sketch_telemetry"] = {
        k: obs.telemetry.counter(k).value
        for k in ("sketch.merges", "sketch.compactions", "sketch.state_bytes_saved")
    }
    return out


def sketch_main(smoke: bool) -> None:
    """``bench.py --sketch [--smoke]``: one JSON line with the sketch scenario numbers.

    The ``make sketch-smoke`` gate asserts on this payload: measured quantile/AUC error
    within the documented bounds, fixed sketch state strictly below the cat footprint
    (and invariant across stream lengths), and the exact mode bit-identical to the
    functional path.
    """
    if smoke:
        batch, n_batches = 4096, 6
    else:
        batch, n_batches = 65536, 16
    extras = bench_sketch(batch=batch, n_batches=n_batches)
    extras.update(_contention_report())
    try:
        from torchmetrics_tpu import obs

        extras["telemetry"] = obs.bench_extras()
        extras["cost_ledger"] = [
            {k: r[k] for k in ("key", "metric", "kernel", "tier", "flops",
                               "bytes_accessed", "temp_bytes", "argument_bytes", "available")}
            for r in obs.cost_ledger()
            if r["metric"] in ("StreamingQuantile", "BinaryAUROC")
        ]
    except Exception as err:  # pragma: no cover - extras are best-effort
        extras["telemetry_error"] = repr(err)
    print(
        json.dumps(
            {
                "metric": "sketch_auroc_samples_per_sec",
                "value": extras.get("sketch_auroc_samples_per_sec"),
                "unit": ("[SMOKE tiny-N lane — not a recordable perf number] " if smoke else "") + (
                    "samples/s through BinaryAUROC(approx='sketch') updates+compute"
                    " (O(1)-state streaming histogram pair vs the unbounded-cat exact"
                    " mode; state bytes, error-vs-bound, quantile sketch numbers, and"
                    " exact-mode bit-identity in extras)"
                ),
                "vs_baseline": extras.get("sketch_vs_cat_auroc_speedup"),
                "extras": extras,
            }
        )
    )


def bench_serve(batch: int, n_batches: int, poisson_events: int) -> dict:
    """``--serve`` scenario (docs/serving.md): the async ingestion engine under load.

    The request stream is realistic serving traffic: each batch arrives as a
    zlib-compressed logits payload the handler must decode (pure-C decompress —
    GIL-released host work, the thing the drain thread overlaps). Four lanes:

    1. **synchronous baseline** — decode + ``update`` per batch; its throughput is the
       service rate everything else is calibrated against.
    2. **sustained Poisson lane (the gate)** — arrivals paced at 1.2x the synchronous
       rate, handler does decode + ``update_async`` (block mode): the engine must
       COMMIT above the synchronous throughput with zero sheds and zero backpressure
       stalls, with p50/p99 enqueue latency recorded. Self-calibrating: the offered
       rate scales with whatever this machine's sync rate is.
    3. **bit identity** — the async value equals the synchronous value, and a
       journaled async run preempted MID-OVERLAP (window non-empty) recovers
       ``snapshot + replay`` to the same bits.
    4. **overload shed lane** — unpaced enqueues against a held drain, ``on_full=
       "shed"``: graceful degradation with EXACT shed accounting, never OOM.
    5. **adaptive control lane** (docs/serving.md "Control loop") — a seeded
       calm/overload square wave drives a block-mode engine with the
       :class:`ServeController` attached vs a static ``on_full="shed"`` twin:
       ``adaptive_shed_ratio`` must stay ≤ 1.0, actuator toggles under the
       decision-rate cap, and WAL-minus-journaled-sheds replay bit-identical.
    """
    import random as _random
    import tempfile
    import zlib

    import jax

    from torchmetrics_tpu.classification import MulticlassAccuracy
    from torchmetrics_tpu.robust import journal as _journal
    from torchmetrics_tpu.serve import ServeOptions

    rng = np.random.RandomState(11)
    logits = rng.randn(n_batches, batch, NUM_CLASSES).astype(np.float32)
    target = rng.randint(0, NUM_CLASSES, size=(n_batches, batch)).astype(np.int32)
    payloads = [
        (zlib.compress(logits[i].tobytes(), 1), zlib.compress(target[i].tobytes(), 1))
        for i in range(n_batches)
    ]

    def _decode(pp: bytes, tp: bytes):
        p = np.frombuffer(zlib.decompress(pp), np.float32).reshape(batch, NUM_CLASSES)
        t = np.frombuffer(zlib.decompress(tp), np.int32)
        return p, t

    def make():
        # validate_args=False is the serving hot-path configuration: per-request host
        # validation would cost more than the update dispatch at these batch sizes
        return MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)

    serve_opts = ServeOptions(
        max_inflight=64, on_full="block", queue_timeout_s=120.0, coalesce=16, linger_ms=2.0
    )

    # --- lane 1: completion throughput, sync vs async (paired windows) -------------
    m_sync = make()
    m_sync.update(*_decode(*payloads[0]))  # compile out of window
    m_sync.reset()

    def sync_window():
        m_sync.reset()
        for b in payloads:
            m_sync.update(*_decode(*b))
        jax.block_until_ready(list(m_sync._state.tensors.values()))

    def _warm_async(metric, engine):
        """Compile the plain update AND every quantized coalesce width out of window."""
        metric.update_async(*_decode(*payloads[0]))
        engine.quiesce()
        w = 2
        while w <= engine.options.coalesce:
            engine.pause()
            for i in range(w):
                metric.update_async(*_decode(*payloads[i % n_batches]))
            engine.resume()
            engine.quiesce()
            w *= 2
        metric.reset()

    m_async = make()
    eng = m_async.serve(serve_opts)
    _warm_async(m_async, eng)

    def async_window():
        m_async.reset()
        for b in payloads:
            m_async.update_async(*_decode(*b))
        eng.quiesce()
        jax.block_until_ready(list(m_async._state.tensors.values()))

    # interleave the two lanes so machine drift (CPU contention, frequency steps)
    # lands on both equally — unpaired best-ofs measured on a noisy host can swing
    # +-20% between lanes and drown the structural difference
    best_sync = best_async = float("inf")
    for _ in range(6):
        t0 = time.perf_counter()
        sync_window()
        best_sync = min(best_sync, time.perf_counter() - t0)
        t0 = time.perf_counter()
        async_window()
        best_async = min(best_async, time.perf_counter() - t0)
    sync_rate = n_batches / best_sync
    async_rate = n_batches / best_async

    # --- lane 2: sustained Poisson arrivals at 1.2x the service rate (the gate) ----
    arrival = _random.Random(23)
    lam = 1.2 * sync_rate
    m_p = make()
    eng_p = m_p.serve(serve_opts)
    _warm_async(m_p, eng_p)

    # enqueue-call latency rides a KLL-backed registry series instead of an ad-hoc list:
    # O(1) memory however many events stream through, and the same quantile machinery
    # the live serving dashboards read (obs.timeseries; docs/observability.md)
    from torchmetrics_tpu import obs

    enq_series = obs.telemetry.series("bench.serve.enqueue_latency_us")

    def poisson_pass(events: int) -> float:
        t0 = time.perf_counter()
        next_t = t0
        committed0 = eng_p.stats()["committed"]
        for i in range(events):
            next_t += arrival.expovariate(lam)
            args = _decode(*payloads[i % n_batches])  # handler decodes, then enqueues
            # hybrid pacing: coarse sleep, then spin the final ms — time.sleep()'s
            # ~100us overshoot at sub-ms inter-arrivals would silently lower the
            # offered rate below its 1.2x target
            remaining = next_t - time.perf_counter()
            if remaining > 0.002:
                time.sleep(remaining - 0.001)
            while time.perf_counter() < next_t:
                pass
            s = time.perf_counter()
            m_p.update_async(*args)
            enq_series.record((time.perf_counter() - s) * 1e6)
        eng_p.quiesce()
        jax.block_until_ready(list(m_p._state.tensors.values()))
        wall = time.perf_counter() - t0
        return (eng_p.stats()["committed"] - committed0) / wall

    poisson_pass(min(16, poisson_events))  # shake out residual first-pass jitter
    sustained = 0.0
    for _ in range(3):  # the lane is milliseconds; best-of covers GC/contention spikes
        m_p.reset()
        sustained = max(sustained, poisson_pass(poisson_events))
    stats_p = eng_p.stats()
    lat_p50, lat_p99 = enq_series.quantiles((0.5, 0.99))

    print(
        f"serve: sync {sync_rate:.1f}/s, async completion {async_rate:.1f}/s,"
        f" sustained@1.2x {sustained:.1f}/s (sheds={stats_p['shed']},"
        f" stalls={stats_p['backpressure_stalls']})",
        file=sys.stderr,
    )

    # --- lane 3: bit identity (async vs sync, and preempt-mid-overlap replay) ------
    v_sync = np.asarray(m_sync.compute())
    bit_identical = bool(np.array_equal(v_sync, np.asarray(m_async.compute())))

    jdir = tempfile.mkdtemp(prefix="tm-serve-bench-wal-")
    m_j = make()
    eng_j = m_j.serve(ServeOptions(max_inflight=64), journal=_journal.Journal(jdir))
    half = n_batches // 2
    for b in payloads[:half]:
        m_j.update_async(*_decode(*b))
    eng_j.quiesce()
    eng_j.pause()  # hold the drain: the tail stays in the window, journaled only
    for b in payloads[half:]:
        m_j.update_async(*_decode(*b))
    eng_j.abandon()  # preemption mid-overlap
    m_rec = make()
    _journal.recover(m_rec, jdir)
    replay_identical = bool(np.array_equal(v_sync, np.asarray(m_rec.compute())))

    # --- lane 4: overload shed (held drain, exact drop accounting) -----------------
    m_o = make()
    eng_o = m_o.serve(ServeOptions(max_inflight=8, on_full="shed", queue_timeout_s=5.0))
    m_o.update_async(*_decode(*payloads[0]))
    eng_o.quiesce()
    m_o.reset()
    eng_o.pause()
    overload_tickets = [m_o.update_async(*_decode(*payloads[i % n_batches])) for i in range(24)]
    eng_o.resume()
    eng_o.quiesce()
    overload_sheds = sum(1 for t in overload_tickets if t.shed)

    # --- lane 5: adaptive controller vs static shed under square-wave overload -----
    # the control-loop gate (docs/serving.md "Control loop"): the same seeded
    # calm/overload square wave drives an adaptive engine (block base + controller +
    # WAL) and a static on_full='shed' twin. Adaptive must shed no more than static
    # (adaptive_shed_ratio <= 1.0), keep actuator toggles under the decision-rate cap
    # (zero thrash), and replay bit-identically from WAL minus the journaled sheds.
    from torchmetrics_tpu.serve import ControlOptions, ServeController, adaptive_recover

    osc_events = max(24, min(64, poisson_events // 2))
    period = 5
    osc_opts = dict(max_inflight=4, queue_timeout_s=0.05, coalesce=4)

    def square_wave(metric, engine):
        for i in range(osc_events):
            if (i // period) % 2 == 1:
                engine.pause()
            else:
                engine.resume()
            metric.update_async(*_decode(*payloads[i % n_batches]))
        engine.resume()
        engine.quiesce()

    ctrl = ServeController(ControlOptions(
        decision_every=2, window_short=4, window_long=8, min_hold_ticks=4,
        timed_block_timeout_s=0.01,
    ))
    adir = tempfile.mkdtemp(prefix="tm-serve-bench-ctrl-wal-")
    m_a = make()
    eng_a = m_a.serve(
        ServeOptions(on_full="block", **osc_opts), journal=_journal.Journal(adir)
    )
    ctrl.attach(eng_a)
    square_wave(m_a, eng_a)
    m_s = make()
    eng_s = m_s.serve(ServeOptions(on_full="shed", **osc_opts))
    square_wave(m_s, eng_s)
    adaptive_shed = eng_a.stats()["shed"]
    static_shed = eng_s.stats()["shed"]
    cstats = ctrl.stats()
    m_rec_a = make()
    adaptive_recover(m_rec_a, adir)
    adaptive_replay_identical = bool(
        np.array_equal(np.asarray(m_a.compute()), np.asarray(m_rec_a.compute()))
    )

    return {
        "serve_sync_updates_per_sec": round(sync_rate, 2),
        "serve_async_updates_per_sec": round(async_rate, 2),
        "serve_async_vs_sync_completion": round(async_rate / sync_rate, 3),
        "serve_sustained_updates_per_sec": round(sustained, 2),
        "serve_sustained_vs_sync": round(sustained / sync_rate, 3),
        "serve_poisson_target_rate": round(lam, 2),
        "serve_poisson_events": poisson_events,
        "serve_block_mode_sheds": stats_p["shed"],
        "serve_block_mode_stalls": stats_p["backpressure_stalls"],
        "serve_enqueue_p50_us": round(lat_p50, 1),
        "serve_enqueue_p99_us": round(lat_p99, 1),
        "serve_enqueue_latency_samples": enq_series.count,
        "serve_bit_identical_async_vs_sync": bit_identical,
        "serve_bit_identical_preempt_replay": replay_identical,
        "serve_overload_sheds_exact": overload_sheds == 24 - 8,
        "serve_overload_sheds": overload_sheds,
        "controller_decisions": cstats["decisions"],
        "controller_escalations": cstats["escalations"],
        "controller_transitions": sum(
            ctrl.channel_report(eng_a)["transitions"].values()
        ),
        "adaptive_shed_ratio": round(adaptive_shed / max(1, static_shed), 3),
        "serve_adaptive_sheds": adaptive_shed,
        "serve_static_sheds": static_shed,
        "serve_adaptive_thrash_free": ctrl.toggle_rate_ok(eng_a),
        "serve_adaptive_replay_identical": adaptive_replay_identical,
        "serve_batch": batch,
        "serve_n_batches": n_batches,
    }


def serve_main(smoke: bool) -> None:
    """``bench.py --serve [--smoke]``: one JSON line with the serving scenario numbers."""
    if smoke:
        batch, n_batches, poisson_events = 512, 64, 96
    else:
        batch, n_batches, poisson_events = 2048, 256, 600
    extras = bench_serve(batch, n_batches, poisson_events)
    extras.update(_contention_report())
    try:
        from torchmetrics_tpu import obs

        extras["telemetry"] = obs.bench_extras()
    except Exception as err:  # pragma: no cover - extras are best-effort
        extras["telemetry_error"] = repr(err)
    print(
        json.dumps(
            {
                "metric": "serve_sustained_updates_per_sec",
                "value": extras["serve_sustained_updates_per_sec"],
                "unit": ("[SMOKE tiny-N lane — not a recordable perf number] " if smoke else "") + (
                    "committed updates/s under Poisson arrivals at 1.2x the synchronous"
                    " service rate (MulticlassAccuracy via update_async, bounded"
                    " in-flight window; sync-vs-async completion rates, p50/p99 enqueue"
                    " latency, exact shed counts, and bit-identity flags in extras)"
                ),
                "vs_baseline": extras.get("serve_async_vs_sync"),
                "extras": extras,
            }
        )
    )


def bench_obs(batch: int, n_batches: int) -> dict:
    """``--obs`` scenario (docs/observability.md "Serving traces, live series & SLOs").

    The end-to-end observability proof in four lanes:

    1. **traced serve burst** — telemetry on, a coalescing async burst, trace exported
       to disk and the Perfetto FLOW contract validated against the file: every
       ``ph:"s"`` pairs with one ``ph:"f"`` under a unique per-ticket id, committed
       flows resolve onto the drain-thread track.
    2. **OpenMetrics round-trip** — the whole registry rendered as exposition text,
       driven through the strict line parser, and fetched once over the opt-in
       localhost scrape endpoint (byte-identical modulo live counters).
    3. **SLO shed storm** — a healthy run must NOT fire; an injected shed storm
       against a held 2-deep window MUST fire the shed-ratio burn alarm.
    4. **disabled-path overhead** — with telemetry off, the per-enqueue observability
       hook chain (trace mint + stage emit + two always-on series records) is timed
       directly; the acceptance bound is <= 2us/enqueue added vs the PR-11 baseline.
    """
    import tempfile
    import urllib.request
    import warnings

    from torchmetrics_tpu import obs
    from torchmetrics_tpu.classification import MulticlassAccuracy
    from torchmetrics_tpu.obs import openmetrics as _openmetrics
    from torchmetrics_tpu.obs import trace as _trace
    from torchmetrics_tpu.serve import ServeOptions

    rng = np.random.RandomState(13)
    preds = [rng.randint(0, NUM_CLASSES, size=(batch,)).astype(np.int32) for _ in range(n_batches)]
    target = [rng.randint(0, NUM_CLASSES, size=(batch,)).astype(np.int32) for _ in range(n_batches)]

    def make():
        return MulticlassAccuracy(num_classes=NUM_CLASSES, validate_args=False)

    out: dict = {}

    # --- lane 1: traced serve burst -> exported trace -> flow validation -----------
    _trace.clear()
    with obs.enabled():
        m = make()
        eng = m.serve(ServeOptions(max_inflight=32, coalesce=8))
        for p, t in zip(preds, target):
            m.update_async(p, t)
        eng.quiesce()
        trace_path = tempfile.mktemp(prefix="tm-obs-smoke-", suffix=".json")
        obs.export_trace(trace_path)
    exported = json.load(open(trace_path))["traceEvents"]
    verdict = _trace.validate_flows(exported)
    out["obs_trace_flows_valid"] = verdict["valid"]
    out["obs_trace_flows"] = verdict["flows"]
    out["obs_trace_committed_cross_thread"] = verdict["committed_cross_thread"]
    out["obs_trace_spans"] = _trace.span_count()
    out["obs_trace_path"] = trace_path

    # --- lane 2: OpenMetrics exposition -> strict parse -> scrape endpoint ---------
    text = _openmetrics.render()
    parsed = _openmetrics.parse(text)
    out["obs_openmetrics_valid"] = parsed["samples"] > 0
    out["obs_openmetrics_bytes"] = len(text.encode("utf-8"))
    out["obs_openmetrics_families"] = len(parsed["families"])
    with _openmetrics.serve_scrape() as srv:
        with urllib.request.urlopen(srv.url, timeout=10.0) as resp:
            scraped = resp.read().decode("utf-8")
    out["obs_scrape_valid"] = _openmetrics.parse(scraped)["samples"] > 0

    # --- lane 3: SLO burn-rate — quiet on health, loud on a shed storm -------------
    specs = obs.default_serve_specs(windows=((5.0, 1.0), (60.0, 1.0)))
    monitor = obs.SloMonitor([s for s in specs if s.name == "shed-ratio"])
    healthy = monitor.evaluate()
    out["obs_slo_quiet_when_healthy"] = not any(s.burning for s in healthy)
    m_storm = make()
    eng_storm = m_storm.serve(
        ServeOptions(max_inflight=2, on_full="shed", queue_timeout_s=5.0)
    )
    eng_storm.pause()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        storm_tickets = [
            m_storm.update_async(preds[i % n_batches], target[i % n_batches])
            for i in range(32)
        ]
        eng_storm.resume()
        eng_storm.quiesce()
        stormy = monitor.evaluate()
    out["obs_slo_alarm_fired"] = any(s.burning for s in stormy)
    out["obs_slo_storm_sheds"] = sum(1 for t in storm_tickets if t.shed)
    out["obs_slo_burn_rate"] = round(max(s.worst_burn for s in stormy), 2)
    out["obs_slo_alarms_counter"] = obs.telemetry.counter("slo.alarms").value
    out["obs_slo_signals"] = monitor.signals()

    # --- lane 4: tracing-disabled per-enqueue overhead bound -----------------------
    # time the exact hook chain _admit adds per enqueue (trace mint + stage emit +
    # queue-depth/enqueue-event series records) with telemetry off — the <=2us/enqueue
    # acceptance bound, measured without the dispatch noise of a full enqueue
    obs.disable()
    qd = obs.telemetry.series("serve.queue_depth")
    # warm the per-geometry compiled KLL fold out of window (the engine pays it once
    # per process, like every other first-dispatch compile; steady state is the bound)
    for _ in range(qd._fold_every + 1):
        qd.record(3.0)
    reps = 20_000
    tel = obs.telemetry
    per_call_us = float("inf")
    for _ in range(3):  # best-of: GC/contention spikes must not fail the bound
        t0 = time.perf_counter()
        for i in range(reps):  # the exact guarded hook chain engine._admit runs
            tid = _trace.mint() if tel.enabled else None
            qd.record(3.0)
            if tid is not None:
                _trace.enqueue_span(tid, 0.0, i, 3, None)
        per_call_us = min(per_call_us, (time.perf_counter() - t0) / reps * 1e6)
    out["obs_disabled_hook_overhead_us"] = round(per_call_us, 3)
    out["obs_disabled_overhead_bound_us"] = 2.0
    out["obs_disabled_overhead_ok"] = per_call_us <= 2.0
    return out


def obs_main(smoke: bool) -> None:
    """``bench.py --obs [--smoke]``: one JSON line with the observability proof."""
    batch, n_batches = (256, 48) if smoke else (2048, 256)
    extras = bench_obs(batch, n_batches)
    try:
        from torchmetrics_tpu import obs

        extras["telemetry"] = obs.bench_extras()
    except Exception as err:  # pragma: no cover - extras are best-effort
        extras["telemetry_error"] = repr(err)
    print(
        json.dumps(
            {
                "metric": "obs_disabled_hook_overhead_us",
                "value": extras["obs_disabled_hook_overhead_us"],
                "unit": ("[SMOKE tiny-N lane — not a recordable perf number] " if smoke else "") + (
                    "per-enqueue cost of the serving observability hooks with tracing"
                    " DISABLED (bound: 2us); trace flow validation, OpenMetrics"
                    " round-trip/scrape, and SLO shed-storm alarm evidence in extras"
                ),
                "vs_baseline": None,
                "extras": extras,
            }
        )
    )


def bench_flight(batch: int, n_batches: int) -> dict:
    """``--flight`` scenario (docs/observability.md "Flight recorder & post-mortem bundles").

    Four lanes:

    1. **record-path overhead** — the always-on flight ring is NOT gated on telemetry,
       so its per-event cost is paid on every failure-seam event in production; the
       acceptance bound is ≤ 2µs/event (best-of-3 — GC/contention spikes must not
       fail the bound).
    2. **bundle capture latency** — wall time of one full ``capture_bundle`` (build +
       per-section CRC + atomic write + fsync), plus strict validation of the result
       through ``python -m torchmetrics_tpu.obs.bundle validate``'s code path.
    3. **memory-ledger accuracy** — ``obs.memory_ledger()`` resident-bytes rows vs the
       ``np.asarray(state).nbytes`` ground truth for a keyed ``[N,...]`` tenant table,
       an online window ring, and a KLL sketch state; acceptance: within 1%.
    4. **budget alarm discipline** — a :class:`MemoryBudget` under an injected
       over-budget keyed table fires its one-shot warning EXACTLY once across repeated
       evaluations, and stays silent under budget.
    """
    import tempfile
    import warnings

    import jax.numpy as jnp

    from torchmetrics_tpu import obs
    from torchmetrics_tpu.aggregation import MeanMetric, SumMetric
    from torchmetrics_tpu.keyed import KeyedMetric
    from torchmetrics_tpu.online import Windowed
    from torchmetrics_tpu.sketch import StreamingQuantile

    del batch, n_batches  # the flight lanes are event/byte-shaped, not batch-shaped
    out: dict = {}

    # --- lane 1: always-on record-path overhead ------------------------------------
    reps = 20_000
    per_event_us = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for i in range(reps):
            obs.flightrec.record("bench.tick", step=i)
        per_event_us = min(per_event_us, (time.perf_counter() - t0) / reps * 1e6)
    out["flight_record_us_per_event"] = round(per_event_us, 3)
    out["flight_record_bound_us"] = 2.0
    out["flight_record_ok"] = per_event_us <= 2.0

    # --- lane 2: bundle capture latency + strict validation ------------------------
    m_ctx = SumMetric()
    m_ctx.update(np.asarray([1.0, 2.0], np.float32))
    bdir = tempfile.mkdtemp(prefix="tm-flight-bench-")
    capture_ms = float("inf")
    path = None
    for _ in range(3):
        t0 = time.perf_counter()
        path = obs.capture_bundle("bench-flight", metric=m_ctx, directory=bdir)
        capture_ms = min(capture_ms, (time.perf_counter() - t0) * 1e3)
    out["bundle_capture_ms"] = round(capture_ms, 2)
    try:
        verdict = obs.validate_bundle(path)
        out["bundle_validates"] = bool(verdict["valid"])
        out["bundle_flight_events"] = verdict["flight_events"]
    except Exception as err:
        out["bundle_validates"] = False
        out["bundle_validate_error"] = repr(err)

    # --- lane 3: memory-ledger accuracy vs nbytes ground truth ---------------------
    n_keys = 1000
    keyed = KeyedMetric(SumMetric(nan_strategy="ignore"), n_keys)
    keyed.update(jnp.asarray(np.arange(64) % n_keys, jnp.int32),
                 jnp.asarray(np.ones(64, np.float32)))
    windowed = Windowed(MeanMetric(nan_strategy="ignore"), window=8, advance_every=8, emit=False)
    windowed.update(jnp.asarray(np.ones(32, np.float32)))
    sketch = StreamingQuantile(q=0.5)
    sketch.update(jnp.asarray(np.linspace(0.0, 1.0, 256, dtype=np.float32)))
    max_rel_err = 0.0
    kinds_seen = set()
    for metric, label in ((keyed, "keyed"), (windowed, "windowed"), (sketch, "sketch")):
        ledger = obs.memory_ledger(metrics=[metric], cross_check=False)
        truth = sum(np.asarray(v).nbytes for v in metric._state.tensors.values()) + sum(
            np.asarray(e).nbytes for vs in metric._state.lists.values() for e in vs
        )
        got = ledger["totals"]["resident_bytes"]
        rel = abs(got - truth) / truth if truth else 0.0
        max_rel_err = max(max_rel_err, rel)
        kinds_seen.update(r["kind"] for r in ledger["rows"])
        out[f"memory_ledger_bytes_{label}"] = got
        out[f"memory_truth_bytes_{label}"] = int(truth)
    out["memory_ledger_max_rel_err"] = round(max_rel_err, 6)
    out["memory_ledger_err_bound"] = 0.01
    out["memory_ledger_ok"] = max_rel_err <= 0.01
    out["memory_ledger_kinds"] = sorted(kinds_seen)
    out["memory_resident_bytes_total"] = obs.memory_ledger(cross_check=False)["totals"][
        "resident_bytes"
    ]

    # --- lane 4: MemoryBudget one-shot alarm discipline ----------------------------
    keyed_bytes = int(out["memory_ledger_bytes_keyed"])
    quiet = obs.MemoryBudget(
        bytes=keyed_bytes * 10, name="bench-quiet", metrics=[keyed], windows=((60.0, 1.0),)
    )
    loud = obs.MemoryBudget(
        bytes=max(1, keyed_bytes // 2), name="bench-loud", metrics=[keyed],
        windows=((60.0, 1.0),),
    )
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        quiet_burning = any(s.burning for _ in range(3) for s in quiet.evaluate())
        loud_burning = all(s.burning for _ in range(3) for s in loud.evaluate())
    alarm_warns = [w for w in caught if "bench-loud" in str(w.message)]
    quiet_warns = [w for w in caught if "bench-quiet" in str(w.message)]
    out["memory_budget_quiet_under_budget"] = not quiet_burning and not quiet_warns
    out["memory_budget_fires_over_budget"] = bool(loud_burning)
    out["memory_budget_warned_exactly_once"] = len(alarm_warns) == 1
    out["flight_events_total"] = obs.telemetry.counter("flight.events").value
    out["bundles_captured_total"] = obs.telemetry.counter("flight.bundles_captured").value
    return out


def flight_main(smoke: bool) -> None:
    """``bench.py --flight [--smoke]``: one JSON line with the flight-recorder proof."""
    extras = bench_flight(*((256, 16) if smoke else (2048, 64)))
    try:
        from torchmetrics_tpu import obs

        extras["telemetry"] = obs.bench_extras()
    except Exception as err:  # pragma: no cover - extras are best-effort
        extras["telemetry_error"] = repr(err)
    print(
        json.dumps(
            {
                "metric": "flight_record_us_per_event",
                "value": extras["flight_record_us_per_event"],
                "unit": ("[SMOKE tiny-N lane — not a recordable perf number] " if smoke else "") + (
                    "per-event cost of the ALWAYS-ON flight ring record path (bound:"
                    " 2us); bundle capture latency + strict validation, memory-ledger"
                    " accuracy vs nbytes ground truth, and MemoryBudget one-shot alarm"
                    " evidence in extras"
                ),
                "vs_baseline": None,
                "extras": extras,
            }
        )
    )


def bench_explain(batch: int, n_batches: int) -> dict:
    """``--explain`` scenario (docs/observability.md "Compile plane").

    Four lanes:

    1. **burst across tiers** — fresh metrics driven through the jit update/compute
       tiers, the AOT fused forward, and the whole-stack scan, with ONE forced int32
       dtype flip per class; acceptance: the compile ledger holds rows under BOTH
       tiers and the retrace attributor named the exact culprit leaf (``args[1]``,
       dtype) for every probe class.
    2. **decision-path overhead** — ``note_decision`` is on the disabled/fallback
       dispatch path, so its per-call cost is paid on every eager-tier dispatch;
       acceptance bound: ≤ 2µs/dispatch (best-of-3).
    3. **seam matrix validity** — the live matrix carries the full eight-seam axis on
       every row, the OpenMetrics export strict-``parse()``\\ s with the
       ``tm_seam_matrix`` info family present, and the post-mortem bundle section
       round-trips through strict ``validate_bundle``.
    4. **explain surface** — ``Metric.explain_dispatch()`` returns flags + tiers +
       decisions + per-instance compile rows for a driven metric.
    """
    import tempfile

    import jax.numpy as jnp

    from torchmetrics_tpu import obs
    from torchmetrics_tpu.aggregation import MeanMetric, SumMetric
    from torchmetrics_tpu.obs import xplane

    n = max(16, batch)
    del n_batches
    out: dict = {}

    # --- lane 1: compile-plane burst across tiers ----------------------------------
    x = jnp.asarray(np.linspace(0.5, 2.0, n, dtype=np.float32))
    x_i32 = jnp.asarray((np.arange(n) % 7).astype(np.int32))
    stack = jnp.asarray(np.linspace(0.1, 1.0, 4 * n, dtype=np.float32).reshape(4, n))
    xplane.reset()
    driven = []
    for cls in (SumMetric, MeanMetric):
        m = cls(nan_strategy="ignore")
        m.update(x)
        m.update(x)        # cache hit: must not append a ledger row
        m.update(x_i32)    # the forced dtype-flip retrace
        m(x)
        m(x)
        m.update_batches(stack)
        m.compute()
        driven.append(m)
    recs = xplane.compile_records()
    tiers_seen = {r["tier"] for r in recs}
    attributed = [r for r in recs if r["attribution"]]
    out["compile_ledger_rows"] = len(recs)
    out["compile_tiers_seen"] = sorted(tiers_seen)
    out["compile_both_tiers"] = tiers_seen >= {"jit", "aot"}
    out["retraces_attributed"] = len(attributed)
    out["retrace_culprits_exact"] = bool(attributed) and all(
        r["attribution"]["path"] == "args[1]" and r["attribution"]["change"] == "dtype"
        for r in attributed
    )
    out["retrace_flight_events"] = sum(
        1 for e in obs.flightrec.events() if e["kind"] == "compile.retrace"
    )
    out["aot_fingerprints"] = sum(1 for r in recs if r["fingerprint"])

    # --- lane 2: decision-path overhead (the disabled-dispatch tax) ----------------
    reps = 20_000
    probe = SumMetric(nan_strategy="ignore")
    per_call_us = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _i in range(reps):
            xplane.note_decision(probe, "update", "jit", "fast_update_class_off")
        per_call_us = min(per_call_us, (time.perf_counter() - t0) / reps * 1e6)
    out["explain_decision_us_per_dispatch"] = round(per_call_us, 3)
    out["explain_decision_bound_us"] = 2.0
    out["explain_decision_ok"] = per_call_us <= 2.0

    # --- lane 3: seam matrix validity (live, OpenMetrics, bundle) ------------------
    matrix = xplane.seam_matrix(driven)
    out["seam_matrix_rows"] = matrix["count"]
    out["seam_matrix_full_axis"] = all(
        sorted(r["seams"]) == sorted(xplane.SEAMS) for r in matrix["metrics"]
    )
    try:
        families = obs.openmetrics.parse(obs.openmetrics.render())["families"]
        out["seam_matrix_openmetrics_ok"] = "tm_seam_matrix" in families
    except Exception as err:
        out["seam_matrix_openmetrics_ok"] = False
        out["seam_matrix_openmetrics_error"] = repr(err)
    bdir = tempfile.mkdtemp(prefix="tm-explain-bench-")
    try:
        path = obs.capture_bundle("bench-explain", directory=bdir)
        verdict = obs.validate_bundle(path)
        xp = obs.bundle.load_bundle(path)["sections"]["xplane"]
        out["seam_matrix_bundle_ok"] = bool(verdict["valid"]) and xp["seam_matrix"]["count"] >= 0
    except Exception as err:
        out["seam_matrix_bundle_ok"] = False
        out["seam_matrix_bundle_error"] = repr(err)

    # --- lane 4: the explain surface -----------------------------------------------
    info = driven[0].explain_dispatch()
    out["explain_has_flags"] = set(info["flags"]) >= {"fast_update", "fast_dispatch_env"}
    out["explain_has_tiers"] = bool(info["tiers"])
    out["explain_has_decisions"] = bool(info["decisions"])
    out["explain_has_compiles"] = bool(info["compiles"])
    return out


def explain_main(smoke: bool) -> None:
    """``bench.py --explain [--smoke]``: one JSON line with the compile-plane proof."""
    extras = bench_explain(*((64, 8) if smoke else (2048, 64)))
    try:
        from torchmetrics_tpu import obs

        extras["telemetry"] = obs.bench_extras()
    except Exception as err:  # pragma: no cover - extras are best-effort
        extras["telemetry_error"] = repr(err)
    print(
        json.dumps(
            {
                "metric": "explain_decision_us_per_dispatch",
                "value": extras["explain_decision_us_per_dispatch"],
                "unit": ("[SMOKE tiny-N lane — not a recordable perf number] " if smoke else "") + (
                    "per-dispatch cost of the tier-decision note on the fallback path"
                    " (bound: 2us); compile-ledger burst coverage, retrace-attribution"
                    " exactness, seam-matrix OpenMetrics/bundle validity, and the"
                    " explain_dispatch surface in extras"
                ),
                "vs_baseline": None,
                "extras": extras,
            }
        )
    )


def bench_fleet(n_peers: int, points_per_peer: int) -> dict:
    """``--fleet`` scenario (docs/observability.md "Fleet federation & incident correlation").

    Four lanes over a real in-process fleet (N scrape servers on localhost, one
    fleet-tier :class:`~torchmetrics_tpu.obs.federation.Federator` polling them over
    actual HTTP):

    1. **federation poll latency** — wall time of one full poll (N ``/metrics`` GETs,
       strict parses, N ``/federation`` sidecar GETs, aggregate + SLO evaluation),
       best-of-3 after a warmup poll so the sketch-merge jit compile is excluded.
    2. **merged-scrape cost** — byte size of the tier-labelled merged exposition, and
       proof it strict-``parse()``\\ s; counter-sum and pooled-quantile (true
       ``kll_merge``) correctness are asserted, not just measured.
    3. **incident correlation** — two bundle captures join one incident whose id is
       visible in the federated scrape, and ``merge_fleet_bundles`` assembles them
       into a bundle that strict ``validate_bundle`` accepts.
    4. **degradation** — one peer killed mid-fleet: the next poll must not raise, must
       count exactly one unhealthy peer, and the merged scrape must stay parseable.
    """
    from torchmetrics_tpu.obs import federation, openmetrics
    from torchmetrics_tpu.obs.telemetry import Telemetry

    out: dict = {}
    regs = []
    for i in range(n_peers):
        t = Telemetry(enabled=False)
        t.counter("serve.enqueued").inc((i + 1) * 10)
        s = t.series("fleet.bench_lat")
        for v in range(i * points_per_peer, (i + 1) * points_per_peer):
            s.record(float(v))
        regs.append(t)
    servers = [openmetrics.serve_scrape(registry=r) for r in regs]
    try:
        peers = [
            federation.Peer(name=f"p{i}", url=f"http://127.0.0.1:{srv.bound_port()}")
            for i, srv in enumerate(servers)
        ]
        fed = federation.Federator(peers, tier="fleet", timeout_s=10.0)

        # --- lane 1: poll latency (warmup excludes the kll_merge jit compile) -------
        fed.poll()
        poll_ms = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            summary = fed.poll()
            poll_ms = min(poll_ms, (time.perf_counter() - t0) * 1e3)
        out["fleet_poll_ms"] = round(poll_ms, 2)
        out["fleet_peers"] = n_peers
        out["fleet_unhealthy"] = summary["unhealthy"]

        # --- lane 2: merged-scrape bytes + semantic proof ---------------------------
        text = fed.render()
        out["merged_scrape_bytes"] = len(text.encode("utf-8"))
        parsed = openmetrics.parse(text)
        out["merged_scrape_parses"] = parsed["samples"] > 0
        agg = [
            s
            for s in parsed["families"]["tm_serve_enqueued"]["samples"]
            if s["labels"].get("tier") == "fleet"
        ]
        want = sum((i + 1) * 10 for i in range(n_peers))
        out["fleet_counter_sum"] = agg[0]["value"] if agg else None
        out["fleet_counter_sum_ok"] = bool(agg) and agg[0]["value"] == want
        n_total = n_peers * points_per_peer
        p99 = next(
            (
                s["value"]
                for s in parsed["families"]["tm_fleet_bench_lat"]["samples"]
                if s["labels"].get("quantile") == "0.99"
                and s["labels"].get("tier") == "fleet"
            ),
            None,
        )
        out["fleet_p99"] = p99
        # pooled quantile within the documented KLL rank-error bound (2% of N ranks)
        out["fleet_p99_ok"] = p99 is not None and abs(p99 - 0.99 * (n_total - 1)) <= (
            0.02 * n_total + 1
        )

        # --- lane 3: incident-id propagation into a validated fleet bundle ----------
        import tempfile

        from torchmetrics_tpu import obs
        from torchmetrics_tpu.obs import flightrec

        flightrec.clear_incidents()
        bdir = tempfile.mkdtemp(prefix="tm-fleet-bench-")
        obs.capture_bundle("fleet-bench-timeout", directory=bdir)
        obs.capture_bundle("fleet-bench-drain", directory=bdir)  # joins the incident
        inc_id = flightrec.current_incident()
        out["incident_minted"] = inc_id is not None
        fed.poll()
        scrape = fed.render()
        out["incident_in_federated_scrape"] = bool(inc_id) and inc_id in scrape
        try:
            merged = obs.merge_fleet_bundles([bdir])
            verdict = obs.validate_bundle(merged)
            out["fleet_bundle_validates"] = bool(verdict["valid"])
            out["fleet_bundle_incident_matches"] = verdict.get("incident_id") == inc_id
        except Exception as err:
            out["fleet_bundle_validates"] = False
            out["fleet_bundle_error"] = repr(err)
        flightrec.clear_incidents()

        # --- lane 4: peer death degrades, never raises ------------------------------
        servers[-1].close()
        fed.timeout_s = 1.0
        try:
            after = fed.poll()
            openmetrics.parse(fed.render())
            out["degrade_unhealthy"] = after["unhealthy"]
            out["degrade_ok"] = after["unhealthy"] == 1
        except Exception as err:  # a dead peer must never fail the scrape
            out["degrade_ok"] = False
            out["degrade_error"] = repr(err)
    finally:
        for srv in servers:
            srv.close()
    return out


def fleet_main(smoke: bool) -> None:
    """``bench.py --fleet [--smoke]``: one JSON line with the federation proof."""
    extras = bench_fleet(*((3, 100) if smoke else (8, 2000)))
    try:
        from torchmetrics_tpu import obs

        extras["telemetry"] = obs.bench_extras()
    except Exception as err:  # pragma: no cover - extras are best-effort
        extras["telemetry_error"] = repr(err)
    print(
        json.dumps(
            {
                "metric": "fleet_poll_ms",
                "value": extras["fleet_poll_ms"],
                "unit": ("[SMOKE tiny-N lane — not a recordable perf number] " if smoke else "") + (
                    "wall ms for one full federation poll over live localhost peers"
                    " (strict parse + sidecar + aggregate + fleet SLOs); merged-scrape"
                    " bytes, counter-sum/pooled-p99 proofs, and peer-death degradation"
                    " evidence in extras"
                ),
                "vs_baseline": None,
                "extras": extras,
            }
        )
    )


def bench_online(batch: int, n_batches: int) -> dict:
    """``--online`` scenario (docs/online.md): windowed monitoring on the hot path.

    Four lanes:

    1. **overhead** — per-update wall time of a windowed metric vs its plain template
       (same stream, same tier). The ring adds one dynamic slot read/write and the
       advance select to the fused program; the acceptance bound at smoke shapes is
       windowed <= 1.5x plain.
    2. **advance + detector cost** — amortized manual-advance launch time and the
       host-side drift-detector evaluation latency (sketch-to-sketch, no raw data).
    3. **bit-identity** — sliding ``compute()`` vs a fresh template fed exactly the
       window's batches, across the AOT+donation / jit / buffered / scan tiers
       (integer-valued f32 so reduction order cannot hide behind epsilons).
    4. **drift alarm** — a KS detector over a windowed KLL sketch must stay quiet on
       a stationary stream and fire its one-shot warn EXACTLY once on an injected
       distribution shift.
    """
    import warnings

    from torchmetrics_tpu import obs
    from torchmetrics_tpu.aggregation import MeanMetric, SumMetric
    from torchmetrics_tpu.online import DriftMonitor, DriftSpec, KsDrift, Windowed
    from torchmetrics_tpu.sketch import StreamingQuantile

    rng = np.random.RandomState(29)
    out: dict = {}
    window, every = 8, 8
    stream = [rng.randint(-6, 7, size=batch).astype(np.float32) for _ in range(n_batches)]

    # --- lane 1: windowed-vs-plain per-update overhead -----------------------------
    def _time_updates(metric, reps: int) -> float:
        for b in stream[: min(8, len(stream))]:  # warm the compiled programs
            metric.update(b)
        t0 = time.perf_counter()
        for i in range(reps):
            metric.update(stream[i % len(stream)])
        return (time.perf_counter() - t0) / reps

    reps = max(64, n_batches)
    plain_s = _time_updates(MeanMetric(), reps)
    windowed_s = _time_updates(
        Windowed(MeanMetric(), window=window, advance_every=every, emit=False), reps
    )
    out["online_plain_updates_per_sec"] = round(1.0 / plain_s, 1)
    out["online_windowed_updates_per_sec"] = round(1.0 / windowed_s, 1)
    out["online_windowed_vs_plain_overhead"] = round(windowed_s / plain_s, 3)
    out["online_overhead_bound"] = 1.5

    # --- lane 2: advance launch cost + detector eval latency -----------------------
    wa = Windowed(SumMetric(), window=window, advance_every=None, emit=False)
    wa.update(stream[0])
    wa.advance()  # compile out of window
    t0 = time.perf_counter()
    adv_reps = 32
    for _ in range(adv_reps):
        wa.advance()
    out["online_advance_cost_us"] = round((time.perf_counter() - t0) / adv_reps * 1e6, 1)

    wq = Windowed(StreamingQuantile(q=0.5, capacity=32, levels=12), window=4,
                  advance_every=2, emit=False)
    ref_sample = rng.normal(0.0, 1.0, 4096).astype(np.float32)
    for _ in range(6):
        wq.update(rng.normal(0.0, 1.0, batch).astype(np.float32))
    det = KsDrift(wq, ref_sample)
    det.score()  # warm the merge kernel
    t0 = time.perf_counter()
    det_reps = 16
    for _ in range(det_reps):
        det.score()
    out["online_detector_eval_us"] = round((time.perf_counter() - t0) / det_reps * 1e6, 1)

    # --- lane 3: bit-identity vs the direct twin across dispatch tiers -------------
    start = max(0, len(stream) // every - window + 1) * every
    direct = MeanMetric()
    for b in stream[start:]:
        direct.update(b)
    direct_bytes = np.asarray(direct.compute()).tobytes()
    for tier in ("aot", "jit", "buffered", "scan"):
        m = Windowed(MeanMetric(), window=window, advance_every=every, emit=False)
        if tier == "jit":
            m.fast_dispatch = False
            m.fast_update = False
        if tier == "buffered":
            with m.buffered(4) as buf:
                for b in stream:
                    buf.update(b)
        elif tier == "scan":
            m.update_batches(np.stack(stream))
        else:
            for b in stream:
                m.update(b)
        out[f"online_bit_identical_{tier}"] = (
            np.asarray(m.compute()).tobytes() == direct_bytes
        )

    # --- lane 4: drift alarm — quiet on stationary, one-shot loud on a shift -------
    from torchmetrics_tpu.utils.prints import reset_warning_cache

    reset_warning_cache()
    wd = Windowed(StreamingQuantile(q=0.5, capacity=32, levels=12), window=4,
                  advance_every=2, emit=False)
    mon = DriftMonitor([
        DriftSpec(name="bench-online-drift", detector=KsDrift(wd, ref_sample),
                  threshold=0.2, windows=((5.0, 1.0),)),
    ])
    now = 10_000.0
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        for _ in range(10):
            wd.update(rng.normal(0.0, 1.0, batch).astype(np.float32))
            now += 1.0
            statuses = mon.evaluate(now=now)
        quiet = not any(s.drifting for s in statuses)
        quiet_warns = sum(1 for x in rec if "burning" in str(x.message))
        for _ in range(10):
            wd.update(rng.normal(4.0, 1.0, batch).astype(np.float32))
            now += 1.0
            statuses = mon.evaluate(now=now)
        loud = any(s.drifting for s in statuses)
        fired = sum(1 for x in rec if "burning" in str(x.message))
    out["online_drift_quiet_stationary"] = bool(quiet and quiet_warns == 0)
    out["online_drift_alarm_fired_once"] = bool(loud and fired == 1)
    out["online_drift_score_final"] = (
        None if statuses[0].score is None else round(statuses[0].score, 4)
    )
    out["online_windows_advanced"] = obs.telemetry.counter("online.windows_advanced").value
    out["online_drift_evaluations"] = obs.telemetry.counter("drift.evaluations").value
    out["online_drift_alarms"] = obs.telemetry.counter("drift.alarms").value
    return out


def online_main(smoke: bool) -> None:
    """``bench.py --online [--smoke]``: one JSON line with the windowed-monitoring proof."""
    batch, n_batches = (256, 64) if smoke else (2048, 256)
    extras = bench_online(batch, n_batches)
    extras.update(_contention_report())
    try:
        from torchmetrics_tpu import obs

        extras["telemetry"] = obs.bench_extras()
    except Exception as err:  # pragma: no cover - extras are best-effort
        extras["telemetry_error"] = repr(err)
    print(
        json.dumps(
            {
                "metric": "online_windowed_vs_plain_overhead",
                "value": extras["online_windowed_vs_plain_overhead"],
                "unit": ("[SMOKE tiny-N lane — not a recordable perf number] " if smoke else "") + (
                    "per-update cost of a sliding ring vs its plain template (bound:"
                    " 1.5x); advance cost, detector latency, tier bit-identity flags,"
                    " and the one-shot drift-alarm evidence in extras"
                ),
                "vs_baseline": None,
                "extras": extras,
            }
        )
    )


def bench_reference(preds: np.ndarray, target: np.ndarray) -> float:
    """Same sweep through the reference torchmetrics (torch backend)."""
    import types

    # minimal lightning_utilities shim (not installed in this image)
    if "lightning_utilities" not in sys.modules:
        lu = types.ModuleType("lightning_utilities")
        core = types.ModuleType("lightning_utilities.core")
        imports_mod = types.ModuleType("lightning_utilities.core.imports")
        enums_mod = types.ModuleType("lightning_utilities.core.enums")

        import importlib.util
        from enum import Enum

        def package_available(name: str) -> bool:
            try:
                return importlib.util.find_spec(name) is not None
            except Exception:
                return False

        def compare_version(package: str, op, version: str, use_base_version: bool = False) -> bool:
            try:
                from packaging.version import Version

                mod = __import__(package)
                return op(Version(mod.__version__), Version(version))
            except Exception:
                return False

        class StrEnum(str, Enum):
            @classmethod
            def from_str(cls, value, source="key"):
                for st in cls:
                    if st.value.lower() == str(value).lower() or st.name.lower() == str(value).lower():
                        return st
                return None

            @classmethod
            def try_from_str(cls, value, source="key"):
                return cls.from_str(value, source)

            def __eq__(self, other):
                if isinstance(other, str):
                    return self.value.lower() == other.lower()
                return super().__eq__(other)

            def __hash__(self):
                return hash(self.value.lower())

        def apply_to_collection(data, dtype, function, *args, **kwargs):
            if isinstance(data, dtype):
                return function(data, *args, **kwargs)
            if isinstance(data, dict):
                return {k: apply_to_collection(v, dtype, function, *args, **kwargs) for k, v in data.items()}
            if isinstance(data, (list, tuple)):
                out = [apply_to_collection(v, dtype, function, *args, **kwargs) for v in data]
                return type(data)(out) if isinstance(data, tuple) else out
            return data

        imports_mod.package_available = package_available
        imports_mod.compare_version = compare_version
        enums_mod.StrEnum = StrEnum
        lu.apply_to_collection = apply_to_collection
        core.imports = imports_mod
        core.enums = enums_mod
        lu.core = core
        sys.modules["lightning_utilities"] = lu
        sys.modules["lightning_utilities.core"] = core
        sys.modules["lightning_utilities.core.imports"] = imports_mod
        sys.modules["lightning_utilities.core.enums"] = enums_mod

    sys.path.insert(0, "/root/reference/src")
    import torch
    from torchmetrics import MetricCollection as RefCollection
    from torchmetrics.classification import (
        MulticlassAccuracy,
        MulticlassF1Score,
        MulticlassPrecision,
        MulticlassRecall,
    )

    def make():
        return RefCollection(
            [
                MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False),
                MulticlassPrecision(num_classes=NUM_CLASSES, average="macro", validate_args=False),
                MulticlassRecall(num_classes=NUM_CLASSES, average="macro", validate_args=False),
                MulticlassF1Score(num_classes=NUM_CLASSES, average="macro", validate_args=False),
            ]
        )

    dev_preds = [torch.from_numpy(p).long() for p in preds]
    dev_target = [torch.from_numpy(t).long() for t in target]

    # measure a slice and extrapolate (reference torch-CPU path is slow). Protocol matches
    # bench_ours_per_step: per-batch forward() calls returning the batch value.
    n_meas = min(N_BATCHES, 30)
    mc = make()
    mc(dev_preds[0], dev_target[0])  # group formation + first forward
    t0 = time.perf_counter()
    for i in range(1, n_meas):
        mc(dev_preds[i], dev_target[i])
    _ = mc.compute()
    elapsed = time.perf_counter() - t0
    print(f"reference (per-step forward): {n_meas - 1} updates in {elapsed:.3f}s", file=sys.stderr)
    return (n_meas - 1) / elapsed


_WINDOW_STATS = {"spreads": []}  # best/median divergence per timed section (contention telemetry)


def _best_of(run_window, windows: int = 5) -> float:
    """Fastest of several independently timed windows (shared-chip interference damping).

    Also records the best/median spread: when the median window is much slower than the best,
    the chip was contended during the run and even the best number is suspect.
    """
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        run_window()
        times.append(time.perf_counter() - t0)
    times.sort()
    best = times[0]
    median = times[len(times) // 2]
    _WINDOW_STATS["spreads"].append(median / best if best > 0 else 1.0)
    return best


def _slope_rate(run_j, per_call: float, k1: int = 4, k2: int = 64, max_k: int = 4096):
    """True device throughput via a two-point slope on a runtime-trip-count program.

    ``run_j(k)`` must execute the workload k times in ONE launch (``fori_loop``). Timing it at
    two k values and dividing extra work by extra time cancels every constant cost — host
    dispatch, tunnel round-trip latency, result fetch — which otherwise bound any per-launch
    protocol on this link (~100ms blocking round-trip here). k2 doubles until the time split
    is decisive (>=30ms), so fast kernels get a long enough run to measure.

    Returns (rate_per_sec, t1, t2, k1, k2) where rate is ``per_call`` units per second.
    """
    import jax

    t1 = _best_of(lambda: jax.block_until_ready(run_j(k1)), windows=3)
    while True:
        t2 = _best_of(lambda: jax.block_until_ready(run_j(k2)), windows=3)
        if t2 - t1 > 0.03 or k2 >= max_k:
            break
        k2 *= 4
    if t2 - t1 <= 0.01:
        # the timings never separated: the kernel is too fast to resolve even at max_k, or the
        # chip is too noisy. A slope here would be fiction — fall back to the conservative
        # whole-launch rate (constant overhead included) and flag it.
        _WINDOW_STATS["unresolved_slopes"] = _WINDOW_STATS.get("unresolved_slopes", 0) + 1
        return k2 * per_call / t2, t1, t2, k1, k2
    rate = (k2 - k1) * per_call / (t2 - t1)
    return rate, t1, t2, k1, k2


def _contention_report() -> dict:
    """Summarise window spreads; flag suspected contention when median/best diverges >2x."""
    spreads = _WINDOW_STATS["spreads"]
    if not spreads:
        return {"contention_suspected": False}
    worst = max(spreads)
    return {
        "window_spread_max": round(worst, 2),
        "window_spread_mean": round(sum(spreads) / len(spreads), 2),
        "contention_suspected": worst > 2.0,
        "unresolved_slopes": _WINDOW_STATS.get("unresolved_slopes", 0),
    }


def bench_functional_stat_scores() -> dict:
    """BASELINE config #2: jitted functional stat_scores/confmat/F1 sweeps over 1M samples."""
    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu.functional.classification.confusion_matrix import multiclass_confusion_matrix
    from torchmetrics_tpu.functional.classification.f_beta import binary_f1_score, multiclass_f1_score
    from torchmetrics_tpu.functional.classification.stat_scores import multiclass_stat_scores

    rng = np.random.RandomState(3)
    mc_preds = jnp.asarray(rng.randint(0, NUM_CLASSES, size=TOTAL_SAMPLES).astype(np.int32))
    mc_target = jnp.asarray(rng.randint(0, NUM_CLASSES, size=TOTAL_SAMPLES).astype(np.int32))
    b_preds = jnp.asarray(rng.rand(TOTAL_SAMPLES).astype(np.float32))
    b_target = jnp.asarray(rng.randint(0, 2, size=TOTAL_SAMPLES).astype(np.int32))

    mc_args = (mc_preds, mc_target)
    fns = {
        "multiclass_stat_scores": (jax.jit(
            lambda p, t: multiclass_stat_scores(p, t, NUM_CLASSES, average="macro", validate_args=False)
        ), mc_args),
        "multiclass_confusion_matrix": (jax.jit(
            lambda p, t: multiclass_confusion_matrix(p, t, NUM_CLASSES, validate_args=False)
        ), mc_args),
        "multiclass_f1": (jax.jit(
            lambda p, t: multiclass_f1_score(p, t, NUM_CLASSES, average="macro", validate_args=False)
        ), mc_args),
        "binary_f1": (jax.jit(lambda p, t: binary_f1_score(p, t, validate_args=False)), (b_preds, b_target)),
    }
    out = {}
    for name, (fn, args) in fns.items():
        # int_mod=2 keeps salted values valid for BOTH multiclass labels and binary targets
        out[name] = _kernel_device_rate(fn, args, TOTAL_SAMPLES, int_mod=2)
    return {f"{n}_samples_per_sec": round(v, 0) for n, v in out.items()}


def _kernel_device_rate(fn, args, n_per_call: float, int_mod: int = 2) -> float:
    """Device slope rate for a jitted kernel: k salted calls folded into one fori_loop launch.

    Integer inputs are salted ``(x + i) % int_mod``, float inputs ``mod(x + i*1e-3, 1)`` so XLA
    cannot hoist the loop-invariant call; the added elementwise op is noise next to the kernel.
    """
    import jax
    import jax.numpy as jnp

    def salted(i, a):
        if jnp.issubdtype(a.dtype, jnp.integer):
            return (a + i) % int_mod
        return jnp.mod(a + 1e-3 * jnp.asarray(i, a.dtype), 1.0)

    def run(k):
        def body(i, acc):
            res = fn(*(salted(i, a) for a in args))
            leaves = jax.tree_util.tree_leaves(res)
            return acc + sum(jnp.sum(jnp.asarray(x, jnp.float32)) for x in leaves)

        return jax.lax.fori_loop(0, k, body, jnp.zeros(()))

    run_j = jax.jit(run)
    jax.block_until_ready(run_j(2))  # compile
    rate, *_ = _slope_rate(run_j, per_call=n_per_call)
    return rate


def bench_binned_curves() -> dict:
    """BASELINE config #3: binned AUROC / AveragePrecision over 1M samples (the flagship
    O(N+T) searchsorted+histogram curve kernel)."""
    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu.functional.classification.auroc import (
        binary_auroc,
        multiclass_auroc,
        multilabel_auroc,
    )
    from torchmetrics_tpu.functional.classification.average_precision import binary_average_precision

    rng = np.random.RandomState(5)
    b_preds = jnp.asarray(rng.rand(TOTAL_SAMPLES).astype(np.float32))
    b_target = jnp.asarray(rng.randint(0, 2, size=TOTAL_SAMPLES).astype(np.int32))
    mc_preds = jnp.asarray(rng.rand(TOTAL_SAMPLES // 5, NUM_CLASSES).astype(np.float32))
    mc_target = jnp.asarray(rng.randint(0, NUM_CLASSES, size=TOTAL_SAMPLES // 5).astype(np.int32))
    ml_preds = mc_preds
    ml_target = jnp.asarray(rng.randint(0, 2, size=(TOTAL_SAMPLES // 5, NUM_CLASSES)).astype(np.int32))

    fns = {
        "binary_auroc": (
            jax.jit(lambda p, t: binary_auroc(p, t, thresholds=200, validate_args=False)),
            (b_preds, b_target), TOTAL_SAMPLES,
        ),
        "binary_ap": (
            jax.jit(lambda p, t: binary_average_precision(p, t, thresholds=200, validate_args=False)),
            (b_preds, b_target), TOTAL_SAMPLES,
        ),
        "multiclass_auroc": (
            jax.jit(lambda p, t: multiclass_auroc(p, t, NUM_CLASSES, thresholds=200, validate_args=False)),
            (mc_preds, mc_target), TOTAL_SAMPLES // 5,
        ),
        "multilabel_auroc": (
            jax.jit(lambda p, t: multilabel_auroc(p, t, NUM_CLASSES, thresholds=200, validate_args=False)),
            (ml_preds, ml_target), TOTAL_SAMPLES // 5,
        ),
    }
    out = {}
    for name, (fn, args, n) in fns.items():
        out[f"{name}_samples_per_sec"] = round(_kernel_device_rate(fn, args, n, int_mod=2), 0)
    return out


def bench_retrieval_cat() -> dict:
    """BASELINE config #5: RetrievalMAP/NDCG cat-state sweep, update + flat fused compute.

    The flat segment-reduce compute has no shape-determining host fetch, so the whole
    (reset -> update -> compute) iteration pipelines; the window blocks once at the end."""
    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu.retrieval import RetrievalMAP, RetrievalNormalizedDCG

    n = 1 << 20  # 1,048,576 docs (power of two: no pad, one compiled shape)
    n_queries = 10_000
    rng = np.random.RandomState(9)
    preds = jnp.asarray(rng.rand(n).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 2, size=n).astype(np.int32))
    indexes = jnp.asarray(np.sort(rng.randint(0, n_queries, size=n)).astype(np.int32))
    jax.block_until_ready((preds, target, indexes))
    out = {}
    for name, cls in (("retrieval_map", RetrievalMAP), ("retrieval_ndcg", RetrievalNormalizedDCG)):
        m = cls()
        m.update(preds, target, indexes=indexes)
        jax.block_until_ready(m.compute())  # compile

        def _window():
            results = []
            for _ in range(3):
                m.reset()
                m.update(preds, target, indexes=indexes)
                results.append(m.compute())
            jax.block_until_ready(results)

        best = _best_of(_window)
        out[f"{name}_samples_per_sec"] = round(3 * n / best, 0)
    return out


_SYNC8_SNIPPET = r"""
import json, time
import numpy as np
import jax
# config-API pin: selection via the JAX_PLATFORMS env var alone wedges backend init when a
# dead axon tunnel plugin is discoverable (verified rc=124); the config API is immune
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from torchmetrics_tpu.parallel.sync import shard_map_unchecked, sync_state

NUM_CLASSES = 5
devices = jax.devices()
n = len(devices)
mesh = Mesh(np.array(devices), ("dp",))
state = {
    "tp": jnp.zeros((n, NUM_CLASSES), jnp.float32),
    "cat": jnp.zeros((n * 1024,), jnp.float32),
}
fx = {"tp": "sum", "cat": "cat"}

@jax.jit
@shard_map_unchecked(mesh, in_specs=(P("dp"), P("dp")), out_specs=P())
def sync(tp, cat):
    world = sync_state({"tp": tp[0], "cat": cat}, fx, axis_name="dp")
    return world["tp"], jnp.sum(world["cat"])

args = (
    jax.device_put(state["tp"], NamedSharding(mesh, P("dp"))),
    jax.device_put(state["cat"], NamedSharding(mesh, P("dp"))),
)
jax.block_until_ready(sync(*args))
k = 30
best = float("inf")
# block per call: queueing many async 8-participant collectives on the shared CPU thread
# pool can starve one device's thread past the 40s rendezvous watchdog (hard crash); the
# blocking round-trip is also the honest "sync latency" definition
for _ in range(5):
    t0 = time.perf_counter()
    for _ in range(k):
        jax.block_until_ready(sync(*args))
    best = min(best, time.perf_counter() - t0)
print(json.dumps({"sync_state_latency_us_mesh8cpu": round(best / k * 1e6, 1), "sync_mesh_devices": n}))
"""


def bench_sync_mesh8() -> dict:
    """North-star sync latency over a VIRTUAL 8-device CPU mesh (multi-chip TPU hardware is not
    available in this environment; labeled accordingly). Runs in a subprocess so the XLA
    host-device-count flag can be set before jax initialises."""
    import os
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.run(
        [sys.executable, "-c", _SYNC8_SNIPPET], capture_output=True, text=True, env=env,
        timeout=300, cwd="/root/repo",
    )
    if proc.returncode != 0:
        raise RuntimeError(f"sync@8 subprocess failed: {proc.stderr[-500:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_dispatch_latency() -> dict:
    """Per-launch overhead of the environment (tunneled chip): the floor for ANY per-step
    protocol. per-step forward ≈ one launch, so its updates/s ceiling is 1/roundtrip."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros(8, jnp.float32)
    jax.block_until_ready(f(x))
    k = 30
    t0 = time.perf_counter()
    for _ in range(k):
        jax.block_until_ready(f(x))
    roundtrip = (time.perf_counter() - t0) / k
    t0 = time.perf_counter()
    jax.block_until_ready([f(x) for _ in range(k)])
    pipelined = (time.perf_counter() - t0) / k
    return {
        "dispatch_roundtrip_ms": round(roundtrip * 1e3, 2),
        "dispatch_pipelined_ms": round(pipelined * 1e3, 2),
    }


def bench_sync_latency() -> dict:
    """Single-chip sync-path latency on the real device (collectives are no-ops at world=1;
    this measures dispatch + program overhead of the sync program only)."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchmetrics_tpu.parallel.sync import shard_map_unchecked, sync_state

    devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.array(devices), ("dp",))
    state = {
        "tp": jnp.zeros((n, NUM_CLASSES), jnp.float32),
        "cat": jnp.zeros((n * 1024,), jnp.float32),
    }
    fx = {"tp": "sum", "cat": "cat"}

    @jax.jit
    @shard_map_unchecked(mesh, in_specs=(P("dp"), P("dp")), out_specs=P())
    def sync(tp, cat):
        world = sync_state({"tp": tp[0], "cat": cat}, fx, axis_name="dp")
        return world["tp"], jnp.sum(world["cat"])

    args = (
        jax.device_put(state["tp"], NamedSharding(mesh, P("dp"))),
        jax.device_put(state["cat"], NamedSharding(mesh, P("dp"))),
    )
    jax.block_until_ready(sync(*args))
    k = 30
    best = _best_of(lambda: jax.block_until_ready([sync(*args) for _ in range(k)]))

    # individually-timed blocking round-trips feed the telemetry sync-latency histogram, so
    # the BENCH extras carry p50/p99 (distribution shape, not just the best-case mean)
    from torchmetrics_tpu import obs

    hist = obs.telemetry.histogram("sync.latency_us")
    for _ in range(20):
        t0 = time.perf_counter()
        jax.block_until_ready(sync(*args))
        hist.record((time.perf_counter() - t0) * 1e6)
    return {"sync_state_latency_us": round(best / k * 1e6, 1), "sync_mesh_devices": n}


def _resolve_platform(probe_timeout_s: float = 90.0) -> str:
    """Pick the fastest healthy platform: the env-requested one, else the tunneled TPU
    plugin, else CPU. Every candidate is probed in a subprocess with a hard timeout — in this
    environment a dead axon tunnel hangs backend init forever (rc=124 artifacts in r4), so no
    candidate is trusted until a fresh process has actually run an op on it. Probe logic lives
    in ``torchmetrics_tpu.utils.platform`` (shared with the examples and the dryrun)."""
    import os

    from torchmetrics_tpu.utils.platform import resolve_healthy_platform

    candidates = []
    env = os.environ.get("JAX_PLATFORMS")
    if env and env.split(",")[0] not in ("", "cpu"):
        candidates.append(env.split(",")[0])
    elif not env:
        candidates += ["axon", "tpu"]  # absent plugins fail the probe fast; dead ones time out
    return resolve_healthy_platform(
        candidates, probe_timeout_s, log=lambda m: print(f"bench: {m}", file=sys.stderr)
    )


def _emit_failure_json(reason: str, platform: str) -> None:
    """The driver must ALWAYS get one parseable JSON line — a failed run is a recorded
    failure, never an unparsed rc=1 tail (r4 lost its whole perf round to that)."""
    print(
        json.dumps(
            {
                "metric": "metric_updates_per_sec_1M_sample_multiclass_sweep",
                "value": 0.0,
                "unit": f"updates/s (BENCH FAILED on platform={platform}: {reason})",
                "vs_baseline": None,
                "extras": {"platform": platform, "error": reason},
            }
        )
    )


def orchestrate() -> None:
    """Probe for a healthy platform, then run the real bench in a watchdog subprocess.

    Guarantees exactly one JSON line on stdout regardless of what the backend does: the
    worker's line if it succeeds, a TPU-failed retry on CPU if it doesn't, and a recorded
    failure payload if even CPU fails.
    """
    import os
    import subprocess

    platform = _resolve_platform()
    timeout_s = float(os.environ.get("BENCH_TIMEOUT_S", "1500"))
    here = os.path.abspath(__file__)
    attempts = [platform] if platform == "cpu" else [platform, "cpu"]
    last_reason = "unknown"
    for plat in attempts:
        try:
            proc = subprocess.run(
                [sys.executable, here, "--worker", plat],
                timeout=timeout_s, capture_output=True, text=True,
                cwd=os.path.dirname(here),
            )
        except subprocess.TimeoutExpired as err:
            last_reason = f"worker timed out after {timeout_s:.0f}s"
            tail = err.stderr or ""
            if isinstance(tail, bytes):
                tail = tail.decode(errors="replace")
            sys.stderr.write(tail[-2000:])
            print(f"bench: worker on {plat!r} timed out", file=sys.stderr)
            continue
        sys.stderr.write(proc.stderr[-4000:])
        for line in reversed(proc.stdout.strip().splitlines() or []):
            try:
                payload = json.loads(line)
            except ValueError:
                continue
            payload.setdefault("extras", {})["platform"] = plat
            print(json.dumps(payload))
            return
        last_reason = f"worker rc={proc.returncode}, no JSON line on stdout"
        print(f"bench: worker on {plat!r} produced no JSON (rc={proc.returncode})", file=sys.stderr)
    _emit_failure_json(last_reason, attempts[-1])


def main() -> None:
    preds, target = _gen_data()
    ours = bench_ours(preds, target)
    try:
        per_step = bench_ours_per_step(preds, target)
        ours_per_step = per_step["rate"]
        host_overhead_us = per_step["host_overhead_us"]
    except Exception as err:
        print(f"per-step bench failed: {err!r}", file=sys.stderr)
        ours_per_step = float("nan")
        host_overhead_us = None
    try:
        buffered_rate = bench_buffered_updates(preds, target)
    except Exception as err:
        print(f"buffered bench failed: {err!r}", file=sys.stderr)
        buffered_rate = float("nan")
    if SMOKE:
        ref = float("nan")  # the torch reference import alone dwarfs a smoke budget
    else:
        try:
            ref = bench_reference(preds, target)
        except Exception as err:  # reference unavailable -> report absolute number only
            print(f"reference bench failed: {err!r}", file=sys.stderr)
            ref = float("nan")
    ours_fused = ours["device_rate"]
    # like-for-like TASK comparison: wall-clock to fold 1M samples into the 4-metric collection
    # and read the values back, best API of each framework, all latencies included
    ref_wall = N_BATCHES / ref if ref == ref else float("nan")
    vs = ref_wall / ours["wall_one_sweep_s"] if ref == ref else float("nan")

    extras = {
        "wall_1M_sweep_ours_s": round(ours["wall_one_sweep_s"], 4),
        "wall_1M_sweep_reference_s": round(ref_wall, 4) if ref_wall == ref_wall else None,
        "host_api_sweep_updates_per_sec": round(ours["host_api_rate"], 2),
        "updates_per_sec_per_step_forward": round(ours_per_step, 2) if ours_per_step == ours_per_step else None,
        # r06+: per-batch arrays are pre-built OUTSIDE the window (protocol parity with the
        # reference's tensor list); r01-r05 sliced the device stack in-loop, paying two
        # extra eager dispatches per step — trajectory comparisons must account for this
        "per_step_protocol": "presplit-batch-list",
        "per_step_host_overhead_us": host_overhead_us,
        "buffered_updates_per_sec": round(buffered_rate, 2) if buffered_rate == buffered_rate else None,
        "updates_per_sec_reference_per_step": round(ref, 2) if ref == ref else None,
        "per_step_vs_reference": round(ours_per_step / ref, 3) if ref == ref and ours_per_step == ours_per_step else None,
    }
    extras["fused_samples_per_sec"] = round(ours_fused * BATCH, 0)
    extra_benches = (
        ("dispatch_latency", bench_dispatch_latency),
        ("functional_stat_scores", bench_functional_stat_scores),
        ("binned_curves", bench_binned_curves),
        ("retrieval_cat_state", bench_retrieval_cat),
        ("sync_single_chip", bench_sync_latency),
        ("sync_mesh8", bench_sync_mesh8),
    )
    if SMOKE:  # keep only the cheap launch-floor probe; the rest are minutes-scale
        extra_benches = (("dispatch_latency", bench_dispatch_latency),)
    for name, fn in extra_benches:
        try:
            extras.update(fn())
        except Exception as err:
            print(f"extra bench {name} failed: {err!r}", file=sys.stderr)
            extras[f"{name}_error"] = repr(err)
    extras.update(_contention_report())

    # telemetry block: retrace/dispatch/sync counters recorded during this very run — a
    # regression like r02→r03 now ships its own recompile-churn evidence in the BENCH file
    try:
        from torchmetrics_tpu import obs

        extras["telemetry"] = obs.bench_extras()
    except Exception as err:
        extras["telemetry_error"] = repr(err)

    # XLA cost ledger: compiler-level FLOPs / bytes-accessed / memory footprint per benched
    # metric kernel (docs/observability.md "Cost profiling & perf gate"). Resolving the
    # jit-tier rows compiles each remaining kernel once — outside every timed window — and
    # makes the BENCH file diffable by the perf gate and `bench.py --compare`.
    try:
        from torchmetrics_tpu import obs

        extras["cost_ledger"] = [
            {k: r[k] for k in ("key", "metric", "kernel", "tier", "flops",
                               "bytes_accessed", "temp_bytes", "argument_bytes", "available")}
            for r in obs.cost_ledger()
        ]
    except Exception as err:
        extras["cost_ledger_error"] = repr(err)

    print(
        json.dumps(
            {
                "metric": "metric_updates_per_sec_1M_sample_multiclass_sweep",
                "value": round(ours_fused, 2),
                "unit": ("[SMOKE tiny-N lane — not a recordable perf number] " if SMOKE else "") + (
                    "updates/s (batch=10k, MetricCollection[Acc,P,R,F1] one-launch fused sweep,"
                    " DEVICE RATE from a two-point K-sweep slope — constant tunnel dispatch/latency"
                    " cancelled; vs_baseline = reference torch-CPU wall-clock for one full 1M-sample"
                    " sweep divided by ours, latencies included, best API of each framework;"
                    " per-step forward protocol + dispatch context in extras)"
                ),
                "vs_baseline": round(vs, 3) if vs == vs else None,
                "extras": extras,
            }
        )
    )


def compare_main(path_a: str, path_b: str) -> int:
    """``bench.py --compare A.json B.json``: per-metric delta table between two BENCH files.

    Reuses the perf gate's tolerance logic (``torchmetrics_tpu.obs.ledger``): throughput
    numbers regress when B falls below A by more than the bench tolerance, latency/overhead
    numbers when they rise above it, and embedded ``cost_ledger`` rows are diffed field by
    field with the flops/bytes/memory tolerances. Exit code 1 when anything regresses —
    jax is never initialised, so this runs anywhere the JSON files do.
    """
    from torchmetrics_tpu.obs import ledger as _ledger

    a = _ledger.load_bench_payload(path_a)
    b = _ledger.load_bench_payload(path_b)
    if not a or not b:
        print(f"bench --compare: no bench payload found in {path_a if not a else path_b}",
              file=sys.stderr)
        return 2

    def numbers(payload: dict) -> dict:
        out = {}
        if isinstance(payload.get("value"), (int, float)):
            out["value"] = payload["value"]
        for k, v in (payload.get("extras") or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[k] = v
        return out

    nums_a, nums_b = numbers(a), numbers(b)
    shared = sorted(set(nums_a) & set(nums_b))
    deltas = _ledger.compare_bench(nums_a, nums_b, keys=shared)
    print(_ledger.render_deltas(deltas, title=f"bench compare: {path_a} -> {path_b}"))

    rows_a = {r["key"]: r for r in (a.get("extras") or {}).get("cost_ledger") or []}
    rows_b = {r["key"]: r for r in (b.get("extras") or {}).get("cost_ledger") or []}
    ledger_deltas = []
    if rows_a and rows_b:
        ledger_deltas = _ledger.compare_ledger(rows_a, rows_b)
        print(_ledger.render_deltas(ledger_deltas, title="cost-ledger deltas"))
    else:
        print("cost-ledger deltas: skipped (one or both files carry no cost_ledger extras)")
    return 1 if _ledger.regressions(deltas) or _ledger.regressions(ledger_deltas) else 0


if __name__ == "__main__":
    if "--compare" in sys.argv:
        idx = sys.argv.index("--compare")
        if len(sys.argv) < idx + 3:
            print("usage: bench.py --compare A.json B.json", file=sys.stderr)
            sys.exit(2)
        sys.exit(compare_main(sys.argv[idx + 1], sys.argv[idx + 2]))
    if "--sharded" in sys.argv:
        # sharded-state scenario (make shard-smoke / docs/distributed.md): the multi-device
        # host mesh must be forced BEFORE the first jax backend touch, and smoke pins CPU
        # via the config API like the other lanes
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
        import jax

        smoke = "--smoke" in sys.argv
        jax.config.update("jax_platforms", "cpu" if smoke else _resolve_platform())
        sharded_main(smoke)
    elif "--sync-compress" in sys.argv:
        # compressed-collective lane (make compress-smoke / docs/distributed.md
        # "Compressed collectives"): smoke pins CPU via the config API like the others
        import jax

        smoke = "--smoke" in sys.argv
        jax.config.update("jax_platforms", "cpu" if smoke else _resolve_platform())
        sync_compress_main(smoke)
    elif "--serve" in sys.argv:
        # serving scenario (make serve-smoke / docs/serving.md): smoke pins CPU via the
        # config API like the other lanes; full mode probes for a healthy platform
        import jax

        smoke = "--smoke" in sys.argv
        jax.config.update("jax_platforms", "cpu" if smoke else _resolve_platform())
        serve_main(smoke)
    elif "--obs" in sys.argv:
        # serving-observability proof lane (make obs-smoke / docs/observability.md
        # "Serving traces, live series & SLOs"): smoke pins CPU like the other lanes
        import jax

        smoke = "--smoke" in sys.argv
        jax.config.update("jax_platforms", "cpu" if smoke else _resolve_platform())
        obs_main(smoke)
    elif "--flight" in sys.argv:
        # flight-recorder & post-mortem-bundle lane (make bundle-smoke /
        # docs/observability.md "Flight recorder"): smoke pins CPU like the other lanes
        import jax

        smoke = "--smoke" in sys.argv
        jax.config.update("jax_platforms", "cpu" if smoke else _resolve_platform())
        flight_main(smoke)
    elif "--explain" in sys.argv:
        # compile-plane lane (make explain-smoke / docs/observability.md "Compile
        # plane"): smoke pins CPU like the other lanes
        import jax

        smoke = "--smoke" in sys.argv
        jax.config.update("jax_platforms", "cpu" if smoke else _resolve_platform())
        explain_main(smoke)
    elif "--fleet" in sys.argv:
        # fleet federation lane (make fleet-smoke / docs/observability.md "Fleet
        # federation & incident correlation"): smoke pins CPU like the other lanes
        import jax

        smoke = "--smoke" in sys.argv
        jax.config.update("jax_platforms", "cpu" if smoke else _resolve_platform())
        fleet_main(smoke)
    elif "--online" in sys.argv:
        # online windowed-monitoring lane (make online-smoke / docs/online.md): smoke
        # pins CPU like the other lanes; full mode probes for a healthy platform
        import jax

        smoke = "--smoke" in sys.argv
        jax.config.update("jax_platforms", "cpu" if smoke else _resolve_platform())
        online_main(smoke)
    elif "--sketch" in sys.argv:
        # sketch-state scenario (make sketch-smoke / docs/sketches.md): smoke pins CPU
        # via the config API like the other lanes; full mode probes for a healthy platform
        import jax

        smoke = "--smoke" in sys.argv
        jax.config.update("jax_platforms", "cpu" if smoke else _resolve_platform())
        sketch_main(smoke)
    elif "--keyed" in sys.argv:
        # keyed multi-tenant scenario (make keyed-smoke / docs/keyed.md): smoke pins CPU
        # via the config API like the bench smoke lane; full mode probes for a healthy
        # platform first (a dead tunnel plugin must not wedge the run)
        import jax

        smoke = "--smoke" in sys.argv
        jax.config.update("jax_platforms", "cpu" if smoke else _resolve_platform())
        keyed_main(smoke)
    elif "--smoke" in sys.argv:
        # CI smoke lane (make bench-smoke): tiny sizes, CPU pinned via the config API (the
        # env-var route can wedge on a dead tunnel plugin), no subprocess orchestration —
        # one parseable JSON line in seconds or a nonzero rc
        import jax

        _apply_smoke_sizes()
        jax.config.update("jax_platforms", "cpu")
        main()
    elif len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        import jax

        jax.config.update("jax_platforms", sys.argv[2])
        main()
    else:
        orchestrate()
