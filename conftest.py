"""Root pytest configuration: platform pin for package doctests.

``--doctest-modules`` over ``torchmetrics_tpu/`` (pyproject ``testpaths``) executes docstring
examples that initialise the JAX backend OUTSIDE ``tests/unittests/conftest.py``'s scope — and
in this environment default platform discovery can wedge forever on a dead axon TPU tunnel
(plugin discovery hangs even under ``JAX_PLATFORMS=cpu``; only the config API is safe). Pin
the virtual CPU mesh here so every pytest entry point — tests AND doctests — initialises
instantly and deterministically.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="run tests marked slow (the heavy sweeps split into their own CI lane)",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy sweep kept out of the default lane (run with --runslow; CI has a"
        " dedicated lane) so the default suite stays within its runtime budget",
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip_slow = pytest.mark.skip(reason="slow lane: pass --runslow (CI runs these separately)")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip_slow)
