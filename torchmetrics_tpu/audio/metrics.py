"""Audio module metrics (reference ``src/torchmetrics/audio/``).

Every class follows the reference's state design: a scalar dB sum + sample count, both
``dist_reduce_fx="sum"`` — trivially ``psum``-able across a mesh.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.audio.deps import (
    perceptual_evaluation_speech_quality,
    short_time_objective_intelligibility,
)
from torchmetrics_tpu.functional.audio.srmr import speech_reverberation_modulation_energy_ratio
from torchmetrics_tpu.functional.audio.pit import permutation_invariant_training
from torchmetrics_tpu.functional.audio.sdr import signal_distortion_ratio
from torchmetrics_tpu.functional.audio.snr import (
    complex_scale_invariant_signal_noise_ratio,
    scale_invariant_signal_distortion_ratio,
    scale_invariant_signal_noise_ratio,
    signal_noise_ratio,
    source_aggregated_signal_distortion_ratio,
)
from torchmetrics_tpu.metric import Metric


class _MeanOverSamplesMetric(Metric):
    """Accumulate ``metric(...)`` summed over samples + the sample count; compute the mean."""

    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_metric", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")

    def _batch_values(self, preds: Array, target: Array) -> Array:
        raise NotImplementedError

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        vals = self._batch_values(preds, target)
        return {
            "sum_metric": state["sum_metric"] + jnp.sum(vals),
            "total": state["total"] + vals.size,
        }

    def _compute(self, state: Dict[str, Any]) -> Array:
        return state["sum_metric"] / state["total"]


class SignalNoiseRatio(_MeanOverSamplesMetric):
    """SNR (reference ``audio/snr.py:30``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.audio import SignalNoiseRatio
        >>> rng = np.random.RandomState(42)
        >>> target = rng.randn(100).astype(np.float32)
        >>> preds = target + 0.1 * rng.randn(100).astype(np.float32)
        >>> metric = SignalNoiseRatio()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.2f}")
        19.63
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean

    def _batch_values(self, preds: Array, target: Array) -> Array:
        return signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean)


class ScaleInvariantSignalNoiseRatio(_MeanOverSamplesMetric):
    """SI-SNR (reference ``audio/snr.py:124``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.audio import ScaleInvariantSignalNoiseRatio
        >>> rng = np.random.RandomState(42)
        >>> target = rng.randn(100).astype(np.float32)
        >>> preds = target * 0.9 + 0.05 * rng.randn(100).astype(np.float32)
        >>> metric = ScaleInvariantSignalNoiseRatio()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.2f}")
        24.69
    """

    is_differentiable = True
    higher_is_better = True

    def _batch_values(self, preds: Array, target: Array) -> Array:
        return scale_invariant_signal_noise_ratio(preds=preds, target=target)


class ComplexScaleInvariantSignalNoiseRatio(_MeanOverSamplesMetric):
    """C-SI-SNR (reference ``audio/snr.py:232``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.audio import ComplexScaleInvariantSignalNoiseRatio
        >>> rng = np.random.RandomState(42)
        >>> target = rng.randn(1, 10, 20, 2).astype(np.float32)  # (..., freq, time, re/im)
        >>> preds = target * 0.9 + 0.05 * rng.randn(1, 10, 20, 2).astype(np.float32)
        >>> metric = ComplexScaleInvariantSignalNoiseRatio()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.2f}")
        24.69
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Argument `zero_mean` must be a bool, but got {zero_mean}")
        self.zero_mean = zero_mean

    def _batch_values(self, preds: Array, target: Array) -> Array:
        return complex_scale_invariant_signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean)


class SignalDistortionRatio(_MeanOverSamplesMetric):
    """SDR (reference ``audio/sdr.py:37``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.audio import SignalDistortionRatio
        >>> rng = np.random.RandomState(1)
        >>> target = rng.randn(8000).astype(np.float32)
        >>> preds = target * 0.9 + 0.05 * rng.randn(8000).astype(np.float32)
        >>> metric = SignalDistortionRatio()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.2f}")
        25.34
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        use_cg_iter: Optional[int] = None,
        filter_length: int = 512,
        zero_mean: bool = False,
        load_diag: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.use_cg_iter = use_cg_iter
        self.filter_length = filter_length
        self.zero_mean = zero_mean
        self.load_diag = load_diag

    def _batch_values(self, preds: Array, target: Array) -> Array:
        return signal_distortion_ratio(
            preds, target, self.use_cg_iter, self.filter_length, self.zero_mean, self.load_diag
        )


class ScaleInvariantSignalDistortionRatio(_MeanOverSamplesMetric):
    """SI-SDR (reference ``audio/sdr.py:173``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.audio import ScaleInvariantSignalDistortionRatio
        >>> rng = np.random.RandomState(42)
        >>> target = rng.randn(100).astype(np.float32)
        >>> preds = target * 0.9 + 0.05 * rng.randn(100).astype(np.float32)
        >>> metric = ScaleInvariantSignalDistortionRatio()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.2f}")
        24.75
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean

    def _batch_values(self, preds: Array, target: Array) -> Array:
        return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=self.zero_mean)


class SourceAggregatedSignalDistortionRatio(_MeanOverSamplesMetric):
    """SA-SDR (reference ``audio/sdr.py:282``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.audio import SourceAggregatedSignalDistortionRatio
        >>> rng = np.random.RandomState(42)
        >>> target = rng.randn(1, 2, 200).astype(np.float32)  # (batch, sources, time)
        >>> preds = target * 0.9 + 0.05 * rng.randn(1, 2, 200).astype(np.float32)
        >>> metric = SourceAggregatedSignalDistortionRatio()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.2f}")
        24.69
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, scale_invariant: bool = True, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(scale_invariant, bool):
            raise ValueError(f"Expected argument `scale_invariant` to be a bool, but got {scale_invariant}")
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Argument `zero_mean` must be a bool, but got {zero_mean}")
        self.scale_invariant = scale_invariant
        self.zero_mean = zero_mean

    def _batch_values(self, preds: Array, target: Array) -> Array:
        return source_aggregated_signal_distortion_ratio(
            preds=preds, target=target, scale_invariant=self.scale_invariant, zero_mean=self.zero_mean
        )


class PermutationInvariantTraining(_MeanOverSamplesMetric):
    """PIT (reference ``audio/pit.py:30``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.audio import PermutationInvariantTraining
        >>> from torchmetrics_tpu.functional.audio import scale_invariant_signal_noise_ratio
        >>> preds = np.array([[[0.6, 0.4, 0.2], [0.2, 0.4, 0.6]]], np.float32)
        >>> target = np.array([[[0.2, 0.4, 0.6], [0.6, 0.4, 0.2]]], np.float32)
        >>> metric = PermutationInvariantTraining(scale_invariant_signal_noise_ratio,
        ...                                       eval_func='max')
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.2f}")
        58.27
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        metric_func: Callable,
        mode: str = "speaker-wise",
        eval_func: str = "max",
        **kwargs: Any,
    ) -> None:
        base_kwargs = {
            k: kwargs.pop(k)
            for k in list(kwargs)
            if k in (
                "compute_on_cpu", "dist_sync_on_step", "process_group", "dist_sync_fn",
                "distributed_available_fn", "sync_on_compute", "compute_with_cache",
            )
        }
        super().__init__(**base_kwargs)
        if eval_func not in ("max", "min"):
            raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
        if mode not in ("speaker-wise", "permutation-wise"):
            raise ValueError(f'mode can only be "speaker-wise" or "permutation-wise" but got {mode}')
        self.metric_func = metric_func
        self.mode = mode
        self.eval_func = eval_func
        self.kwargs = kwargs  # forwarded to metric_func (reference audio/pit.py:100)

    def _batch_values(self, preds: Array, target: Array) -> Array:
        best_metric, _ = permutation_invariant_training(
            preds, target, self.metric_func, self.mode, self.eval_func, **self.kwargs
        )
        return best_metric


class PerceptualEvaluationSpeechQuality(_MeanOverSamplesMetric):
    """PESQ (reference ``audio/pesq.py:29``); requires the host ``pesq`` package.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.audio import PerceptualEvaluationSpeechQuality
        >>> metric = PerceptualEvaluationSpeechQuality(8000, 'nb')  # needs `pesq`  # doctest: +SKIP
        >>> metric.update(np.random.randn(8000), np.random.randn(8000))  # doctest: +SKIP
        >>> metric.compute()  # doctest: +SKIP
    """

    is_differentiable = False
    higher_is_better = True
    jit_update = False
    scan_update = False

    def __init__(self, fs: int, mode: str, n_processes: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        # fail at construction when the backend is missing (reference pesq.py:85-89)
        from torchmetrics_tpu.functional.audio.deps import _require_pesq

        _require_pesq()
        self.fs = fs
        self.mode = mode
        self.n_processes = n_processes

    def _batch_values(self, preds: Array, target: Array) -> Array:
        return perceptual_evaluation_speech_quality(preds, target, self.fs, self.mode, n_processes=self.n_processes)


class ShortTimeObjectiveIntelligibility(_MeanOverSamplesMetric):
    """STOI (reference ``audio/stoi.py:29``); requires the host ``pystoi`` package.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.audio import ShortTimeObjectiveIntelligibility
        >>> metric = ShortTimeObjectiveIntelligibility(8000)  # needs `pystoi`  # doctest: +SKIP
        >>> metric.update(np.random.randn(8000), np.random.randn(8000))  # doctest: +SKIP
        >>> metric.compute()  # doctest: +SKIP
    """

    is_differentiable = False
    higher_is_better = True
    jit_update = False
    scan_update = False

    def __init__(self, fs: int, extended: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        from torchmetrics_tpu.functional.audio.deps import _require_pystoi

        _require_pystoi()
        self.fs = fs
        self.extended = extended

    def _batch_values(self, preds: Array, target: Array) -> Array:
        return short_time_objective_intelligibility(preds, target, self.fs, self.extended)


class SpeechReverberationModulationEnergyRatio(_MeanOverSamplesMetric):
    """SRMR (reference ``audio/srmr.py:37``): non-intrusive (no target), mean over samples.

    Backed by the self-contained gammatone/modulation pipeline in
    ``functional/audio/srmr.py`` — no external DSP packages needed.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.audio import SpeechReverberationModulationEnergyRatio
        >>> rng = np.random.RandomState(0)
        >>> speech = rng.randn(8000).astype(np.float32)
        >>> metric = SpeechReverberationModulationEnergyRatio(fs=8000)
        >>> metric.update(speech)
        >>> print(f"{float(metric.compute()):.4f}")
        0.3171
    """

    is_differentiable = False
    higher_is_better = True
    jit_update = False
    scan_update = False

    def __init__(
        self,
        fs: int,
        n_cochlear_filters: int = 23,
        low_freq: float = 125,
        min_cf: float = 4,
        max_cf: Optional[float] = None,
        norm: bool = False,
        fast: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        from torchmetrics_tpu.functional.audio.srmr import _srmr_arg_validate

        _srmr_arg_validate(fs, n_cochlear_filters, low_freq, min_cf, max_cf, norm, fast)
        self.fs = fs
        self.n_cochlear_filters = n_cochlear_filters
        self.low_freq = low_freq
        self.min_cf = min_cf
        self.max_cf = max_cf
        self.norm = norm
        self.fast = fast

    def _update(self, state: Dict[str, Array], preds: Array, target: Array = None) -> Dict[str, Array]:
        # single-argument (non-intrusive) form: forward()/update_batches() pass preds only
        return super()._update(state, preds, None)

    def _batch_values(self, preds: Array, target: Array = None) -> Array:
        return speech_reverberation_modulation_energy_ratio(
            preds, self.fs, self.n_cochlear_filters, self.low_freq, self.min_cf, self.max_cf, self.norm, self.fast
        )
