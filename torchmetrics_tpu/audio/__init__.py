"""Audio module metrics (reference ``src/torchmetrics/audio/``)."""
from torchmetrics_tpu.audio.metrics import (
    ComplexScaleInvariantSignalNoiseRatio,
    PerceptualEvaluationSpeechQuality,
    PermutationInvariantTraining,
    ScaleInvariantSignalDistortionRatio,
    ScaleInvariantSignalNoiseRatio,
    ShortTimeObjectiveIntelligibility,
    SignalDistortionRatio,
    SignalNoiseRatio,
    SourceAggregatedSignalDistortionRatio,
    SpeechReverberationModulationEnergyRatio,
)

__all__ = [
    "ComplexScaleInvariantSignalNoiseRatio",
    "PerceptualEvaluationSpeechQuality",
    "PermutationInvariantTraining",
    "ScaleInvariantSignalDistortionRatio",
    "ScaleInvariantSignalNoiseRatio",
    "ShortTimeObjectiveIntelligibility",
    "SignalDistortionRatio",
    "SignalNoiseRatio",
    "SourceAggregatedSignalDistortionRatio",
    "SpeechReverberationModulationEnergyRatio",
]
