"""Host-delegation adapters for pretrained-model metrics (FID/KID/IS/MiFID, LPIPS, CLIP, BERT).

The reference ships working defaults for its model-based metrics: torch-fidelity's
``NoTrainInceptionV3`` (``image/fid.py:44-66``), pretrained LPIPS (``image/lpip.py:40``),
HuggingFace CLIP (``multimodal/clip_score.py:43``) and a default BERT (``text/bert.py:54``).
The TPU compute path cannot run torch modules, but the metrics only need features — so each
adapter here resolves the default through whatever host stack is installed (torch-fidelity,
torchvision, transformers + locally cached weights) and exposes it as a plain
``jnp array -> jnp array`` host callable. When the stack is truly absent the adapters raise
the reference's exact ``ModuleNotFoundError`` text, so reference users see identical behavior.

Everything in this module runs OUTSIDE jit on the host; only the returned features enter the
device-side metric state.
"""
from __future__ import annotations

import importlib.util
import os
from typing import Any, Callable, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array


def _package_available(name: str) -> bool:
    try:
        return importlib.util.find_spec(name) is not None
    except Exception:
        return False


_TORCH_AVAILABLE = _package_available("torch")
_TORCH_FIDELITY_AVAILABLE = _package_available("torch_fidelity")
_TORCHVISION_AVAILABLE = _package_available("torchvision")
_LPIPS_AVAILABLE = _package_available("lpips")
_TRANSFORMERS_AVAILABLE = _package_available("transformers")


def hf_model_cached(model_id: str) -> bool:
    """True if ``model_id`` has a snapshot in the local HuggingFace cache (no network touch)."""
    if not _TRANSFORMERS_AVAILABLE:
        return False
    try:
        from huggingface_hub import constants

        cache_dir = constants.HF_HUB_CACHE
    except Exception:
        cache_dir = os.path.expanduser("~/.cache/huggingface/hub")
    folder = os.path.join(cache_dir, "models--" + model_id.replace("/", "--"))
    snapshots = os.path.join(folder, "snapshots")
    return os.path.isdir(snapshots) and bool(os.listdir(snapshots))


def host_reachable(host: str, port: int = 443) -> bool:
    """One cheap DNS resolution — zero-egress environments fail this instantly, skipping a
    download client's multi-minute retry/backoff loop (HF hub, nltk, ...)."""
    import socket

    try:
        socket.getaddrinfo(host, port)
        return True
    except OSError:
        return False


def _hub_reachable() -> bool:
    return host_reachable("huggingface.co")


def _from_pretrained(cls: Any, model_id: str, **kwargs: Any) -> Any:
    """Cache-first ``from_pretrained``: try the local snapshot, then the network (reference
    behavior) — so zero-egress environments fail fast instead of waiting on hub retries."""
    try:
        return cls.from_pretrained(model_id, local_files_only=True, **kwargs)
    except Exception:
        if not _hub_reachable():
            raise
        return cls.from_pretrained(model_id, **kwargs)


# ---------------------------------------------------------------------------
# InceptionV3 features for FID / KID / IS / MiFID
# ---------------------------------------------------------------------------

def inception_feature_extractor(
    feature: Any, metric_display: str
) -> Callable[[Array], Array]:
    """Resolve the reference's integer/str ``feature`` argument to a host extractor.

    ``feature`` ∈ {64, 192, 768, 2048} selects the torch-fidelity InceptionV3 block;
    ``"logits_unbiased"`` selects the IS logits head. Raises the reference's exact
    ``ModuleNotFoundError`` when torch-fidelity is not installed
    (``/root/reference/src/torchmetrics/image/fid.py:286-289``).
    """
    if not (_TORCH_AVAILABLE and _TORCH_FIDELITY_AVAILABLE):
        raise ModuleNotFoundError(
            f"{metric_display} metric requires that `Torch-fidelity` is installed."
            " Either install as `pip install torchmetrics[image]` or `pip install torch-fidelity`."
        )
    import torch
    from torch_fidelity.feature_extractor_inceptionv3 import FeatureExtractorInceptionV3

    name = str(feature)
    net = FeatureExtractorInceptionV3(name="inception-v3-compat", features_list=[name])
    net.eval()

    def extract(imgs: Array) -> Array:
        x = torch.as_tensor(np.asarray(imgs))
        if x.ndim == 3:
            x = x.unsqueeze(0)
        if x.dtype != torch.uint8:
            # mirror torch-fidelity's input assertion instead of silently truncating floats
            raise ValueError(
                "The InceptionV3 extractor expects uint8 images in [0, 255]; got dtype"
                f" {x.dtype}. Pass `normalize=True` for [0, 1] float inputs."
            )
        with torch.no_grad():
            (out,) = net(x)
        return jnp.asarray(out.cpu().numpy())

    return extract


# ---------------------------------------------------------------------------
# LPIPS
# ---------------------------------------------------------------------------

def lpips_network(net_type: str) -> Callable[[Array, Array], Array]:
    """Pretrained LPIPS distance as a host callable ``(img1, img2) -> (N,)``.

    Raises the reference's exact error when torchvision is absent
    (``/root/reference/src/torchmetrics/image/lpip.py:115-118``).
    """
    if not (_TORCH_AVAILABLE and _TORCHVISION_AVAILABLE):
        raise ModuleNotFoundError(
            "LPIPS metric requires that torchvision is installed."
            " Either install as `pip install torchmetrics[image]` or `pip install torchvision`."
        )
    if not _LPIPS_AVAILABLE:  # torchvision backbones without the learned weights are not a parity path
        raise ModuleNotFoundError(
            "LPIPS metric requires the `lpips` package for its learned weights."
            " Install it with `pip install lpips`."
        )
    import torch
    import lpips as _lpips

    net = _lpips.LPIPS(net=net_type, verbose=False)
    net.eval()

    def distance(img1: Array, img2: Array) -> Array:
        t1 = torch.as_tensor(np.asarray(img1, np.float32))
        t2 = torch.as_tensor(np.asarray(img2, np.float32))
        with torch.no_grad():
            out = net(t1, t2, normalize=False)
        return jnp.asarray(out.reshape(-1).cpu().numpy())

    return distance


# ---------------------------------------------------------------------------
# CLIP (CLIPScore / CLIP-IQA)
# ---------------------------------------------------------------------------

def clip_encoders(
    model_id: str, rescale_uint8: bool = True
) -> Tuple[Callable[[Any], Array], Callable[[List[str]], Array]]:
    """(image_encoder, text_encoder) host callables over a HuggingFace CLIP checkpoint.

    Raises the reference's exact error when transformers is absent
    (``/root/reference/src/torchmetrics/functional/multimodal/clip_score.py:109-112``); raises a
    build-specific ``ModuleNotFoundError`` when transformers is present but the checkpoint
    cannot be loaded (no cache, no egress).
    """
    if not _TRANSFORMERS_AVAILABLE:
        raise ModuleNotFoundError(
            "`clip_score` metric requires `transformers` package be installed."
            " Either install with `pip install transformers>=4.10.0` or `pip install torchmetrics[multimodal]`."
        )
    try:
        import torch
        from transformers import CLIPModel, CLIPProcessor

        model = _from_pretrained(CLIPModel, model_id)
        processor = _from_pretrained(CLIPProcessor, model_id)
        model.eval()
    except Exception as err:
        raise ModuleNotFoundError(
            f"Loading CLIP checkpoint {model_id!r} failed (no local cache and no network egress"
            " in this build). Pass `model_name_or_path` as a pair of callables"
            " (image_encoder, text_encoder) instead."
        ) from err

    def image_encoder(images: Any) -> Array:
        imgs = [torch.as_tensor(np.asarray(i)) for i in images]
        with torch.no_grad():
            inp = processor(images=imgs, return_tensors="pt", padding=True, do_rescale=rescale_uint8)
            feats = model.get_image_features(inp["pixel_values"])
        return jnp.asarray(feats.cpu().numpy())

    def text_encoder(text: List[str]) -> Array:
        with torch.no_grad():
            inp = processor(text=list(text), return_tensors="pt", padding=True)
            max_pos = model.config.text_config.max_position_embeddings
            feats = model.get_text_features(
                inp["input_ids"][..., :max_pos], inp["attention_mask"][..., :max_pos]
            )
        return jnp.asarray(feats.cpu().numpy())

    return image_encoder, text_encoder


# ---------------------------------------------------------------------------
# BERT (BERTScore / InfoLM)
# ---------------------------------------------------------------------------

def torch_bert_encoder(
    model: Any,
    tokenizer: Any,
    forward_fn: Optional[Callable] = None,
    num_layers: Optional[int] = None,
    max_length: int = 512,
    all_layers: bool = False,
):
    """Encoder over a USER-SUPPLIED torch model + HF-style tokenizer (the reference's
    ``own_model``/``user_tokenizer``/``user_forward_fn`` path, ``functional/text/bert.py:95-115``).

    ``forward_fn(model, batch_dict) -> (N, L, D)`` overrides the default
    ``model(input_ids, attention_mask, output_hidden_states=True)`` call. Special [CLS]/[SEP]
    positions are zeroed from the mask the way the reference does
    (``helper_embedding_metric.py:33-48``: first position, plus the last attended position).
    """
    import torch

    def _special_free_mask(attention_mask: "torch.Tensor") -> "torch.Tensor":
        mask = attention_mask.clone()
        mask[:, 0] = 0
        sep_pos = torch.cumsum(mask - 0.1, dim=-1).argmax(-1)
        mask[torch.arange(mask.size(0)), sep_pos] = 0
        return mask

    def encoder(sentences: List[str]):
        batch = tokenizer(
            sentences, return_tensors="pt", padding=True, truncation=True, max_length=max_length
        )
        with torch.no_grad():
            if all_layers:
                out = model(batch["input_ids"], batch["attention_mask"], output_hidden_states=True)
                hidden = torch.stack(out.hidden_states, dim=1)  # (N, Λ, L, D)
            elif forward_fn is not None:
                hidden = forward_fn(model, dict(batch))
            else:
                out = model(batch["input_ids"], batch["attention_mask"], output_hidden_states=True)
                hidden = out.hidden_states[num_layers if num_layers is not None else -1]
        mask = _special_free_mask(batch["attention_mask"])
        return jnp.asarray(hidden.cpu().numpy()), jnp.asarray(mask.cpu().numpy())

    def tokenize(sentences: List[str]) -> Tuple[np.ndarray, np.ndarray]:
        batch = tokenizer(
            sentences, return_tensors="pt", padding=True, truncation=True, max_length=max_length
        )
        mask = _special_free_mask(batch["attention_mask"])
        return np.asarray(batch["input_ids"].numpy(), np.int64), np.asarray(mask.numpy())

    # same composition contract as bert_encoder: an all_layers builder returns the
    # (N, Λ, L, D) stack, so tag it for bert_score's all_layers+encoder check
    encoder.layer_stacked = bool(all_layers)
    return encoder, tokenize


def hf_bert_model_and_tokenizer(
    model_id: str, load_model: bool = True, load_tokenizer: bool = True
) -> Tuple[Any, Any]:
    """Raw (model, tokenizer) over a cached HF checkpoint — for callers that mix a resolved
    model with user-supplied tokenizer/forward hooks (reference ``text/bert.py:95-115``).
    Only the requested pieces are loaded (checkpoint weights are ~GBs); the other slot of the
    returned pair is ``None``."""
    if not _TRANSFORMERS_AVAILABLE:
        raise ModuleNotFoundError(
            "`bert_score` metric requires `transformers` package be installed."
            " Either install with `pip install transformers` or `pip install torchmetrics[text]`."
        )
    try:
        from transformers import AutoModel, AutoTokenizer

        tokenizer = _from_pretrained(AutoTokenizer, model_id) if load_tokenizer else None
        model = _from_pretrained(AutoModel, model_id) if load_model else None
        if model is not None:
            model.eval()
    except Exception as err:
        raise ModuleNotFoundError(
            f"Loading checkpoint {model_id!r} failed (no local cache and no network egress"
            " in this build). Pass an `encoder` callable `(sentences) -> (embeddings, mask)` instead."
        ) from err
    return model, tokenizer


def bert_encoder(
    model_id: str, num_layers: Optional[int] = None, max_length: int = 512,
    all_layers: bool = False,
):
    """``sentences -> (hidden (N, L, D), mask (N, L))`` host callable over a cached HF model.

    Also returns the tokenizer-level tokenize function used by idf weighting. Result is
    ``(encoder, tokenize)`` where ``tokenize(sentences) -> (ids (N, L) np.int64, mask (N, L))``.
    """
    if not _TRANSFORMERS_AVAILABLE:
        raise ModuleNotFoundError(
            "`bert_score` metric requires `transformers` package be installed."
            " Either install with `pip install transformers` or `pip install torchmetrics[text]`."
        )
    try:
        import torch
        from transformers import AutoModel, AutoTokenizer

        tokenizer = _from_pretrained(AutoTokenizer, model_id)
        model = _from_pretrained(AutoModel, model_id)
        model.eval()
    except Exception as err:
        raise ModuleNotFoundError(
            f"Loading checkpoint {model_id!r} failed (no local cache and no network egress"
            " in this build). Pass an `encoder` callable `(sentences) -> (embeddings, mask)` instead."
        ) from err

    def tokenize(sentences: List[str]) -> Tuple[np.ndarray, np.ndarray]:
        batch = tokenizer(
            sentences, return_tensors="np", padding=True, truncation=True, max_length=max_length,
            return_special_tokens_mask=True,
        )
        mask = batch["attention_mask"] * (1 - batch["special_tokens_mask"])
        return np.asarray(batch["input_ids"], np.int64), np.asarray(mask)

    def encoder(sentences: List[str]):
        with torch.no_grad():
            batch = tokenizer(
                sentences, return_tensors="pt", padding=True, truncation=True, max_length=max_length,
                return_special_tokens_mask=True,
            )
            special = batch.pop("special_tokens_mask")
            out = model(**batch, output_hidden_states=True)
            if all_layers:
                hidden = torch.stack(out.hidden_states, dim=1)  # (N, Λ, L, D)
            else:
                hidden = out.hidden_states[num_layers if num_layers is not None else -1]
        mask = batch["attention_mask"] * (1 - special)
        return jnp.asarray(hidden.cpu().numpy()), jnp.asarray(mask.cpu().numpy())

    # lets bert_score distinguish a default-built (N, Λ, L, D) encoder from a user 3-D one,
    # so a cached all_layers encoder composes with the all_layers=True flag
    encoder.layer_stacked = bool(all_layers)
    return encoder, tokenize
