"""Utility layer (reference ``src/torchmetrics/utilities/__init__.py``)."""
from torchmetrics_tpu.utils.checks import _check_same_shape, is_traced
from torchmetrics_tpu.utils.compute import _safe_divide, _safe_xlogy, auc, interp
from torchmetrics_tpu.utils.data import (
    _bincount,
    _cumsum,
    _flexible_bincount,
    allclose,
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
    select_topk,
    to_categorical,
    to_onehot,
)
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError, TorchMetricsUserWarning
from torchmetrics_tpu.utils.prints import rank_zero_debug, rank_zero_info, rank_zero_warn

__all__ = [
    "_check_same_shape",
    "is_traced",
    "_safe_divide",
    "_safe_xlogy",
    "auc",
    "interp",
    "_bincount",
    "_cumsum",
    "_flexible_bincount",
    "allclose",
    "dim_zero_cat",
    "dim_zero_max",
    "dim_zero_mean",
    "dim_zero_min",
    "dim_zero_sum",
    "select_topk",
    "to_categorical",
    "to_onehot",
    "TorchMetricsUserError",
    "TorchMetricsUserWarning",
    "rank_zero_debug",
    "rank_zero_info",
    "rank_zero_warn",
]
