"""Small numerical kernels shared across metrics.

Parity: reference ``src/torchmetrics/utilities/compute.py`` (``_safe_divide:46``,
``_safe_xlogy:31``, ``_auc_compute_without_check:88``, ``interp:134``). All functions are pure
jax and safe to call under ``jit``.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import Array


def _safe_matmul(x: Array, y: Array) -> Array:
    """Matmul with float32 accumulation (MXU-friendly on TPU)."""
    return jnp.matmul(x, y, precision="highest")


def _safe_divide(num: Array, denom: Array, zero_division: float = 0.0) -> Array:
    """Elementwise ``num / denom`` returning ``zero_division`` where ``denom == 0``.

    Unlike a post-hoc ``nan_to_num``, the denominator is patched *before* the division so no
    inf/nan is ever produced (keeps XLA happy and gradients finite).
    """
    num = num if jnp.issubdtype(jnp.asarray(num).dtype, jnp.floating) else jnp.asarray(num, jnp.float32)
    denom = denom if jnp.issubdtype(jnp.asarray(denom).dtype, jnp.floating) else jnp.asarray(denom, jnp.float32)
    zero_mask = denom == 0
    patched = jnp.where(zero_mask, jnp.ones_like(denom), denom)
    return jnp.where(zero_mask, jnp.asarray(zero_division, num.dtype), num / patched)


def _safe_xlogy(x: Array, y: Array) -> Array:
    """``x * log(y)`` that is 0 where ``x == 0`` (even if ``y == 0``)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    res = jnp.where(x == 0, 0.0, x * jnp.log(jnp.where(x == 0, 1.0, y)))
    return res


def _adjust_weights_safe_divide(
    score: Array, average: Optional[str], multilabel: bool, tp: Array, fp: Array, fn: Array,
    top_k: int = 1,
) -> Array:
    """Apply micro/macro/weighted reduction of a per-class ``score``."""
    if average is None or average == "none":
        return score
    if average == "weighted":
        weights = (tp + fn).astype(score.dtype)
    else:
        weights = jnp.ones_like(score)
        if not multilabel:
            zero = (tp + fp + fn == 0) if top_k == 1 else (tp + fn == 0)
            weights = jnp.where(zero, 0.0, weights)
    return _safe_divide(jnp.sum(weights * score, axis=-1), jnp.sum(weights, axis=-1))


def _auc_compute_without_check(x: Array, y: Array, direction: float, axis: int = -1) -> Array:
    """Trapezoidal area under (x, y); ``direction`` flips sign for descending x."""
    dx = jnp.diff(x, axis=axis)
    y_avg = (jnp.take(y, jnp.arange(1, y.shape[axis]), axis=axis) + jnp.take(y, jnp.arange(0, y.shape[axis] - 1), axis=axis)) / 2.0
    return jnp.sum(dx * y_avg, axis=axis) * direction


def _auc_compute(x: Array, y: Array, reorder: bool = False) -> Array:
    if reorder:
        order = jnp.argsort(x)
        x = x[order]
        y = y[order]
    return _auc_compute_without_check(x, y, 1.0)


def auc(x: Array, y: Array, reorder: bool = False) -> Array:
    """Area under the curve y=f(x) via the trapezoidal rule."""
    return _auc_compute(x, y, reorder=reorder)


def interp(x: Array, xp: Array, fp: Array) -> Array:
    """1-D linear interpolation, monotonically increasing ``xp`` (reference ``compute.py:134``)."""
    return jnp.interp(x, xp, fp)


def normalize_logits_if_needed(preds: Array, normalization: str = "sigmoid") -> Array:
    """Apply sigmoid/softmax only when ``preds`` is not already a probability.

    The reference branches on ``preds.min() < 0 or preds.max() > 1`` at trace time; under XLA
    that is a data-dependent decision, so the predicate is computed on-device and the branch
    picked with ``lax.cond`` — only the taken branch executes at runtime, so already-normalised
    probabilities skip the transcendental pass entirely (sigmoid's ``exp`` over 1M elements
    costs ~20ms on the CPU backend, ~10x the min/max predicate). Under vmap, ``cond``
    degrades to computing both branches — identical to the previous ``where`` formulation.
    """
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        return preds
    outside = (jnp.min(preds) < 0) | (jnp.max(preds) > 1)
    if normalization == "sigmoid":
        return jax.lax.cond(outside, jax.nn.sigmoid, lambda x: x, preds)
    return jax.lax.cond(outside, lambda x: jax.nn.softmax(x, axis=-1), lambda x: x, preds)
