"""String-valued enums for task / averaging dispatch.

Parity: reference ``src/torchmetrics/utilities/enums.py:56-154``.
"""
from __future__ import annotations

from enum import Enum
from typing import Optional


class EnumStr(str, Enum):
    """Base for case-insensitive string enums (``from_str`` resolves ``"Macro"`` → ``MACRO``)."""

    @staticmethod
    def _name() -> str:
        return "Task"

    @classmethod
    def from_str(cls, value: str, source: str = "key") -> "EnumStr":
        try:
            return cls[value.replace("-", "_").upper()]
        except KeyError:
            valid = [m.lower() for m in cls.__members__]
            raise ValueError(f"Invalid {cls._name()}: expected one of {valid}, but got {value}.") from None

    def __str__(self) -> str:
        return self.value.lower()


class DataType(EnumStr):
    """Type of an input batch (reference ``enums.py:56``)."""

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"

    @staticmethod
    def _name() -> str:
        return "Data type"


class AverageMethod(EnumStr):
    """Averaging strategy over classes (reference ``enums.py:74``)."""

    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = None  # type: ignore[assignment]
    SAMPLES = "samples"

    @staticmethod
    def _name() -> str:
        return "Average method"


class MDMCAverageMethod(EnumStr):
    """Multi-dim multi-class averaging (reference ``enums.py:97``)."""

    GLOBAL = "global"
    SAMPLEWISE = "samplewise"


class ClassificationTask(EnumStr):
    """Classification task dispatch key (reference ``enums.py:108``)."""

    BINARY = "binary"
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"

    @staticmethod
    def _name() -> str:
        return "Classification task"


class ClassificationTaskNoBinary(EnumStr):
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"

    @staticmethod
    def _name() -> str:
        return "Classification task"


class ClassificationTaskNoMultilabel(EnumStr):
    BINARY = "binary"
    MULTICLASS = "multiclass"

    @staticmethod
    def _name() -> str:
        return "Classification task"


def _validate_average(average: Optional[str], allowed: tuple = ("micro", "macro", "weighted", "none", None)) -> None:
    if average not in allowed:
        raise ValueError(f"Argument `average` has to be one of {allowed}, got {average}.")
