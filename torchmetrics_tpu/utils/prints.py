"""Rank-gated printing / warning helpers.

Parity: reference ``src/torchmetrics/utilities/prints.py:22-73``. TPU-native twist: the rank is
``jax.process_index()`` when JAX is initialised, falling back to the usual env vars so the helpers
work before distributed init.
"""
from __future__ import annotations

import logging
import os
import warnings
from functools import partial, wraps
from typing import Any, Callable

log = logging.getLogger("torchmetrics_tpu")

# One-shot warning semantics: a (message, category) pair fires at most once per process, so
# per-step warnings (e.g. the obs retrace-churn detector, compute-before-update) cannot spam a
# training loop. Tests reset via reset_warning_cache() (autouse fixture in the suite).
_SEEN_WARNINGS: set = set()
_SEEN_WARNINGS_CAP = 10_000  # bound memory for pathological message churn


def reset_warning_cache() -> None:
    """Clear the one-shot warning memo so deduplicated warnings can fire again."""
    _SEEN_WARNINGS.clear()


def _get_rank() -> int:
    for env in ("LOCAL_RANK", "RANK", "PROCESS_ID"):
        if env in os.environ:
            try:
                return int(os.environ[env])
            except ValueError:
                pass
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def rank_zero_only(fn: Callable) -> Callable:
    """Run ``fn`` only on process 0."""

    @wraps(fn)
    def wrapped_fn(*args: Any, **kwargs: Any) -> Any:
        if _get_rank() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped_fn


@rank_zero_only
def rank_zero_warn(message: str, category: type = UserWarning, stacklevel: int = 5, **kwargs: Any) -> None:
    key = (str(message), category)
    if key in _SEEN_WARNINGS:
        return
    if len(_SEEN_WARNINGS) >= _SEEN_WARNINGS_CAP:
        _SEEN_WARNINGS.clear()
    _SEEN_WARNINGS.add(key)
    warnings.warn(message, category=category, stacklevel=stacklevel, **kwargs)


@rank_zero_only
def rank_zero_info(message: str, **kwargs: Any) -> None:
    log.info(message, **kwargs)


@rank_zero_only
def rank_zero_debug(message: str, **kwargs: Any) -> None:
    log.debug(message, **kwargs)


def _future_warning(message: str) -> None:
    warnings.warn(message, FutureWarning, stacklevel=5)


rank_zero_deprecation = rank_zero_only(partial(_future_warning))
