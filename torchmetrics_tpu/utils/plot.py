"""Plotting helpers (matplotlib-optional).

Parity: reference ``src/torchmetrics/utilities/plot.py`` (``plot_single_or_multi_val:62``,
``plot_confusion_matrix:199``, ``plot_curve:268``).
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as np

from torchmetrics_tpu.utils.imports import _MATPLOTLIB_AVAILABLE

if _MATPLOTLIB_AVAILABLE:
    import matplotlib
    import matplotlib.pyplot as plt

    _AX_TYPE = "matplotlib.axes.Axes"
    _PLOT_OUT_TYPE = Tuple["plt.Figure", Union["matplotlib.axes.Axes", np.ndarray]]

    style_change = plt.style.context  # reference ``plot.py:32``: themeable plot context
else:
    from contextlib import contextmanager

    _AX_TYPE = Any
    _PLOT_OUT_TYPE = Tuple[Any, Any]

    @contextmanager
    def style_change(*args: Any, **kwargs: Any):
        """No-op stand-in when matplotlib is absent."""
        yield


def _error_on_missing_matplotlib() -> None:
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(
            "Plot function expects `matplotlib` to be installed. Install with `pip install matplotlib`."
        )


def _get_col_row_split(n: int) -> Tuple[int, int]:
    """Near-square (rows, cols) grid that fits ``n`` panels (reference ``plot.py:172``)."""
    nsq = np.sqrt(n)
    if nsq * nsq == n:
        return int(nsq), int(nsq)
    if np.floor(nsq) * np.ceil(nsq) >= n:
        return int(np.floor(nsq)), int(np.ceil(nsq))
    return int(np.ceil(nsq)), int(np.ceil(nsq))


def trim_axs(axs, nb: int):
    """Hide grid axes beyond the ``nb`` used panels; return the used ones (reference ``plot.py:182``)."""
    if isinstance(axs, np.ndarray):
        flat = axs.ravel()
        for ax in flat[nb:]:
            ax.set_visible(False)
        return flat[:nb]
    return axs


def plot_single_or_multi_val(
    val,
    ax=None,
    higher_is_better: Optional[bool] = None,
    name: Optional[str] = None,
    lower_bound: Optional[float] = None,
    upper_bound: Optional[float] = None,
    legend_name: Optional[str] = None,
):
    """Plot a single or sequence of (possibly dict-valued) metric values (reference ``plot.py:62``)."""
    _error_on_missing_matplotlib()
    fig, ax = (ax.get_figure(), ax) if ax is not None else plt.subplots()
    if isinstance(val, dict):
        for i, (k, v) in enumerate(val.items()):
            ax.plot(i, np.asarray(v), "o", label=k)
    elif isinstance(val, Sequence):
        n_steps = len(val)
        if isinstance(val[0], dict):
            val_dict = {k: [np.asarray(v[k]) for v in val] for k in val[0]}
            for k, v in val_dict.items():
                ax.plot(range(n_steps), np.stack([np.atleast_1d(x) for x in v]), label=k)
        else:
            arr = np.stack([np.atleast_1d(np.asarray(v)) for v in val])
            for c in range(arr.shape[1]):
                lbl = f"{legend_name or 'class'} {c}" if arr.shape[1] > 1 else None
                ax.plot(range(n_steps), arr[:, c], marker="o", label=lbl)
    else:
        arr = np.atleast_1d(np.asarray(val))
        for c, v in enumerate(arr):
            lbl = f"{legend_name or 'class'} {c}" if arr.size > 1 else None
            ax.plot([0], [v], "o", label=lbl)
    if name is not None:
        ax.set_title(name)
    handles, labels = ax.get_legend_handles_labels()
    if labels:
        ax.legend()
    ax.grid(True)
    # metric bounds as dashed guides, with the optimal side annotated (reference plot.py:138-168)
    bounds = [b for b in (lower_bound, upper_bound) if b is not None]
    if bounds:
        ylim = ax.get_ylim()
        pad = 0.1 * ((upper_bound - lower_bound) if len(bounds) == 2 else (ylim[1] - ylim[0]))
        ax.set_ylim(
            bottom=(lower_bound - pad) if lower_bound is not None else ylim[0] - pad,
            top=(upper_bound + pad) if upper_bound is not None else ylim[1] + pad,
        )
        xlim = ax.get_xlim()
        ax.hlines(bounds, xlim[0], xlim[1], linestyles="dashed", colors="k")
        optimal = (
            upper_bound if (higher_is_better and upper_bound is not None)
            else lower_bound if (higher_is_better is False and lower_bound is not None)
            else None
        )
        if optimal is not None:
            ax.set_xlim(xlim[0] - 0.1 * (xlim[1] - xlim[0]), xlim[1])
            ax.text(xlim[0], optimal, s="Optimal \n value", ha="center", va="center")
    return fig, ax


def plot_confusion_matrix(
    confmat,
    ax=None,
    add_text: bool = True,
    labels: Optional[List[str]] = None,
    cmap: Optional[str] = None,
):
    """Heatmap of a (C, C) (or (N, 2, 2) multilabel) confusion matrix (reference ``plot.py:199``)."""
    _error_on_missing_matplotlib()
    confmat = np.asarray(confmat)
    if confmat.ndim == 3:  # multilabel
        nb, rows, cols = confmat.shape
    else:
        nb, rows, cols = 1, *confmat.shape
        confmat = confmat[None]
    if labels is not None and confmat.ndim != 3 and len(labels) != rows:
        raise ValueError("Expected number of elements in arg `labels` to match number of labels in confmat")
    labels = labels or np.arange(rows).tolist()
    if ax is None:
        grid_rows, grid_cols = _get_col_row_split(nb)
        fig, axs = plt.subplots(nrows=grid_rows, ncols=grid_cols)
        axs = trim_axs(axs, nb)
    else:
        fig, axs = ax.get_figure(), ax
    axs_list = np.atleast_1d(np.asarray(axs, dtype=object)).ravel().tolist()
    for i in range(nb):
        ax_i = axs_list[i] if i < len(axs_list) else axs_list[0]
        im = ax_i.imshow(confmat[i], cmap=cmap)
        ax_i.set_xlabel("Predicted class")
        ax_i.set_ylabel("True class")
        ax_i.set_xticks(range(cols))
        ax_i.set_yticks(range(rows))
        ax_i.set_xticklabels(labels, rotation=45)
        ax_i.set_yticklabels(labels)
        if add_text:
            for ii in range(rows):
                for jj in range(cols):
                    ax_i.text(jj, ii, str(round(float(confmat[i, ii, jj]), 2)), ha="center", va="center")
    fig.colorbar(im)
    return fig, axs


def plot_curve(
    curve: Tuple,
    score=None,
    ax=None,
    label_names: Optional[Tuple[str, str]] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
):
    """Plot a (x, y, thresholds)-style curve (reference ``plot.py:268``)."""
    _error_on_missing_matplotlib()
    x, y = np.asarray(curve[0]), np.asarray(curve[1])
    fig, ax = (ax.get_figure(), ax) if ax is not None else plt.subplots()
    if y.ndim > 1:
        for i in range(y.shape[0]):
            lbl = f"{legend_name or 'class'} {i}"
            if score is not None and np.ndim(score) > 0:
                lbl += f" AUC={float(np.asarray(score).ravel()[i]):0.3f}"
            ax.plot(x[i] if x.ndim > 1 else x, y[i], linestyle="-", linewidth=2, label=lbl)
    else:
        lbl = None
        if score is not None:
            lbl = f"AUC={float(np.asarray(score)):0.3f}"
        ax.plot(x, y, linestyle="-", linewidth=2, label=lbl)
    if label_names is not None:
        ax.set_xlabel(label_names[0])
        ax.set_ylabel(label_names[1])
    if name is not None:
        ax.set_title(name)
    handles, labels = ax.get_legend_handles_labels()
    if labels:
        ax.legend()
    return fig, ax
