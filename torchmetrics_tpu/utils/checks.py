"""Host-side input validation.

Parity: reference ``src/torchmetrics/utilities/checks.py`` (``_check_same_shape:39``,
``_check_retrieval_inputs:540``). XLA note: value-dependent checks (e.g. "targets must be in
[0, C)") cannot run inside a traced computation, so every check here no-ops when handed tracers —
metrics call them from the host shell before dispatching to the jitted kernel, matching the
reference's ``validate_args`` contract (``functional/classification/stat_scores.py:48-87``).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array


def is_traced(*arrays) -> bool:
    """True if any input is an abstract tracer (inside jit/vmap/scan)."""
    return any(isinstance(a, jax.core.Tracer) for a in arrays)


def _check_same_shape(preds: Array, target: Array) -> None:
    """Raise if shapes differ (shape is static — safe even under trace)."""
    if jnp.shape(preds) != jnp.shape(target):
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, but got {jnp.shape(preds)} and"
            f" {jnp.shape(target)}."
        )


def _check_valid_int_labels(x: Array, num_classes: int, name: str, ignore_index: Optional[int] = None) -> None:
    if is_traced(x):
        return
    xv = np.asarray(x)
    if ignore_index is not None:
        xv = xv[xv != ignore_index]
    if xv.size and (xv.min() < 0 or xv.max() >= num_classes):
        raise RuntimeError(
            f"Detected more unique values in `{name}` than expected. Expected only {num_classes} values in"
            f" range [0, {num_classes}), but found values in range [{xv.min()}, {xv.max()}]."
        )


def _check_probabilities(x: Array, name: str = "preds") -> None:
    if is_traced(x):
        return
    xv = np.asarray(x)
    if xv.size and (xv.min() < 0 or xv.max() > 1):
        raise ValueError(f"`{name}` should be probabilities in [0,1], but got values outside that range.")


def _check_retrieval_inputs(
    indexes: Array, preds: Array, target: Array, allow_non_binary_target: bool = False,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array]:
    """Validate + flatten retrieval triplets (reference ``checks.py:540``)."""
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `targets` must be of the same shape")
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    indexes, preds, target = jnp.reshape(indexes, (-1,)), jnp.reshape(preds, (-1,)), jnp.reshape(target, (-1,))
    if not is_traced(target):
        tv = np.asarray(target)
        if ignore_index is not None:
            tv = tv[tv != ignore_index]
        if not allow_non_binary_target and tv.size and (tv.max() > 1 or tv.min() < 0):
            raise ValueError("`target` must contain `binary` values")
    return indexes, preds, target


def _check_retrieval_functional_inputs(
    preds: Array, target: Array, allow_non_binary_target: bool = False
) -> Tuple[Array, Array]:
    if preds.shape != target.shape:
        raise ValueError("`preds` and `target` must be of the same shape")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    preds, target = jnp.reshape(preds, (-1,)), jnp.reshape(target, (-1,))
    if not allow_non_binary_target and not is_traced(target):
        tv = np.asarray(target)
        if tv.size and (tv.max() > 1 or tv.min() < 0):
            raise ValueError("`target` must contain `binary` values")
    return preds, target
