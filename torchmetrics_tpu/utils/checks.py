"""Host-side input validation.

Parity: reference ``src/torchmetrics/utilities/checks.py`` (``_check_same_shape:39``,
``_check_retrieval_inputs:540``). XLA note: value-dependent checks (e.g. "targets must be in
[0, C)") cannot run inside a traced computation, so every check here no-ops when handed tracers —
metrics call them from the host shell before dispatching to the jitted kernel, matching the
reference's ``validate_args`` contract (``functional/classification/stat_scores.py:48-87``).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array


def is_traced(*arrays) -> bool:
    """True if any input is an abstract tracer (inside jit/vmap/scan)."""
    return any(isinstance(a, jax.core.Tracer) for a in arrays)


def _check_same_shape(preds: Array, target: Array) -> None:
    """Raise if shapes differ (shape is static — safe even under trace)."""
    if jnp.shape(preds) != jnp.shape(target):
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, but got {jnp.shape(preds)} and"
            f" {jnp.shape(target)}."
        )


def _check_valid_int_labels(x: Array, num_classes: int, name: str, ignore_index: Optional[int] = None) -> None:
    if is_traced(x):
        return
    xv = np.asarray(x)
    if ignore_index is not None:
        xv = xv[xv != ignore_index]
    if xv.size and (xv.min() < 0 or xv.max() >= num_classes):
        raise RuntimeError(
            f"Detected more unique values in `{name}` than expected. Expected only {num_classes} values in"
            f" range [0, {num_classes}), but found values in range [{xv.min()}, {xv.max()}]."
        )


def _check_probabilities(x: Array, name: str = "preds") -> None:
    if is_traced(x):
        return
    xv = np.asarray(x)
    if xv.size and (xv.min() < 0 or xv.max() > 1):
        raise ValueError(f"`{name}` should be probabilities in [0,1], but got values outside that range.")


def _check_retrieval_inputs(
    indexes: Array, preds: Array, target: Array, allow_non_binary_target: bool = False,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array]:
    """Validate + flatten retrieval triplets (reference ``checks.py:540``)."""
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `targets` must be of the same shape")
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    indexes, preds, target = jnp.reshape(indexes, (-1,)), jnp.reshape(preds, (-1,)), jnp.reshape(target, (-1,))
    if not is_traced(target):
        tv = np.asarray(target)
        if ignore_index is not None:
            tv = tv[tv != ignore_index]
        if not allow_non_binary_target and tv.size and (tv.max() > 1 or tv.min() < 0):
            raise ValueError("`target` must contain `binary` values")
    return indexes, preds, target


def _check_retrieval_functional_inputs(
    preds: Array, target: Array, allow_non_binary_target: bool = False
) -> Tuple[Array, Array]:
    if preds.shape != target.shape:
        raise ValueError("`preds` and `target` must be of the same shape")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    preds, target = jnp.reshape(preds, (-1,)), jnp.reshape(target, (-1,))
    if not allow_non_binary_target and not is_traced(target):
        tv = np.asarray(target)
        if tv.size and (tv.max() > 1 or tv.min() < 0):
            raise ValueError("`target` must contain `binary` values")
    return preds, target


def _allclose_recursive(res1, res2, atol: float = 1e-6) -> bool:
    """Elementwise closeness over nested dict/sequence results (reference ``checks.py:614-633``)."""
    if isinstance(res1, dict):
        return all(_allclose_recursive(res1[k], res2[k], atol) for k in res1)
    if isinstance(res1, (list, tuple)):
        return all(_allclose_recursive(r1, r2, atol) for r1, r2 in zip(res1, res2))
    return bool(np.allclose(np.asarray(res1), np.asarray(res2), atol=atol))


def check_forward_full_state_property(
    metric_class,
    init_args=None,
    input_args=None,
    num_update_to_compare=(10, 100, 1000),
    reps: int = 5,
) -> None:
    """Profile whether a metric can safely run the reduce-state ``forward`` fast path.

    Analog of reference ``utilities/checks.py:636``, extended for the TPU engine (SURVEY §5):
    besides full-state vs reduce-state ``forward`` timing/agreement, it also times the fused
    ``update_batches`` ``lax.scan`` sweep against the per-batch ``update`` loop — the two axes a
    metric author tunes on this engine.
    """
    import time

    import jax

    init_args = init_args or {}
    input_args = input_args or {}

    class FullState(metric_class):
        full_state_update = True

    class PartState(metric_class):
        full_state_update = False

    fullstate = FullState(**init_args)
    partstate = PartState(**init_args)

    equal = True
    try:  # failure usually means update needs access to the full accumulated state
        for _ in range(num_update_to_compare[0]):
            equal = equal and _allclose_recursive(fullstate(**input_args), partstate(**input_args))
        res1 = fullstate.compute()
        res2 = partstate.compute()
        equal = equal and _allclose_recursive(res1, res2)
    except Exception:
        equal = False

    if not equal:
        print("Recommended setting `full_state_update=True`")
        return

    timings = np.zeros((2, len(num_update_to_compare), reps))
    for i, metric in enumerate((fullstate, partstate)):
        for j, steps in enumerate(num_update_to_compare):
            for r in range(reps):
                metric.reset()
                start = time.perf_counter()
                for _ in range(steps):
                    out = metric(**input_args)
                jax.block_until_ready(out)
                timings[i, j, r] = time.perf_counter() - start
            label = "Full" if i == 0 else "Partial"
            print(f"{label} state for {steps} steps took: {timings[i, j].mean():.4f}s")

    # fused-scan sweep vs per-batch loop (engine-specific axis)
    try:
        stacked = {
            k: jnp.stack([jnp.asarray(v)] * num_update_to_compare[0]) for k, v in input_args.items()
        }
        metric = PartState(**init_args)
        metric.update_batches(**stacked)  # compile
        metric.reset()
        start = time.perf_counter()
        metric.update_batches(**stacked)
        jax.block_until_ready(list(metric._state.tensors.values()))
        scan_time = time.perf_counter() - start
        metric.reset()
        start = time.perf_counter()
        for _ in range(num_update_to_compare[0]):
            metric.update(**input_args)
        jax.block_until_ready(list(metric._state.tensors.values()))
        loop_time = time.perf_counter() - start
        print(
            f"Fused update_batches for {num_update_to_compare[0]} steps took: {scan_time:.4f}s"
            f" vs per-batch loop {loop_time:.4f}s ({loop_time / max(scan_time, 1e-9):.1f}x)"
        )
    except Exception as err:
        print(f"update_batches sweep unavailable for this metric: {err!r}")

    faster = bool(timings[1].sum() < timings[0].sum())
    print(f"Recommended setting `full_state_update={not faster}`")
