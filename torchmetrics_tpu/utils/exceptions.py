"""User-facing exception types.

Parity: reference ``src/torchmetrics/utilities/exceptions.py:1-21``.
"""


class TorchMetricsUserError(Exception):
    """Error raised on wrong usage of the metric API."""


class TorchMetricsUserWarning(UserWarning):
    """Warning raised on questionable usage of the metric API."""


class NumericPoisonError(TorchMetricsUserError):
    """Raised at ``compute()`` when ``nan_policy="raise"`` detected non-finite inputs.

    The detection itself is in-graph (a poison-counter state accumulated alongside the
    metric state) so ``update``/``forward`` never pay a host sync; the single deferred
    host read happens here, at finalisation.
    """


class SnapshotError(TorchMetricsUserError):
    """Raised when a metric state snapshot cannot be taken or restored.

    Covers mid-flight snapshots (state buffers donated to an in-progress dispatch),
    snapshots with batches pending in a buffered accumulator, and restores of blobs
    that fail format/version/CRC/shape validation.
    """


class SyncTimeoutError(TorchMetricsUserError):
    """Raised when a bounded multi-process sync exhausts its deadline and retries.

    Only raised when degraded mode is off; with ``degraded_mode=True`` the sync instead
    falls back to local state and marks the result non-world-consistent.

    ``responses`` optionally carries the partial per-rank responses (``{rank: value}``)
    that DID arrive before the deadline — a quorum-capable gather attaches them so the
    sync layer can aggregate over the responding subset instead of dropping to
    local-only state (``SyncOptions(quorum=...)``, docs/robustness.md).
    """

    def __init__(self, *args, responses=None):
        super().__init__(*args)
        self.responses = responses


class JournalError(TorchMetricsUserError):
    """Raised when a write-ahead update journal cannot be appended, read, or replayed.

    Covers corrupted (CRC), truncated-mid-stream, or structurally alien journal records;
    a torn TAIL record (a crash mid-append on a filesystem that lost the rename) is
    tolerated with a warning instead — see ``torchmetrics_tpu.robust.journal``.
    """


class ServeError(TorchMetricsUserError):
    """Raised by the async ingestion tier (``torchmetrics_tpu.serve``) on engine faults.

    Covers a drain thread that died and could not be restarted, an enqueued batch whose
    deferred apply failed (surfaced at the next quiesce so ``compute()`` can never
    silently miss a committed-looking batch), and invalid ``ServeOptions``.
    """


class BackpressureError(ServeError):
    """Raised when the bounded in-flight window rejects an ``update_async`` enqueue.

    Fired immediately with ``ServeOptions(on_full="raise")``, or after
    ``queue_timeout_s`` of blocking with ``on_full="block"``. With ``on_full="shed"``
    the batch is dropped-and-counted instead and no exception is raised — see
    ``docs/serving.md`` for the on-full semantics table.
    """


class BundleError(TorchMetricsUserError):
    """Raised when a post-mortem flight bundle fails capture-time or read-time validation.

    Covers files that are not bundles (bad magic/truncated header), container or
    per-section CRC mismatches, unknown format versions, and bundles missing required
    sections — see ``torchmetrics_tpu.obs.bundle`` and docs/observability.md
    "Flight recorder & post-mortem bundles".
    """


class ReconciliationError(TorchMetricsUserError):
    """Raised when a rank re-admission handshake blob fails validation.

    The reconciliation offer wraps a quorum-merged snapshot; accepting it into a metric
    whose registered states/class do not match — or from an incompatible format version —
    fails loudly instead of silently merging mismatched state.
    """
