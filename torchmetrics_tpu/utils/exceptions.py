"""User-facing exception types.

Parity: reference ``src/torchmetrics/utilities/exceptions.py:1-21``.
"""


class TorchMetricsUserError(Exception):
    """Error raised on wrong usage of the metric API."""


class TorchMetricsUserWarning(UserWarning):
    """Warning raised on questionable usage of the metric API."""


class NumericPoisonError(TorchMetricsUserError):
    """Raised at ``compute()`` when ``nan_policy="raise"`` detected non-finite inputs.

    The detection itself is in-graph (a poison-counter state accumulated alongside the
    metric state) so ``update``/``forward`` never pay a host sync; the single deferred
    host read happens here, at finalisation.
    """


class SnapshotError(TorchMetricsUserError):
    """Raised when a metric state snapshot cannot be taken or restored.

    Covers mid-flight snapshots (state buffers donated to an in-progress dispatch),
    snapshots with batches pending in a buffered accumulator, and restores of blobs
    that fail format/version/CRC/shape validation.
    """


class SyncTimeoutError(TorchMetricsUserError):
    """Raised when a bounded multi-process sync exhausts its deadline and retries.

    Only raised when degraded mode is off; with ``degraded_mode=True`` the sync instead
    falls back to local state and marks the result non-world-consistent.
    """
