"""User-facing exception types.

Parity: reference ``src/torchmetrics/utilities/exceptions.py:1-21``.
"""


class TorchMetricsUserError(Exception):
    """Error raised on wrong usage of the metric API."""


class TorchMetricsUserWarning(UserWarning):
    """Warning raised on questionable usage of the metric API."""


class NumericPoisonError(TorchMetricsUserError):
    """Raised at ``compute()`` when ``nan_policy="raise"`` detected non-finite inputs.

    The detection itself is in-graph (a poison-counter state accumulated alongside the
    metric state) so ``update``/``forward`` never pay a host sync; the single deferred
    host read happens here, at finalisation.
    """


class SnapshotError(TorchMetricsUserError):
    """Raised when a metric state snapshot cannot be taken or restored.

    Covers mid-flight snapshots (state buffers donated to an in-progress dispatch),
    snapshots with batches pending in a buffered accumulator, and restores of blobs
    that fail format/version/CRC/shape validation.
    """


class SyncTimeoutError(TorchMetricsUserError):
    """Raised when a bounded multi-process sync exhausts its deadline and retries.

    Only raised when degraded mode is off; with ``degraded_mode=True`` the sync instead
    falls back to local state and marks the result non-world-consistent.

    ``responses`` optionally carries the partial per-rank responses (``{rank: value}``)
    that DID arrive before the deadline — a quorum-capable gather attaches them so the
    sync layer can aggregate over the responding subset instead of dropping to
    local-only state (``SyncOptions(quorum=...)``, docs/robustness.md).
    """

    def __init__(self, *args, responses=None):
        super().__init__(*args)
        self.responses = responses


class JournalError(TorchMetricsUserError):
    """Raised when a write-ahead update journal cannot be appended, read, or replayed.

    Covers corrupted (CRC), truncated-mid-stream, or structurally alien journal records;
    a torn TAIL record (a crash mid-append on a filesystem that lost the rename) is
    tolerated with a warning instead — see ``torchmetrics_tpu.robust.journal``.
    """


class ReconciliationError(TorchMetricsUserError):
    """Raised when a rank re-admission handshake blob fails validation.

    The reconciliation offer wraps a quorum-merged snapshot; accepting it into a metric
    whose registered states/class do not match — or from an incompatible format version —
    fails loudly instead of silently merging mismatched state.
    """
