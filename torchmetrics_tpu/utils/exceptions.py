"""User-facing exception types.

Parity: reference ``src/torchmetrics/utilities/exceptions.py:1-21``.
"""


class TorchMetricsUserError(Exception):
    """Error raised on wrong usage of the metric API."""


class TorchMetricsUserWarning(UserWarning):
    """Warning raised on questionable usage of the metric API."""
