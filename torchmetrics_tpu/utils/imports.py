"""Optional-dependency availability flags.

Parity: reference ``src/torchmetrics/utilities/imports.py:32-68``. The TPU build's base deps are
jax/numpy only; everything else is feature-gated here.
"""
from __future__ import annotations

import importlib.util


def package_available(name: str) -> bool:
    """True if ``name`` is importable (spec found, no import executed)."""
    try:
        return importlib.util.find_spec(name) is not None
    except (ImportError, ModuleNotFoundError, ValueError):
        return False


_SKLEARN_AVAILABLE = package_available("sklearn")
_SCIPY_AVAILABLE = package_available("scipy")
_MATPLOTLIB_AVAILABLE = package_available("matplotlib")
_TRANSFORMERS_AVAILABLE = package_available("transformers")
_TORCH_AVAILABLE = package_available("torch")
_NLTK_AVAILABLE = package_available("nltk")
_REGEX_AVAILABLE = package_available("regex")
_PESQ_AVAILABLE = package_available("pesq")
_PYSTOI_AVAILABLE = package_available("pystoi")
_GAMMATONE_AVAILABLE = package_available("gammatone")
_PYCOCOTOOLS_AVAILABLE = package_available("pycocotools")
_LPIPS_AVAILABLE = package_available("lpips")
_TORCHVISION_AVAILABLE = package_available("torchvision")
_PANDAS_AVAILABLE = package_available("pandas")
