"""Data-movement helpers: dim-zero reductions, one-hot, topk, bincount.

Parity: reference ``src/torchmetrics/utilities/data.py`` (``dim_zero_*:28-55``, ``to_onehot:80``,
``select_topk:115``, ``to_categorical:142``, ``_bincount:169``, ``_cumsum:200``,
``_flexible_bincount:212``, ``allclose:231``).

TPU-first notes: the reference needs a deterministic arange+eq fallback for ``bincount`` on
XLA backends (``data.py:193-195``); here bincount IS the XLA-native design — see
``torchmetrics_tpu.ops.bincount`` which lowers to a one-hot matmul on the MXU for small
cardinalities and a segment-sum scatter otherwise.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.ops import bincount as _ops_bincount


def dim_zero_cat(x: Union[Array, List[Array]]) -> Array:
    """Concatenate a (possibly list-valued) state along dim 0."""
    if isinstance(x, (jax.Array, np.ndarray)):
        return jnp.asarray(x)
    # the isinstance early-return above narrows x to a host LIST here, so the emptiness
    # test is a len() check on a Python container, never a bool() on a traced array
    if not x:  # empty list state  # jaxlint: disable=TPU002
        raise ValueError("No samples to concatenate")
    x = [jnp.atleast_1d(jnp.asarray(e)) for e in x]
    return jnp.concatenate(x, axis=0)


def dim_zero_sum(x: Array) -> Array:
    return jnp.sum(x, axis=0)


def dim_zero_mean(x: Array) -> Array:
    return jnp.mean(x, axis=0)


def dim_zero_max(x: Array) -> Array:
    return jnp.max(x, axis=0)


def dim_zero_min(x: Array) -> Array:
    return jnp.min(x, axis=0)


def _flatten(x: Sequence) -> list:
    """Flatten one level of nesting."""
    return [item for sublist in x for item in sublist]


def to_onehot(label_tensor: Array, num_classes: Optional[int] = None) -> Array:
    """Convert (N, ...) int labels to (N, C, ...) one-hot (reference ``data.py:80``)."""
    if num_classes is None:
        num_classes = int(jax.device_get(jnp.max(label_tensor))) + 1
    oh = jax.nn.one_hot(label_tensor, num_classes, dtype=jnp.int32)  # (N, ..., C)
    return jnp.moveaxis(oh, -1, 1)


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binary (0/1) mask of the top-k entries along ``dim`` (reference ``data.py:115``).

    XLA-native: uses ``jax.lax.top_k`` (sorted network on TPU) + one-hot scatter-free union.
    """
    if topk == 1:  # fast path: argmax one-hot
        idx = jnp.argmax(prob_tensor, axis=dim)
        return jnp.moveaxis(jax.nn.one_hot(idx, prob_tensor.shape[dim], dtype=jnp.int32), -1, dim)
    moved = jnp.moveaxis(prob_tensor, dim, -1)
    _, idx = jax.lax.top_k(moved, topk)  # (..., k)
    mask = jnp.sum(jax.nn.one_hot(idx, moved.shape[-1], dtype=jnp.int32), axis=-2)
    mask = jnp.clip(mask, 0, 1)
    return jnp.moveaxis(mask, -1, dim)


def to_categorical(x: Array, argmax_dim: int = 1) -> Array:
    """Probabilities → class index via argmax (reference ``data.py:142``)."""
    return jnp.argmax(x, axis=argmax_dim)


def _bincount(x: Array, minlength: Optional[int] = None) -> Array:
    """Count occurrences of each value in ``x`` of ints in ``[0, minlength)``.

    Static output shape (required by XLA) — ``minlength`` must be known at trace time.
    """
    if minlength is None:
        minlength = int(jax.device_get(jnp.max(x))) + 1 if x.size else 1
    return _ops_bincount(jnp.reshape(x, (-1,)), minlength)


def _cumsum(x: Array, axis: int = 0, dtype=None) -> Array:
    """Cumulative sum (XLA's is deterministic; no CPU fallback needed — reference ``data.py:200``)."""
    return jnp.cumsum(x, axis=axis, dtype=dtype)


def _flexible_bincount(x: Array) -> Array:
    """Bincount over the values actually present (dynamic cardinality).

    The reference (``data.py:212``) remaps via ``unique``; XLA needs static shapes so this is a
    host-returning helper for eager (non-jit) compute paths only.
    """
    x = np.asarray(x)
    _, inverse = np.unique(x, return_inverse=True)
    counts = np.bincount(inverse)
    return jnp.asarray(counts)


def allclose(t1: Array, t2: Array, atol: float = 1e-8) -> bool:
    """Shape+value closeness check usable on any backend (reference ``data.py:231``)."""
    if jnp.shape(t1) != jnp.shape(t2):
        return False
    return bool(jax.device_get(jnp.allclose(jnp.asarray(t1, jnp.float32), jnp.asarray(t2, jnp.float32), atol=atol)))
