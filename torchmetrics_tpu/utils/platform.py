"""Healthy-platform selection for driver-facing entry points (bench, examples, dryrun).

In this environment the experimental ``axon`` TPU tunnel plugin can wedge JAX backend init
indefinitely: BOTH default discovery and ``JAX_PLATFORMS`` env-var selection hang (plugin
discovery still runs), while ``jax.config.update("jax_platforms", ...)`` with a healthy
platform initialises instantly. Every entry point therefore (a) probes a non-CPU candidate in
a fresh subprocess with a hard timeout before pinning it, and (b) guards any query that might
touch an already-chosen default backend with a thread watchdog so a wedge becomes a recorded
error instead of an unbounded hang (round-4 drivers recorded rc=124/rc=1 artifacts and lost
the round's evidence to exactly this).

This is the single home for that logic — ``bench.py``, ``examples/_env.py`` and
``__graft_entry__.py`` all import from here so the recipe cannot drift apart.
"""
from __future__ import annotations

import subprocess
import sys
import time
from typing import Dict, Iterable, Optional

# Per-process probe memo: each probe subprocess pays a full interpreter + jax import (a known
# test-flake and wall-clock tax when several entry points re-probe the same platform), and a
# platform's health does not change within one process lifetime. ``refresh=True`` re-probes.
_PROBE_CACHE: Dict[str, bool] = {}


# Platforms whose probe-failure note was already printed this process: the memoised probe
# answers instantly on every later resolve, and re-printing "failed its health probe" once
# per entry point turned bench stderr into a wall of the same line (r05 artifacts).
_SKIP_NOTED: set = set()


def probe_cache_clear() -> None:
    """Drop all memoised probe results (tests / long-lived drivers that must re-check)."""
    _PROBE_CACHE.clear()
    _SKIP_NOTED.clear()


def _telemetry():
    """The obs registry, or None if the package (with its jax import) isn't loadable yet."""
    try:
        from torchmetrics_tpu.obs import telemetry

        return telemetry
    except Exception:
        return None


def platform_responds(platform: str, timeout_s: float = 25.0, refresh: bool = False) -> bool:
    """True iff a fresh process can init the backend AND run one jitted op on ``platform``.

    Results are memoised per process (the probe costs a full interpreter + jax import);
    pass ``refresh=True`` to force a re-probe. Every attempt and outcome — including cache
    hits — lands in telemetry under ``platform.probe.*``.
    """
    tel = _telemetry()
    if not refresh and platform in _PROBE_CACHE:
        healthy = _PROBE_CACHE[platform]
        if tel is not None:
            tel.counter("platform.probe.cache_hits").inc()
            tel.event(
                "platform.probe", cat="platform",
                args={"platform": platform, "outcome": "cached", "healthy": healthy},
            )
        return healthy
    code = (
        "import jax; jax.config.update('jax_platforms', %r);"
        " import jax.numpy as jnp;"
        " jax.block_until_ready(jax.jit(lambda x: x + 1)(jnp.zeros(8)))" % platform
    )
    t0 = time.perf_counter()
    try:
        healthy = (
            subprocess.run(
                [sys.executable, "-c", code], timeout=timeout_s, capture_output=True
            ).returncode
            == 0
        )
        outcome = "ok" if healthy else "probe_failed"
    except (subprocess.TimeoutExpired, OSError) as err:
        healthy = False
        outcome = type(err).__name__
    dur_us = (time.perf_counter() - t0) * 1e6
    _PROBE_CACHE[platform] = healthy
    if tel is not None:
        tel.counter("platform.probe.attempts").inc()
        if not healthy:
            tel.counter("platform.probe.failures").inc()
        tel.event(
            "platform.probe", ph="X", cat="platform",
            ts_us=tel.now_us() - dur_us, dur_us=dur_us,
            args={"platform": platform, "outcome": outcome, "healthy": healthy},
        )
    return healthy


def resolve_healthy_platform(
    candidates: Iterable[str], probe_timeout_s: float = 90.0, log=None
) -> str:
    """First candidate that passes :func:`platform_responds`; ``"cpu"`` when none do.

    The probe-failure note prints ONCE per platform per process, rank zero only — every
    retry still records its probe outcome in telemetry (``platform.probe.*``).
    """
    from torchmetrics_tpu.utils.prints import rank_zero_only

    for cand in candidates:
        if platform_responds(cand, probe_timeout_s):
            return cand
        if log is not None and cand not in _SKIP_NOTED:
            _SKIP_NOTED.add(cand)
            rank_zero_only(log)(
                f"platform {cand!r} failed its health probe — skipping (noted once per process)"
            )
    return "cpu"


def query_devices_watchdog(timeout_s: float = 120.0):
    """``jax.devices()`` behind a watchdog: a wedged platform plugin becomes a RuntimeError.

    Backend init runs in a daemon thread; if it doesn't return within ``timeout_s`` the main
    thread raises with the known-good recipe. The hung thread can't be cancelled, but a raised
    error lets the caller record a real failure and exit.
    """
    import threading

    import jax

    result: dict = {}

    def _query():
        try:
            result["devices"] = jax.devices()
        except Exception as err:  # surfaced in the main thread below
            result["err"] = err

    t = threading.Thread(target=_query, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise RuntimeError(
            f"jax backend init did not complete within {timeout_s:.0f}s — a platform plugin"
            " (e.g. the experimental 'axon' TPU tunnel) wedged during discovery. Pin the"
            " platform through the config API before the first backend query:"
            " jax.config.update('jax_platforms', 'cpu'). Selecting via the JAX_PLATFORMS env"
            " var alone does NOT avoid the wedge (plugin discovery still runs)."
        )
    if "err" in result:
        raise result["err"]
    return result["devices"]


def requested_platform(default: str = "cpu") -> Optional[str]:
    """The first platform named by the ``JAX_PLATFORMS`` env var, or ``default`` if unset."""
    import os

    env = os.environ.get("JAX_PLATFORMS")
    if not env:
        return default
    return env.split(",")[0] or default
