"""MultioutputWrapper (reference ``src/torchmetrics/wrappers/multioutput.py:43``)."""
from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


def _get_nan_indices(*tensors) -> jnp.ndarray:
    """Rows where ANY tensor has a NaN (reference ``multioutput.py:26``)."""
    if len(tensors) == 0:
        raise ValueError("Must pass at least one tensor as argument")
    nan_idxs = jnp.zeros(tensors[0].shape[0], bool)
    for t in tensors:
        flat = jnp.reshape(t, (t.shape[0], -1))
        nan_idxs = nan_idxs | jnp.any(jnp.isnan(flat), axis=1)
    return nan_idxs


class MultioutputWrapper(WrapperMetric):
    """Evaluate one metric per output column (reference ``multioutput.py:43``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.regression import MeanSquaredError
        >>> from torchmetrics_tpu.wrappers import MultioutputWrapper
        >>> metric = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
        >>> metric.update(np.array([[2.5, 0.0], [2.0, 8.0]], np.float32),
        ...               np.array([[3.0, -0.5], [2.0, 7.0]], np.float32))
        >>> [round(float(v), 4) for v in np.asarray(metric.compute())]
        [0.125, 0.625]
    """

    is_differentiable = False

    def __init__(
        self,
        base_metric: Metric,
        num_outputs: int,
        output_dim: int = -1,
        remove_nans: bool = True,
        squeeze_outputs: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.metrics = [base_metric.clone() for _ in range(num_outputs)]
        self.output_dim = output_dim
        self.remove_nans = remove_nans
        self.squeeze_outputs = squeeze_outputs

    def _get_args_kwargs_by_output(self, *args, **kwargs) -> List[Tuple[tuple, dict]]:
        """Slice column i of every input for metric i (reference ``multioutput.py:101-136``)."""
        args_kwargs_by_output = []
        for i in range(len(self.metrics)):
            selected_args = [jnp.take(a, jnp.asarray([i]), axis=self.output_dim) for a in args]
            selected_kwargs = {
                k: jnp.take(v, jnp.asarray([i]), axis=self.output_dim) for k, v in kwargs.items()
            }
            if self.remove_nans:
                tensors = [*selected_args, *selected_kwargs.values()]
                if tensors:
                    nan_idxs = np.asarray(_get_nan_indices(*tensors))
                    keep = ~nan_idxs
                    selected_args = [a[keep] for a in selected_args]
                    selected_kwargs = {k: v[keep] for k, v in selected_kwargs.items()}
            if self.squeeze_outputs:
                selected_args = [jnp.squeeze(a, axis=self.output_dim) for a in selected_args]
                selected_kwargs = {k: jnp.squeeze(v, axis=self.output_dim) for k, v in selected_kwargs.items()}
            args_kwargs_by_output.append((tuple(selected_args), selected_kwargs))
        return args_kwargs_by_output

    def update(self, *args: Any, **kwargs: Any) -> None:
        args = tuple(jnp.asarray(a) for a in args)
        kwargs = {k: jnp.asarray(v) for k, v in kwargs.items()}
        for (selected_args, selected_kwargs), metric in zip(
            self._get_args_kwargs_by_output(*args, **kwargs), self.metrics
        ):
            metric.update(*selected_args, **selected_kwargs)
        self._update_count += 1
        self._update_called = True

    def compute(self) -> Any:
        return jnp.stack([m.compute() for m in self.metrics], axis=0)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        args = tuple(jnp.asarray(a) for a in args)
        kwargs = {k: jnp.asarray(v) for k, v in kwargs.items()}
        results = []
        for (selected_args, selected_kwargs), metric in zip(
            self._get_args_kwargs_by_output(*args, **kwargs), self.metrics
        ):
            results.append(metric(*selected_args, **selected_kwargs))
        self._update_count += 1
        self._update_called = True
        if results[0] is None:
            return None
        return jnp.stack(results, axis=0)

    def reset(self) -> None:
        for m in self.metrics:
            m.reset()
        super().reset()

    def _filter_kwargs(self, **kwargs: Any) -> dict:
        return self.metrics[0]._filter_kwargs(**kwargs)
