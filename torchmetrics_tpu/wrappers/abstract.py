"""WrapperMetric base (reference ``src/torchmetrics/wrappers/abstract.py:19-42``).

Wrappers forward everything to the wrapped metric; sync is the wrapped metric's business, so the
wrapper's own sync hooks are no-ops.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from torchmetrics_tpu.metric import Metric


class WrapperMetric(Metric):
    """Abstract base class for wrapper metrics."""

    def _sync_dist(self, dist_sync_fn: Optional[Callable] = None, process_group: Optional[Any] = None) -> None:
        pass  # wrapped metric handles its own sync

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        raise NotImplementedError
