"""ClasswiseWrapper (reference ``src/torchmetrics/wrappers/classwise.py:27``)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax.numpy as jnp

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


class ClasswiseWrapper(WrapperMetric):
    """Split a per-class output tensor into a ``{label: scalar}`` dict (reference ``classwise.py:27``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> from torchmetrics_tpu.classification import MulticlassAccuracy
        >>> from torchmetrics_tpu.wrappers import ClasswiseWrapper
        >>> metric = ClasswiseWrapper(MulticlassAccuracy(num_classes=3, average=None))
        >>> metric.update(preds, target)
        >>> {k: round(float(v), 2) for k, v in sorted(metric.compute().items())}
        {'multiclassaccuracy_0': 0.5, 'multiclassaccuracy_1': 1.0, 'multiclassaccuracy_2': 1.0}
    """

    def __init__(
        self,
        metric: Metric,
        labels: Optional[List[str]] = None,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(metric, Metric):
            raise ValueError(f"Expected argument `metric` to be an instance of `Metric` but got {metric}")
        if labels is not None and not (isinstance(labels, list) and all(isinstance(lab, str) for lab in labels)):
            raise ValueError(f"Argument `labels` must be either `None` or a list of strings but got {labels}")
        if prefix is not None and not isinstance(prefix, str):
            raise ValueError(f"Argument `prefix` must be either `None` or a string but got {prefix}")
        if postfix is not None and not isinstance(postfix, str):
            raise ValueError(f"Argument `postfix` must be either `None` or a string but got {postfix}")
        self.metric = metric
        self.labels = labels
        self._prefix = prefix
        self._postfix = postfix
        self._update_count = 1

    def _convert(self, x) -> Dict[str, Any]:
        if not self._prefix and not self._postfix:
            prefix = f"{type(self.metric).__name__.lower()}_"
            postfix = ""
        else:
            prefix = self._prefix or ""
            postfix = self._postfix or ""
        if self.labels is None:
            return {f"{prefix}{i}{postfix}": val for i, val in enumerate(x)}
        return {f"{prefix}{lab}{postfix}": val for lab, val in zip(self.labels, x)}

    def update(self, *args: Any, **kwargs: Any) -> None:
        self.metric.update(*args, **kwargs)
        self._update_called = True

    def compute(self) -> Dict[str, Any]:
        return self._convert(self.metric.compute())

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        return self._convert(self.metric(*args, **kwargs))

    def reset(self) -> None:
        self.metric.reset()

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        return self.metric._filter_kwargs(**kwargs)
