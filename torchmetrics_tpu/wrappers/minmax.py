"""MinMaxMetric (reference ``src/torchmetrics/wrappers/minmax.py:29``)."""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


class MinMaxMetric(WrapperMetric):
    """Track the min and max of the wrapped metric's compute over time (reference ``minmax.py:29``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> from torchmetrics_tpu.wrappers import MinMaxMetric
        >>> metric = MinMaxMetric(BinaryAccuracy())
        >>> metric.update(np.array([0.1, 0.4, 0.35, 0.8], np.float32), np.array([0, 0, 1, 1]))
        >>> {k: float(v) for k, v in sorted(metric.compute().items())}
        {'max': 0.75, 'min': 0.75, 'raw': 0.75}
    """

    full_state_update = True

    def __init__(self, base_metric: Metric, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `torchmetrics_tpu.Metric` but received {base_metric}"
            )
        self._base_metric = base_metric
        self.min_val = jnp.asarray(jnp.inf)
        self.max_val = jnp.asarray(-jnp.inf)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._base_metric.update(*args, **kwargs)
        self._update_count += 1
        self._update_called = True

    def compute(self) -> Dict[str, Any]:
        val = self._base_metric.compute()
        if not self._is_suitable_val(val):
            raise RuntimeError(f"Returned value from base metric must be a float or scalar tensor, but got {val}.")
        self.max_val = jnp.maximum(self.max_val, val)
        self.min_val = jnp.minimum(self.min_val, val)
        return {"raw": val, "max": self.max_val, "min": self.min_val}

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self.update(*args, **kwargs)
        return self.compute()

    def reset(self) -> None:
        self._base_metric.reset()
        super().reset()
        self.min_val = jnp.asarray(jnp.inf)
        self.max_val = jnp.asarray(-jnp.inf)

    @staticmethod
    def _is_suitable_val(val: Any) -> bool:
        if isinstance(val, (int, float)):
            return True
        if hasattr(val, "size"):
            return val.size == 1
        return False
