"""Running wrapper (reference ``src/torchmetrics/wrappers/running.py:27``)."""
from __future__ import annotations

from typing import Any

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


class Running(WrapperMetric):
    """Metric over a fixed-size running window of recent updates (reference ``running.py:27``).

    Keeps ``window`` copies of the wrapped metric's state (one per recent update); compute merges
    them with the base metric's reduce-fx semantics.
    """

    def __init__(self, base_metric: Metric, window: int = 5) -> None:
        super().__init__()
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected argument `metric` to be an instance of `torchmetrics_tpu.Metric` but got {base_metric}"
            )
        if not (isinstance(window, int) and window > 0):
            raise ValueError(f"Argument `window` must be a positive integer but got {window}")
        self.base_metric = base_metric
        self.window = window
        if base_metric.full_state_update is not False:
            raise ValueError(
                f"Expected attribute `full_state_update` set to `False` but got {base_metric.full_state_update}"
            )
        self._num_vals_seen = 0
        for key in base_metric._defaults:
            for i in range(window):
                self.add_state(
                    name=f"{key}_{i}",
                    default=base_metric._defaults[key] if not isinstance(base_metric._defaults[key], list) else [],
                    dist_reduce_fx=base_metric._reductions[key],
                )

    def _save_slot(self) -> None:
        val = self._num_vals_seen % self.window
        for key in self.base_metric._defaults:
            if key in self.base_metric._state.tensors:
                self._state.tensors[f"{key}_{val}"] = self.base_metric._state.tensors[key]
            else:
                self._state.lists[f"{key}_{val}"] = list(self.base_metric._state.lists[key])
        self.base_metric.reset()
        self._num_vals_seen += 1
        self._computed = None

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update the base metric, stash its state into the current window slot (reference ``running.py:106``)."""
        self.base_metric.update(*args, **kwargs)
        self._save_slot()
        self._update_count += 1
        self._update_called = True

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Batch value from the base metric; state stashed as in update (reference ``running.py:115``)."""
        res = self.base_metric(*args, **kwargs)
        # base was reset after the previous slot save, so its state now holds exactly this batch
        self._save_slot()
        self._update_count += 1
        self._update_called = True
        return res

    def compute(self) -> Any:
        """Merge the window slots into the base metric and compute (reference ``running.py:126``)."""
        self.base_metric.reset()
        for i in range(self.window):
            slot = {}
            for key in self.base_metric._defaults:
                name = f"{key}_{i}"
                if name in self._state.tensors:
                    slot[key] = self._state.tensors[name]
                else:
                    slot[key] = list(self._state.lists[name])
            self.base_metric._update_count = i + 1
            self.base_metric._reduce_states(dict(self.base_metric._state.tensors), slot)
        if self._num_vals_seen > 0:
            self.base_metric._update_called = True  # states were merged in, not update()-ed
        # an empty window keeps _update_called False so compute() warns like any fresh metric
        val = self.base_metric.compute()
        self.base_metric.reset()
        return val

    def reset(self) -> None:
        super().reset()
        self.base_metric.reset()
        self._num_vals_seen = 0
