"""MetricTracker (reference ``src/torchmetrics/wrappers/tracker.py:31``)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.prints import rank_zero_warn
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


class MetricTracker(WrapperMetric):
    """Track a metric (or collection) over epochs: ``increment()`` per epoch, ``best_metric()``
    at the end (reference ``tracker.py:31,108``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> from torchmetrics_tpu.classification import MulticlassAccuracy
        >>> from torchmetrics_tpu.wrappers import MetricTracker
        >>> tracker = MetricTracker(MulticlassAccuracy(num_classes=3, average='micro'))
        >>> for epoch in range(2):
        ...     tracker.increment()
        ...     tracker.update(preds, target)
        >>> best, step = tracker.best_metric(return_step=True)
        >>> print(f"{float(best):.4f}", step)
        0.7500 0
    """

    def __init__(self, metric: Union[Metric, MetricCollection], maximize: Union[bool, List[bool]] = True) -> None:
        super().__init__()
        if not isinstance(metric, (Metric, MetricCollection)):
            raise TypeError(
                "Metric arg need to be an instance of a torchmetrics_tpu"
                f" `Metric` or `MetricCollection` but got {metric}"
            )
        self._base_metric = metric
        self._metrics: List[Union[Metric, MetricCollection]] = []
        if not isinstance(maximize, (bool, list)):
            raise ValueError("Argument `maximize` should either be a single bool or list of bool")
        if isinstance(maximize, list) and not all(isinstance(m, bool) for m in maximize):
            raise ValueError("Argument `maximize` should either be a single bool or list of bool")
        if isinstance(maximize, list) and isinstance(metric, MetricCollection) and len(maximize) != len(metric):
            raise ValueError("The len of argument `maximize` should match the length of the metric collection")
        self.maximize = maximize
        self._increment_called = False

    @property
    def n_steps(self) -> int:
        """Number of times increment has been called."""
        self._check_for_increment("n_steps")
        return len(self._metrics)

    def increment(self) -> None:
        """Start tracking a new version (e.g. a new epoch) of the metric."""
        self._increment_called = True
        self._metrics.append(self._base_metric.clone())
        if isinstance(self._metrics[-1], (Metric, MetricCollection)):
            self._metrics[-1].reset()

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._check_for_increment("update")
        self._metrics[-1].update(*args, **kwargs)
        self._update_called = True

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self._check_for_increment("forward")
        self._update_called = True
        return self._metrics[-1](*args, **kwargs)

    def compute(self) -> Any:
        self._check_for_increment("compute")
        return self._metrics[-1].compute()

    def compute_all(self) -> Any:
        """Stacked results from all tracked versions (reference ``tracker.py:142``)."""
        self._check_for_increment("compute_all")
        # the i=0 version only serves as a template and is never updated
        res = [metric.compute() for metric in self._metrics]
        if isinstance(self._base_metric, MetricCollection):
            keys = res[0].keys()
            return {k: jnp.stack([r[k] for r in res], axis=0) for k in keys}
        return jnp.stack(res, axis=0)

    def reset(self) -> None:
        """Reset the current metric being tracked."""
        if self._metrics:
            self._metrics[-1].reset()

    def reset_all(self) -> None:
        for metric in self._metrics:
            metric.reset()

    def best_metric(
        self, return_step: bool = False
    ) -> Union[Any, Tuple[Any, Any]]:
        """Best value (and optionally its step) across tracked versions (reference ``tracker.py:160``)."""
        res = self.compute_all()
        if isinstance(res, dict):
            maximize = self.maximize if isinstance(self.maximize, list) else [self.maximize] * len(res)
            value, idx = {}, {}
            for i, (k, v) in enumerate(res.items()):
                try:
                    arr = np.asarray(v)
                    fn = np.argmax if maximize[i] else np.argmin
                    best = int(fn(arr))
                    value[k], idx[k] = float(arr[best]), best
                except (ValueError, TypeError) as err:
                    rank_zero_warn(
                        f"Encountered the following error when trying to get the best metric for metric {k}:"
                        f"{err}. This is probably because the metric in the collection is lacking a `higher_is_better`"
                        " flag or produces a non-scalar output. Returning `None` instead.",
                        UserWarning,
                    )
                    value[k], idx[k] = None, None
            if return_step:
                return value, idx
            return value
        try:
            arr = np.asarray(res)
            fn = np.argmax if self.maximize else np.argmin
            best = int(fn(arr))
            if return_step:
                return float(arr[best]), best
            return float(arr[best])
        except (ValueError, TypeError) as err:
            rank_zero_warn(
                f"Encountered the following error when trying to get the best metric: {err}."
                " Returning `None` instead.",
                UserWarning,
            )
            if return_step:
                return None, None
            return None

    def _check_for_increment(self, method: str) -> None:
        if not self._increment_called:
            raise ValueError(f"`{method}` cannot be called before `.increment()` has been called.")
