from torchmetrics_tpu.wrappers.abstract import WrapperMetric
from torchmetrics_tpu.wrappers.bootstrapping import BootStrapper
from torchmetrics_tpu.wrappers.classwise import ClasswiseWrapper
from torchmetrics_tpu.wrappers.minmax import MinMaxMetric
from torchmetrics_tpu.wrappers.multioutput import MultioutputWrapper
from torchmetrics_tpu.wrappers.multitask import MultitaskWrapper
from torchmetrics_tpu.wrappers.running import Running
from torchmetrics_tpu.wrappers.tracker import MetricTracker

__all__ = [
    "BootStrapper",
    "ClasswiseWrapper",
    "MetricTracker",
    "MinMaxMetric",
    "MultioutputWrapper",
    "MultitaskWrapper",
    "Running",
    "WrapperMetric",
]
