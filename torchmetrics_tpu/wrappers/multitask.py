"""MultitaskWrapper (reference ``src/torchmetrics/wrappers/multitask.py:29``)."""
from __future__ import annotations

from typing import Any, Dict, Union

from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


class MultitaskWrapper(WrapperMetric):
    """Dict of task -> metric; dict preds/targets in, dict results out (reference ``multitask.py:29``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> from torchmetrics_tpu.regression import MeanSquaredError
        >>> from torchmetrics_tpu.wrappers import MultitaskWrapper
        >>> metric = MultitaskWrapper({'cls': BinaryAccuracy(), 'reg': MeanSquaredError()})
        >>> metric.update(
        ...     {'cls': np.array([0.1, 0.4, 0.35, 0.8], np.float32), 'reg': np.array([2.5, 0.0], np.float32)},
        ...     {'cls': np.array([0, 0, 1, 1]), 'reg': np.array([3.0, -0.5], np.float32)})
        >>> {k: round(float(v), 4) for k, v in sorted(metric.compute().items())}
        {'cls': 0.75, 'reg': 0.25}
    """

    is_differentiable = False

    def __init__(self, task_metrics: Dict[str, Union[Metric, MetricCollection]], **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(task_metrics, dict):
            raise TypeError(f"Argument `task_metrics` must be a dict. Found task_metrics = {task_metrics}")
        for metric in task_metrics.values():
            if not isinstance(metric, (Metric, MetricCollection)):
                raise TypeError(
                    "Expected each task's metric to be a Metric or a MetricCollection. "
                    f"Found a metric of type {type(metric)}"
                )
        self.task_metrics = task_metrics

    def items(self):
        return self.task_metrics.items()

    def keys(self):
        return self.task_metrics.keys()

    def values(self):
        return self.task_metrics.values()

    def _check_all_tasks_covered(self, d: Dict[str, Any], name: str) -> None:
        if d.keys() != self.task_metrics.keys():
            raise ValueError(
                f"Expected arguments `task_preds` and `task_targets` to have the same keys as the wrapped"
                f" `task_metrics`. Found task_preds.keys() = {d.keys()}, task_targets.keys() ="
                f" {name}, task_metrics.keys() = {self.task_metrics.keys()}"
            )

    def update(self, task_preds: Dict[str, Any], task_targets: Dict[str, Any]) -> None:
        """Update each task's metric (reference ``multitask.py:129``)."""
        if task_preds.keys() != task_targets.keys() or task_preds.keys() != self.task_metrics.keys():
            raise ValueError(
                "Expected arguments `task_preds` and `task_targets` to have the same keys as the wrapped"
                f" `task_metrics`. Found task_preds.keys() = {task_preds.keys()},"
                f" task_targets.keys() = {task_targets.keys()}"
                f" and task_metrics.keys() = {self.task_metrics.keys()}"
            )
        for task_name, metric in self.task_metrics.items():
            metric.update(task_preds[task_name], task_targets[task_name])
        self._update_count += 1
        self._update_called = True

    def compute(self) -> Dict[str, Any]:
        return {task_name: metric.compute() for task_name, metric in self.task_metrics.items()}

    def forward(self, task_preds: Dict[str, Any], task_targets: Dict[str, Any]) -> Dict[str, Any]:
        self._update_count += 1
        self._update_called = True
        return {
            task_name: metric(task_preds[task_name], task_targets[task_name])
            for task_name, metric in self.task_metrics.items()
        }

    def reset(self) -> None:
        for metric in self.task_metrics.values():
            metric.reset()
        super().reset()
