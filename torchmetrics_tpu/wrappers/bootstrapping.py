"""BootStrapper (reference ``src/torchmetrics/wrappers/bootstrapping.py:54+``)."""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.wrappers.abstract import WrapperMetric


def _bootstrap_sampler(size: int, sampling_strategy: str = "poisson", rng: Optional[np.random.RandomState] = None):
    """Resample indices along dim 0 with replacement (reference ``bootstrapping.py:31-53``)."""
    rng = rng or np.random
    if sampling_strategy == "poisson":
        n = rng.poisson(1, size=size)
        return jnp.asarray(np.repeat(np.arange(size), n))
    if sampling_strategy == "multinomial":
        return jnp.asarray(rng.randint(0, size, size=size))
    raise ValueError("Unknown sampling strategy")


class BootStrapper(WrapperMetric):
    """Bootstrapped confidence estimates of any metric (reference ``bootstrapping.py:54``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> from torchmetrics_tpu.wrappers import BootStrapper
        >>> metric = BootStrapper(BinaryAccuracy(), num_bootstraps=4, seed=0)
        >>> metric.update(preds, target)
        >>> {k: round(float(v), 4) for k, v in sorted(metric.compute().items())}
        {'mean': 0.8681, 'std': 0.1049}
    """

    full_state_update = True

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        mean: bool = True,
        std: bool = True,
        quantile: Optional[Union[float, Sequence[float]]] = None,
        raw: bool = False,
        sampling_strategy: str = "poisson",
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of torchmetrics_tpu.Metric but received {base_metric}"
            )
        self.metrics = [base_metric.clone() for _ in range(num_bootstraps)]
        self.num_bootstraps = num_bootstraps
        self.mean = mean
        self.std = std
        self.quantile = quantile
        self.raw = raw
        allowed_sampling = ("poisson", "multinomial")
        if sampling_strategy not in allowed_sampling:
            raise ValueError(
                f"Expected argument ``sampling_strategy`` to be one of {allowed_sampling}"
                f" but received {sampling_strategy}"
            )
        self.sampling_strategy = sampling_strategy
        # `seed` is an extension beyond the reference API: resampling happens on host, so a
        # seeded RandomState (rather than jax.random) gives reproducible bootstraps cheaply.
        self.seed = seed
        self._rng = np.random.RandomState(seed)

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Resample inputs per bootstrap copy, then update each copy (reference ``bootstrapping.py:124``)."""
        args_sizes = [a.shape[0] for a in args if hasattr(a, "shape") and jnp.ndim(a) > 0]
        kwargs_sizes = [v.shape[0] for v in kwargs.values() if hasattr(v, "shape") and jnp.ndim(v) > 0]
        if args_sizes:
            size = args_sizes[0]
        elif kwargs_sizes:
            size = kwargs_sizes[0]
        else:
            raise ValueError("None of the input contained any tensor, so no sampling could be done")
        for idx in range(self.num_bootstraps):
            sample_idx = _bootstrap_sampler(size, self.sampling_strategy, self._rng)
            if sample_idx.size == 0:
                continue
            new_args = tuple(jnp.asarray(a)[sample_idx] if jnp.ndim(a) > 0 else a for a in args)
            new_kwargs = {
                k: jnp.asarray(v)[sample_idx] if jnp.ndim(v) > 0 else v for k, v in kwargs.items()
            }
            self.metrics[idx].update(*new_args, **new_kwargs)
        self._update_count += 1
        self._update_called = True

    def compute(self) -> Dict[str, Any]:
        """mean/std/quantile/raw over bootstrap copies (reference ``bootstrapping.py:147``)."""
        computed_vals = jnp.stack([m.compute() for m in self.metrics], axis=0)
        output_dict = {}
        if self.mean:
            output_dict["mean"] = jnp.mean(computed_vals, axis=0)
        if self.std:
            output_dict["std"] = jnp.std(computed_vals, axis=0, ddof=1)
        if self.quantile is not None:
            output_dict["quantile"] = jnp.quantile(computed_vals, jnp.asarray(self.quantile), axis=0)
        if self.raw:
            output_dict["raw"] = computed_vals
        return output_dict

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self.update(*args, **kwargs)
        return self.compute()

    def reset(self) -> None:
        for m in self.metrics:
            m.reset()
        if self.seed is not None:
            self._rng = np.random.RandomState(self.seed)  # reset() restarts the reproducible stream
        super().reset()
