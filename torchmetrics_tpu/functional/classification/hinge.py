"""Hinge loss kernels (reference ``src/torchmetrics/functional/classification/hinge.py``)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape, is_traced
from torchmetrics_tpu.utils.compute import _safe_divide, normalize_logits_if_needed


def _hinge_loss_update(measures: Array, weight: Array) -> Tuple[Array, Array]:
    return jnp.sum(measures * weight, axis=0), jnp.sum(weight)


def _hinge_loss_compute(measure: Array, total: Array) -> Array:
    return _safe_divide(measure, total)


def _binary_hinge_loss_arg_validation(squared: bool, ignore_index: Optional[int] = None) -> None:
    if not isinstance(squared, bool):
        raise ValueError(f"Argument `squared` must be an bool but got {squared}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Argument `ignore_index` must be either `None` or an integer, but got {ignore_index}")


def _binary_hinge_loss_tensor_validation(
    preds: Array, target: Array, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        raise ValueError(
            "Expected argument `preds` to be floating tensor with probabilities/logits"
            f" but got tensor with dtype {jnp.asarray(preds).dtype}"
        )
    if is_traced(preds, target):
        return
    t = np.asarray(target)
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    unique = set(np.unique(t).tolist())
    if not unique.issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {sorted(unique)} but expected only"
            f" the following values {sorted(allowed)}."
        )


def _binary_hinge_update(
    preds: Array, target: Array, squared: bool, ignore_index: Optional[int] = None
) -> Tuple[Array, Array]:
    preds = jnp.reshape(preds, (-1,))
    target = jnp.reshape(target, (-1,))
    preds = normalize_logits_if_needed(preds, "sigmoid")
    if ignore_index is not None:
        weight = (target != ignore_index).astype(jnp.float32)
        target = jnp.where(target == ignore_index, 0, target)
    else:
        weight = jnp.ones(target.shape, jnp.float32)
    target_pm = target.astype(jnp.float32) * 2 - 1  # {0,1} -> {-1,+1}
    margin = preds * target_pm
    measures = jnp.maximum(1 - margin, 0.0)
    if squared:
        measures = measures**2
    return _hinge_loss_update(measures, weight)


def binary_hinge_loss(
    preds: Array,
    target: Array,
    squared: bool = False,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Mean hinge loss for binary tasks (reference ``hinge.py:96``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import binary_hinge_loss
        >>> preds = np.array([0.9, 0.1, 0.8, 0.4], np.float32)
        >>> target = np.array([1, 0, 1, 1])
        >>> print(f"{float(binary_hinge_loss(preds, target)):.4f}")
        0.5000
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _binary_hinge_loss_arg_validation(squared, ignore_index)
        _binary_hinge_loss_tensor_validation(preds, target, ignore_index)
    measure, total = _binary_hinge_update(preds, target, squared, ignore_index)
    return _hinge_loss_compute(measure, total)


def _multiclass_hinge_loss_arg_validation(
    num_classes: int,
    squared: bool = False,
    multiclass_mode: str = "crammer-singer",
    ignore_index: Optional[int] = None,
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Argument `num_classes` must be an integer larger than 1, but got {num_classes}")
    _binary_hinge_loss_arg_validation(squared, ignore_index)
    if multiclass_mode not in ("crammer-singer", "one-vs-all"):
        raise ValueError(
            f"Expected argument `multiclass_mode` to be one of 'crammer-singer', 'one-vs-all',"
            f" but got {multiclass_mode}"
        )


def _multiclass_hinge_loss_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    if preds.ndim != target.ndim + 1:
        raise ValueError("Expected `preds` to have one more dimension than `target`")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError(f"`preds` must be a float tensor, but got {preds.dtype}")
    if preds.shape[1] != num_classes:
        raise ValueError(f"Expected `preds.shape[1]={preds.shape[1]}` to equal num_classes {num_classes}")
    if is_traced(preds, target):
        return
    t = np.asarray(target)
    if ignore_index is not None:
        t = t[t != ignore_index]
    if t.size and (t.min() < 0 or t.max() >= num_classes):
        raise RuntimeError(f"Detected values in `target` outside [0, {num_classes})")


def _multiclass_hinge_update(
    preds: Array,
    target: Array,
    num_classes: int,
    squared: bool,
    multiclass_mode: str = "crammer-singer",
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array]:
    preds = jnp.moveaxis(preds, 1, -1).reshape((-1, num_classes))
    target = jnp.reshape(target, (-1,))
    preds = normalize_logits_if_needed(preds, "softmax")
    if ignore_index is not None:
        weight = (target != ignore_index).astype(jnp.float32)
        target = jnp.where(target == ignore_index, 0, target)
    else:
        weight = jnp.ones(target.shape, jnp.float32)
    onehot = (target[:, None] == jnp.arange(num_classes)[None, :]).astype(jnp.float32)
    if multiclass_mode == "crammer-singer":
        true_score = jnp.sum(preds * onehot, axis=-1)
        best_other = jnp.max(jnp.where(onehot > 0, -jnp.inf, preds), axis=-1)
        margin = true_score - best_other
        measures = jnp.maximum(1 - margin, 0.0)
        if squared:
            measures = measures**2
        return _hinge_loss_update(measures, weight)
    # one-vs-all: per-class binary hinge with +-1 targets; returns per-class losses
    target_pm = onehot * 2 - 1
    margin = preds * target_pm
    measures = jnp.maximum(1 - margin, 0.0)
    if squared:
        measures = measures**2
    return jnp.sum(measures * weight[:, None], axis=0), jnp.sum(weight)


def multiclass_hinge_loss(
    preds: Array,
    target: Array,
    num_classes: int,
    squared: bool = False,
    multiclass_mode: str = "crammer-singer",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Mean hinge loss for multiclass tasks (reference ``hinge.py:205``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multiclass_hinge_loss_arg_validation(num_classes, squared, multiclass_mode, ignore_index)
        _multiclass_hinge_loss_tensor_validation(preds, target, num_classes, ignore_index)
    measure, total = _multiclass_hinge_update(preds, target, num_classes, squared, multiclass_mode, ignore_index)
    return _hinge_loss_compute(measure, total)


def hinge_loss(
    preds: Array,
    target: Array,
    task: str,
    num_classes: Optional[int] = None,
    squared: bool = False,
    multiclass_mode: str = "crammer-singer",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatching entrypoint (reference ``hinge.py:290``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import hinge_loss
        >>> preds = np.array([0.25, 0.25, 0.55, 0.75, 0.75], np.float32)
        >>> target = np.array([0, 0, 1, 1, 1])
        >>> print(f"{float(hinge_loss(preds, target, task='binary')):.4f}")
        0.6900
    """
    from torchmetrics_tpu.utils.enums import ClassificationTaskNoMultilabel

    task = ClassificationTaskNoMultilabel.from_str(task)
    if task == ClassificationTaskNoMultilabel.BINARY:
        return binary_hinge_loss(preds, target, squared, ignore_index, validate_args)
    if task == ClassificationTaskNoMultilabel.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_hinge_loss(
            preds, target, num_classes, squared, multiclass_mode, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
