"""Exact-match kernels (reference
``src/torchmetrics/functional/classification/exact_match.py``)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification.stat_scores import (
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
)
from torchmetrics_tpu.utils.compute import _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTaskNoBinary


def _exact_match_reduce(correct: Array, total: Array) -> Array:
    return _safe_divide(correct, total)


def _multiclass_exact_match_update(
    preds: Array, target: Array, multidim_average: str = "global", ignore_index: Optional[int] = None
) -> tuple:
    """All positions in a sample must match (reference ``exact_match.py:46-77``)."""
    mask = (target != ignore_index) if ignore_index is not None else jnp.ones(target.shape, bool)
    match = (preds == target) | ~mask
    correct_per_sample = jnp.all(match, axis=1).astype(jnp.float32)
    if multidim_average == "global":
        return jnp.sum(correct_per_sample), jnp.asarray(correct_per_sample.shape[0], jnp.float32)
    return correct_per_sample, jnp.ones_like(correct_per_sample)


def multiclass_exact_match(preds, target, num_classes: int, multidim_average: str = "global",
                           ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Reference ``exact_match.py:80``.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import multiclass_exact_match
        >>> preds = np.array([[0, 1], [1, 1]])
        >>> target = np.array([[0, 1], [0, 1]])
        >>> print(f"{float(multiclass_exact_match(preds, target, num_classes=2)):.4f}")
        0.5000
    """
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, 1, None, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    preds, target = _multiclass_stat_scores_format(preds, target, 1)
    correct, total = _multiclass_exact_match_update(preds, target, multidim_average, ignore_index)
    return _exact_match_reduce(correct, total)


def _multilabel_exact_match_update(
    preds: Array, target: Array, mask: Array, multidim_average: str = "global"
) -> tuple:
    """(N, L, S): all labels must match per (sample, position)."""
    match = (preds == target) | (mask == 0)
    correct = jnp.all(match, axis=1).astype(jnp.float32)  # (N, S)
    if multidim_average == "global":
        return jnp.sum(correct), jnp.asarray(correct.shape[0] * correct.shape[1], jnp.float32)
    return jnp.sum(correct, axis=1), jnp.full((correct.shape[0],), correct.shape[1], jnp.float32)


def multilabel_exact_match(preds, target, num_labels: int, threshold: float = 0.5,
                           multidim_average: str = "global", ignore_index: Optional[int] = None,
                           validate_args: bool = True) -> Array:
    """Reference ``exact_match.py:224``."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, None, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target, mask = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    correct, total = _multilabel_exact_match_update(preds, target, mask, multidim_average)
    return _exact_match_reduce(correct, total)


def exact_match(preds, target, task: str, num_classes: Optional[int] = None, num_labels: Optional[int] = None,
                threshold: float = 0.5, multidim_average: str = "global", ignore_index: Optional[int] = None,
                validate_args: bool = True) -> Array:
    """Task-dispatching exact match (reference ``exact_match.py:355``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import exact_match
        >>> preds = np.array([[0, 1], [1, 1]])
        >>> target = np.array([[0, 1], [0, 1]])
        >>> print(f"{float(exact_match(preds, target, task='multilabel', num_labels=2)):.4f}")
        0.5000
    """
    task = ClassificationTaskNoBinary.from_str(task)
    if task == ClassificationTaskNoBinary.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_exact_match(preds, target, num_classes, multidim_average, ignore_index, validate_args)
    if task == ClassificationTaskNoBinary.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
        return multilabel_exact_match(preds, target, num_labels, threshold, multidim_average,
                                      ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
