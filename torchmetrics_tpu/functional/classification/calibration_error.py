"""Calibration error kernels (reference
``src/torchmetrics/functional/classification/calibration_error.py``).

TPU-first state redesign: the reference keeps raw confidence/accuracy lists and bins at compute;
binning against a FIXED uniform grid commutes with accumulation, so here the state is three
``(n_bins + 1,)`` sum tensors (count / confidence-sum / accuracy-sum; the extra slot holds
``conf == 1.0`` exactly, matching the reference's bucketize indexing) — O(n_bins) memory, exact
same result, single psum to sync.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array
from torchmetrics_tpu.utils.checks import _check_same_shape, is_traced
from torchmetrics_tpu.utils.compute import _safe_divide, normalize_logits_if_needed


def _binning_bucketize(
    confidences: Array, accuracies: Array, weight: Array, n_bins: int
) -> Tuple[Array, Array, Array]:
    """Per-bin (count, conf_sum, acc_sum) against a uniform [0, 1] grid.

    Matches the reference's ``torch.bucketize(conf, linspace(0, 1, n_bins + 1), right=True) - 1``
    (reference ``calibration_error.py:48``): a value exactly on a boundary goes to the UPPER bin,
    and ``conf == 1.0`` lands in its own extra slot — hence ``n_bins + 1`` state slots. A naive
    ``(conf * n_bins).astype(int)`` truncation mis-bins boundary values under float32 rounding.
    """
    # cumulative-indicator matmul instead of searchsorted+bincount: suffix[k] = Σ x_i·[c_i >= b_k]
    # via one (3, N) @ (N, n_bins+1) dot (the broadcast compare fuses into the dot operand — XLA's
    # searchsorted lowering is per-element binary-search gathers, ~1000x slower on TPU), then
    # per-bin sums as adjacent differences. `>= b_k` is exactly bucketize-right's boundary rule.
    boundaries = jnp.linspace(0.0, 1.0, n_bins + 1, dtype=confidences.dtype)
    ind = (confidences[:, None] >= boundaries[None, :]).astype(jnp.float32)  # (N, B+1)
    w = weight.astype(jnp.float32)
    stacked = jnp.stack([w, confidences * w, accuracies * w])  # (3, N)
    suffix = jnp.matmul(stacked, ind, precision=jax.lax.Precision.HIGHEST)  # (3, B+1)
    # bin k (k < n_bins) spans [b_k, b_{k+1}); the extra slot n_bins holds conf == 1.0 exactly.
    # values below b_0 = 0.0 cannot occur (confidences are probabilities), matching the clip.
    sums = jnp.concatenate([suffix[:, :-1] - suffix[:, 1:], suffix[:, -1:]], axis=1)
    return sums[0], sums[1], sums[2]


def _ce_compute(count: Array, conf_sum: Array, acc_sum: Array, norm: str = "l1") -> Array:
    """Expected/max calibration error from per-bin sums (reference ``calibration_error.py:72``)."""
    total = jnp.sum(count)
    prop = _safe_divide(count, total)
    conf_mean = _safe_divide(conf_sum, count)
    acc_mean = _safe_divide(acc_sum, count)
    gap = jnp.abs(acc_mean - conf_mean)
    if norm == "l1":
        return jnp.sum(gap * prop)
    if norm == "l2":
        return jnp.sqrt(jnp.maximum(jnp.sum(gap**2 * prop), 0.0))
    if norm == "max":
        return jnp.max(jnp.where(count > 0, gap, 0.0))
    raise ValueError(f"Argument `norm` is expected to be one of 'l1', 'l2', 'max' but got {norm}")


def _binary_calibration_error_arg_validation(
    n_bins: int, norm: str = "l1", ignore_index: Optional[int] = None
) -> None:
    if not isinstance(n_bins, int) or n_bins < 1:
        raise ValueError(f"Argument `n_bins` must be an integer larger than 0, but got {n_bins}")
    if norm not in ("l1", "l2", "max"):
        raise ValueError(f"Argument `norm` is expected to be one of 'l1', 'l2', 'max' but got {norm}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Argument `ignore_index` must be either `None` or an integer, but got {ignore_index}")


def _binary_calibration_error_tensor_validation(
    preds: Array, target: Array, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        raise ValueError(f"Expected argument `preds` to be floating tensor, but got {jnp.asarray(preds).dtype}")
    if is_traced(preds, target):
        return
    t = np.asarray(target)
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    unique = set(np.unique(t).tolist())
    if not unique.issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {sorted(unique)} but expected only"
            f" the following values {sorted(allowed)}."
        )


def _binary_confidences_accuracies(
    preds: Array, target: Array, ignore_index: Optional[int] = None
) -> Tuple[Array, Array, Array]:
    preds = jnp.reshape(preds, (-1,))
    target = jnp.reshape(target, (-1,))
    preds = normalize_logits_if_needed(preds, "sigmoid")
    if ignore_index is not None:
        weight = (target != ignore_index).astype(jnp.float32)
        target = jnp.where(target == ignore_index, 0, target)
    else:
        weight = jnp.ones(target.shape, jnp.float32)
    confidences = jnp.where(preds > 0.5, preds, 1 - preds)
    accuracies = (jnp.where(preds > 0.5, 1, 0) == target).astype(jnp.float32)
    return confidences, accuracies, weight


def binary_calibration_error(
    preds: Array,
    target: Array,
    n_bins: int = 15,
    norm: str = "l1",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Top-label calibration error, binary (reference ``calibration_error.py:129``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import binary_calibration_error
        >>> preds = np.array([0.25, 0.25, 0.55, 0.75, 0.75], np.float32)
        >>> target = np.array([0, 0, 1, 1, 1])
        >>> print(f"{float(binary_calibration_error(preds, target, n_bins=2)):.4f}")
        0.2900
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)
        _binary_calibration_error_tensor_validation(preds, target, ignore_index)
    confidences, accuracies, weight = _binary_confidences_accuracies(preds, target, ignore_index)
    count, conf_sum, acc_sum = _binning_bucketize(confidences, accuracies, weight, n_bins)
    return _ce_compute(count, conf_sum, acc_sum, norm)


def _multiclass_calibration_error_arg_validation(
    num_classes: int, n_bins: int, norm: str = "l1", ignore_index: Optional[int] = None
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Argument `num_classes` must be an integer larger than 1, but got {num_classes}")
    _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)


def _multiclass_calibration_error_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    if preds.ndim != target.ndim + 1:
        raise ValueError("Expected `preds` to have one more dimension than `target`")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError(f"`preds` must be a float tensor, but got {preds.dtype}")
    if preds.shape[1] != num_classes:
        raise ValueError(f"Expected `preds.shape[1]={preds.shape[1]}` to equal num_classes {num_classes}")
    if is_traced(preds, target):
        return
    t = np.asarray(target)
    if ignore_index is not None:
        t = t[t != ignore_index]
    if t.size and (t.min() < 0 or t.max() >= num_classes):
        raise RuntimeError(f"Detected values in `target` outside [0, {num_classes})")


def _multiclass_confidences_accuracies(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> Tuple[Array, Array, Array]:
    preds = jnp.moveaxis(preds, 1, -1).reshape((-1, num_classes))
    target = jnp.reshape(target, (-1,))
    preds = normalize_logits_if_needed(preds, "softmax")
    if ignore_index is not None:
        weight = (target != ignore_index).astype(jnp.float32)
        target = jnp.where(target == ignore_index, 0, target)
    else:
        weight = jnp.ones(target.shape, jnp.float32)
    confidences = jnp.max(preds, axis=-1)
    accuracies = (jnp.argmax(preds, axis=-1) == target).astype(jnp.float32)
    return confidences, accuracies, weight


def multiclass_calibration_error(
    preds: Array,
    target: Array,
    num_classes: int,
    n_bins: int = 15,
    norm: str = "l1",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Top-label calibration error, multiclass (reference ``calibration_error.py:263``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multiclass_calibration_error_arg_validation(num_classes, n_bins, norm, ignore_index)
        _multiclass_calibration_error_tensor_validation(preds, target, num_classes, ignore_index)
    confidences, accuracies, weight = _multiclass_confidences_accuracies(
        preds, target, num_classes, ignore_index
    )
    count, conf_sum, acc_sum = _binning_bucketize(confidences, accuracies, weight, n_bins)
    return _ce_compute(count, conf_sum, acc_sum, norm)


def calibration_error(
    preds: Array,
    target: Array,
    task: str,
    n_bins: int = 15,
    norm: str = "l1",
    num_classes: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatching entrypoint (reference ``calibration_error.py:390``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import calibration_error
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> print(f"{float(calibration_error(preds, target, task='binary', n_bins=2)):.4f}")
        0.0125
    """
    from torchmetrics_tpu.utils.enums import ClassificationTaskNoMultilabel

    task = ClassificationTaskNoMultilabel.from_str(task)
    if task == ClassificationTaskNoMultilabel.BINARY:
        return binary_calibration_error(preds, target, n_bins, norm, ignore_index, validate_args)
    if task == ClassificationTaskNoMultilabel.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_calibration_error(preds, target, num_classes, n_bins, norm, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
