"""Stat-scores (tp/fp/tn/fn) kernels — the foundation of the classification stack.

Parity: reference ``src/torchmetrics/functional/classification/stat_scores.py`` with the same
5-function decomposition per task (``_arg_validation:25`` → ``_tensor_validation:48`` →
``_format:90`` → ``_update:120`` → ``_compute:134`` for binary; multiclass ``:363-448``;
multilabel below that).

TPU-first redesign:

- ``ignore_index`` never drops elements (dynamic shapes): a float mask rides along and weights
  every count — XLA fuses it into the reductions.
- the multiclass path is a weighted one-hot matmul on the MXU (``ops.confusion_matrix_update``)
  instead of the reference's fused-index bincount (``stat_scores.py:405-418``).
- logits-vs-probs is decided on-device (``normalize_logits_if_needed``) instead of host branching.

All ``_format``/``_update``/``_compute`` functions are pure and jit-safe; ``_tensor_validation``
is host-side and no-ops under trace.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.ops import confusion_matrix_update
from torchmetrics_tpu.utils.checks import _check_same_shape, is_traced
from torchmetrics_tpu.utils.compute import _safe_divide, normalize_logits_if_needed
from torchmetrics_tpu.utils.data import select_topk
from torchmetrics_tpu.utils.enums import ClassificationTask

CountType = jnp.float32  # counts are carried as f32 (exact up to 2**24; states sum across batches)


# --------------------------------------------------------------------- binary
def _binary_stat_scores_arg_validation(
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Argument `threshold` must be a float in the [0,1] range, but got {threshold}.")
    if multidim_average not in ("global", "samplewise"):
        raise ValueError(
            f"Expected argument `multidim_average` to be one of ['global', 'samplewise'], but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Argument `ignore_index` must be either `None` or an integer, but got {ignore_index}")


def _binary_stat_scores_tensor_validation(
    preds: Array,
    target: Array,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    _check_same_shape(preds, target)
    if multidim_average != "global" and preds.ndim < 2:
        raise ValueError('Inputs must be at least 2D when multidim_average is set to `samplewise`')
    if is_traced(preds, target):
        return
    t = np.asarray(target)
    unique = np.unique(t)
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    if not set(unique.tolist()).issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {sorted(unique.tolist())} but expected only"
            f" the following values {sorted(allowed)}."
        )
    p = np.asarray(preds)
    # jnp.issubdtype: numpy's hierarchy does not classify ml_dtypes' bfloat16 as floating,
    # so bf16 probability tensors would be misread as label tensors
    if not jnp.issubdtype(p.dtype, jnp.floating):
        uniquep = set(np.unique(p).tolist())
        if not uniquep.issubset({0, 1}):
            raise RuntimeError(
                f"Detected the following values in `preds`: {sorted(uniquep)} but expected only"
                " the following values [0,1] since preds is a label tensor."
            )


def _binary_stat_scores_format(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array]:
    """Flatten to (N, S); binarise preds; build the ignore mask. Returns (preds01, target01, mask)."""
    if jnp.issubdtype(preds.dtype, jnp.floating):
        preds = normalize_logits_if_needed(preds, "sigmoid")
        preds = (preds > threshold).astype(jnp.int32)
    else:
        preds = preds.astype(jnp.int32)
    n = target.shape[0] if target.ndim else 1
    preds = jnp.reshape(preds, (n, -1))
    target_r = jnp.reshape(target, (n, -1))
    if ignore_index is not None:
        mask = (target_r != ignore_index).astype(CountType)
        target_r = jnp.where(target_r == ignore_index, 0, target_r)
    else:
        mask = jnp.ones(target_r.shape, CountType)
    return preds, target_r.astype(jnp.int32), mask


def _binary_stat_scores_update(
    preds: Array,
    target: Array,
    mask: Array,
    multidim_average: str = "global",
) -> Tuple[Array, Array, Array, Array]:
    """Masked tp/fp/tn/fn sums (reference ``stat_scores.py:120-131``)."""
    axis = 1 if multidim_average == "samplewise" else None
    p = preds.astype(CountType)
    t = target.astype(CountType)
    tp = jnp.sum(mask * p * t, axis=axis)
    fp = jnp.sum(mask * p * (1 - t), axis=axis)
    fn = jnp.sum(mask * (1 - p) * t, axis=axis)
    tn = jnp.sum(mask * (1 - p) * (1 - t), axis=axis)
    if multidim_average == "global":
        tp, fp, tn, fn = (jnp.reshape(x, ()) for x in (tp, fp, tn, fn))
    return tp, fp, tn, fn


def _binary_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array, multidim_average: str = "global"
) -> Array:
    """Pack [tp, fp, tn, fn, support] (reference ``stat_scores.py:134``)."""
    stacked = jnp.stack([tp, fp, tn, fn, tp + fn], axis=0 if tp.ndim == 0 else -1)
    return stacked.astype(jnp.int32)


def binary_stat_scores(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Number of tp/fp/tn/fn for binary tasks (reference ``stat_scores.py:156``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import binary_stat_scores
        >>> preds = np.array([0.9, 0.1, 0.8, 0.4], np.float32)
        >>> target = np.array([1, 0, 1, 1])
        >>> print(np.asarray(binary_stat_scores(preds, target)))
        [2 0 1 1 3]
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
    preds, target, mask = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    tp, fp, tn, fn = _binary_stat_scores_update(preds, target, mask, multidim_average)
    return _binary_stat_scores_compute(tp, fp, tn, fn, multidim_average)


# ------------------------------------------------------------------ multiclass
def _multiclass_stat_scores_arg_validation(
    num_classes: int,
    top_k: int = 1,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Argument `num_classes` must be an integer larger than 1, but got {num_classes}")
    if not isinstance(top_k, int) and top_k < 1:
        raise ValueError(f"Expected argument `top_k` to be an integer larger than or equal to 1, but got {top_k}")
    if top_k > num_classes:
        raise ValueError(
            f"Expected argument `top_k` to be smaller or equal to `num_classes` but got {top_k} and {num_classes}"
        )
    allowed_average = ("micro", "macro", "weighted", "none", None)
    if average not in allowed_average:
        raise ValueError(f"Expected argument `average` to be one of {allowed_average}, but got {average}")
    if multidim_average not in ("global", "samplewise"):
        raise ValueError(
            f"Expected argument `multidim_average` to be one of ['global', 'samplewise'], but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Argument `ignore_index` must be either `None` or an integer, but got {ignore_index}")


def _multiclass_stat_scores_tensor_validation(
    preds: Array,
    target: Array,
    num_classes: int,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    top_k: int = 1,
) -> None:
    if preds.ndim == target.ndim + 1:
        if not jnp.issubdtype(preds.dtype, jnp.floating):
            raise ValueError('If `preds` have one dimension more than `target`, `preds` must be a float tensor.')
        if preds.shape[1] != num_classes:
            raise ValueError("If `preds` have one dimension more than `target`, `preds.shape[1]` should be"
                             " equal to number of classes.")
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should be"
                " (N, C, ...), and the shape of `target` should be (N, ...)."
            )
        if multidim_average != "global" and preds.ndim < 3:
            raise ValueError("If `preds` have one dimension more than `target`, the shape of `preds` should"
                             " be at least 3D when multidim_average is set to `samplewise`")
    elif preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError("The `preds` and `target` should have the same shape,")
        if multidim_average != "global" and preds.ndim < 2:
            raise ValueError("When `preds` and `target` have the same shape, the shape should be at least 2D"
                             " when multidim_average is set to `samplewise`")
        if top_k != 1:
            raise ValueError("If `preds` and `target` have the same shape, then `top_k` should be set to 1.")
    else:
        raise ValueError("Either `preds` and `target` both should have the (same) shape (N, ...), or `target`"
                         " should be (N, ...) and `preds` should be (N, C, ...).")
    if is_traced(preds, target):
        return
    t = np.asarray(target)
    if ignore_index is not None:
        t = t[t != ignore_index]
    if t.size and (t.min() < 0 or t.max() >= num_classes):
        if not (ignore_index is not None and (t.max() == ignore_index or t.min() == ignore_index)):
            raise RuntimeError(
                f"Detected more unique values in `target` than expected. Expected only {num_classes} but found"
                f" values in range [{t.min()}, {t.max()}]."
            )
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        p = np.asarray(preds)
        if p.size and (p.min() < 0 or p.max() >= num_classes):
            raise RuntimeError(
                f"Detected more unique values in `preds` than expected. Expected only {num_classes} but found"
                f" values in range [{p.min()}, {p.max()}]."
            )


def _multiclass_stat_scores_format(
    preds: Array,
    target: Array,
    top_k: int = 1,
) -> Tuple[Array, Array]:
    """(N, C, S...) float preds → (N, S) labels (top_k=1) or keep scores; flatten extra dims."""
    if jnp.issubdtype(preds.dtype, jnp.floating) and preds.ndim == target.ndim + 1:
        if top_k == 1:
            preds = jnp.argmax(preds, axis=1)
            preds = jnp.reshape(preds, (preds.shape[0], -1))
        else:
            preds = jnp.reshape(preds, (preds.shape[0], preds.shape[1], -1))
    else:
        preds = jnp.reshape(preds, (preds.shape[0], -1)).astype(jnp.int32)
    target = jnp.reshape(target, (target.shape[0], -1))
    return preds, target


def _multiclass_stat_scores_update(
    preds: Array,
    target: Array,
    num_classes: int,
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Per-class (C,) [global] or per-sample-per-class (N, C) [samplewise] counts.

    MXU path: weighted one-hot products; the global top_k=1 case is a single (C, N)x(N, C)
    matmul via ``confusion_matrix_update``.
    """
    mask = (target != ignore_index).astype(CountType) if ignore_index is not None else jnp.ones(target.shape, CountType)
    target_safe = jnp.where(mask > 0, target, 0).astype(jnp.int32)

    if top_k > 1:
        # preds: (N, C, S) scores; one-hot top-k membership
        pred_mask = select_topk(preds, top_k, dim=1).astype(CountType)  # (N, C, S)
        oh_t = jnp.moveaxis(jax.nn.one_hot(target_safe, num_classes, dtype=CountType), -1, 1)  # (N, C, S)
        w = mask[:, None, :]
        axis = (2,) if multidim_average == "samplewise" else (0, 2)
        tp = jnp.sum(pred_mask * oh_t * w, axis=axis)
        fp = jnp.sum(pred_mask * (1 - oh_t) * w, axis=axis)
        fn = jnp.sum((1 - pred_mask) * oh_t * w, axis=axis)
        if multidim_average == "global":
            n_valid = jnp.sum(mask)
            tn = n_valid - tp - fp - fn
        else:
            n_valid = jnp.sum(mask, axis=1)
            tn = n_valid[:, None] - tp - fp - fn
        return tp, fp, tn, fn

    if multidim_average == "global":
        cm = confusion_matrix_update(
            jnp.reshape(preds, (-1,)), jnp.reshape(target_safe, (-1,)), num_classes,
            weights=jnp.reshape(mask, (-1,)), dtype=CountType,
        )  # (C, C), rows = target, cols = preds
        tp = jnp.diagonal(cm)
        fp = jnp.sum(cm, axis=0) - tp
        fn = jnp.sum(cm, axis=1) - tp
        tn = jnp.sum(cm) - tp - fp - fn
        return tp, fp, tn, fn

    # samplewise: per-sample one-hot sums over the flattened extra dim
    oh_p = jax.nn.one_hot(preds, num_classes, dtype=CountType)  # (N, S, C)
    oh_t = jax.nn.one_hot(target_safe, num_classes, dtype=CountType)
    w = mask[..., None]
    tp = jnp.sum(oh_p * oh_t * w, axis=1)
    fp = jnp.sum(oh_p * (1 - oh_t) * w, axis=1)
    fn = jnp.sum((1 - oh_p) * oh_t * w, axis=1)
    n_valid = jnp.sum(mask, axis=1)
    tn = n_valid[:, None] - tp - fp - fn
    return tp, fp, tn, fn


def _multiclass_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
) -> Array:
    """Apply micro/macro averaging and pack [tp, fp, tn, fn, support]."""
    res = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    if average == "micro":
        res = jnp.sum(res, axis=-2)
    elif average in ("macro", "weighted"):
        pass  # reference returns per-class counts for macro/weighted too (stat_scores only)
    return res.astype(jnp.int32)


def multiclass_stat_scores(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """tp/fp/tn/fn for multiclass tasks (reference ``stat_scores.py:451``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index, top_k)
    preds, target = _multiclass_stat_scores_format(preds, target, top_k)
    tp, fp, tn, fn = _multiclass_stat_scores_update(
        preds, target, num_classes, top_k, multidim_average, ignore_index
    )
    return _multiclass_stat_scores_compute(tp, fp, tn, fn, average, multidim_average)


# ------------------------------------------------------------------ multilabel
def _multilabel_stat_scores_arg_validation(
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Argument `num_labels` must be an integer larger than 1, but got {num_labels}")
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Argument `threshold` must be a float, but got {threshold}.")
    allowed_average = ("micro", "macro", "weighted", "none", None)
    if average not in allowed_average:
        raise ValueError(f"Expected argument `average` to be one of {allowed_average}, but got {average}")
    if multidim_average not in ("global", "samplewise"):
        raise ValueError(
            f"Expected argument `multidim_average` to be one of ['global', 'samplewise'], but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Argument `ignore_index` must be either `None` or an integer, but got {ignore_index}")


def _multilabel_stat_scores_tensor_validation(
    preds: Array,
    target: Array,
    num_labels: int,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    _check_same_shape(preds, target)
    if preds.shape[1] != num_labels:
        raise ValueError(
            f"Expected both `target.shape[1]` and `preds.shape[1]` to be equal to the number of labels"
            f" but got {preds.shape[1]} and expected {num_labels}"
        )
    if multidim_average != "global" and preds.ndim < 3:
        raise ValueError('Inputs must be at least 3D when multidim_average is set to `samplewise`')
    if is_traced(preds, target):
        return
    t = np.asarray(target)
    unique = set(np.unique(t).tolist())
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    if not unique.issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {sorted(unique)} but expected only"
            f" the following values {sorted(allowed)}."
        )


def _multilabel_stat_scores_format(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array]:
    """(N, L, S...) → thresholded int preds, target, mask; extra dims flattened."""
    if jnp.issubdtype(preds.dtype, jnp.floating):
        preds = normalize_logits_if_needed(preds, "sigmoid")
        preds = (preds > threshold).astype(jnp.int32)
    else:
        preds = preds.astype(jnp.int32)
    preds = jnp.reshape(preds, (preds.shape[0], preds.shape[1], -1))
    target_r = jnp.reshape(target, (target.shape[0], target.shape[1], -1))
    if ignore_index is not None:
        mask = (target_r != ignore_index).astype(CountType)
        target_r = jnp.where(target_r == ignore_index, 0, target_r)
    else:
        mask = jnp.ones(target_r.shape, CountType)
    return preds, target_r.astype(jnp.int32), mask


def _multilabel_stat_scores_update(
    preds: Array,
    target: Array,
    mask: Array,
    multidim_average: str = "global",
) -> Tuple[Array, Array, Array, Array]:
    """Per-label counts: (L,) [global] or (N, L) [samplewise]."""
    axis = (0, 2) if multidim_average == "global" else (2,)
    p = preds.astype(CountType)
    t = target.astype(CountType)
    tp = jnp.sum(mask * p * t, axis=axis)
    fp = jnp.sum(mask * p * (1 - t), axis=axis)
    fn = jnp.sum(mask * (1 - p) * t, axis=axis)
    tn = jnp.sum(mask * (1 - p) * (1 - t), axis=axis)
    return tp, fp, tn, fn


def _multilabel_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
) -> Array:
    res = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    if average == "micro":
        res = jnp.sum(res, axis=-2)
    return res.astype(jnp.int32)


def multilabel_stat_scores(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """tp/fp/tn/fn for multilabel tasks (reference ``stat_scores.py:742``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target, mask = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    tp, fp, tn, fn = _multilabel_stat_scores_update(preds, target, mask, multidim_average)
    return _multilabel_stat_scores_compute(tp, fp, tn, fn, average, multidim_average)


def stat_scores(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: str = "global",
    top_k: int = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatching entrypoint (reference ``stat_scores.py:1040``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import stat_scores
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> np.asarray(stat_scores(preds, target, task='multiclass', num_classes=3, average='micro')).tolist()
        [3, 1, 7, 1, 4]
    """
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_stat_scores(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_stat_scores(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
        return multilabel_stat_scores(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
