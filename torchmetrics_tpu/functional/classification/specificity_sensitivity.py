"""Best specificity at a fixed sensitivity floor (reference
``src/torchmetrics/functional/classification/specificity_sensitivity.py``)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.functional.classification.roc import (
    _binary_roc_compute,
    _multiclass_roc_compute,
    _multilabel_roc_compute,
)


def _specificity_at_sensitivity(
    specificity: Array, sensitivity: Array, thresholds: Array, min_sensitivity: float
) -> Tuple[Array, Array]:
    """max specificity subject to sensitivity >= min_sensitivity; (0, 1e6) when infeasible."""
    mask = sensitivity >= min_sensitivity
    spec_m = jnp.where(mask, specificity, -1.0)
    idx = jnp.argmax(spec_m, axis=-1)
    has_any = jnp.any(mask, axis=-1)
    best = jnp.where(has_any, jnp.take_along_axis(spec_m, idx[..., None], axis=-1)[..., 0], 0.0)
    best = jnp.maximum(best, 0.0)
    thr = jnp.where(
        has_any, jnp.take_along_axis(jnp.broadcast_to(thresholds, spec_m.shape), idx[..., None], axis=-1)[..., 0], 1e6
    )
    return best, thr


def _val_arg(min_sensitivity: float) -> None:
    if not isinstance(min_sensitivity, float) or not (0 <= min_sensitivity <= 1):
        raise ValueError(
            f"Argument `min_sensitivity` must be an float in the [0,1] range, but got {min_sensitivity}"
        )


def binary_specificity_at_sensitivity(
    preds: Array,
    target: Array,
    min_sensitivity: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """(max specificity, threshold) at fixed sensitivity (reference ``:130``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _val_arg(min_sensitivity)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, weight, thresholds = _binary_precision_recall_curve_format(
        preds, target, thresholds, ignore_index
    )
    if thresholds is None:
        fpr, tpr, thr = _binary_roc_compute((preds, target, weight), None)
    else:
        state = _binary_precision_recall_curve_update(preds, target, weight, thresholds)
        fpr, tpr, thr = _binary_roc_compute(state, thresholds)
    return _specificity_at_sensitivity(1 - fpr, tpr, thr, min_sensitivity)


def multiclass_specificity_at_sensitivity(
    preds: Array,
    target: Array,
    num_classes: int,
    min_sensitivity: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-class (max specificity, threshold) at fixed sensitivity (reference ``:232``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
        _val_arg(min_sensitivity)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, weight, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None:
        fpr, tpr, thr = _multiclass_roc_compute((preds, target, weight), num_classes, None)
        res = [
            _specificity_at_sensitivity(1 - f, t, h, min_sensitivity) for f, t, h in zip(fpr, tpr, thr)
        ]
        return jnp.stack([v for v, _ in res]), jnp.stack([h for _, h in res])
    state = _multiclass_precision_recall_curve_update(preds, target, weight, num_classes, thresholds)
    fpr, tpr, thr = _multiclass_roc_compute(state, num_classes, thresholds)
    return _specificity_at_sensitivity(1 - fpr, tpr, thr, min_sensitivity)


def multilabel_specificity_at_sensitivity(
    preds: Array,
    target: Array,
    num_labels: int,
    min_sensitivity: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-label (max specificity, threshold) at fixed sensitivity (reference ``:330``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _val_arg(min_sensitivity)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, weight, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    if thresholds is None:
        fpr, tpr, thr = _multilabel_roc_compute((preds, target, weight), num_labels, None, ignore_index)
        res = [
            _specificity_at_sensitivity(1 - f, t, h, min_sensitivity) for f, t, h in zip(fpr, tpr, thr)
        ]
        return jnp.stack([v for v, _ in res]), jnp.stack([h for _, h in res])
    state = _multilabel_precision_recall_curve_update(preds, target, weight, num_labels, thresholds)
    fpr, tpr, thr = _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)
    return _specificity_at_sensitivity(1 - fpr, tpr, thr, min_sensitivity)
