"""Dice score kernels (reference ``src/torchmetrics/functional/classification/dice.py``).

Dice = 2·tp / (2·tp + fp + fn) — F1 under another name; the reference's single legacy ``dice``
entrypoint (auto-detecting binary/multiclass inputs, ``average`` ∈ micro/macro/none/samples,
``mdmc_average`` ∈ global/samplewise, ``ignore_index`` dropping a CLASS from the statistics) is
reproduced over the new-style stat-scores kernels.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification.stat_scores import (
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_update,
)
from torchmetrics_tpu.utils.checks import is_traced
from torchmetrics_tpu.utils.compute import _safe_divide, normalize_logits_if_needed


def _dice_from_counts(
    tp: Array, fp: Array, fn: Array, average: Optional[str], zero_division: float = 0.0
) -> Array:
    if average in ("micro", "samples"):
        # "samples": counts arrive as (N, C) samplewise; micro-reduce within each sample,
        # then mean over samples (reference average='samples' semantics)
        tp, fp, fn = jnp.sum(tp, axis=-1), jnp.sum(fp, axis=-1), jnp.sum(fn, axis=-1)
    score = _safe_divide(2 * tp, 2 * tp + fp + fn, zero_division)
    if average == "macro":
        # classes absent from both preds and target are dropped from the mean (reference
        # _reduce_stat_scores ignores tp+fp+fn == 0 rows)
        present = (tp + fp + fn) > 0
        return _safe_divide(
            jnp.sum(jnp.where(present, score, 0.0), axis=-1),
            jnp.sum(present, axis=-1),
            zero_division,
        )
    if average == "samples":
        return jnp.mean(score)
    return score


def _dice_update(
    preds: Array,
    target: Array,
    num_classes: int,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    ignore_index: Optional[int] = None,
    samplewise: bool = False,
) -> Tuple[Array, Array, Array]:
    """Per-class (tp, fp, fn); ``ignore_index`` drops that class's statistics (legacy semantics)."""
    if jnp.issubdtype(preds.dtype, jnp.floating) and preds.ndim == target.ndim:
        # binary probabilities
        preds = (normalize_logits_if_needed(preds, "sigmoid") > threshold).astype(jnp.int32)
    preds_f, target_f = _multiclass_stat_scores_format(preds, target, top_k or 1)
    tp, fp, tn, fn = _multiclass_stat_scores_update(
        preds_f, target_f, num_classes, top_k or 1,
        "samplewise" if samplewise else "global", None,
    )
    if ignore_index is not None:
        keep = jnp.arange(num_classes) != ignore_index
        tp = tp[..., keep]
        fp = fp[..., keep]
        fn = fn[..., keep]
    return tp, fp, fn


def _to_binary_for_multiclass_false(preds: Array, target: Array):
    """Legacy ``multiclass=False`` re-read (reference ``checks.py:440-450``): 2-column scores
    become the positive-class indicator; integer inputs must already be binary. Value checks
    are host-side and skip under trace (the ``validate_args`` contract of this codebase)."""
    if preds.ndim == target.ndim + 1 and jnp.issubdtype(preds.dtype, jnp.floating):
        if preds.shape[1] != 2:
            raise ValueError(
                "You have set `multiclass=False`, but have more than 2 classes in your data,"
                " based on the C dimension of `preds`."
            )
        preds = (jnp.argmax(preds, axis=1) == 1).astype(jnp.int32)
    elif not is_traced(preds) and int(jnp.max(preds)) > 1:
        raise ValueError(
            "If you set `multiclass=False` and `preds` are integers, then `preds` should not exceed 1."
        )
    if not is_traced(target) and int(jnp.max(target)) > 1:
        raise ValueError("If you set `multiclass=False`, then `target` should not exceed 1.")
    return preds, target


def _infer_num_classes(preds: Array, target: Array, num_classes: Optional[int]) -> int:
    if num_classes is not None:
        return num_classes
    if preds.ndim == target.ndim + 1:
        return preds.shape[1]
    m = max(int(jax.device_get(jnp.max(preds))), int(jax.device_get(jnp.max(target))))
    return max(m + 1, 2)


def dice(
    preds: Array,
    target: Array,
    zero_division: float = 0.0,
    average: Optional[str] = "micro",
    mdmc_average: Optional[str] = "global",
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Array:
    """Dice score (reference ``dice.py:89``).

    ``multiclass`` is the legacy type-override flag (reference ``utilities/checks.py:440-450``):
    ``False`` re-interprets 2-class data as binary (positive-class column), ``True`` keeps the
    multiclass treatment (which the one-hot kernel here already applies to binary labels).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import dice
        >>> preds = np.array([0, 2, 1, 2])
        >>> target = np.array([0, 1, 1, 2])
        >>> print(f"{float(dice(preds, target)):.4f}")
        0.7500
    """
    allowed = ("micro", "macro", "samples", "none", None)
    if average not in allowed:
        raise ValueError(f"The `average` has to be one of {allowed}, got {average}.")
    if mdmc_average not in ("global", "samplewise", None):
        raise ValueError(f"The `mdmc_average` has to be 'global', 'samplewise' or None, got {mdmc_average}.")
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if multiclass is False:
        if ignore_index is not None:
            # the legacy formatter reduces the data to binary, where ignore_index is rejected
            # (reference checks.py via dice: "You can not use `ignore_index` with binary data.")
            raise ValueError("You can not use `ignore_index` with binary data.")
        preds, target = _to_binary_for_multiclass_false(preds, target)
    samplewise = average == "samples" or mdmc_average == "samplewise"
    if (
        preds.ndim == target.ndim + 1
        and jnp.issubdtype(preds.dtype, jnp.floating)
        and (top_k or 1) == 1
    ):
        preds_fmt = jnp.argmax(preds, axis=1)
    else:
        preds_fmt = preds  # top_k > 1 keeps the (N, C, ...) scores for the top-k path
    n_cls = _infer_num_classes(preds, target, num_classes)
    tp, fp, fn = _dice_update(preds_fmt, target, n_cls, threshold, top_k, ignore_index, samplewise)
    if multiclass is False:
        # the legacy formatter keeps only the positive-class column (checks.py:440-441), so
        # the reduction sees positive-class statistics alone
        tp, fp, fn = tp[..., 1:2], fp[..., 1:2], fn[..., 1:2]
    if mdmc_average == "samplewise" and average != "samples":
        # per-sample reduction first, then mean over samples (reference mdmc semantics)
        score = _dice_from_counts(tp, fp, fn, average, zero_division)
        return jnp.mean(score, axis=0)
    return _dice_from_counts(tp, fp, fn, average, zero_division)
