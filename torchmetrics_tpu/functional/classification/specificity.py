"""Specificity kernels (reference ``src/torchmetrics/functional/classification/specificity.py``:
``_specificity_reduce:22``, entrypoints ``:62-420``)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification._counts import binary_counts, multiclass_counts, multilabel_counts
from torchmetrics_tpu.utils.compute import _adjust_weights_safe_divide, _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTask


def _specificity_reduce(
    tp: Array, fp: Array, tn: Array, fn: Array,
    average: Optional[str], multidim_average: str = "global", multilabel: bool = False, top_k: int = 1,
) -> Array:
    if average == "binary":
        return _safe_divide(tn, tn + fp)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tn = jnp.sum(tn, axis=axis)
        fp = jnp.sum(fp, axis=axis)
        return _safe_divide(tn, tn + fp)
    specificity_score = _safe_divide(tn, tn + fp)
    return _adjust_weights_safe_divide(specificity_score, average, multilabel, tp, fp, fn, top_k)


def binary_specificity(preds, target, threshold: float = 0.5, multidim_average: str = "global",
                       ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Reference ``specificity.py:62``.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import binary_specificity
        >>> preds = np.array([0.9, 0.1, 0.8, 0.4], np.float32)
        >>> target = np.array([1, 0, 1, 1])
        >>> print(f"{float(binary_specificity(preds, target)):.4f}")
        1.0000
    """
    tp, fp, tn, fn = binary_counts(preds, target, threshold, multidim_average, ignore_index, validate_args)
    return _specificity_reduce(tp, fp, tn, fn, "binary", multidim_average)


def multiclass_specificity(preds, target, num_classes: int, average: Optional[str] = "macro", top_k: int = 1,
                           multidim_average: str = "global", ignore_index: Optional[int] = None,
                           validate_args: bool = True) -> Array:
    """Reference ``specificity.py:129``."""
    tp, fp, tn, fn = multiclass_counts(preds, target, num_classes, average, top_k, multidim_average,
                                       ignore_index, validate_args)
    return _specificity_reduce(tp, fp, tn, fn, average, multidim_average, top_k=top_k)


def multilabel_specificity(preds, target, num_labels: int, threshold: float = 0.5, average: Optional[str] = "macro",
                           multidim_average: str = "global", ignore_index: Optional[int] = None,
                           validate_args: bool = True) -> Array:
    """Reference ``specificity.py:214``."""
    tp, fp, tn, fn = multilabel_counts(preds, target, num_labels, threshold, average, multidim_average,
                                       ignore_index, validate_args)
    return _specificity_reduce(tp, fp, tn, fn, average, multidim_average, multilabel=True)


def specificity(preds, target, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
                num_labels: Optional[int] = None, average: Optional[str] = "micro", multidim_average: str = "global",
                top_k: int = 1, ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Task-dispatching specificity (reference ``specificity.py:299``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import specificity
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> print(f"{float(specificity(preds, target, task='multiclass', num_classes=3)):.4f}")
        0.8750
    """
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_specificity(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_specificity(preds, target, num_classes, average, top_k, multidim_average,
                                      ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
        return multilabel_specificity(preds, target, num_labels, threshold, average, multidim_average,
                                      ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
