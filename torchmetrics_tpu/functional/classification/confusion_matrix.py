"""Confusion-matrix kernels (reference
``src/torchmetrics/functional/classification/confusion_matrix.py``).

TPU-first: the (C, C) tally is a weighted one-hot matmul on the MXU
(``ops.confusion_matrix_update``) instead of the reference's fused-index bincount.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.ops import confusion_matrix_update
from torchmetrics_tpu.utils.checks import _check_same_shape, is_traced
from torchmetrics_tpu.utils.compute import normalize_logits_if_needed
from torchmetrics_tpu.utils.enums import ClassificationTask
from torchmetrics_tpu.utils.prints import rank_zero_warn


def _confusion_matrix_reduce(confmat: Array, normalize: Optional[str] = None) -> Array:
    """Normalise over true/pred/all (reference ``confusion_matrix.py:35-61``)."""
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Argument `normalize` needs to one of the following: {allowed_normalize}")
    if normalize is None or normalize == "none":
        return confmat
    confmat = confmat.astype(jnp.float32)
    if normalize == "true":
        cm = confmat / jnp.sum(confmat, axis=-1, keepdims=True)
    elif normalize == "pred":
        cm = confmat / jnp.sum(confmat, axis=-2, keepdims=True)
    else:
        cm = confmat / jnp.sum(confmat, axis=(-2, -1), keepdims=True)
    return jnp.nan_to_num(cm, nan=0.0)


# --------------------------------------------------------------------- binary
def _binary_confusion_matrix_arg_validation(
    threshold: float = 0.5, ignore_index: Optional[int] = None, normalize: Optional[str] = None
) -> None:
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Argument `threshold` must be a float in the [0,1] range, but got {threshold}.")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Argument `ignore_index` must be either `None` or an integer, but got {ignore_index}")
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Argument `normalize` needs to one of the following: {allowed_normalize}")


def _binary_confusion_matrix_tensor_validation(
    preds: Array, target: Array, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if is_traced(preds, target):
        return
    t = np.asarray(target)
    unique = set(np.unique(t).tolist())
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    if not unique.issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {sorted(unique)} but expected only"
            f" the following values {sorted(allowed)}."
        )
    p = np.asarray(preds)
    # jnp.issubdtype: numpy's hierarchy does not classify ml_dtypes' bfloat16 as floating
    if not jnp.issubdtype(p.dtype, jnp.floating):
        uniquep = set(np.unique(p).tolist())
        if not uniquep.issubset({0, 1}):
            raise RuntimeError(
                f"Detected the following values in `preds`: {sorted(uniquep)} but expected only"
                " binary values since preds is an int tensor."
            )


def _binary_confusion_matrix_format(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    convert_to_labels: bool = True,
) -> tuple:
    preds = jnp.reshape(jnp.asarray(preds), (-1,))
    target = jnp.reshape(jnp.asarray(target), (-1,))
    if jnp.issubdtype(preds.dtype, jnp.floating):
        preds = normalize_logits_if_needed(preds, "sigmoid")
        if convert_to_labels:
            preds = (preds > threshold).astype(jnp.int32)
    else:
        preds = preds.astype(jnp.int32)
    if ignore_index is not None:
        mask = (target != ignore_index)
        target = jnp.where(mask, target, -1)  # -1 rows are dropped by the kernel
    return preds, target.astype(jnp.int32)


def _binary_confusion_matrix_update(preds: Array, target: Array) -> Array:
    return confusion_matrix_update(preds, target, 2)


def _binary_confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    return _confusion_matrix_reduce(confmat, normalize)


def binary_confusion_matrix(
    preds, target, threshold: float = 0.5, normalize: Optional[str] = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """(2, 2) confusion matrix (reference ``confusion_matrix.py:156``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    if validate_args:
        _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize)
        _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    preds, target = _binary_confusion_matrix_format(preds, target, threshold, ignore_index)
    confmat = _binary_confusion_matrix_update(preds, target)
    return _binary_confusion_matrix_compute(confmat, normalize)


# ------------------------------------------------------------------ multiclass
def _multiclass_confusion_matrix_arg_validation(
    num_classes: int, ignore_index: Optional[int] = None, normalize: Optional[str] = None
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Argument `num_classes` must be an integer larger than 1, but got {num_classes}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Argument `ignore_index` must be either `None` or an integer, but got {ignore_index}")
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Argument `normalize` needs to one of the following: {allowed_normalize}")


def _multiclass_confusion_matrix_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    if preds.ndim == target.ndim + 1:
        if not jnp.issubdtype(preds.dtype, jnp.floating):
            raise ValueError('If `preds` have one dimension more than `target`, `preds` must be a float tensor.')
        if preds.shape[1] != num_classes:
            raise ValueError("If `preds` have one dimension more than `target`, `preds.shape[1]` should be"
                             " equal to number of classes.")
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError("If `preds` have one dimension more than `target`, the shape of `preds` should be"
                             " (N, C, ...), and the shape of `target` should be (N, ...).")
    elif preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError("The `preds` and `target` should have the same shape,")
    else:
        raise ValueError("Either `preds` and `target` both should have the (same) shape (N, ...), or `target`"
                         " should be (N, ...) and `preds` should be (N, C, ...).")
    if is_traced(preds, target):
        return
    t = np.asarray(target)
    if ignore_index is not None:
        t = t[t != ignore_index]
    if t.size and (t.min() < 0 or t.max() >= num_classes):
        raise RuntimeError(
            f"Detected more unique values in `target` than expected. Expected only {num_classes} but found"
            f" values in range [{t.min()}, {t.max()}]."
        )
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        p = np.asarray(preds)
        if p.size and (p.min() < 0 or p.max() >= num_classes):
            raise RuntimeError(
                f"Detected more unique values in `preds` than expected. Expected only {num_classes} but found"
                f" values in range [{p.min()}, {p.max()}]."
            )


def _multiclass_confusion_matrix_format(
    preds: Array, target: Array, ignore_index: Optional[int] = None, convert_to_labels: bool = True
) -> tuple:
    if preds.ndim == target.ndim + 1 and convert_to_labels:
        preds = jnp.argmax(preds, axis=1)
    preds = jnp.reshape(preds, (-1,)) if convert_to_labels else jnp.reshape(preds, (-1, preds.shape[1]))
    target = jnp.reshape(target, (-1,))
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, -1, target)  # dropped by kernel
    return preds, target.astype(jnp.int32)


def _multiclass_confusion_matrix_update(preds: Array, target: Array, num_classes: int) -> Array:
    return confusion_matrix_update(preds, target, num_classes)


def _multiclass_confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    return _confusion_matrix_reduce(confmat, normalize)


def multiclass_confusion_matrix(
    preds, target, num_classes: int, normalize: Optional[str] = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """(C, C) confusion matrix (reference ``confusion_matrix.py:286``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import multiclass_confusion_matrix
        >>> preds = np.array([0, 2, 1, 2])
        >>> target = np.array([0, 1, 1, 2])
        >>> print(np.asarray(multiclass_confusion_matrix(preds, target, num_classes=3)))
        [[1 0 0]
         [0 1 1]
         [0 0 1]]
    """
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    if validate_args:
        _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize)
        _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target = _multiclass_confusion_matrix_format(preds, target, ignore_index)
    confmat = _multiclass_confusion_matrix_update(preds, target, num_classes)
    return _multiclass_confusion_matrix_compute(confmat, normalize)


# ------------------------------------------------------------------ multilabel
def _multilabel_confusion_matrix_arg_validation(
    num_labels: int, threshold: float = 0.5, ignore_index: Optional[int] = None, normalize: Optional[str] = None
) -> None:
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Argument `num_labels` must be an integer larger than 1, but got {num_labels}")
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Argument `threshold` must be a float, but got {threshold}.")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Argument `ignore_index` must be either `None` or an integer, but got {ignore_index}")
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Argument `normalize` needs to one of the following: {allowed_normalize}")


def _multilabel_confusion_matrix_tensor_validation(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if preds.shape[1] != num_labels:
        raise ValueError(
            f"Expected both `target.shape[1]` and `preds.shape[1]` to be equal to the number of labels"
            f" but got {preds.shape[1]} and expected {num_labels}"
        )
    if is_traced(preds, target):
        return
    t = np.asarray(target)
    unique = set(np.unique(t).tolist())
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    if not unique.issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {sorted(unique)} but expected only"
            f" the following values {sorted(allowed)}."
        )


def _multilabel_confusion_matrix_format(
    preds: Array, target: Array, num_labels: int, threshold: float = 0.5,
    ignore_index: Optional[int] = None, should_threshold: bool = True,
) -> tuple:
    if jnp.issubdtype(preds.dtype, jnp.floating):
        preds = normalize_logits_if_needed(preds, "sigmoid")
        if should_threshold:
            preds = (preds > threshold).astype(jnp.int32)
    preds = jnp.moveaxis(jnp.reshape(preds, (preds.shape[0], preds.shape[1], -1)), 1, -1).reshape(-1, num_labels)
    target = jnp.moveaxis(jnp.reshape(target, (target.shape[0], target.shape[1], -1)), 1, -1).reshape(-1, num_labels)
    if ignore_index is not None:
        mask = target == ignore_index
        preds = jnp.where(mask, -1, preds)
        target = jnp.where(mask, -1, target)
    return preds.astype(jnp.int32), target.astype(jnp.int32)


def _multilabel_confusion_matrix_update(preds: Array, target: Array, num_labels: int) -> Array:
    """(L, 2, 2) per-label confusion matrices — vectorised masked sums, no scatter."""
    p = preds.astype(jnp.float32)
    t = target.astype(jnp.float32)
    valid = ((preds >= 0) & (target >= 0)).astype(jnp.float32)
    tp = jnp.sum(valid * p * t, axis=0)
    fp = jnp.sum(valid * p * (1 - t), axis=0)
    fn = jnp.sum(valid * (1 - p) * t, axis=0)
    tn = jnp.sum(valid * (1 - p) * (1 - t), axis=0)
    return jnp.stack([tn, fp, fn, tp], axis=-1).reshape(num_labels, 2, 2).astype(jnp.int32)


def _multilabel_confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    return _confusion_matrix_reduce(confmat, normalize)


def multilabel_confusion_matrix(
    preds, target, num_labels: int, threshold: float = 0.5, normalize: Optional[str] = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """(L, 2, 2) confusion matrices (reference ``confusion_matrix.py:427``)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    if validate_args:
        _multilabel_confusion_matrix_arg_validation(num_labels, threshold, ignore_index, normalize)
        _multilabel_confusion_matrix_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target = _multilabel_confusion_matrix_format(preds, target, num_labels, threshold, ignore_index)
    confmat = _multilabel_confusion_matrix_update(preds, target, num_labels)
    return _multilabel_confusion_matrix_compute(confmat, normalize)


def confusion_matrix(
    preds, target, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
    num_labels: Optional[int] = None, normalize: Optional[str] = None,
    ignore_index: Optional[int] = None, validate_args: bool = True,
) -> Array:
    """Task-dispatching confusion matrix (reference ``confusion_matrix.py:578``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import confusion_matrix
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> np.asarray(confusion_matrix(preds, target, task='multiclass', num_classes=3)).tolist()
        [[1, 1, 0], [0, 1, 0], [0, 0, 1]]
    """
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_confusion_matrix(preds, target, threshold, normalize, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_confusion_matrix(preds, target, num_classes, normalize, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
        return multilabel_confusion_matrix(preds, target, num_labels, threshold, normalize, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
