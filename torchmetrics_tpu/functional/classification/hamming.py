"""Hamming distance kernels (reference
``src/torchmetrics/functional/classification/hamming.py``: ``_hamming_distance_reduce:22``,
entrypoints ``:78-437``)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification._counts import binary_counts, multiclass_counts, multilabel_counts
from torchmetrics_tpu.utils.compute import _adjust_weights_safe_divide, _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTask


def _hamming_distance_reduce(
    tp: Array, fp: Array, tn: Array, fn: Array,
    average: Optional[str], multidim_average: str = "global", multilabel: bool = False, top_k: int = 1,
) -> Array:
    """1 - accuracy-style reduce (reference ``hamming.py:22-77``)."""
    if average == "binary":
        return 1 - _safe_divide(tp + tn, tp + fp + tn + fn)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tp = jnp.sum(tp, axis=axis)
        fn = jnp.sum(fn, axis=axis)
        if multilabel:
            fp = jnp.sum(fp, axis=axis)
            tn = jnp.sum(tn, axis=axis)
            return 1 - _safe_divide(tp + tn, tp + tn + fp + fn)
        return 1 - _safe_divide(tp, tp + fn)
    score = _safe_divide(tp + tn, tp + tn + fp + fn) if multilabel else _safe_divide(tp, tp + fn)
    return 1 - _adjust_weights_safe_divide(score, average, multilabel, tp, fp, fn, top_k)


def binary_hamming_distance(preds, target, threshold: float = 0.5, multidim_average: str = "global",
                            ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Reference ``hamming.py:78``.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import binary_hamming_distance
        >>> preds = np.array([0.9, 0.1, 0.8, 0.4], np.float32)
        >>> target = np.array([1, 0, 1, 1])
        >>> print(f"{float(binary_hamming_distance(preds, target)):.4f}")
        0.2500
    """
    tp, fp, tn, fn = binary_counts(preds, target, threshold, multidim_average, ignore_index, validate_args)
    return _hamming_distance_reduce(tp, fp, tn, fn, "binary", multidim_average)


def multiclass_hamming_distance(preds, target, num_classes: int, average: Optional[str] = "macro", top_k: int = 1,
                                multidim_average: str = "global", ignore_index: Optional[int] = None,
                                validate_args: bool = True) -> Array:
    """Reference ``hamming.py:146``."""
    tp, fp, tn, fn = multiclass_counts(preds, target, num_classes, average, top_k, multidim_average,
                                       ignore_index, validate_args)
    return _hamming_distance_reduce(tp, fp, tn, fn, average, multidim_average, top_k=top_k)


def multilabel_hamming_distance(preds, target, num_labels: int, threshold: float = 0.5,
                                average: Optional[str] = "macro", multidim_average: str = "global",
                                ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Reference ``hamming.py:231``."""
    tp, fp, tn, fn = multilabel_counts(preds, target, num_labels, threshold, average, multidim_average,
                                       ignore_index, validate_args)
    return _hamming_distance_reduce(tp, fp, tn, fn, average, multidim_average, multilabel=True)


def hamming_distance(preds, target, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
                     num_labels: Optional[int] = None, average: Optional[str] = "micro",
                     multidim_average: str = "global", top_k: int = 1, ignore_index: Optional[int] = None,
                     validate_args: bool = True) -> Array:
    """Task-dispatching hamming distance (reference ``hamming.py:316``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import hamming_distance
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> print(f"{float(hamming_distance(preds, target, task='multiclass', num_classes=3)):.4f}")
        0.2500
    """
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_hamming_distance(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_hamming_distance(preds, target, num_classes, average, top_k, multidim_average,
                                           ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
        return multilabel_hamming_distance(preds, target, num_labels, threshold, average, multidim_average,
                                           ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
