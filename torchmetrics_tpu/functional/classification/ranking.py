"""Multilabel ranking kernels (reference ``src/torchmetrics/functional/classification/ranking.py``).

Coverage error, label-ranking average precision, label-ranking loss — sklearn semantics, computed
with rank statistics (argsort-free where possible, jit-safe throughout).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape, is_traced
from torchmetrics_tpu.utils.compute import _safe_divide


def _rank_data(x: Array) -> Array:
    """1-based rank of every element along the last axis (average ties NOT needed here: ranks
    by strictly-less counts + 1, matching reference ``ranking.py:24``)."""
    return jnp.sum(x[..., None, :] < x[..., :, None], axis=-1) + 1


def _multilabel_ranking_arg_validation(num_labels: int, ignore_index: Optional[int] = None) -> None:
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Argument `num_labels` must be an integer larger than 1, but got {num_labels}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Argument `ignore_index` must be either `None` or an integer, but got {ignore_index}")


def _multilabel_ranking_tensor_validation(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        raise ValueError(f"`preds` must be a float tensor, but got {jnp.asarray(preds).dtype}")
    if preds.shape[1] != num_labels:
        raise ValueError(f"Expected `preds.shape[1]={preds.shape[1]}` to equal num_labels {num_labels}")
    if is_traced(preds, target):
        return
    t = np.asarray(target)
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    unique = set(np.unique(t).tolist())
    if not unique.issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {sorted(unique)} but expected only"
            f" the following values {sorted(allowed)}."
        )


def _format(preds: Array, target: Array, num_labels: int, ignore_index: Optional[int]):
    preds = jnp.reshape(preds, (-1, num_labels))
    target = jnp.reshape(target, (-1, num_labels))
    if ignore_index is not None:
        weight = (target != ignore_index).astype(jnp.float32)
        target = jnp.where(target == ignore_index, 0, target)
    else:
        weight = jnp.ones(target.shape, jnp.float32)
    return preds.astype(jnp.float32), target.astype(jnp.float32), weight


def _multilabel_coverage_error_update(
    preds: Array, target: Array, weight: Array
) -> Tuple[Array, Array]:
    """Per-sample coverage = max rank (descending) over relevant labels (sklearn semantics)."""
    min_relevant_score = jnp.min(jnp.where((target > 0) & (weight > 0), preds, jnp.inf), axis=-1)
    has_relevant = jnp.any((target > 0) & (weight > 0), axis=-1)
    # coverage = number of labels with score >= min relevant score (among non-ignored)
    cov = jnp.sum((preds >= min_relevant_score[..., None]) * (weight > 0), axis=-1)
    cov = jnp.where(has_relevant, cov, 0.0)
    return jnp.sum(cov.astype(jnp.float32)), jnp.asarray(preds.shape[0], jnp.float32)


def multilabel_coverage_error(
    preds: Array,
    target: Array,
    num_labels: int,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """How far to go down the ranking to cover all relevant labels (reference ``ranking.py:107``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multilabel_ranking_arg_validation(num_labels, ignore_index)
        _multilabel_ranking_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, weight = _format(preds, target, num_labels, ignore_index)
    cov_sum, n = _multilabel_coverage_error_update(preds, target, weight)
    return _safe_divide(cov_sum, n)


def _multilabel_ranking_average_precision_update(
    preds: Array, target: Array, weight: Array
) -> Tuple[Array, Array]:
    """Per-sample LRAP (sklearn ``label_ranking_average_precision_score`` semantics)."""
    relevant = (target > 0) & (weight > 0)
    valid = weight > 0
    # rank among valid labels (descending score): rank_i = #{j valid: score_j >= score_i}
    ge = (preds[..., None, :] >= preds[..., :, None]) & valid[..., None, :]
    rank = jnp.sum(ge, axis=-1).astype(jnp.float32)  # (N, L)
    # L_i = #{j relevant: score_j >= score_i}
    ge_rel = (preds[..., None, :] >= preds[..., :, None]) & relevant[..., None, :]
    l_rank = jnp.sum(ge_rel, axis=-1).astype(jnp.float32)
    per_label = jnp.where(relevant, _safe_divide(l_rank, rank), 0.0)
    n_relevant = jnp.sum(relevant, axis=-1).astype(jnp.float32)
    n_valid = jnp.sum(valid, axis=-1).astype(jnp.float32)
    per_sample = _safe_divide(jnp.sum(per_label, axis=-1), n_relevant)
    # samples with no relevant labels (or all relevant) score 1.0 (sklearn)
    degenerate = (n_relevant == 0) | (n_relevant == n_valid)
    per_sample = jnp.where(degenerate, 1.0, per_sample)
    return jnp.sum(per_sample), jnp.asarray(preds.shape[0], jnp.float32)


def multilabel_ranking_average_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Label-ranking average precision (reference ``ranking.py:167``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multilabel_ranking_arg_validation(num_labels, ignore_index)
        _multilabel_ranking_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, weight = _format(preds, target, num_labels, ignore_index)
    s, n = _multilabel_ranking_average_precision_update(preds, target, weight)
    return _safe_divide(s, n)


def _multilabel_ranking_loss_update(
    preds: Array, target: Array, weight: Array
) -> Tuple[Array, Array]:
    """Per-sample ranking loss = fraction of mis-ordered (relevant, irrelevant) pairs."""
    relevant = ((target > 0) & (weight > 0)).astype(jnp.float32)
    irrelevant = ((target == 0) & (weight > 0)).astype(jnp.float32)
    # count pairs (i relevant, j irrelevant) with score_i <= score_j
    le = (preds[..., :, None] <= preds[..., None, :]).astype(jnp.float32)  # [i, j]
    bad = jnp.einsum("...ij,...i,...j->...", le, relevant, irrelevant)
    n_rel = jnp.sum(relevant, axis=-1)
    n_irr = jnp.sum(irrelevant, axis=-1)
    denom = n_rel * n_irr
    per_sample = jnp.where(denom > 0, bad / jnp.maximum(denom, 1.0), 0.0)
    return jnp.sum(per_sample), jnp.asarray(preds.shape[0], jnp.float32)


def multilabel_ranking_loss(
    preds: Array,
    target: Array,
    num_labels: int,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Label-ranking loss (reference ``ranking.py:227``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multilabel_ranking_arg_validation(num_labels, ignore_index)
        _multilabel_ranking_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, weight = _format(preds, target, num_labels, ignore_index)
    s, n = _multilabel_ranking_loss_update(preds, target, weight)
    return _safe_divide(s, n)
