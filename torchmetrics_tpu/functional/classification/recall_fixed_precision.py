"""Best recall at a fixed precision floor (reference
``src/torchmetrics/functional/classification/recall_fixed_precision.py``).

The reference masks rows (dynamic shape) and lex-argmaxes on (recall, precision, threshold);
here the same selection is a trace-safe ``lexsort`` over masked keys — jit/binned-state friendly.
"""
from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)


def _lex_select_at_constraint(
    maximize: Array, tiebreak: Array, thresholds: Array, constraint_value: Array, constraint_min: float
) -> Tuple[Array, Array]:
    """max over rows satisfying ``constraint_value >= constraint_min`` of ``maximize``,
    lexicographic tie-break by (tiebreak, threshold); returns (best value, its threshold).

    No-satisfying-rows and best-value-0 both map the threshold to 1e6 (reference semantics).
    """
    n = min(maximize.shape[-1], tiebreak.shape[-1], thresholds.shape[-1])
    maximize, tiebreak, thresholds = maximize[..., :n], tiebreak[..., :n], thresholds[..., :n]
    mask = constraint_value[..., :n] >= constraint_min
    key_primary = jnp.where(mask, maximize, -1.0)
    key_secondary = jnp.where(mask, tiebreak, -1.0)
    key_tertiary = jnp.where(mask, thresholds, -1.0)
    order = jnp.lexsort((key_tertiary, key_secondary, key_primary), axis=-1)
    idx = order[..., -1]
    best = jnp.where(jnp.any(mask, axis=-1), jnp.take_along_axis(key_primary, idx[..., None], axis=-1)[..., 0], 0.0)
    best = jnp.maximum(best, 0.0)
    thr = jnp.take_along_axis(key_tertiary, idx[..., None], axis=-1)[..., 0]
    thr = jnp.where(best == 0.0, 1e6, thr)
    return best, thr


def _recall_at_precision(
    precision: Array, recall: Array, thresholds: Array, min_precision: float
) -> Tuple[Array, Array]:
    return _lex_select_at_constraint(recall, precision, thresholds, precision, min_precision)


def _binary_recall_at_fixed_precision_arg_validation(
    min_precision: float, thresholds: Thresholds = None, ignore_index: Optional[int] = None
) -> None:
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
    if not isinstance(min_precision, float) or not (0 <= min_precision <= 1):
        raise ValueError(
            f"Argument `min_precision` must be an float in the [0,1] range, but got {min_precision}"
        )


def _binary_recall_at_fixed_precision_compute(
    state, thresholds: Optional[Array], min_precision: float
) -> Tuple[Array, Array]:
    precision, recall, thresholds = _binary_precision_recall_curve_compute(state, thresholds)
    return _recall_at_precision(precision, recall, thresholds, min_precision)


def binary_recall_at_fixed_precision(
    preds: Array,
    target: Array,
    min_precision: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """(max recall, threshold) subject to precision >= min_precision (reference ``:153``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _binary_recall_at_fixed_precision_arg_validation(min_precision, thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, weight, thresholds = _binary_precision_recall_curve_format(
        preds, target, thresholds, ignore_index
    )
    if thresholds is None:
        return _binary_recall_at_fixed_precision_compute((preds, target, weight), None, min_precision)
    state = _binary_precision_recall_curve_update(preds, target, weight, thresholds)
    return _binary_recall_at_fixed_precision_compute(state, thresholds, min_precision)


def _multiclass_recall_at_fixed_precision_arg_validation(
    num_classes: int, min_precision: float, thresholds: Thresholds = None, ignore_index: Optional[int] = None
) -> None:
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
    if not isinstance(min_precision, float) or not (0 <= min_precision <= 1):
        raise ValueError(
            f"Argument `min_precision` must be an float in the [0,1] range, but got {min_precision}"
        )


def _multiclass_recall_at_fixed_precision_compute(
    state, num_classes: int, thresholds: Optional[Array], min_precision: float
) -> Tuple[Array, Array]:
    precision, recall, thresholds = _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)
    if isinstance(precision, list):
        res = [
            _recall_at_precision(p, r, t, min_precision) for p, r, t in zip(precision, recall, thresholds)
        ]
        return jnp.stack([v for v, _ in res]), jnp.stack([t for _, t in res])
    # binned: thresholds shared (T,), curves (C, T+1) — broadcast thresholds per class
    thr = jnp.broadcast_to(thresholds, (precision.shape[0], thresholds.shape[0]))
    return _recall_at_precision(precision, recall, thr, min_precision)


def multiclass_recall_at_fixed_precision(
    preds: Array,
    target: Array,
    num_classes: int,
    min_precision: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-class (max recall, threshold) at fixed precision (reference ``:253``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multiclass_recall_at_fixed_precision_arg_validation(num_classes, min_precision, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, weight, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None:
        return _multiclass_recall_at_fixed_precision_compute(
            (preds, target, weight), num_classes, None, min_precision
        )
    state = _multiclass_precision_recall_curve_update(preds, target, weight, num_classes, thresholds)
    return _multiclass_recall_at_fixed_precision_compute(state, num_classes, thresholds, min_precision)


def _multilabel_recall_at_fixed_precision_arg_validation(
    num_labels: int, min_precision: float, thresholds: Thresholds = None, ignore_index: Optional[int] = None
) -> None:
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
    if not isinstance(min_precision, float) or not (0 <= min_precision <= 1):
        raise ValueError(
            f"Argument `min_precision` must be an float in the [0,1] range, but got {min_precision}"
        )


def _multilabel_recall_at_fixed_precision_compute(
    state, num_labels: int, thresholds: Optional[Array], ignore_index: Optional[int], min_precision: float
) -> Tuple[Array, Array]:
    precision, recall, thresholds = _multilabel_precision_recall_curve_compute(
        state, num_labels, thresholds, ignore_index
    )
    if isinstance(precision, list):
        res = [
            _recall_at_precision(p, r, t, min_precision) for p, r, t in zip(precision, recall, thresholds)
        ]
        return jnp.stack([v for v, _ in res]), jnp.stack([t for _, t in res])
    thr = jnp.broadcast_to(thresholds, (precision.shape[0], thresholds.shape[0]))
    return _recall_at_precision(precision, recall, thr, min_precision)


def multilabel_recall_at_fixed_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    min_precision: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-label (max recall, threshold) at fixed precision (reference ``:353``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multilabel_recall_at_fixed_precision_arg_validation(num_labels, min_precision, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, weight, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    if thresholds is None:
        return _multilabel_recall_at_fixed_precision_compute(
            (preds, target, weight), num_labels, None, ignore_index, min_precision
        )
    state = _multilabel_precision_recall_curve_update(preds, target, weight, num_labels, thresholds)
    return _multilabel_recall_at_fixed_precision_compute(
        state, num_labels, thresholds, ignore_index, min_precision
    )
