"""Matthews correlation coefficient kernels (reference
``src/torchmetrics/functional/classification/matthews_corrcoef.py``: ``_matthews_corrcoef_reduce:37``).

The reference's data-dependent edge-case branches become ``jnp.where`` selections so the whole
reduce stays a single fused XLA computation.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.classification.confusion_matrix import (
    binary_confusion_matrix,
    multiclass_confusion_matrix,
    multilabel_confusion_matrix,
)
from torchmetrics_tpu.utils.enums import ClassificationTask


def _matthews_corrcoef_reduce(confmat: Array) -> Array:
    confmat = jnp.sum(confmat, axis=0) if confmat.ndim == 3 else confmat  # multilabel → binary
    confmat = confmat.astype(jnp.float32)

    tk = jnp.sum(confmat, axis=-1)
    pk = jnp.sum(confmat, axis=-2)
    c = jnp.trace(confmat)
    s = jnp.sum(confmat)

    cov_ytyp = c * s - jnp.sum(tk * pk)
    cov_ypyp = s**2 - jnp.sum(pk * pk)
    cov_ytyt = s**2 - jnp.sum(tk * tk)
    denom = cov_ypyp * cov_ytyt

    if confmat.size == 4:  # binary edge cases (reference matthews_corrcoef.py:46-74)
        tn, fp, fn, tp = jnp.reshape(confmat, (-1,))
        eps = jnp.asarray(np.finfo(np.float32).eps, jnp.float32)
        # fallback numerator/denominator when denom == 0
        a = jnp.where((tp == 0) | (tn == 0), tp + tn, 0.0)
        b = jnp.where((fp == 0) | (fn == 0), fp + fn, 0.0)
        fallback_num = jnp.sqrt(eps) * (a - b)
        fallback_denom = (tp + fp + eps) * (tp + fn + eps) * (tn + fp + eps) * (tn + fn + eps)
        numerator = jnp.where(denom == 0, fallback_num, cov_ytyp)
        denominator = jnp.where(denom == 0, fallback_denom, denom)
        res = numerator / jnp.sqrt(denominator)
        res = jnp.where((tp + tn != 0) & (fp + fn == 0), 1.0, res)
        res = jnp.where((tp + tn == 0) & (fp + fn != 0), -1.0, res)
        return res
    return jnp.where(denom == 0, 0.0, cov_ytyp / jnp.sqrt(jnp.where(denom == 0, 1.0, denom)))


def binary_matthews_corrcoef(preds, target, threshold: float = 0.5, ignore_index: Optional[int] = None,
                             validate_args: bool = True) -> Array:
    """Reference ``matthews_corrcoef.py:82``.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import binary_matthews_corrcoef
        >>> preds = np.array([0.9, 0.1, 0.8, 0.4], np.float32)
        >>> target = np.array([1, 0, 1, 1])
        >>> print(f"{float(binary_matthews_corrcoef(preds, target)):.4f}")
        0.5774
    """
    confmat = binary_confusion_matrix(preds, target, threshold, None, ignore_index, validate_args)
    return _matthews_corrcoef_reduce(confmat)


def multiclass_matthews_corrcoef(preds, target, num_classes: int, ignore_index: Optional[int] = None,
                                 validate_args: bool = True) -> Array:
    """Reference ``matthews_corrcoef.py:143``."""
    confmat = multiclass_confusion_matrix(preds, target, num_classes, None, ignore_index, validate_args)
    return _matthews_corrcoef_reduce(confmat)


def multilabel_matthews_corrcoef(preds, target, num_labels: int, threshold: float = 0.5,
                                 ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Reference ``matthews_corrcoef.py:209``."""
    confmat = multilabel_confusion_matrix(preds, target, num_labels, threshold, None, ignore_index, validate_args)
    return _matthews_corrcoef_reduce(confmat)


def matthews_corrcoef(preds, target, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
                      num_labels: Optional[int] = None, ignore_index: Optional[int] = None,
                      validate_args: bool = True) -> Array:
    """Task-dispatching MCC (reference ``matthews_corrcoef.py:276``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import matthews_corrcoef
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> print(f"{float(matthews_corrcoef(preds, target, task='multiclass', num_classes=3)):.4f}")
        0.7000
    """
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_matthews_corrcoef(preds, target, threshold, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_matthews_corrcoef(preds, target, num_classes, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
        return multilabel_matthews_corrcoef(preds, target, num_labels, threshold, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
