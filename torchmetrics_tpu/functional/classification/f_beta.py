"""F-beta / F1 kernels (reference ``src/torchmetrics/functional/classification/f_beta.py``:
``_fbeta_reduce:25``, entrypoints ``:84-1181``)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification._counts import binary_counts, multiclass_counts, multilabel_counts
from torchmetrics_tpu.utils.compute import _adjust_weights_safe_divide, _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTask


def _fbeta_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    beta: float,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
    top_k: int = 1,
) -> Array:
    beta2 = beta**2
    if average == "binary":
        return _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tp = jnp.sum(tp, axis=axis)
        fn = jnp.sum(fn, axis=axis)
        fp = jnp.sum(fp, axis=axis)
        return _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp)
    fbeta_score = _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp)
    return _adjust_weights_safe_divide(fbeta_score, average, multilabel, tp, fp, fn, top_k)


def _validate_beta(beta: float) -> None:
    if not (isinstance(beta, float) and beta > 0):
        raise ValueError(f"Argument `beta` must be a float larger than 0, but got {beta}.")


def binary_fbeta_score(preds, target, beta: float, threshold: float = 0.5, multidim_average: str = "global",
                       ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Reference ``f_beta.py:84``."""
    if validate_args:
        _validate_beta(beta)
    tp, fp, tn, fn = binary_counts(preds, target, threshold, multidim_average, ignore_index, validate_args)
    return _fbeta_reduce(tp, fp, tn, fn, beta, "binary", multidim_average)


def multiclass_fbeta_score(preds, target, beta: float, num_classes: int, average: Optional[str] = "macro",
                           top_k: int = 1, multidim_average: str = "global", ignore_index: Optional[int] = None,
                           validate_args: bool = True) -> Array:
    """Reference ``f_beta.py:157``."""
    if validate_args:
        _validate_beta(beta)
    tp, fp, tn, fn = multiclass_counts(preds, target, num_classes, average, top_k, multidim_average,
                                       ignore_index, validate_args)
    return _fbeta_reduce(tp, fp, tn, fn, beta, average, multidim_average, top_k=top_k)


def multilabel_fbeta_score(preds, target, beta: float, num_labels: int, threshold: float = 0.5,
                           average: Optional[str] = "macro", multidim_average: str = "global",
                           ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Reference ``f_beta.py:247``."""
    if validate_args:
        _validate_beta(beta)
    tp, fp, tn, fn = multilabel_counts(preds, target, num_labels, threshold, average, multidim_average,
                                       ignore_index, validate_args)
    return _fbeta_reduce(tp, fp, tn, fn, beta, average, multidim_average, multilabel=True)


def binary_f1_score(preds, target, threshold: float = 0.5, multidim_average: str = "global",
                    ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Reference ``f_beta.py:337``.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import binary_f1_score
        >>> preds = np.array([0.9, 0.1, 0.8, 0.4], np.float32)
        >>> target = np.array([1, 0, 1, 1])
        >>> print(f"{float(binary_f1_score(preds, target)):.4f}")
        0.8000
    """
    return binary_fbeta_score(preds, target, 1.0, threshold, multidim_average, ignore_index, validate_args)


def multiclass_f1_score(preds, target, num_classes: int, average: Optional[str] = "macro", top_k: int = 1,
                        multidim_average: str = "global", ignore_index: Optional[int] = None,
                        validate_args: bool = True) -> Array:
    """Reference ``f_beta.py:403``.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import multiclass_f1_score
        >>> preds = np.array([0, 2, 1, 2])
        >>> target = np.array([0, 1, 1, 2])
        >>> print(f"{float(multiclass_f1_score(preds, target, num_classes=3, average='macro')):.4f}")
        0.7778
    """
    return multiclass_fbeta_score(preds, target, 1.0, num_classes, average, top_k, multidim_average,
                                  ignore_index, validate_args)


def multilabel_f1_score(preds, target, num_labels: int, threshold: float = 0.5, average: Optional[str] = "macro",
                        multidim_average: str = "global", ignore_index: Optional[int] = None,
                        validate_args: bool = True) -> Array:
    """Reference ``f_beta.py:486``."""
    return multilabel_fbeta_score(preds, target, 1.0, num_labels, threshold, average, multidim_average,
                                  ignore_index, validate_args)


def fbeta_score(preds, target, task: str, beta: float = 1.0, threshold: float = 0.5,
                num_classes: Optional[int] = None, num_labels: Optional[int] = None,
                average: Optional[str] = "micro", multidim_average: str = "global", top_k: int = 1,
                ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Task-dispatching F-beta (reference ``f_beta.py:1026``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import fbeta_score
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> print(f"{float(fbeta_score(preds, target, task='multiclass', num_classes=3, beta=0.5)):.4f}")
        0.7500
    """
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_fbeta_score(preds, target, beta, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_fbeta_score(preds, target, beta, num_classes, average, top_k, multidim_average,
                                      ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
        return multilabel_fbeta_score(preds, target, beta, num_labels, threshold, average, multidim_average,
                                      ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")


def f1_score(preds, target, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
             num_labels: Optional[int] = None, average: Optional[str] = "micro", multidim_average: str = "global",
             top_k: int = 1, ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Task-dispatching F1 (reference ``f_beta.py:1090``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import f1_score
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> print(f"{float(f1_score(preds, target, task='multiclass', num_classes=3)):.4f}")
        0.7500
    """
    return fbeta_score(preds, target, task, 1.0, threshold, num_classes, num_labels, average,
                       multidim_average, top_k, ignore_index, validate_args)
