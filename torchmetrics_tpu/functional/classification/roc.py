"""ROC curve kernels (reference ``src/torchmetrics/functional/classification/roc.py:40+``).

Shares the precision-recall-curve state machinery (binned (T, ., 2, 2) confusion state / exact
score lists) — only the finalisation differs.
"""
from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _binary_clf_curve_exact,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.utils.compute import _safe_divide
from torchmetrics_tpu.utils.prints import rank_zero_warn


def _roc_from_confmat(confmat: Array, thresholds: Array) -> Tuple[Array, Array, Array]:
    """(..., T, 2, 2) → (fpr, tpr, thresholds) with thresholds flipped to descending."""
    tps = confmat[..., 1, 1]
    fps = confmat[..., 0, 1]
    fns = confmat[..., 1, 0]
    tns = confmat[..., 0, 0]
    tpr = _safe_divide(tps, tps + fns)[..., ::-1]
    fpr = _safe_divide(fps, fps + tns)[..., ::-1]
    return fpr, tpr, jnp.asarray(thresholds)[::-1]  # thresholds may be a host-concrete grid


def _roc_from_exact(preds: np.ndarray, target: np.ndarray, weight: np.ndarray) -> Tuple[Array, Array, Array]:
    fps, tps, thres = _binary_clf_curve_exact(preds, target, weight)
    tps = np.hstack([0.0, tps])  # ensure the curve starts at (0, 0)
    fps = np.hstack([0.0, fps])
    thres = np.hstack([thres[0] + 1.0, thres])
    if fps[-1] <= 0:
        rank_zero_warn(
            'No negative samples in targets, the false-positive rate here is meaningless. Returning zero tensor in false positive score',
            UserWarning,
        )
        fpr = np.zeros_like(thres)
    else:
        fpr = fps / fps[-1]
    if tps[-1] <= 0:
        rank_zero_warn(
            'No positive samples in targets, the true-positive rate here is meaningless. Returning zero tensor in true positive score',
            UserWarning,
        )
        tpr = np.zeros_like(thres)
    else:
        tpr = tps / tps[-1]
    return jnp.asarray(fpr, jnp.float32), jnp.asarray(tpr, jnp.float32), jnp.asarray(thres, jnp.float32)


def _binary_roc_compute(
    state: Union[Array, Tuple[Array, Array, Array]],
    thresholds: Optional[Array],
) -> Tuple[Array, Array, Array]:
    if thresholds is not None and not isinstance(state, tuple):
        return _roc_from_confmat(state, thresholds)
    preds, target, weight = state
    # exact mode (thresholds=None) is host-mediated by contract: jit callers must bin
    # (pass thresholds) — the static early-return above is the traced path
    return _roc_from_exact(np.asarray(preds), np.asarray(target), np.asarray(weight))  # jaxlint: disable=TPU003


def binary_roc(
    preds: Array,
    target: Array,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array, Array]:
    """ROC curve for binary tasks (reference ``roc.py:92``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import binary_roc
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> fpr, tpr, thresholds = binary_roc(preds, target, thresholds=5)
        >>> print(np.asarray(fpr))
        [0.  0.  0.  0.5 1. ]
        >>> print(np.asarray(tpr))
        [0.  0.5 0.5 1.  1. ]
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, weight, thresholds = _binary_precision_recall_curve_format(
        preds, target, thresholds, ignore_index
    )
    if thresholds is None:
        return _binary_roc_compute((preds, target, weight), None)
    state = _binary_precision_recall_curve_update(preds, target, weight, thresholds)
    return _binary_roc_compute(state, thresholds)


def _multiclass_roc_compute(
    state: Union[Array, Tuple[Array, Array, Array]],
    num_classes: int,
    thresholds: Optional[Array],
    average: Optional[str] = None,
):
    if average == "micro":
        return _binary_roc_compute(state, thresholds)
    if thresholds is not None and not isinstance(state, tuple):
        return _roc_from_confmat(jnp.moveaxis(state, 0, 1), thresholds)  # (C, T, 2, 2)
    preds, target, weight = state
    preds_np, target_np, weight_np = np.asarray(preds), np.asarray(target), np.asarray(weight)
    fprs, tprs, thrs = [], [], []
    for c in range(num_classes):
        f, t, th = _roc_from_exact(preds_np[:, c], (target_np == c).astype(np.float64), weight_np)
        fprs.append(f)
        tprs.append(t)
        thrs.append(th)
    return fprs, tprs, thrs


def multiclass_roc(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Thresholds = None,
    average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """One-vs-rest ROC curves (reference ``roc.py:162``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index, average)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, weight, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index, average
    )
    if average == "micro":
        if thresholds is None:
            return _binary_roc_compute((preds, target, weight), None)
        state = _binary_precision_recall_curve_update(preds, target, weight, thresholds)
        return _binary_roc_compute(state, thresholds)
    if thresholds is None:
        return _multiclass_roc_compute((preds, target, weight), num_classes, None, average)
    state = _multiclass_precision_recall_curve_update(preds, target, weight, num_classes, thresholds)
    return _multiclass_roc_compute(state, num_classes, thresholds, average)


def _multilabel_roc_compute(
    state: Union[Array, Tuple[Array, Array, Array]],
    num_labels: int,
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
):
    if thresholds is not None and not isinstance(state, tuple):
        return _roc_from_confmat(jnp.moveaxis(state, 0, 1), thresholds)
    preds, target, weight = state
    preds_np, target_np, weight_np = np.asarray(preds), np.asarray(target), np.asarray(weight)
    fprs, tprs, thrs = [], [], []
    for lbl in range(num_labels):
        f, t, th = _roc_from_exact(preds_np[:, lbl], target_np[:, lbl], weight_np[:, lbl])
        fprs.append(f)
        tprs.append(t)
        thrs.append(th)
    return fprs, tprs, thrs


def multilabel_roc(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Per-label ROC curves (reference ``roc.py:310``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, weight, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    if thresholds is None:
        return _multilabel_roc_compute((preds, target, weight), num_labels, None, ignore_index)
    state = _multilabel_precision_recall_curve_update(preds, target, weight, num_labels, thresholds)
    return _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)


def roc(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task-dispatching entrypoint (reference ``roc.py:470``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import roc
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> fpr, tpr, thr = roc(preds, target, task='binary', thresholds=4)
        >>> np.asarray(fpr, np.float64).round(4).tolist()
        [0.0, 0.0, 0.5, 1.0]
        >>> np.asarray(tpr, np.float64).round(4).tolist()
        [0.0, 0.5, 1.0, 1.0]
    """
    from torchmetrics_tpu.utils.enums import ClassificationTask

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_roc(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_roc(preds, target, num_classes, thresholds, average, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
        return multilabel_roc(preds, target, num_labels, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
