"""Accuracy kernels (reference ``src/torchmetrics/functional/classification/accuracy.py``).

All heavy lifting is in the stat-scores kernels; this file adds the ``_accuracy_reduce``
finalisation (reference ``accuracy.py:23-80``) and the three task entrypoints.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
    _binary_stat_scores_update,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multiclass_stat_scores_update,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
    _multilabel_stat_scores_update,
)
from torchmetrics_tpu.utils.compute import _adjust_weights_safe_divide, _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTask


def _accuracy_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
    top_k: int = 1,
) -> Array:
    """Reference ``accuracy.py:23-80``."""
    if average == "binary":
        return _safe_divide(tp + tn, tp + tn + fp + fn)
    if average == "micro":
        tp = jnp.sum(tp, axis=0 if multidim_average == "global" else 1)
        fn = jnp.sum(fn, axis=0 if multidim_average == "global" else 1)
        if multilabel:
            fp = jnp.sum(fp, axis=0 if multidim_average == "global" else 1)
            tn = jnp.sum(tn, axis=0 if multidim_average == "global" else 1)
            return _safe_divide(tp + tn, tp + tn + fp + fn)
        return _safe_divide(tp, tp + fn)
    score = _safe_divide(tp + tn, tp + tn + fp + fn) if multilabel else _safe_divide(tp, tp + fn)
    return _adjust_weights_safe_divide(score, average, multilabel, tp, fp, fn, top_k)


def binary_accuracy(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Binary accuracy (reference ``accuracy.py:84``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import binary_accuracy
        >>> preds = np.array([0.9, 0.1, 0.8, 0.4], np.float32)
        >>> target = np.array([1, 0, 1, 1])
        >>> print(f"{float(binary_accuracy(preds, target)):.4f}")
        0.7500
    """
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
    preds, target, mask = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    tp, fp, tn, fn = _binary_stat_scores_update(preds, target, mask, multidim_average)
    return _accuracy_reduce(tp, fp, tn, fn, average="binary", multidim_average=multidim_average)


def multiclass_accuracy(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multiclass accuracy (reference ``accuracy.py:153``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import multiclass_accuracy
        >>> preds = np.array([0, 2, 1, 2])
        >>> target = np.array([0, 1, 1, 2])
        >>> print(f"{float(multiclass_accuracy(preds, target, num_classes=3, average='micro')):.4f}")
        0.7500
    """
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index, top_k)
    preds, target = _multiclass_stat_scores_format(preds, target, top_k)
    tp, fp, tn, fn = _multiclass_stat_scores_update(preds, target, num_classes, top_k, multidim_average, ignore_index)
    return _accuracy_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average, top_k=top_k)


def multilabel_accuracy(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multilabel accuracy (reference ``accuracy.py:233``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import multilabel_accuracy
        >>> preds = np.array([[0.9, 0.1], [0.2, 0.7]], np.float32)
        >>> target = np.array([[1, 0], [0, 1]])
        >>> print(f"{float(multilabel_accuracy(preds, target, num_labels=2)):.4f}")
        1.0000
    """
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target, mask = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    tp, fp, tn, fn = _multilabel_stat_scores_update(preds, target, mask, multidim_average)
    return _accuracy_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average, multilabel=True)


def accuracy(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: str = "global",
    top_k: int = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatching accuracy (reference ``accuracy.py:315``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import accuracy
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> print(f"{float(accuracy(preds, target, task='multiclass', num_classes=3)):.4f}")
        0.7500
    """
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_accuracy(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        if not isinstance(top_k, int):
            raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
        return multiclass_accuracy(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
        return multilabel_accuracy(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
