"""Precision-recall curve kernels — the foundation of the curve family (ROC / AUROC / AP /
fixed-operating-point metrics).

Parity: reference ``src/torchmetrics/functional/classification/precision_recall_curve.py`` —
two state regimes (``:190-250``): binned O(T) multi-threshold confusion state vs exact O(N) raw
score state, same 5-function decomposition per task.

TPU-first redesign:

- **Binned mode is the native default.** The reference's vectorized (N, T) comparison has a 50k
  crossover to a Python loop (``:203-250``); here per-threshold tp/fp are ONE class-batched
  matmul against the threshold indicator (``_indicator_counts``) — XLA fuses the broadcast
  compare into the dot operand, so nothing (N, T)-shaped ever hits HBM and the reduction runs on
  the MXU at memory-bound speed. Shape-static, jit/shard-safe at any size.
- ``ignore_index`` rides along as a weight vector (masking, never dropping — dynamic shapes
  don't exist under XLA).
- **Exact mode is the host path** (as in the reference, where unbounded cat-state compute happens
  outside the hot loop): compute runs eagerly in numpy with full sklearn semantics.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array
from torchmetrics_tpu.utils.checks import _check_same_shape, is_traced
from torchmetrics_tpu.utils.compute import _safe_divide, normalize_logits_if_needed

Thresholds = Union[int, List[float], Array, None]


# ----------------------------------------------------------------- shared bits
def _adjust_threshold_arg(thresholds: Thresholds = None) -> Optional[Array]:
    """Normalise the ``thresholds`` argument to a sorted 1-D array (or None = exact mode).

    int/list inputs build the grid in NUMPY (host-concrete): under jit, omnistaging would
    turn a ``jnp.linspace`` into a tracer, hiding the grid's uniformity from
    ``_uniform_grid_params`` and forcing the O(N·T) dot lowering on platforms where the
    O(N) histogram wins. numpy operands compose with every downstream jnp op.
    """
    if thresholds is None:
        return None
    if isinstance(thresholds, int):
        return np.linspace(0.0, 1.0, thresholds, dtype=np.float32)
    if isinstance(thresholds, (list, tuple)):
        return np.sort(np.asarray(thresholds, np.float32))
    return jnp.sort(jnp.asarray(thresholds))


def _validate_thresholds_arg(thresholds: Thresholds) -> None:
    if thresholds is not None and not isinstance(thresholds, (int, list, tuple, jnp.ndarray, np.ndarray)):
        raise ValueError(
            "Expected argument `thresholds` to either be an integer, list of floats or"
            f" tensor of floats, but got {thresholds}"
        )
    if isinstance(thresholds, int) and thresholds < 2:
        raise ValueError(
            f"If argument `thresholds` is an integer, expected it to be larger than 1, but got {thresholds}"
        )
    if isinstance(thresholds, (list, tuple)) and not all(
        isinstance(t, float) and 0 <= t <= 1 for t in thresholds
    ):
        raise ValueError(
            f"If argument `thresholds` is a list, expected all elements to be floats in the [0,1] range,"
            f" but got {thresholds}"
        )


def _uniform_grid_params(thresholds: Array) -> Optional[Tuple[float, float]]:
    """``(lo, step)`` when ``thresholds`` is a concrete ascending uniform grid whose f32
    bucketize candidate ``floor((t - lo)/step)`` lands within ±1 of the true index at every
    knot (host-verified); ``None`` for traced, non-uniform, or numerically hostile grids.

    The ±1 bound is what makes ``_uniform_hist_counts`` exact: between two knots the
    candidate map is monotone, so an error bounded by 1 at the knots bounds it by 1
    everywhere, and a single gather-compare correction step recovers the true count.
    """
    try:
        t = np.asarray(thresholds)
    except Exception:  # traced thresholds (derived from runtime values) — cannot verify
        return None
    if t.ndim != 1 or t.size < 2 or not np.all(np.isfinite(t)):
        return None
    d = np.diff(t)
    if not (d > 0).all():
        return None
    step = (t[-1] - t[0]) / (t.size - 1)
    if step <= 0 or not np.allclose(d, step, rtol=1e-4):
        return None
    lo = t[0]
    cand = np.floor((t.astype(np.float32) - np.float32(lo)) / np.float32(step)).astype(np.int64)
    if np.abs(cand - np.arange(t.size)).max() > 1:
        return None
    return float(lo), float(step)


def _uniform_hist_counts(
    scores: Array, pos: Array, neg: Array, thresholds: Array, lo: float, step: float
) -> Tuple[Array, Array]:
    """O(N + T) twin of the indicator dot for uniform grids, inputs (C, N) -> (C, T).

    ``nb(s) = #{j: thr_j <= s}`` comes from one multiply+floor plus a ±1 gather-compare
    correction (see ``_uniform_grid_params`` for why ±1 suffices); per-threshold counts are
    then a weighted histogram over T+1 bins and a suffix cumsum — no (N, T) product anywhere.
    ~90x faster than the dot on the CPU backend at 1M samples; same f32 2^24 exactness
    contract.
    """
    num_t = thresholds.shape[0]
    thresholds = jnp.asarray(thresholds)  # may arrive as a host-concrete numpy grid
    b = jnp.clip(jnp.floor((scores - lo) / step).astype(jnp.int32), 0, num_t - 1)  # (C, N)
    promote = (b + 1 <= num_t - 1) & (scores >= thresholds[jnp.minimum(b + 1, num_t - 1)])
    demote = scores < thresholds[b]
    nb = b + 1 + promote.astype(jnp.int32) - demote.astype(jnp.int32)  # in [0, T]
    # NaN scores fail every `>=` compare in the dot path and count nowhere; floor(NaN)
    # would land them in bucket 0's suffix here — route them to the dropped nb=0 bucket
    nb = jnp.where(jnp.isnan(scores), 0, nb)

    # one flattened segment_sum over C*(T+1) offset bins instead of a vmapped per-class
    # scatter (2x on the CPU backend: one big scatter beats C batched ones)
    num_classes = nb.shape[0]
    offsets = jnp.arange(num_classes, dtype=jnp.int32)[:, None] * (num_t + 1)
    flat_bins = (nb + offsets).reshape(-1)
    hist_p = jax.ops.segment_sum(
        pos.reshape(-1), flat_bins, num_segments=num_classes * (num_t + 1)
    ).reshape(num_classes, num_t + 1)
    hist_n = jax.ops.segment_sum(
        neg.reshape(-1), flat_bins, num_segments=num_classes * (num_t + 1)
    ).reshape(num_classes, num_t + 1)
    # tp[t] = Σ_{nb >= t+1}: suffix sums, dropping the nb=0 bucket
    tp = jnp.cumsum(hist_p[:, ::-1], axis=1)[:, ::-1][:, 1:]
    fp = jnp.cumsum(hist_n[:, ::-1], axis=1)[:, ::-1][:, 1:]
    return tp, fp


def _indicator_counts(
    scores: Array, pos: Array, neg: Array, thresholds: Array
) -> Tuple[Array, Array]:
    """``tp[c, t] = Σ_i pos[c, i]·[scores[c, i] >= thr_t]`` (and fp from neg), inputs (C, N).

    Two lowerings, picked per platform + threshold structure:

    - **MXU dot** (TPU default): a class-batched ``(C, 2, N) @ (C, N, T)`` dot against the
      threshold indicator. Replaces the earlier searchsorted+histogram formulation there:
      XLA lowers ``searchsorted`` to per-element binary-search gathers, measured ~1000x
      slower than this matmul on a v5e chip, and scatter-heavy histograms are similarly
      weak on TPU.
    - **uniform-grid histogram** (CPU backend, concrete uniform thresholds — the default
      ``thresholds=int`` linspace): the CPU backend runs the dot at HIGHEST precision
      ~90x slower than an O(N) bucketize+histogram, so the structure-exploiting path wins
      there (measured 100M vs 1.1M samples/s at N=1M, T=200).

    f32 accumulation either way: counts are exact up to 2^24 (~16.7M) samples per update,
    the same contract as the confusion-matrix kernel (``ops/histogram.py``).
    """
    if jax.default_backend() == "cpu":
        grid = _uniform_grid_params(thresholds)
        if grid is not None:
            return _uniform_hist_counts(scores, pos, neg, thresholds, *grid)
    ind = (scores[:, :, None] >= thresholds[None, None, :]).astype(jnp.float32)  # (C, N, T)
    both = jnp.stack([pos, neg], axis=1)  # (C, 2, N)
    res = jax.lax.dot_general(
        both, ind, (((2,), (1,)), ((0,), (0,))), precision=jax.lax.Precision.HIGHEST
    )  # (C, 2, T)
    return res[:, 0], res[:, 1]


_CURVE_BACKEND = "xla"  # "xla" (indicator matmul) or "pallas" (VMEM-tiled custom kernel)


def set_curve_backend(backend: str) -> None:
    """Select the binary threshold-counts lowering: ``"xla"`` (default) or ``"pallas"``.

    The Pallas kernel (``ops.pallas_curve``) builds each threshold-indicator tile in registers
    and reduces it on the spot — the (N, T) indicator never exists. Kept as the tuning point
    for shapes where the dot formulation's operand layout is weak; same f32-count contract.
    """
    if backend not in ("xla", "pallas"):
        raise ValueError(f"curve backend must be 'xla' or 'pallas', got {backend!r}")
    global _CURVE_BACKEND
    if backend == "pallas":
        # warm-up compile NOW, eagerly: a Mosaic failure inside a user's outer jit would
        # surface at THEIR compile, after _binned_counts' own try/except has already passed —
        # probing here flips unsupported platforms back to 'xla' before any user trace
        try:
            import jax

            from torchmetrics_tpu.ops.pallas_curve import curve_counts_pallas

            jax.block_until_ready(
                curve_counts_pallas(
                    jnp.linspace(0.0, 1.0, 256),
                    jnp.ones((256,), jnp.float32),
                    jnp.zeros((256,), jnp.float32),
                    jnp.linspace(0.0, 1.0, 8),
                )
            )
        except Exception as err:
            from torchmetrics_tpu.utils.prints import rank_zero_warn

            rank_zero_warn(
                f"Pallas curve kernel failed its warm-up compile on this platform ({err!r});"
                " keeping the 'xla' backend."
            )
            _CURVE_BACKEND = "xla"
            return
    _CURVE_BACKEND = backend


def _binned_counts(
    scores: Array, positive: Array, weight: Array, thresholds: Array
) -> Tuple[Array, Array, Array, Array]:
    """Per-threshold (tp, fp, tn, fn), each shape (T,), via the indicator matmul."""
    w = weight.astype(jnp.float32)
    pos = positive.astype(jnp.float32) * w
    neg = (1.0 - positive.astype(jnp.float32)) * w
    tp = fp = None
    if _CURVE_BACKEND == "pallas":
        try:
            from torchmetrics_tpu.ops.pallas_curve import curve_counts_pallas

            tp, fp = curve_counts_pallas(scores, pos, neg, thresholds)
        except Exception:
            # trace-time failure -> dot path (same contract). NOTE: under an outer jit the
            # kernel may instead fail at the OUTER compile, after this function returned —
            # the fallback can only cover failures that surface while tracing/eager.
            pass
    if tp is None:
        tp, fp = _indicator_counts(scores[None], pos[None], neg[None], thresholds)
        tp, fp = tp[0], fp[0]
    fn = jnp.sum(pos) - tp
    tn = jnp.sum(neg) - fp
    return tp, fp, tn, fn


def _counts_to_confmat(tp: Array, fp: Array, tn: Array, fn: Array) -> Array:
    """Pack per-threshold counts as (..., T, 2, 2) with layout [t, target, pred]."""
    row0 = jnp.stack([tn, fp], axis=-1)
    row1 = jnp.stack([fn, tp], axis=-1)
    return jnp.stack([row0, row1], axis=-2)


def _binary_clf_curve_exact(
    preds: np.ndarray, target: np.ndarray, weight: Optional[np.ndarray] = None
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """fps/tps/thresholds at each distinct score, descending (sklearn semantics; host path).

    Reference equivalent: ``_binary_clf_curve`` (``precision_recall_curve.py:28-80``).
    """
    preds = np.asarray(preds, np.float64)
    target = np.asarray(target, np.float64)
    if weight is not None:
        weight = np.asarray(weight, np.float64)
        keep = weight > 0
        preds, target, weight = preds[keep], target[keep], weight[keep]
    else:
        weight = np.ones_like(preds)
    desc = np.argsort(-preds, kind="stable")
    preds, target, weight = preds[desc], target[desc], weight[desc]
    distinct = np.where(np.diff(preds))[0]
    threshold_idxs = np.r_[distinct, preds.size - 1]
    tps = np.cumsum(target * weight)[threshold_idxs]
    fps = np.cumsum((1 - target) * weight)[threshold_idxs]
    return fps, tps, preds[threshold_idxs]


def _precision_recall_from_exact(
    fps: np.ndarray, tps: np.ndarray, thresholds: np.ndarray
) -> Tuple[Array, Array, Array]:
    precision = tps / np.maximum(tps + fps, 1e-38)
    recall = tps / tps[-1] if tps[-1] > 0 else np.ones_like(tps)
    precision = np.hstack([precision[::-1], 1.0])
    recall = np.hstack([recall[::-1], 0.0])
    thresholds = thresholds[::-1]
    return jnp.asarray(precision, jnp.float32), jnp.asarray(recall, jnp.float32), jnp.asarray(thresholds, jnp.float32)


def _precision_recall_from_confmat(confmat: Array, thresholds: Array) -> Tuple[Array, Array, Array]:
    """(..., T, 2, 2) confusion state → precision/recall curves of length T+1 (binned mode)."""
    tps = confmat[..., 1, 1]
    fps = confmat[..., 0, 1]
    fns = confmat[..., 1, 0]
    precision = _safe_divide(tps, tps + fps)
    recall = _safe_divide(tps, tps + fns)
    ones = jnp.ones_like(precision[..., :1])
    zeros = jnp.zeros_like(recall[..., :1])
    return (
        jnp.concatenate([precision, ones], axis=-1),
        jnp.concatenate([recall, zeros], axis=-1),
        jnp.asarray(thresholds),  # thresholds may be a host-concrete numpy grid
    )


# --------------------------------------------------------------------- binary
def _binary_precision_recall_curve_arg_validation(
    thresholds: Thresholds = None, ignore_index: Optional[int] = None
) -> None:
    _validate_thresholds_arg(thresholds)
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Argument `ignore_index` must be either `None` or an integer, but got {ignore_index}")


def _binary_precision_recall_curve_tensor_validation(
    preds: Array, target: Array, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        raise ValueError(f"Expected argument `preds` to be an floating tensor, but got {jnp.asarray(preds).dtype}")
    if is_traced(preds, target):
        return
    t = np.asarray(target)
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    unique = set(np.unique(t).tolist())
    if not unique.issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {sorted(unique)} but expected only"
            f" the following values {sorted(allowed)}."
        )


def _binary_precision_recall_curve_format(
    preds: Array,
    target: Array,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Optional[Array]]:
    """Flatten, sigmoid-if-logits; return (preds, target01, weight, thresholds)."""
    preds = jnp.reshape(preds, (-1,))
    target = jnp.reshape(target, (-1,))
    preds = normalize_logits_if_needed(preds, "sigmoid")
    if ignore_index is not None:
        weight = (target != ignore_index).astype(jnp.float32)
        target = jnp.where(target == ignore_index, 0, target)
    else:
        weight = jnp.ones(target.shape, jnp.float32)
    return preds, target.astype(jnp.int32), weight, _adjust_threshold_arg(thresholds)


def _binary_precision_recall_curve_update(
    preds: Array, target: Array, weight: Array, thresholds: Optional[Array]
) -> Array:
    """Binned-state contribution: (T, 2, 2) confusion counts (exact mode has no tensor update)."""
    tp, fp, tn, fn = _binned_counts(preds, target, weight, thresholds)
    return _counts_to_confmat(tp, fp, tn, fn)


def _binary_precision_recall_curve_compute(
    state: Union[Array, Tuple[Array, Array, Array]],
    thresholds: Optional[Array],
) -> Tuple[Array, Array, Array]:
    """state = (T,2,2) confmat [binned] or (preds, target, weight) [exact]."""
    if thresholds is not None and isinstance(state, (jnp.ndarray, np.ndarray)) and not isinstance(state, tuple):
        return _precision_recall_from_confmat(state, thresholds)
    preds, target, weight = state
    # exact mode (thresholds=None) is host-mediated by contract: jit callers must bin
    # (pass thresholds) — the static early-return above is the traced path
    fps, tps, thr = _binary_clf_curve_exact(np.asarray(preds), np.asarray(target), np.asarray(weight))  # jaxlint: disable=TPU003
    return _precision_recall_from_exact(fps, tps, thr)


def binary_precision_recall_curve(
    preds: Array,
    target: Array,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array, Array]:
    """Precision-recall pairs at decision thresholds (reference ``precision_recall_curve.py:270``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, weight, thresholds = _binary_precision_recall_curve_format(
        preds, target, thresholds, ignore_index
    )
    if thresholds is None:
        return _binary_precision_recall_curve_compute((preds, target, weight), None)
    state = _binary_precision_recall_curve_update(preds, target, weight, thresholds)
    return _binary_precision_recall_curve_compute(state, thresholds)


# ------------------------------------------------------------------ multiclass
def _multiclass_precision_recall_curve_arg_validation(
    num_classes: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    average: Optional[str] = None,
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Argument `num_classes` must be an integer larger than 1, but got {num_classes}")
    if average not in (None, "micro", "macro"):
        raise ValueError(f"Expected argument `average` to be one of None, 'micro' or 'macro', but got {average}")
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)


def _multiclass_precision_recall_curve_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    if preds.ndim != target.ndim + 1:
        raise ValueError("Expected `preds` to have one more dimension than `target`")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError(f"`preds` must be a float tensor, but got {preds.dtype}")
    if preds.shape[1] != num_classes:
        raise ValueError(f"Expected `preds.shape[1]={preds.shape[1]}` to be equal to the number of classes")
    if preds.shape[0] != target.shape[0] or preds.shape[2:] != target.shape[1:]:
        raise ValueError("Expected the shape of `preds` should be (N, C, ...) and the shape of `target` (N, ...).")
    if is_traced(preds, target):
        return
    t = np.asarray(target)
    if ignore_index is not None:
        t = t[t != ignore_index]
    if t.size and (t.min() < 0 or t.max() >= num_classes):
        raise RuntimeError(f"Detected values in `target` outside [0, {num_classes})")


def _multiclass_precision_recall_curve_format(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    average: Optional[str] = None,
) -> Tuple[Array, Array, Array, Optional[Array]]:
    """→ (scores (N, C), target (N,), weight (N,), thresholds); micro flattens one-vs-rest."""
    preds = jnp.moveaxis(preds, 1, -1).reshape((-1, num_classes))
    target = jnp.reshape(target, (-1,))
    preds = normalize_logits_if_needed(preds, "softmax")
    if ignore_index is not None:
        weight = (target != ignore_index).astype(jnp.float32)
        target = jnp.where(target == ignore_index, 0, target)
    else:
        weight = jnp.ones(target.shape, jnp.float32)
    target = target.astype(jnp.int32)
    if average == "micro":
        # one-vs-rest flattening: every (sample, class) pair becomes a binary decision
        onehot = jnp.zeros((target.shape[0], num_classes), jnp.int32).at[
            jnp.arange(target.shape[0]), target
        ].set(1)
        preds_flat = jnp.reshape(preds, (-1,))
        target_flat = jnp.reshape(onehot, (-1,))
        weight_flat = jnp.repeat(weight, num_classes)
        return preds_flat, target_flat, weight_flat, _adjust_threshold_arg(thresholds)
    return preds, target, weight, _adjust_threshold_arg(thresholds)


def _multiclass_precision_recall_curve_update(
    preds: Array, target: Array, weight: Array, num_classes: int, thresholds: Optional[Array]
) -> Array:
    """(T, C, 2, 2) one-vs-rest confusion counts via the class-batched indicator matmul."""
    pos = (target[:, None] == jnp.arange(num_classes)[None, :]).astype(jnp.float32)  # (N, C)
    w = weight.astype(jnp.float32)[:, None]
    pos_cn = (pos * w).T  # (C, N)
    neg_cn = ((1.0 - pos) * w).T
    tp, fp = _indicator_counts(preds.T, pos_cn, neg_cn, thresholds)  # (C, T)
    fn = jnp.sum(pos_cn, axis=1, keepdims=True) - tp
    tn = jnp.sum(neg_cn, axis=1, keepdims=True) - fp
    confmat = _counts_to_confmat(tp.T, fp.T, tn.T, fn.T)  # (T, C, 2, 2)
    return confmat


def _multiclass_precision_recall_curve_compute(
    state: Union[Array, Tuple[Array, Array, Array]],
    num_classes: int,
    thresholds: Optional[Array],
    average: Optional[str] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    if average == "micro":
        return _binary_precision_recall_curve_compute(state, thresholds)
    if thresholds is not None and not isinstance(state, tuple):
        confmat = jnp.moveaxis(state, 0, 1)  # (C, T, 2, 2)
        return _precision_recall_from_confmat(confmat, thresholds)
    preds, target, weight = state
    preds_np = np.asarray(preds)
    target_np = np.asarray(target)
    weight_np = np.asarray(weight)
    precisions, recalls, thrs = [], [], []
    for c in range(num_classes):
        fps, tps, thr = _binary_clf_curve_exact(preds_np[:, c], (target_np == c).astype(np.float64), weight_np)
        p, r, t = _precision_recall_from_exact(fps, tps, thr)
        precisions.append(p)
        recalls.append(r)
        thrs.append(t)
    return precisions, recalls, thrs


def multiclass_precision_recall_curve(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Thresholds = None,
    average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """One-vs-rest PR curves (reference ``precision_recall_curve.py:510``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index, average)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, weight, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index, average
    )
    if average == "micro":
        if thresholds is None:
            return _binary_precision_recall_curve_compute((preds, target, weight), None)
        state = _binary_precision_recall_curve_update(preds, target, weight, thresholds)
        return _binary_precision_recall_curve_compute(state, thresholds)
    if thresholds is None:
        return _multiclass_precision_recall_curve_compute((preds, target, weight), num_classes, None, average)
    state = _multiclass_precision_recall_curve_update(preds, target, weight, num_classes, thresholds)
    return _multiclass_precision_recall_curve_compute(state, num_classes, thresholds, average)


# ------------------------------------------------------------------ multilabel
def _multilabel_precision_recall_curve_arg_validation(
    num_labels: int, thresholds: Thresholds = None, ignore_index: Optional[int] = None
) -> None:
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Argument `num_labels` must be an integer larger than 1, but got {num_labels}")
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)


def _multilabel_precision_recall_curve_tensor_validation(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError(f"`preds` must be a float tensor, but got {preds.dtype}")
    if preds.shape[1] != num_labels:
        raise ValueError(
            f"Expected `preds.shape[1]={preds.shape[1]}` to be equal to the number of labels {num_labels}"
        )
    if is_traced(preds, target):
        return
    t = np.asarray(target)
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    unique = set(np.unique(t).tolist())
    if not unique.issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {sorted(unique)} but expected only"
            f" the following values {sorted(allowed)}."
        )


def _multilabel_precision_recall_curve_format(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Optional[Array]]:
    preds = jnp.moveaxis(jnp.reshape(preds, (preds.shape[0], num_labels, -1)), 1, -1).reshape((-1, num_labels))
    target = jnp.moveaxis(jnp.reshape(target, (target.shape[0], num_labels, -1)), 1, -1).reshape((-1, num_labels))
    preds = normalize_logits_if_needed(preds, "sigmoid")
    if ignore_index is not None:
        weight = (target != ignore_index).astype(jnp.float32)
        target = jnp.where(target == ignore_index, 0, target)
    else:
        weight = jnp.ones(target.shape, jnp.float32)
    return preds, target.astype(jnp.int32), weight, _adjust_threshold_arg(thresholds)


def _multilabel_precision_recall_curve_update(
    preds: Array, target: Array, weight: Array, num_labels: int, thresholds: Optional[Array]
) -> Array:
    """(T, L, 2, 2) per-label confusion counts via the label-batched indicator matmul."""
    w = weight.astype(jnp.float32)
    pos_ln = (target.astype(jnp.float32) * w).T  # (L, N)
    neg_ln = ((1.0 - target.astype(jnp.float32)) * w).T
    tp, fp = _indicator_counts(preds.T, pos_ln, neg_ln, thresholds)  # (L, T)
    fn = jnp.sum(pos_ln, axis=1, keepdims=True) - tp
    tn = jnp.sum(neg_ln, axis=1, keepdims=True) - fp
    return _counts_to_confmat(tp.T, fp.T, tn.T, fn.T)  # (T, L, 2, 2)


def _multilabel_precision_recall_curve_compute(
    state: Union[Array, Tuple[Array, Array, Array]],
    num_labels: int,
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
):
    if thresholds is not None and not isinstance(state, tuple):
        confmat = jnp.moveaxis(state, 0, 1)  # (L, T, 2, 2)
        return _precision_recall_from_confmat(confmat, thresholds)
    preds, target, weight = state
    preds_np = np.asarray(preds)
    target_np = np.asarray(target)
    weight_np = np.asarray(weight)
    precisions, recalls, thrs = [], [], []
    for lbl in range(num_labels):
        fps, tps, thr = _binary_clf_curve_exact(preds_np[:, lbl], target_np[:, lbl], weight_np[:, lbl])
        p, r, t = _precision_recall_from_exact(fps, tps, thr)
        precisions.append(p)
        recalls.append(r)
        thrs.append(t)
    return precisions, recalls, thrs


def multilabel_precision_recall_curve(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Per-label PR curves (reference ``precision_recall_curve.py:728``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, weight, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    if thresholds is None:
        return _multilabel_precision_recall_curve_compute((preds, target, weight), num_labels, None, ignore_index)
    state = _multilabel_precision_recall_curve_update(preds, target, weight, num_labels, thresholds)
    return _multilabel_precision_recall_curve_compute(state, num_labels, thresholds, ignore_index)


def precision_recall_curve(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task-dispatching entrypoint (reference ``precision_recall_curve.py:947``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import precision_recall_curve
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> prec, rec, thr = precision_recall_curve(preds, target, task='binary', thresholds=4)
        >>> np.asarray(prec, np.float64).round(4).tolist()
        [0.5, 0.6667, 1.0, 0.0, 1.0]
        >>> np.asarray(rec, np.float64).round(4).tolist()
        [1.0, 1.0, 0.5, 0.0, 0.0]
    """
    from torchmetrics_tpu.utils.enums import ClassificationTask

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_precision_recall_curve(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_precision_recall_curve(
            preds, target, num_classes, thresholds, average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
        return multilabel_precision_recall_curve(preds, target, num_labels, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
