"""Average-precision kernels (reference
``src/torchmetrics/functional/classification/average_precision.py:46+``).

AP = Σ (R_n - R_{n-1}) · P_n over the precision-recall curve (step interpolation, sklearn
semantics), computed from the shared curve state.
"""
from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.utils.checks import is_traced
from torchmetrics_tpu.utils.compute import _safe_divide
from torchmetrics_tpu.utils.prints import rank_zero_warn


def _ap_from_curve(precision: Array, recall: Array) -> Array:
    """AP along the last axis of a (.., T+1) curve pair (recall decreasing)."""
    return -jnp.sum((recall[..., 1:] - recall[..., :-1]) * precision[..., :-1], axis=-1)


def _reduce_average_precision(
    precision: Union[Array, List[Array]],
    recall: Union[Array, List[Array]],
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Array:
    """Per-class APs + macro/weighted/none reduction (reference ``average_precision.py:30``)."""
    if isinstance(precision, (list, tuple)):
        res = jnp.stack([_ap_from_curve(p, r) for p, r in zip(precision, recall)])
    else:
        res = _ap_from_curve(precision, recall)
    if average is None or average == "none":
        return res
    if not is_traced(res) and bool(jnp.any(jnp.isnan(res))):
        rank_zero_warn(
            "Average precision score for one or more classes was `nan`. Ignoring these classes in average",
            UserWarning,
        )
    idx = ~jnp.isnan(res)
    if average == "macro":
        return jnp.sum(jnp.where(idx, res, 0.0)) / jnp.maximum(jnp.sum(idx), 1)
    if average == "weighted" and weights is not None:
        weights = jnp.where(idx, weights, 0.0)
        weights = _safe_divide(weights, jnp.sum(weights))
        return jnp.sum(jnp.where(idx, res * weights, 0.0))
    raise ValueError("Received an incompatible combinations of inputs to make reduction.")


def _binary_average_precision_compute(
    state: Union[Array, Tuple[Array, Array, Array]],
    thresholds: Optional[Array],
) -> Array:
    precision, recall, _ = _binary_precision_recall_curve_compute(state, thresholds)
    return _ap_from_curve(precision, recall)


def binary_average_precision(
    preds: Array,
    target: Array,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """AP for binary tasks (reference ``average_precision.py:94``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import binary_average_precision
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> print(f"{float(binary_average_precision(preds, target)):.4f}")
        0.8333
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, weight, thresholds = _binary_precision_recall_curve_format(
        preds, target, thresholds, ignore_index
    )
    if thresholds is None:
        return _binary_average_precision_compute((preds, target, weight), None)
    state = _binary_precision_recall_curve_update(preds, target, weight, thresholds)
    return _binary_average_precision_compute(state, thresholds)


def _multiclass_average_precision_arg_validation(
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> None:
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
    allowed_average = ("macro", "weighted", "none", None)
    if average not in allowed_average:
        raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")


def _multiclass_average_precision_compute(
    state: Union[Array, Tuple[Array, Array, Array]],
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Array] = None,
) -> Array:
    precision, recall, _ = _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)
    if thresholds is not None and not isinstance(state, tuple):
        support = state[0, :, 1, 1] + state[0, :, 1, 0]
    else:
        _, target, weight = state
        support = jnp.sum(
            (jnp.asarray(target)[:, None] == jnp.arange(num_classes)[None, :]) * jnp.asarray(weight)[:, None],
            axis=0,
        )
    return _reduce_average_precision(precision, recall, average, weights=support.astype(jnp.float32))


def multiclass_average_precision(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """One-vs-rest AP for multiclass tasks (reference ``average_precision.py:162``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multiclass_average_precision_arg_validation(num_classes, average, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, weight, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None:
        return _multiclass_average_precision_compute((preds, target, weight), num_classes, average, None)
    state = _multiclass_precision_recall_curve_update(preds, target, weight, num_classes, thresholds)
    return _multiclass_average_precision_compute(state, num_classes, average, thresholds)


def _multilabel_average_precision_arg_validation(
    num_labels: int,
    average: Optional[str],
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> None:
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
    allowed_average = ("micro", "macro", "weighted", "none", None)
    if average not in allowed_average:
        raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")


def _multilabel_average_precision_compute(
    state: Union[Array, Tuple[Array, Array, Array]],
    num_labels: int,
    average: Optional[str],
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
) -> Array:
    if average == "micro":
        if thresholds is not None and not isinstance(state, tuple):
            return _binary_average_precision_compute(jnp.sum(state, axis=1), thresholds)
        preds, target, weight = state
        return _binary_average_precision_compute(
            (jnp.reshape(preds, (-1,)), jnp.reshape(target, (-1,)), jnp.reshape(weight, (-1,))), None
        )
    precision, recall, _ = _multilabel_precision_recall_curve_compute(state, num_labels, thresholds, ignore_index)
    if thresholds is not None and not isinstance(state, tuple):
        support = state[0, :, 1, 1] + state[0, :, 1, 0]
    else:
        _, target, weight = state
        support = jnp.sum(jnp.asarray(target) * jnp.asarray(weight), axis=0)
    return _reduce_average_precision(precision, recall, average, weights=support.astype(jnp.float32))


def multilabel_average_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    average: Optional[str] = "macro",
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Per-label AP (reference ``average_precision.py:320``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multilabel_average_precision_arg_validation(num_labels, average, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, weight, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    if thresholds is None:
        return _multilabel_average_precision_compute((preds, target, weight), num_labels, average, None, ignore_index)
    state = _multilabel_precision_recall_curve_update(preds, target, weight, num_labels, thresholds)
    return _multilabel_average_precision_compute(state, num_labels, average, thresholds, ignore_index)


def average_precision(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatching entrypoint (reference ``average_precision.py:476``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import average_precision
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> print(f"{float(average_precision(preds, target, task='binary')):.4f}")
        0.8333
    """
    from torchmetrics_tpu.utils.enums import ClassificationTask

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_average_precision(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_average_precision(
            preds, target, num_classes, average, thresholds, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
        return multilabel_average_precision(
            preds, target, num_labels, average, thresholds, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
