"""Cohen's kappa kernels (reference
``src/torchmetrics/functional/classification/cohen_kappa.py``: ``_cohen_kappa_reduce:33``)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_arg_validation,
    _multiclass_confusion_matrix_arg_validation,
    binary_confusion_matrix,
    multiclass_confusion_matrix,
)
from torchmetrics_tpu.utils.enums import ClassificationTaskNoMultilabel


def _cohen_kappa_reduce(confmat: Array, weights: Optional[str] = None) -> Array:
    confmat = confmat.astype(jnp.float32)
    num_classes = confmat.shape[0]
    sum0 = jnp.sum(confmat, axis=0, keepdims=True)
    sum1 = jnp.sum(confmat, axis=1, keepdims=True)
    expected = sum1 @ sum0 / jnp.sum(sum0)

    if weights is None or weights == "none":
        w_mat = 1.0 - jnp.eye(num_classes, dtype=confmat.dtype)
    elif weights in ("linear", "quadratic"):
        idx = jnp.arange(num_classes, dtype=confmat.dtype)
        diff = idx[:, None] - idx[None, :]
        w_mat = jnp.abs(diff) if weights == "linear" else diff**2
    else:
        raise ValueError(
            f"Received {weights} for argument ``weights`` but should be either None, 'linear' or 'quadratic'"
        )
    k = jnp.sum(w_mat * confmat) / jnp.sum(w_mat * expected)
    return 1 - k


def _validate_weights(weights: Optional[str]) -> None:
    allowed_weights = ("linear", "quadratic", "none", None)
    if weights not in allowed_weights:
        raise ValueError(f"Expected argument `weight` to be one of {allowed_weights}, but got {weights}.")


def binary_cohen_kappa(preds, target, threshold: float = 0.5, weights: Optional[str] = None,
                       ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Reference ``cohen_kappa.py:75``."""
    if validate_args:
        _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize=None)
        _validate_weights(weights)
    confmat = binary_confusion_matrix(preds, target, threshold, None, ignore_index, validate_args)
    return _cohen_kappa_reduce(confmat, weights)


def multiclass_cohen_kappa(preds, target, num_classes: int, weights: Optional[str] = None,
                           ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Reference ``cohen_kappa.py:157``."""
    if validate_args:
        _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize=None)
        _validate_weights(weights)
    confmat = multiclass_confusion_matrix(preds, target, num_classes, None, ignore_index, validate_args)
    return _cohen_kappa_reduce(confmat, weights)


def cohen_kappa(preds, target, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
                weights: Optional[str] = None, ignore_index: Optional[int] = None,
                validate_args: bool = True) -> Array:
    """Task-dispatching Cohen's kappa (reference ``cohen_kappa.py:250``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import cohen_kappa
        >>> preds = np.array([0, 2, 1, 2])
        >>> target = np.array([0, 1, 1, 2])
        >>> print(f"{float(cohen_kappa(preds, target, task='multiclass', num_classes=3)):.4f}")
        0.6364
    """
    task = ClassificationTaskNoMultilabel.from_str(task)
    if task == ClassificationTaskNoMultilabel.BINARY:
        return binary_cohen_kappa(preds, target, threshold, weights, ignore_index, validate_args)
    if task == ClassificationTaskNoMultilabel.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_cohen_kappa(preds, target, num_classes, weights, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
