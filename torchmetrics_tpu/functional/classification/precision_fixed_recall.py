"""Best precision at a fixed recall floor (reference
``src/torchmetrics/functional/classification/precision_fixed_recall.py``)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.functional.classification.recall_fixed_precision import (
    _binary_recall_at_fixed_precision_arg_validation,
    _lex_select_at_constraint,
    _multiclass_recall_at_fixed_precision_arg_validation,
    _multilabel_recall_at_fixed_precision_arg_validation,
)


def _precision_at_recall(
    precision: Array, recall: Array, thresholds: Array, min_recall: float
) -> Tuple[Array, Array]:
    return _lex_select_at_constraint(precision, recall, thresholds, recall, min_recall)


def binary_precision_at_fixed_recall(
    preds: Array,
    target: Array,
    min_recall: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """(max precision, threshold) subject to recall >= min_recall (reference ``:140``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import binary_precision_at_fixed_recall
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> prec, thr = binary_precision_at_fixed_recall(preds, target, min_recall=0.5, thresholds=4)
        >>> print(f"{float(prec):.4f} {float(thr):.4f}")
        1.0000 0.6667
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _binary_recall_at_fixed_precision_arg_validation(min_recall, thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, weight, thresholds = _binary_precision_recall_curve_format(
        preds, target, thresholds, ignore_index
    )
    if thresholds is None:
        p, r, t = _binary_precision_recall_curve_compute((preds, target, weight), None)
    else:
        state = _binary_precision_recall_curve_update(preds, target, weight, thresholds)
        p, r, t = _binary_precision_recall_curve_compute(state, thresholds)
    return _precision_at_recall(p, r, t, min_recall)


def multiclass_precision_at_fixed_recall(
    preds: Array,
    target: Array,
    num_classes: int,
    min_recall: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-class (max precision, threshold) at fixed recall (reference ``:248``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import multiclass_precision_at_fixed_recall
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> prec, thr = multiclass_precision_at_fixed_recall(preds, target, num_classes=3,
        ...                                                  min_recall=0.5, thresholds=4)
        >>> np.asarray(prec, np.float64).round(4).tolist()
        [1.0, 0.5, 1.0]
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multiclass_recall_at_fixed_precision_arg_validation(num_classes, min_recall, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, weight, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None:
        p, r, t = _multiclass_precision_recall_curve_compute((preds, target, weight), num_classes, None)
    else:
        state = _multiclass_precision_recall_curve_update(preds, target, weight, num_classes, thresholds)
        p, r, t = _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)
    if isinstance(p, list):
        res = [_precision_at_recall(pc, rc, tc, min_recall) for pc, rc, tc in zip(p, r, t)]
        return jnp.stack([v for v, _ in res]), jnp.stack([thr for _, thr in res])
    thr = jnp.broadcast_to(t, (p.shape[0], t.shape[0]))
    return _precision_at_recall(p, r, thr, min_recall)


def multilabel_precision_at_fixed_recall(
    preds: Array,
    target: Array,
    num_labels: int,
    min_recall: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-label (max precision, threshold) at fixed recall (reference ``:348``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import multilabel_precision_at_fixed_recall
        >>> preds = np.array([[0.75, 0.05], [0.35, 0.85]], np.float32)
        >>> target = np.array([[1, 0], [0, 1]])
        >>> prec, thr = multilabel_precision_at_fixed_recall(preds, target, num_labels=2,
        ...                                                  min_recall=0.5, thresholds=4)
        >>> np.asarray(prec, np.float64).round(4).tolist()
        [1.0, 1.0]
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multilabel_recall_at_fixed_precision_arg_validation(num_labels, min_recall, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, weight, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    if thresholds is None:
        p, r, t = _multilabel_precision_recall_curve_compute((preds, target, weight), num_labels, None, ignore_index)
    else:
        state = _multilabel_precision_recall_curve_update(preds, target, weight, num_labels, thresholds)
        p, r, t = _multilabel_precision_recall_curve_compute(state, num_labels, thresholds, ignore_index)
    if isinstance(p, list):
        res = [_precision_at_recall(pc, rc, tc, min_recall) for pc, rc, tc in zip(p, r, t)]
        return jnp.stack([v for v, _ in res]), jnp.stack([thr for _, thr in res])
    thr = jnp.broadcast_to(t, (p.shape[0], t.shape[0]))
    return _precision_at_recall(p, r, thr, min_recall)
