"""AUROC kernels (reference ``src/torchmetrics/functional/classification/auroc.py:82-103+``).

Trapezoidal area under the ROC curve computed from the shared curve state; per-class curves
reduce with macro/weighted averaging (``_reduce_auroc``).
"""
from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.functional.classification.roc import (
    _binary_roc_compute,
    _multiclass_roc_compute,
    _multilabel_roc_compute,
)
from torchmetrics_tpu.utils.checks import is_traced
from torchmetrics_tpu.utils.compute import _auc_compute_without_check, _safe_divide
from torchmetrics_tpu.utils.prints import rank_zero_warn


def _reduce_auroc(
    fpr: Union[Array, List[Array]],
    tpr: Union[Array, List[Array]],
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Array:
    """Per-class trapezoid AUCs + macro/weighted/none reduction (reference ``auroc.py:51``)."""
    if isinstance(fpr, (list, tuple)):
        res = jnp.stack([_auc_compute_without_check(x, y, 1.0) for x, y in zip(fpr, tpr)])
    else:
        res = _auc_compute_without_check(fpr, tpr, 1.0, axis=-1)
    if average is None or average == "none":
        return res
    if not is_traced(res) and bool(jnp.any(jnp.isnan(res))):
        rank_zero_warn(
            "Average precision score for one or more classes was `nan`. Ignoring these classes in average",
            UserWarning,
        )
    idx = ~jnp.isnan(res)
    if average == "macro":
        return jnp.sum(jnp.where(idx, res, 0.0)) / jnp.maximum(jnp.sum(idx), 1)
    if average == "weighted" and weights is not None:
        weights = jnp.where(idx, weights, 0.0)
        weights = _safe_divide(weights, jnp.sum(weights))
        return jnp.sum(jnp.where(idx, res * weights, 0.0))
    raise ValueError("Received an incompatible combinations of inputs to make reduction.")


def _binary_auroc_arg_validation(
    max_fpr: Optional[float] = None,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> None:
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
    if max_fpr is not None and not (isinstance(max_fpr, float) and 0 < max_fpr <= 1):
        raise ValueError(f"Arguments `max_fpr` must be a float in range (0, 1], but got: {max_fpr}")


def _binary_auroc_compute(
    state: Union[Array, Tuple[Array, Array, Array]],
    thresholds: Optional[Array],
    max_fpr: Optional[float] = None,
) -> Array:
    fpr, tpr, _ = _binary_roc_compute(state, thresholds)
    full_auc = _auc_compute_without_check(fpr, tpr, 1.0)
    if max_fpr is None or max_fpr == 1:
        return full_auc
    # Trace-safe partial AUC over [0, max_fpr] with McClish correction (reference auroc.py:89-107).
    # `max_fpr` is a static constructor arg; everything data-dependent stays on device so the
    # whole compute can live inside jit (unlike the reference's host numpy path).
    fpr = jnp.asarray(fpr, jnp.float32)
    tpr = jnp.asarray(tpr, jnp.float32)
    n = fpr.shape[0]
    stop = jnp.clip(jnp.searchsorted(fpr, max_fpr, side="right"), 1, n - 1)
    f_lo = jnp.take(fpr, stop - 1)
    f_hi = jnp.take(fpr, stop)
    t_lo = jnp.take(tpr, stop - 1)
    t_hi = jnp.take(tpr, stop)
    weight = (max_fpr - f_lo) / jnp.maximum(f_hi - f_lo, 1e-38)
    interp_tpr = t_lo + weight * (t_hi - t_lo)
    seg_areas = 0.5 * (tpr[1:] + tpr[:-1]) * (fpr[1:] - fpr[:-1])
    seg_mask = jnp.arange(n - 1) < (stop - 1)
    partial_auc = jnp.sum(jnp.where(seg_mask, seg_areas, 0.0)) + 0.5 * (t_lo + interp_tpr) * (max_fpr - f_lo)
    min_area = 0.5 * max_fpr**2
    mcclish = 0.5 * (1 + (partial_auc - min_area) / (max_fpr - min_area))
    degenerate = (jnp.sum(fpr) == 0) | (jnp.sum(tpr) == 0)
    return jnp.where(degenerate, full_auc, mcclish).astype(jnp.float32)


def binary_auroc(
    preds: Array,
    target: Array,
    max_fpr: Optional[float] = None,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Area under the ROC curve for binary tasks (reference ``auroc.py:112``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import binary_auroc
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> print(f"{float(binary_auroc(preds, target)):.4f}")
        0.7500
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _binary_auroc_arg_validation(max_fpr, thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, weight, thresholds = _binary_precision_recall_curve_format(
        preds, target, thresholds, ignore_index
    )
    if thresholds is None:
        return _binary_auroc_compute((preds, target, weight), None, max_fpr)
    state = _binary_precision_recall_curve_update(preds, target, weight, thresholds)
    return _binary_auroc_compute(state, thresholds, max_fpr)


def _multiclass_auroc_arg_validation(
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> None:
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
    allowed_average = ("macro", "weighted", "none", None)
    if average not in allowed_average:
        raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")


def _multiclass_auroc_compute(
    state: Union[Array, Tuple[Array, Array, Array]],
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Array] = None,
) -> Array:
    fpr, tpr, _ = _multiclass_roc_compute(state, num_classes, thresholds)
    if thresholds is not None and not isinstance(state, tuple):
        support = state[0, :, 1, 1] + state[0, :, 1, 0]  # tp + fn at any threshold = positives
    else:
        _, target, weight = state
        support = jnp.sum(
            (jnp.asarray(target)[:, None] == jnp.arange(num_classes)[None, :]) * jnp.asarray(weight)[:, None],
            axis=0,
        )
    return _reduce_auroc(fpr, tpr, average, weights=support.astype(jnp.float32))


def multiclass_auroc(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """One-vs-rest AUROC for multiclass tasks (reference ``auroc.py:194``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multiclass_auroc_arg_validation(num_classes, average, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, weight, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None:
        return _multiclass_auroc_compute((preds, target, weight), num_classes, average, None)
    state = _multiclass_precision_recall_curve_update(preds, target, weight, num_classes, thresholds)
    return _multiclass_auroc_compute(state, num_classes, average, thresholds)


def _multilabel_auroc_arg_validation(
    num_labels: int,
    average: Optional[str],
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> None:
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
    allowed_average = ("micro", "macro", "weighted", "none", None)
    if average not in allowed_average:
        raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")


def _multilabel_auroc_compute(
    state: Union[Array, Tuple[Array, Array, Array]],
    num_labels: int,
    average: Optional[str],
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
) -> Array:
    if average == "micro":
        if thresholds is not None and not isinstance(state, tuple):
            return _binary_auroc_compute(jnp.sum(state, axis=1), thresholds, max_fpr=None)
        preds, target, weight = state
        return _binary_auroc_compute(
            (jnp.reshape(preds, (-1,)), jnp.reshape(target, (-1,)), jnp.reshape(weight, (-1,))), None, None
        )
    fpr, tpr, _ = _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)
    if thresholds is not None and not isinstance(state, tuple):
        support = state[0, :, 1, 1] + state[0, :, 1, 0]
    else:
        _, target, weight = state
        support = jnp.sum(jnp.asarray(target) * jnp.asarray(weight), axis=0)
    return _reduce_auroc(fpr, tpr, average, weights=support.astype(jnp.float32))


def multilabel_auroc(
    preds: Array,
    target: Array,
    num_labels: int,
    average: Optional[str] = "macro",
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Per-label AUROC (reference ``auroc.py:322``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if validate_args:
        _multilabel_auroc_arg_validation(num_labels, average, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, weight, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    if thresholds is None:
        return _multilabel_auroc_compute((preds, target, weight), num_labels, average, None, ignore_index)
    state = _multilabel_precision_recall_curve_update(preds, target, weight, num_labels, thresholds)
    return _multilabel_auroc_compute(state, num_labels, average, thresholds, ignore_index)


def auroc(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
    max_fpr: Optional[float] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatching entrypoint (reference ``auroc.py:471``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import auroc
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> print(f"{float(auroc(preds, target, task='binary')):.4f}")
        0.7500
    """
    from torchmetrics_tpu.utils.enums import ClassificationTask

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_auroc(preds, target, max_fpr, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_auroc(preds, target, num_classes, average, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
        return multilabel_auroc(preds, target, num_labels, average, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
