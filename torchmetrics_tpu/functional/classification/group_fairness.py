"""Group-fairness kernels (reference
``src/torchmetrics/functional/classification/group_fairness.py``).

Per-group tp/fp/tn/fn accumulate as a single ``(num_groups, 4)`` tensor (one-hot matmul over the
group ids — MXU path) instead of the reference's Python list of per-group index_selects.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
)
from torchmetrics_tpu.ops import bincount_weighted
from torchmetrics_tpu.utils.checks import is_traced
from torchmetrics_tpu.utils.compute import _safe_divide


def _groups_validation(groups: Array, num_groups: int) -> None:
    if is_traced(groups):
        return
    g = np.asarray(groups)
    if g.size and (g.min() < 0 or g.max() >= num_groups):
        raise ValueError(
            f"Expected all values in `groups` to be in the range [0, {num_groups}) but got values"
            f" in range [{g.min()}, {g.max()}]"
        )
    if not np.issubdtype(g.dtype, np.integer):
        raise ValueError(f"Expected dtype of argument `groups` to be int, but got {g.dtype}.")


def _binary_groups_stat_scores_update(
    preds: Array,
    target: Array,
    groups: Array,
    num_groups: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
) -> Array:
    """(num_groups, 4) [tp, fp, tn, fn] counts, fused over groups."""
    preds, target, mask = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    preds = jnp.reshape(preds, (-1,)).astype(jnp.float32)
    target = jnp.reshape(target, (-1,)).astype(jnp.float32)
    mask = jnp.reshape(mask, (-1,))
    groups = jnp.reshape(groups, (-1,))
    tp = bincount_weighted(groups, num_groups, weights=mask * preds * target, dtype=jnp.float32)
    fp = bincount_weighted(groups, num_groups, weights=mask * preds * (1 - target), dtype=jnp.float32)
    fn = bincount_weighted(groups, num_groups, weights=mask * (1 - preds) * target, dtype=jnp.float32)
    tn = bincount_weighted(groups, num_groups, weights=mask * (1 - preds) * (1 - target), dtype=jnp.float32)
    return jnp.stack([tp, fp, tn, fn], axis=-1)


def binary_groups_stat_rates(
    preds: Array,
    target: Array,
    groups: Array,
    num_groups: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """Per-group [tp, fp, tn, fn] rates (reference ``group_fairness.py:105``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    groups = jnp.asarray(groups)
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, "global", ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, "global", ignore_index)
        _groups_validation(groups, num_groups)
    stats = _binary_groups_stat_scores_update(preds, target, groups, num_groups, threshold, ignore_index)
    return {
        f"group_{g}": _safe_divide(stats[g], jnp.sum(stats[g])) for g in range(num_groups)
    }


def _compute_binary_demographic_parity(stats: Array) -> Dict[str, Array]:
    """min/max positive-prediction-rate ratio (reference ``group_fairness.py:164``)."""
    tp, fp, tn, fn = stats[:, 0], stats[:, 1], stats[:, 2], stats[:, 3]
    pos_rates = _safe_divide(tp + fp, tp + fp + tn + fn)
    lo = int(jax.device_get(jnp.argmin(pos_rates)))
    hi = int(jax.device_get(jnp.argmax(pos_rates)))
    return {f"DP_{lo}_{hi}": _safe_divide(pos_rates[lo], pos_rates[hi])}


def _compute_binary_equal_opportunity(stats: Array) -> Dict[str, Array]:
    """min/max true-positive-rate ratio (reference ``group_fairness.py:243``)."""
    tp, fp, tn, fn = stats[:, 0], stats[:, 1], stats[:, 2], stats[:, 3]
    tprs = _safe_divide(tp, tp + fn)
    lo = int(jax.device_get(jnp.argmin(tprs)))
    hi = int(jax.device_get(jnp.argmax(tprs)))
    return {f"EO_{lo}_{hi}": _safe_divide(tprs[lo], tprs[hi])}


def demographic_parity(
    preds: Array,
    groups: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """Demographic-parity ratio (reference ``group_fairness.py:177``)."""
    preds = jnp.asarray(preds)
    groups = jnp.asarray(groups)
    num_groups = int(jax.device_get(jnp.max(groups))) + 1
    target = jnp.zeros(preds.shape, jnp.int32)
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, "global", ignore_index)
        _groups_validation(groups, num_groups)
    stats = _binary_groups_stat_scores_update(preds, target, groups, num_groups, threshold, ignore_index)
    return _compute_binary_demographic_parity(stats)


def equal_opportunity(
    preds: Array,
    target: Array,
    groups: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """Equal-opportunity ratio (reference ``group_fairness.py:258``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    groups = jnp.asarray(groups)
    num_groups = int(jax.device_get(jnp.max(groups))) + 1
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, "global", ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, "global", ignore_index)
        _groups_validation(groups, num_groups)
    stats = _binary_groups_stat_scores_update(preds, target, groups, num_groups, threshold, ignore_index)
    return _compute_binary_equal_opportunity(stats)


def binary_fairness(
    preds: Array,
    target: Array,
    groups: Array,
    task: str = "all",
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """Demographic parity and/or equal opportunity (reference ``group_fairness.py:326``)."""
    if task not in ("demographic_parity", "equal_opportunity", "all"):
        raise ValueError(
            f"Expected argument `task` to either be ``demographic_parity``,"
            f"``equal_opportunity`` or ``all`` but got {task}."
        )
    preds = jnp.asarray(preds)
    groups = jnp.asarray(groups)
    if task == "demographic_parity":
        target = jnp.zeros(preds.shape, jnp.int32)
    target = jnp.asarray(target)
    num_groups = int(jax.device_get(jnp.max(groups))) + 1
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, "global", ignore_index)
        if task != "demographic_parity":
            _binary_stat_scores_tensor_validation(preds, target, "global", ignore_index)
        _groups_validation(groups, num_groups)
    stats = _binary_groups_stat_scores_update(preds, target, groups, num_groups, threshold, ignore_index)
    out: Dict[str, Array] = {}
    if task in ("demographic_parity", "all"):
        out.update(_compute_binary_demographic_parity(stats))
    if task in ("equal_opportunity", "all"):
        out.update(_compute_binary_equal_opportunity(stats))
    return out
