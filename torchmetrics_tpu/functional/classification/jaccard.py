"""Jaccard index (IoU) kernels (reference
``src/torchmetrics/functional/classification/jaccard.py``: ``_jaccard_index_reduce:38``)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification.confusion_matrix import (
    binary_confusion_matrix,
    multiclass_confusion_matrix,
    multilabel_confusion_matrix,
)
from torchmetrics_tpu.utils.compute import _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTask


def _jaccard_index_reduce(
    confmat: Array,
    average: Optional[str],
    ignore_index: Optional[int] = None,
) -> Array:
    allowed_average = ("binary", "micro", "macro", "weighted", "none", None)
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
    confmat = confmat.astype(jnp.float32)
    if average == "binary":
        return confmat[1, 1] / (confmat[0, 1] + confmat[1, 0] + confmat[1, 1])

    ignore_index_cond = ignore_index is not None and 0 <= ignore_index < confmat.shape[0]
    multilabel = confmat.ndim == 3
    if multilabel:
        num = confmat[:, 1, 1]
        denom = confmat[:, 1, 1] + confmat[:, 0, 1] + confmat[:, 1, 0]
    else:
        num = jnp.diagonal(confmat)
        denom = jnp.sum(confmat, axis=0) + jnp.sum(confmat, axis=1) - num

    if average == "micro":
        num_s = jnp.sum(num)
        denom_s = jnp.sum(denom) - (denom[ignore_index] if ignore_index_cond else 0.0)
        return _safe_divide(num_s, denom_s)

    jaccard = _safe_divide(num, denom)
    if average is None or average == "none":
        return jaccard
    if average == "weighted":
        weights = confmat[:, 1, 1] + confmat[:, 1, 0] if multilabel else jnp.sum(confmat, axis=1)
    else:
        weights = jnp.ones_like(jaccard)
        if ignore_index_cond:
            weights = weights.at[ignore_index].set(0.0)
        if not multilabel:
            weights = jnp.where(jnp.sum(confmat, axis=1) + jnp.sum(confmat, axis=0) == 0, 0.0, weights)
    return jnp.sum(weights * jaccard / jnp.sum(weights))


def binary_jaccard_index(preds, target, threshold: float = 0.5, ignore_index: Optional[int] = None,
                         validate_args: bool = True) -> Array:
    """Reference ``jaccard.py:97``.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import binary_jaccard_index
        >>> preds = np.array([0.9, 0.1, 0.8, 0.4], np.float32)
        >>> target = np.array([1, 0, 1, 1])
        >>> print(f"{float(binary_jaccard_index(preds, target)):.4f}")
        0.6667
    """
    confmat = binary_confusion_matrix(preds, target, threshold, None, ignore_index, validate_args)
    return _jaccard_index_reduce(confmat, average="binary")


def multiclass_jaccard_index(preds, target, num_classes: int, average: Optional[str] = "macro",
                             ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Reference ``jaccard.py:152``."""
    confmat = multiclass_confusion_matrix(preds, target, num_classes, None, ignore_index, validate_args)
    return _jaccard_index_reduce(confmat, average=average, ignore_index=ignore_index)


def multilabel_jaccard_index(preds, target, num_labels: int, threshold: float = 0.5,
                             average: Optional[str] = "macro", ignore_index: Optional[int] = None,
                             validate_args: bool = True) -> Array:
    """Reference ``jaccard.py:217``."""
    confmat = multilabel_confusion_matrix(preds, target, num_labels, threshold, None, ignore_index, validate_args)
    return _jaccard_index_reduce(confmat, average=average)


def jaccard_index(preds, target, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
                  num_labels: Optional[int] = None, average: Optional[str] = "macro",
                  ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Task-dispatching jaccard index (reference ``jaccard.py:290``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import jaccard_index
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> print(f"{float(jaccard_index(preds, target, task='multiclass', num_classes=3)):.4f}")
        0.6667
    """
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_jaccard_index(preds, target, threshold, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_jaccard_index(preds, target, num_classes, average, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
        return multilabel_jaccard_index(preds, target, num_labels, threshold, average, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
