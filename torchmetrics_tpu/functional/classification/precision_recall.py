"""Precision / recall kernels (reference
``src/torchmetrics/functional/classification/precision_recall.py``: ``_precision_recall_reduce:22``,
entrypoints ``:79-794``)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification._counts import binary_counts, multiclass_counts, multilabel_counts
from torchmetrics_tpu.utils.compute import _adjust_weights_safe_divide, _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTask


def _precision_recall_reduce(
    stat: str,
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
    top_k: int = 1,
    zero_division: float = 0.0,
) -> Array:
    different_stat = fp if stat == "precision" else fn  # this is what differs between the two
    if average == "binary":
        return _safe_divide(tp, tp + different_stat, zero_division)
    if average == "micro":
        tp = jnp.sum(tp, axis=0 if multidim_average == "global" else 1)
        different_stat = jnp.sum(different_stat, axis=0 if multidim_average == "global" else 1)
        return _safe_divide(tp, tp + different_stat, zero_division)
    score = _safe_divide(tp, tp + different_stat, zero_division)
    return _adjust_weights_safe_divide(score, average, multilabel, tp, fp, fn, top_k)


def binary_precision(preds, target, threshold: float = 0.5, multidim_average: str = "global",
                     ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Reference ``precision_recall.py:79``.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import binary_precision
        >>> preds = np.array([0.9, 0.1, 0.8, 0.4], np.float32)
        >>> target = np.array([1, 0, 1, 1])
        >>> print(f"{float(binary_precision(preds, target)):.4f}")
        1.0000
    """
    tp, fp, tn, fn = binary_counts(preds, target, threshold, multidim_average, ignore_index, validate_args)
    return _precision_recall_reduce("precision", tp, fp, tn, fn, "binary", multidim_average)


def multiclass_precision(preds, target, num_classes: int, average: Optional[str] = "macro", top_k: int = 1,
                         multidim_average: str = "global", ignore_index: Optional[int] = None,
                         validate_args: bool = True) -> Array:
    """Reference ``precision_recall.py:146``."""
    tp, fp, tn, fn = multiclass_counts(preds, target, num_classes, average, top_k, multidim_average,
                                       ignore_index, validate_args)
    return _precision_recall_reduce("precision", tp, fp, tn, fn, average, multidim_average, top_k=top_k)


def multilabel_precision(preds, target, num_labels: int, threshold: float = 0.5, average: Optional[str] = "macro",
                         multidim_average: str = "global", ignore_index: Optional[int] = None,
                         validate_args: bool = True) -> Array:
    """Reference ``precision_recall.py:231``."""
    tp, fp, tn, fn = multilabel_counts(preds, target, num_labels, threshold, average, multidim_average,
                                       ignore_index, validate_args)
    return _precision_recall_reduce("precision", tp, fp, tn, fn, average, multidim_average, multilabel=True)


def binary_recall(preds, target, threshold: float = 0.5, multidim_average: str = "global",
                  ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Reference ``precision_recall.py:316``.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import binary_recall
        >>> preds = np.array([0.9, 0.1, 0.8, 0.4], np.float32)
        >>> target = np.array([1, 0, 1, 1])
        >>> print(f"{float(binary_recall(preds, target)):.4f}")
        0.6667
    """
    tp, fp, tn, fn = binary_counts(preds, target, threshold, multidim_average, ignore_index, validate_args)
    return _precision_recall_reduce("recall", tp, fp, tn, fn, "binary", multidim_average)


def multiclass_recall(preds, target, num_classes: int, average: Optional[str] = "macro", top_k: int = 1,
                      multidim_average: str = "global", ignore_index: Optional[int] = None,
                      validate_args: bool = True) -> Array:
    """Reference ``precision_recall.py:383``."""
    tp, fp, tn, fn = multiclass_counts(preds, target, num_classes, average, top_k, multidim_average,
                                       ignore_index, validate_args)
    return _precision_recall_reduce("recall", tp, fp, tn, fn, average, multidim_average, top_k=top_k)


def multilabel_recall(preds, target, num_labels: int, threshold: float = 0.5, average: Optional[str] = "macro",
                      multidim_average: str = "global", ignore_index: Optional[int] = None,
                      validate_args: bool = True) -> Array:
    """Reference ``precision_recall.py:468``."""
    tp, fp, tn, fn = multilabel_counts(preds, target, num_labels, threshold, average, multidim_average,
                                       ignore_index, validate_args)
    return _precision_recall_reduce("recall", tp, fp, tn, fn, average, multidim_average, multilabel=True)


def precision(preds, target, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
              num_labels: Optional[int] = None, average: Optional[str] = "micro", multidim_average: str = "global",
              top_k: int = 1, ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Task-dispatching precision (reference ``precision_recall.py:553``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import precision
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> print(f"{float(precision(preds, target, task='multiclass', num_classes=3)):.4f}")
        0.7500
    """
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_precision(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_precision(preds, target, num_classes, average, top_k, multidim_average,
                                    ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
        return multilabel_precision(preds, target, num_labels, threshold, average, multidim_average,
                                    ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")


def recall(preds, target, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
           num_labels: Optional[int] = None, average: Optional[str] = "micro", multidim_average: str = "global",
           top_k: int = 1, ignore_index: Optional[int] = None, validate_args: bool = True) -> Array:
    """Task-dispatching recall (reference ``precision_recall.py:625``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import recall
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> print(f"{float(recall(preds, target, task='multiclass', num_classes=3)):.4f}")
        0.7500
    """
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_recall(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
        return multiclass_recall(preds, target, num_classes, average, top_k, multidim_average,
                                 ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
        return multilabel_recall(preds, target, num_labels, threshold, average, multidim_average,
                                 ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
