"""InfoLM (reference ``src/torchmetrics/functional/text/infolm.py``).

InfoLM aggregates the masked-language-model predictive distributions of a sentence's positions
into ONE bag-of-distributions vector per sentence (mean over real positions, reference
``infolm.py:394-421``) and compares candidate vs reference bags under an information measure.
Pluggable-model contract:

    ``masked_lm(sentences: List[str]) -> (probs (N, L, V), mask (N, L))``

returning, per position, the MLM distribution obtained with that position masked (and 1-mask
for real, non-special positions). A locally cached HuggingFace ``model_name_or_path`` builds
this callable automatically. The nine information measures run as jnp kernels.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

MaskedLM = Callable[[List[str]], Tuple[Array, Array]]

_ALLOWED_INFORMATION_MEASURE = (
    "kl_divergence",
    "alpha_divergence",
    "beta_divergence",
    "ab_divergence",
    "renyi_divergence",
    "l1_distance",
    "l2_distance",
    "l_infinity_distance",
    "fisher_rao_distance",
)

_EPS = 1e-12


def _validate_measure(information_measure: str, alpha: Optional[float], beta: Optional[float]) -> None:
    """Parameter constraints of the divergences (reference ``infolm.py:104-134``)."""
    if information_measure not in _ALLOWED_INFORMATION_MEASURE:
        raise ValueError(
            f"Argument `information_measure` expected to be one of {_ALLOWED_INFORMATION_MEASURE},"
            f" got {information_measure}"
        )
    needs_alpha = information_measure in ("alpha_divergence", "ab_divergence", "renyi_divergence")
    needs_beta = information_measure in ("beta_divergence", "ab_divergence")
    if needs_alpha and not isinstance(alpha, float):
        raise ValueError(f"Parameter `alpha` is expected to be defined for {information_measure}.")
    if needs_beta and not isinstance(beta, float):
        raise ValueError(f"Parameter `beta` must be defined for {information_measure}.")
    if information_measure == "alpha_divergence" and alpha in (0.0, 1.0):
        raise ValueError(f"Parameter `alpha` is expected to be float differened from 0 and 1 for {information_measure}.")
    if information_measure == "beta_divergence" and beta in (0.0, -1.0):
        raise ValueError(f"Parameter `beta` must be float differened from 0 and -1 for {information_measure}.")
    if information_measure == "ab_divergence" and (
        alpha is None or beta is None or 0.0 in (alpha, beta, alpha + beta)
    ):
        raise ValueError(
            "Parameters `alpha`, `beta` and their sum are expected to be differened from 0 for ab_divergence"
        )
    if information_measure == "renyi_divergence" and alpha == 1.0:
        raise ValueError(f"Parameter `alpha` is expected to be float differened from 1 for {information_measure}.")


def _information_measure(
    p: Array, q: Array, information_measure: str, alpha: Optional[float], beta: Optional[float]
) -> Array:
    """Per-position divergence over the vocab axis, ``p`` = preds bag, ``q`` = target bag.

    Reference conventions reproduced exactly (``infolm.py:145-245``), verified term-by-term
    against the reference package with a shared tiny masked-LM (the asymmetric placements
    below are invisible at symmetric parameter points like α=β, so the oracle sweep uses
    α≠β): kl is the sign-flipped Σ q·log(p/q); ab splits its first two log-terms as
    (target, preds) in that order; beta is ab with α forced to 1; renyi weights q^α·p^(1-α).
    No epsilon clipping — the reference feeds raw softmax outputs (strictly positive), and a
    clip floor measurably perturbs the ill-conditioned acos in fisher-rao.
    """
    p = jnp.asarray(p, jnp.float32)
    q = jnp.asarray(q, jnp.float32)
    if information_measure == "kl_divergence":
        return jnp.sum(q * (jnp.log(p) - jnp.log(q)), axis=-1)
    if information_measure == "alpha_divergence":
        a = alpha  # denominator α(α-1) — NEGATIVE on (0,1), the reference's convention
        return (1 - jnp.sum(q**a * p ** (1 - a), axis=-1)) / (a * (a - 1))
    if information_measure == "beta_divergence":
        a, b = 1.0, beta  # the reference quirk: beta == ab with alpha pinned to 1
        return (
            jnp.log(jnp.sum(q ** (a + b), axis=-1)) / (b * (a + b))
            + jnp.log(jnp.sum(p ** (a + b), axis=-1)) / (a * (a + b))
            - jnp.log(jnp.sum(q**a * p**b, axis=-1)) / (a * b)
        )
    if information_measure == "ab_divergence":
        a, b = alpha, beta
        return (
            jnp.log(jnp.sum(q ** (a + b), axis=-1)) / (b * (a + b))
            + jnp.log(jnp.sum(p ** (a + b), axis=-1)) / (a * (a + b))
            - jnp.log(jnp.sum(q**a * p**b, axis=-1)) / (a * b)
        )
    if information_measure == "renyi_divergence":
        a = alpha
        return jnp.log(jnp.sum(q**a * p ** (1 - a), axis=-1)) / (a - 1)
    if information_measure == "l1_distance":
        return jnp.sum(jnp.abs(p - q), axis=-1)
    if information_measure == "l2_distance":
        return jnp.sqrt(jnp.sum(jnp.square(p - q), axis=-1))
    if information_measure == "l_infinity_distance":
        return jnp.max(jnp.abs(p - q), axis=-1)
    # fisher_rao_distance
    return 2 * jnp.arccos(jnp.clip(jnp.sum(jnp.sqrt(p * q), axis=-1), 0.0, 1.0))


def _sentence_distribution(probs: Array, mask: Array, weights: Optional[Array] = None) -> Array:
    """Weighted mean of per-position MLM distributions → one (V,) bag per sentence.

    ``weights`` (e.g. idf) multiply the position mask (reference ``infolm.py:409-419``:
    the per-position distribution is scaled by idf and the bag normalised by Σ idf·mask —
    algebraically this weighted mean).
    """
    probs = jnp.asarray(probs, jnp.float32)
    w = jnp.asarray(mask, jnp.float32)
    if weights is not None:
        w = w * jnp.asarray(weights, jnp.float32)
    total = jnp.sum(probs * w[..., None], axis=1)
    return total / jnp.clip(jnp.sum(w, axis=1), _EPS)[..., None]


def _hf_masked_lm(model_name_or_path: str, max_length: Optional[int] = None, temperature: float = 1.0):
    """(masked_lm, tokenize) callables from a cached HF checkpoint.

    Faithful pseudo-likelihood protocol (reference ``infolm.py:394-421``): position ``i``'s
    distribution comes from a forward pass with position ``i`` replaced by ``[MASK]`` — L
    masked copies per sentence, batched — with ``softmax(logits / temperature)``.
    """
    try:
        import torch
        from transformers import AutoModelForMaskedLM, AutoTokenizer

        from torchmetrics_tpu.utils.pretrained import _from_pretrained

        tokenizer = _from_pretrained(AutoTokenizer, model_name_or_path)
        model = _from_pretrained(AutoModelForMaskedLM, model_name_or_path)
        model.eval()
    except Exception as err:
        raise ModuleNotFoundError(
            f"Loading checkpoint {model_name_or_path!r} failed (no local cache and no network egress"
            " in this build). Pass a `masked_lm` callable `(sentences) -> (probs, mask)` instead."
        ) from err

    mask_id = tokenizer.mask_token_id
    if max_length is None:
        # the reference's default: `max_length or model.config.max_length` — the GENERATION
        # config default (20 for BERT), NOT the tokenizer's model_max_length
        # (reference functional/text/infolm.py:634)
        max_length = int(model.config.max_length)

    def tokenize(sentences: List[str]):
        import numpy as _np

        # padding="max_length" (not longest-in-batch) mirrors the reference's fixed grid
        # (reference functional/text/infolm.py:493)
        batch = tokenizer(
            sentences, return_tensors="np", padding="max_length", truncation=True,
            max_length=max_length, return_special_tokens_mask=True,
        )
        mask = batch["attention_mask"] * (1 - batch["special_tokens_mask"])
        return _np.asarray(batch["input_ids"], _np.int64), _np.asarray(mask)

    def masked_lm(sentences: List[str]) -> Tuple[Array, Array]:
        with torch.no_grad():
            batch = tokenizer(
                sentences, return_tensors="pt", padding="max_length", truncation=True,
                max_length=max_length, return_special_tokens_mask=True,
            )
            special = batch.pop("special_tokens_mask")
            ids = batch["input_ids"]
            attn = batch["attention_mask"]
            b, length = ids.shape
            rows = []
            for pos in range(length):
                masked_ids = ids.clone()
                masked_ids[:, pos] = mask_id
                logits = model(masked_ids, attn).logits[:, pos, :]
                rows.append(torch.softmax(logits / temperature, dim=-1))
            probs = torch.stack(rows, dim=1)  # (B, L, V)
        mask = attn * (1 - special)
        return jnp.asarray(probs.numpy()), jnp.asarray(mask.numpy())

    return masked_lm, tokenize


def _corpus_idf_weights(sentences: List[str], tokenize, width: int):
    """Per-position idf weights over a corpus's OWN sentences (reference
    ``TokenizedDataset`` computes idf per dataset, ``helper_embedding_metric.py:267-287``)."""
    from torchmetrics_tpu.functional.text.bert import _idf_weights, _tokens_idf

    ids, mask = tokenize(list(sentences))
    table = _tokens_idf(ids, mask)
    w = jnp.asarray(_idf_weights(ids, table))
    if w.shape[1] < width:
        w = jnp.pad(w, ((0, 0), (0, width - w.shape[1])))
    return w[:, :width]


def infolm(
    preds: Union[str, List[str]],
    target: Union[str, List[str]],
    model_name_or_path: str = "bert-base-uncased",
    temperature: float = 0.25,
    information_measure: str = "kl_divergence",
    idf: bool = True,
    alpha: Optional[float] = None,
    beta: Optional[float] = None,
    masked_lm: Optional[MaskedLM] = None,
    tokenize=None,
    max_length: Optional[int] = None,
    return_sentence_level_score: bool = False,
    **reference_kwargs,
):
    """InfoLM (reference ``infolm.py:545``): information measure between MLM bag distributions.

    Reference defaults throughout: ``bert-base-uncased``, ``temperature=0.25``, ``idf=True``.
    A custom ``masked_lm`` callable replaces the HF model; with ``idf=True`` it must come with
    a ``tokenize`` callable (token ids drive the document frequencies). ``device``/
    ``batch_size``/``num_threads``/``verbose`` are accepted and inert (host execution model).
    """
    _validate_measure(information_measure, alpha, beta)
    if not (isinstance(temperature, (int, float)) and temperature > 0):
        raise ValueError(f"Argument `temperature` must be a positive number, but got {temperature}")
    # inert reference kwargs (host execution model) are accepted with any value; anything
    # else is rejected outright — a misspelled option must never be silently swallowed
    _inert = {"device", "batch_size", "num_threads", "verbose"}
    unknown = sorted(set(reference_kwargs) - _inert)
    if unknown:
        raise TypeError(f"infolm() got unexpected keyword arguments {unknown}")
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if len(preds) != len(target):
        raise ValueError(f"Number of predicted and reference sentences must match: {len(preds)} != {len(target)}")
    # max_length=None resolves inside _hf_masked_lm to model.config.max_length once the
    # model is loaded (the reference's default, functional/text/infolm.py:634)
    if masked_lm is None:
        masked_lm, tokenize = _hf_masked_lm(model_name_or_path, max_length=max_length, temperature=temperature)
    if idf and tokenize is None:
        raise ValueError(
            "`idf=True` needs token ids: pass `tokenize` alongside a custom `masked_lm`, or use a"
            " HuggingFace `model_name_or_path` so the tokenizer is resolved automatically."
        )
    p_probs, p_mask = masked_lm(list(preds))
    t_probs, t_mask = masked_lm(list(target))
    p_w = _corpus_idf_weights(preds, tokenize, p_mask.shape[1]) if idf else None
    t_w = _corpus_idf_weights(target, tokenize, t_mask.shape[1]) if idf else None
    p_bag = _sentence_distribution(p_probs, p_mask, p_w)
    t_bag = _sentence_distribution(t_probs, t_mask, t_w)
    sentence = _information_measure(p_bag, t_bag, information_measure, alpha, beta)
    corpus = jnp.mean(sentence)
    if return_sentence_level_score:
        return corpus, sentence
    return corpus
