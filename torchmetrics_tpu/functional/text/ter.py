"""Translation Edit Rate (reference ``src/torchmetrics/functional/text/ter.py``).

Tercom algorithm — greedy phrase shifts that reduce the word-level Levenshtein distance, with
Tercom's candidate-ranking heuristics and limits (shift size ≤ 10, shift distance ≤ 50, ≤ 1000
candidates). The Levenshtein+trace DP runs as full-matrix numpy (the reference prunes with a
beam and an incremental cache, ``helper.py:54-295`` — exact DP is simpler and differs only on
degenerate inputs); the shift/edit engine is an original implementation of the published
algorithm. The text normalisation rules are the published tercom/sacrebleu ``tokenizer_ter``
regex constants, expressed here as a flag-gated pipeline table. Inherently sequential host
string work; only the accumulator states live on device.
"""
from __future__ import annotations

import re
from functools import lru_cache, partial
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

_MAX_SHIFT_SIZE = 10
_MAX_SHIFT_DIST = 50
_MAX_SHIFT_CANDIDATES = 1000

# ops for the trace; preference order on cost ties is substitution/match, then delete, then
# insert (the flipped-trace convention of tercom/sacrebleu)
_OP_NOTHING, _OP_SUBSTITUTE, _OP_DELETE, _OP_INSERT = 0, 1, 2, 3

# ---------------------------------------------------------------------------
# Tercom text normalisation. The regex constants below are tercom/sacrebleu's published
# ``tokenizer_ter`` tables; the representation is a flag-gated pipeline: each stage is
# (gate over the three boolean flags, pad-with-spaces?, [(pattern, replacement), ...]).
# ---------------------------------------------------------------------------
_ASIAN_PUNCT = r"([、。〈-】〔-〟｡-･・])"
_FULLWIDTH_PUNCT = r"([．，？：；！＂（）])"

_WESTERN_NORMALIZE = [
    # newline stitching + XML entity unescaping
    (r"\n-", ""), (r"\n", " "),
    (r"&quot;", '"'), (r"&amp;", "&"), (r"&lt;", "<"), (r"&gt;", ">"),
    # isolate symbol chars, possessive 's, punctuation not inside numbers, number-dash
    (r"([{-~[-` -&(-+:-@/])", r" \1 "),
    (r"'s ", r" 's "), (r"'s$", r" 's"),
    (r"([^0-9])([\.,])", r"\1 \2 "), (r"([\.,])([^0-9])", r" \1 \2"),
    (r"([0-9])(-)", r"\1 \2 "),
]
_ASIAN_NORMALIZE = [
    (r"([一-鿿㐀-䶿])", r" \1 "),
    (r"([㇀-㇯⺀-⻿])", r" \1 "),
    (r"([㌀-㏿豈-﫿︰-﹏])", r" \1 "),
    (r"([㈀-㼢])", r" \1 "),
    (r"(^|^[぀-ゟ])([぀-ゟ]+)(?=$|^[぀-ゟ])", r"\1 \2 "),
    (r"(^|^[゠-ヿ])([゠-ヿ]+)(?=$|^[゠-ヿ])", r"\1 \2 "),
    (r"(^|^[ㇰ-ㇿ])([ㇰ-ㇿ]+)(?=$|^[ㇰ-ㇿ])", r"\1 \2 "),
    (_ASIAN_PUNCT, r" \1 "), (_FULLWIDTH_PUNCT, r" \1 "),
]
_WESTERN_STRIP = [(r"[\.,\?:;!\"\(\)]", "")]
_ASIAN_STRIP = [(_ASIAN_PUNCT, ""), (_FULLWIDTH_PUNCT, "")]


def _compile_rules(rules):
    return tuple((re.compile(p), r) for p, r in rules)


# stages gated on (normalize, no_punctuation, asian_support); lowercase is not a regex pass and
# is handled directly in ``_tercom_normalize``. ``pad`` wraps the sentence in single spaces
# first (tercom pads before the western normalisation pass).
_STAGES = (
    (lambda norm, nopunct, asian: norm, True, _compile_rules(_WESTERN_NORMALIZE)),
    (lambda norm, nopunct, asian: norm and asian, False, _compile_rules(_ASIAN_NORMALIZE)),
    (lambda norm, nopunct, asian: nopunct, False, _compile_rules(_WESTERN_STRIP)),
    (lambda norm, nopunct, asian: nopunct and asian, False, _compile_rules(_ASIAN_STRIP)),
)


@lru_cache(maxsize=2**16)
def _tercom_normalize(
    sentence: str, normalize: bool, no_punctuation: bool, lowercase: bool, asian_support: bool
) -> str:
    """Run the enabled normalisation stages and collapse whitespace."""
    if not sentence:
        return ""
    if lowercase:
        sentence = sentence.lower()
    for gate, pad, rules in _STAGES:
        if not gate(normalize, no_punctuation, asian_support):
            continue
        if pad:
            sentence = f" {sentence} "
        for pattern, replacement in rules:
            sentence = pattern.sub(replacement, sentence)
    return " ".join(sentence.split())


def _TercomTokenizer(
    normalize: bool = False,
    no_punctuation: bool = False,
    lowercase: bool = True,
    asian_support: bool = False,
) -> Callable[[str], str]:
    """Bind normalisation flags into a ``str -> str`` tokenizer (a picklable partial)."""
    return partial(
        _tercom_normalize,
        normalize=normalize,
        no_punctuation=no_punctuation,
        lowercase=lowercase,
        asian_support=asian_support,
    )


def _validate_inputs(
    ref_corpus: Union[Sequence[str], Sequence[Sequence[str]]],
    hypothesis_corpus: Union[str, Sequence[str]],
) -> Tuple[Sequence[Sequence[str]], Sequence[str]]:
    """Normalise corpus nesting (reference ``helper.py:297-326``)."""
    if isinstance(hypothesis_corpus, str):
        hypothesis_corpus = [hypothesis_corpus]
    if all(isinstance(ref, str) for ref in ref_corpus):
        ref_corpus = [ref_corpus] if len(hypothesis_corpus) == 1 else [[ref] for ref in ref_corpus]
    if hypothesis_corpus and all(ref for ref in ref_corpus) and len(ref_corpus) != len(hypothesis_corpus):
        raise ValueError(f"Corpus has different size {len(ref_corpus)} != {len(hypothesis_corpus)}")
    return ref_corpus, hypothesis_corpus


def _levenshtein_with_trace(hyp: List[str], ref: List[str]) -> Tuple[int, List[int]]:
    """Word Levenshtein distance + operation trace (hyp → ref), tercom tie preference."""
    h, r = len(hyp), len(ref)
    dist = np.zeros((h + 1, r + 1), np.int32)
    op = np.zeros((h + 1, r + 1), np.int8)
    dist[0, :] = np.arange(r + 1)
    op[0, 1:] = _OP_INSERT
    dist[1:, 0] = np.arange(1, h + 1)
    op[1:, 0] = _OP_DELETE
    for i in range(1, h + 1):
        sub_cost = dist[i - 1, :-1] + (np.asarray([hyp[i - 1] != w for w in ref]) if r else 0)
        del_cost = dist[i - 1, 1:] + 1
        # insert chain within the row (cost +1 per step, possibly starting at column 0):
        # dist[i, j] = cols[j] + min_{k<=j} (base[k] - cols[k]) — a prefix-min
        base = np.minimum(sub_cost, del_cost)
        cols = np.arange(1, r + 1)
        chain = np.minimum.accumulate(np.concatenate(([dist[i, 0]], base - cols)))
        dist[i, 1:] = chain[1:] + cols
        # record ops with tie preference sub/nothing > delete > insert
        row = dist[i, 1:]
        is_sub = row == sub_cost
        is_del = (row == del_cost) & ~is_sub
        match = np.asarray([hyp[i - 1] == w for w in ref]) if r else np.zeros(0, bool)
        op[i, 1:] = np.where(is_sub, np.where(match, _OP_NOTHING, _OP_SUBSTITUTE),
                             np.where(is_del, _OP_DELETE, _OP_INSERT))
    # backtrace
    trace: List[int] = []
    i, j = h, r
    while i > 0 or j > 0:
        o = int(op[i, j])
        trace.insert(0, o)
        if o in (_OP_NOTHING, _OP_SUBSTITUTE):
            i -= 1
            j -= 1
        elif o == _OP_INSERT:
            j -= 1
        else:
            i -= 1
    return int(dist[h, r]), trace


def _trace_to_alignment(trace: List[int]) -> Tuple[Dict[int, int], List[int], List[int]]:
    """Alignment + error positions from a hyp→ref trace (reference ``helper.py:381-430``)."""
    ref_pos = hyp_pos = -1
    ref_errors: List[int] = []
    hyp_errors: List[int] = []
    alignments: Dict[int, int] = {}
    for o in trace:
        if o == _OP_NOTHING:
            hyp_pos += 1
            ref_pos += 1
            alignments[ref_pos] = hyp_pos
            ref_errors.append(0)
            hyp_errors.append(0)
        elif o == _OP_SUBSTITUTE:
            hyp_pos += 1
            ref_pos += 1
            alignments[ref_pos] = hyp_pos
            ref_errors.append(1)
            hyp_errors.append(1)
        elif o == _OP_INSERT:
            ref_pos += 1
            alignments[ref_pos] = hyp_pos
            ref_errors.append(1)
        else:  # delete
            hyp_pos += 1
            hyp_errors.append(1)
    return alignments, ref_errors, hyp_errors


def _find_shifted_pairs(pred_words: List[str], target_words: List[str]) -> Iterator[Tuple[int, int, int]]:
    """Matching word sub-sequences (reference ``ter.py:205-240``)."""
    for pred_start in range(len(pred_words)):
        for target_start in range(len(target_words)):
            if abs(target_start - pred_start) > _MAX_SHIFT_DIST:
                continue
            for length in range(1, _MAX_SHIFT_SIZE):
                if pred_words[pred_start + length - 1] != target_words[target_start + length - 1]:
                    break
                yield pred_start, target_start, length
                if len(pred_words) == pred_start + length or len(target_words) == target_start + length:
                    break


def _perform_shift(words: List[str], start: int, length: int, target: int) -> List[str]:
    """Reference ``ter.py:282-311``."""
    if target < start:
        return words[:target] + words[start : start + length] + words[target:start] + words[start + length :]
    if target > start + length:
        return words[:start] + words[start + length : target] + words[start : start + length] + words[target:]
    return (
        words[:start] + words[start + length : length + target] + words[start : start + length] + words[length + target :]
    )


def _shift_words(
    pred_words: List[str],
    target_words: List[str],
    checked_candidates: int,
) -> Tuple[int, List[str], int]:
    """One round of Tercom shift search (reference ``ter.py:314-392``)."""
    edit_distance, trace = _levenshtein_with_trace(pred_words, target_words)
    alignments, target_errors, pred_errors = _trace_to_alignment(trace)

    best: Optional[Tuple[int, int, int, int, List[str]]] = None
    for pred_start, target_start, length in _find_shifted_pairs(pred_words, target_words):
        # corner cases: shift must fix an error on both sides and not move within its own span
        if sum(pred_errors[pred_start : pred_start + length]) == 0:
            continue
        if sum(target_errors[target_start : target_start + length]) == 0:
            continue
        if pred_start <= alignments[target_start] < pred_start + length:
            continue

        prev_idx = -1
        for offset in range(-1, length):
            if target_start + offset == -1:
                idx = 0
            elif target_start + offset in alignments:
                idx = alignments[target_start + offset] + 1
            else:
                break
            if idx == prev_idx:
                continue
            prev_idx = idx
            shifted_words = _perform_shift(pred_words, pred_start, length, idx)
            candidate = (
                edit_distance - _levenshtein_with_trace(shifted_words, target_words)[0],
                length,
                -pred_start,
                -idx,
                shifted_words,
            )
            checked_candidates += 1
            if not best or candidate > best:
                best = candidate
        if checked_candidates >= _MAX_SHIFT_CANDIDATES:
            break

    if not best:
        return 0, pred_words, checked_candidates
    best_score, _, _, _, shifted_words = best
    return best_score, shifted_words, checked_candidates


def _translation_edit_rate(pred_words: List[str], target_words: List[str]) -> float:
    """Edits to match one hypothesis with one reference (reference ``ter.py:395-426``)."""
    if len(target_words) == 0:
        return 0.0
    num_shifts = 0
    checked_candidates = 0
    input_words = pred_words
    while True:
        delta, new_input_words, checked_candidates = _shift_words(input_words, target_words, checked_candidates)
        if checked_candidates >= _MAX_SHIFT_CANDIDATES or delta <= 0:
            break
        num_shifts += 1
        input_words = new_input_words
    edit_distance, _ = _levenshtein_with_trace(input_words, target_words)
    return float(num_shifts + edit_distance)


def _compute_sentence_statistics(
    pred_words: List[str], target_words: List[List[str]]
) -> Tuple[float, float]:
    """Best edits over references + average reference length (reference ``ter.py:429-453``)."""
    tgt_lengths = 0.0
    best_num_edits = 2e16
    for tgt_words in target_words:
        num_edits = _translation_edit_rate(tgt_words, pred_words)
        tgt_lengths += len(tgt_words)
        if num_edits < best_num_edits:
            best_num_edits = num_edits
    avg_tgt_len = tgt_lengths / len(target_words)
    return best_num_edits, avg_tgt_len


def _compute_ter_score_from_statistics(num_edits: float, tgt_length: float) -> float:
    """Reference ``ter.py:456-471``."""
    if tgt_length > 0 and num_edits > 0:
        return num_edits / tgt_length
    if tgt_length == 0 and num_edits > 0:
        return 1.0
    return 0.0


def _ter_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    tokenizer: _TercomTokenizer,
    total_num_edits: float,
    total_tgt_length: float,
    sentence_ter: Optional[List[float]] = None,
) -> Tuple[float, float, Optional[List[float]]]:
    """Reference ``ter.py:474-517``."""
    target, preds = _validate_inputs(target, preds)
    for pred, tgt in zip(preds, target):
        tgt_words_ = [tokenizer(_tgt.rstrip()).split() for _tgt in tgt]
        pred_words_ = tokenizer(pred.rstrip()).split()
        num_edits, tgt_length = _compute_sentence_statistics(pred_words_, tgt_words_)
        total_num_edits += num_edits
        total_tgt_length += tgt_length
        if sentence_ter is not None:
            sentence_ter.append(_compute_ter_score_from_statistics(num_edits, tgt_length))
    return total_num_edits, total_tgt_length, sentence_ter


def translation_edit_rate(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    normalize: bool = False,
    no_punctuation: bool = False,
    lowercase: bool = True,
    asian_support: bool = False,
    return_sentence_level_score: bool = False,
):
    """TER (reference ``ter.py:534-600``).

    Example:
        >>> from torchmetrics_tpu.functional import translation_edit_rate
        >>> print(f"{float(translation_edit_rate(['the cat is on the mat'], [['there is a cat on the mat']])):.4f}")
        0.4286
    """
    for name, val in (
        ("normalize", normalize), ("no_punctuation", no_punctuation),
        ("lowercase", lowercase), ("asian_support", asian_support),
    ):
        if not isinstance(val, bool):
            raise ValueError(f"Expected argument `{name}` to be of type boolean but got {val}.")
    tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
    sentence_ter: Optional[List[float]] = [] if return_sentence_level_score else None
    total_num_edits, total_tgt_length, sentence_ter = _ter_update(
        preds, target, tokenizer, 0.0, 0.0, sentence_ter
    )
    ter = jnp.asarray(_compute_ter_score_from_statistics(total_num_edits, total_tgt_length), jnp.float32)
    if sentence_ter:
        return ter, [jnp.asarray([s], jnp.float32) for s in sentence_ter]
    return ter
