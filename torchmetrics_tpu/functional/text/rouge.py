"""ROUGE score (reference ``src/torchmetrics/functional/text/rouge.py``).

Host string processing by nature (tokenisation, LCS over token sequences); the per-sentence
score triples land in device cat-states. LCS tables are computed with a vectorised numpy DP
(one row at a time) instead of the reference's nested Python lists.
"""
from __future__ import annotations

import re
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

ALLOWED_ROUGE_KEYS: Dict[str, Union[int, str]] = {
    "rouge1": 1, "rouge2": 2, "rouge3": 3, "rouge4": 4, "rouge5": 5, "rouge6": 6,
    "rouge7": 7, "rouge8": 8, "rouge9": 9, "rougeL": "L", "rougeLsum": "Lsum",
}
ALLOWED_ACCUMULATE_VALUES = ("avg", "best")


_PUNKT_AVAILABLE: Optional[bool] = None


def _split_sentence(x: str) -> Sequence[str]:
    """Sentence-split for rougeLsum via nltk punkt (reference ``rouge.py:62-71``).

    When the punkt model is neither on disk nor downloadable (air-gapped hosts), falls back to a
    regex split on sentence-final punctuation — identical on single-sentence inputs, approximate
    on abbreviation-heavy text (documented divergence; the reference raises instead).
    """
    global _PUNKT_AVAILABLE
    import nltk

    x = re.sub("<n>", "", x)  # strip pegasus newline token (the reference discards this result, rouge.py:70)
    if _PUNKT_AVAILABLE is None:
        try:
            nltk.data.find("tokenizers/punkt")
            _PUNKT_AVAILABLE = True
        except LookupError:
            _PUNKT_AVAILABLE = False
            # one cheap DNS resolution before attempting the download — zero-egress hosts
            # fail instantly instead of risking a hung fetch
            from torchmetrics_tpu.utils.pretrained import host_reachable

            if host_reachable("raw.githubusercontent.com"):
                try:
                    nltk.download("punkt", quiet=True, force=False, halt_on_error=False, raise_on_error=True)
                    _PUNKT_AVAILABLE = True
                except Exception:
                    _PUNKT_AVAILABLE = False
    if _PUNKT_AVAILABLE:
        return nltk.sent_tokenize(x)
    return [s for s in re.split(r"(?<=[.!?])\s+", x.strip()) if s]


def _compute_metrics(hits_or_lcs: int, pred_len: int, target_len: int) -> Dict[str, float]:
    """precision/recall/F1 from a hit count (reference ``rouge.py:74-93``)."""
    precision = hits_or_lcs / pred_len
    recall = hits_or_lcs / target_len
    if precision == recall == 0.0:
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    fmeasure = 2 * precision * recall / (precision + recall)
    return {"precision": precision, "recall": recall, "fmeasure": fmeasure}


def _lcs_table(pred: Sequence[str], target: Sequence[str]) -> np.ndarray:
    """LCS DP table via rowwise numpy recurrence; shape (len(target)+1, len(pred)+1).

    Row identity: with ``cand[j] = prev[j-1]+1`` on match else ``prev[j]``, the standard LCS
    recurrence collapses to a prefix-max of ``cand`` (adjacent table cells differ by ≤ 1, so the
    match branch always dominates its neighbours) — one vectorised pass per target token.
    """
    vocab: Dict[str, int] = {}
    pred_ids = np.asarray([vocab.setdefault(t, len(vocab)) for t in pred], np.int64)
    table = np.zeros((len(target) + 1, len(pred) + 1), np.int32)
    for i, tgt_tok in enumerate(target, start=1):
        match = pred_ids == vocab.get(tgt_tok, -1)
        prev = table[i - 1]
        cand = np.where(match, prev[:-1] + 1, prev[1:])
        table[i, 1:] = np.maximum.accumulate(cand)
    return table


def _lcs_len(pred: Sequence[str], target: Sequence[str]) -> int:
    return int(_lcs_table(pred, target)[-1, -1])


def _backtracked_lcs(table: np.ndarray, pred: Sequence[str], target: Sequence[str]) -> List[int]:
    """Indices into ``target`` of one LCS (reference ``rouge.py:119-141``)."""
    i, j = len(pred), len(target)
    out: List[int] = []
    while i > 0 and j > 0:
        if pred[i - 1] == target[j - 1]:
            out.insert(0, j - 1)
            i -= 1
            j -= 1
        elif table[j][i - 1] > table[j - 1][i]:
            i -= 1
        else:
            j -= 1
    return out


def _union_lcs(pred_sentences: Sequence[Sequence[str]], target_sentence: Sequence[str]) -> List[str]:
    """Union of LCS indices of a target sentence vs every pred sentence (reference ``rouge.py:144-163``)."""
    indices: set = set()
    for pred in pred_sentences:
        table = _lcs_table(pred, target_sentence)  # (len(target)+1, len(pred)+1)
        indices.update(_backtracked_lcs(table, pred, target_sentence))
    return [target_sentence[i] for i in sorted(indices)]


def _normalize_and_tokenize_text(
    text: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Sequence[str]:
    """Lowercase, strip non-alphanumerics, optional Porter stemming (reference ``rouge.py:166-200``)."""
    text = normalizer(text) if callable(normalizer) else re.sub(r"[^a-z0-9]+", " ", text.lower())
    tokens = tokenizer(text) if callable(tokenizer) else re.split(r"\s+", text)
    if stemmer:
        tokens = [stemmer.stem(x) if len(x) > 3 else x for x in tokens]
    return [x for x in tokens if (isinstance(x, str) and len(x) > 0)]


def _rouge_n_score(pred: Sequence[str], target: Sequence[str], n_gram: int) -> Dict[str, float]:
    """Reference ``rouge.py:203-227``."""

    def _create_ngrams(tokens: Sequence[str], n: int) -> Counter:
        c: Counter = Counter()
        for ngram in (tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1)):
            c[ngram] += 1
        return c

    pred_ngrams, target_ngrams = _create_ngrams(pred, n_gram), _create_ngrams(target, n_gram)
    pred_len, target_len = sum(pred_ngrams.values()), sum(target_ngrams.values())
    if 0 in (pred_len, target_len):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    hits = sum(min(pred_ngrams[w], target_ngrams[w]) for w in set(pred_ngrams))
    return _compute_metrics(hits, max(pred_len, 1), max(target_len, 1))


def _rouge_l_score(pred: Sequence[str], target: Sequence[str]) -> Dict[str, float]:
    """Reference ``rouge.py:230-243``."""
    if 0 in (len(pred), len(target)):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    return _compute_metrics(_lcs_len(pred, target), len(pred), len(target))


def _rouge_lsum_score(pred: Sequence[Sequence[str]], target: Sequence[Sequence[str]]) -> Dict[str, float]:
    """Reference ``rouge.py:246-285``."""
    pred_len = sum(map(len, pred))
    target_len = sum(map(len, target))
    if 0 in (pred_len, target_len):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    pred_counts: Counter = Counter()
    for s in pred:
        pred_counts.update(s)
    target_counts: Counter = Counter()
    for s in target:
        target_counts.update(s)
    hits = 0
    for tgt in target:
        for token in _union_lcs(pred, tgt):
            if pred_counts[token] > 0 and target_counts[token] > 0:
                hits += 1
                pred_counts[token] -= 1
                target_counts[token] -= 1
    return _compute_metrics(hits, pred_len, target_len)


def _rouge_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    rouge_keys_values: List[Union[int, str]],
    accumulate: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Dict[Union[int, str], List[Dict[str, float]]]:
    """Per-sentence score triples for every rouge key (reference ``rouge.py:288-400``)."""
    results: Dict[Union[int, str], List[Dict[str, float]]] = {k: [] for k in rouge_keys_values}
    for pred_raw, target_raw in zip(preds, target):
        pred = _normalize_and_tokenize_text(pred_raw, stemmer, normalizer, tokenizer)
        pred_lsum = None
        if "Lsum" in rouge_keys_values:
            pred_lsum = [
                _normalize_and_tokenize_text(s, stemmer, normalizer, tokenizer)
                for s in _split_sentence(pred_raw)
            ]
        per_ref: List[Dict[Union[int, str], Dict[str, float]]] = []
        for target_raw_inner in target_raw:
            tgt = _normalize_and_tokenize_text(target_raw_inner, stemmer, normalizer, tokenizer)
            scores: Dict[Union[int, str], Dict[str, float]] = {}
            for key in rouge_keys_values:
                if isinstance(key, int):
                    scores[key] = _rouge_n_score(pred, tgt, key)
                elif key == "L":
                    scores[key] = _rouge_l_score(pred, tgt)
                else:  # Lsum
                    tgt_lsum = [
                        _normalize_and_tokenize_text(s, stemmer, normalizer, tokenizer)
                        for s in _split_sentence(target_raw_inner)
                    ]
                    scores[key] = _rouge_lsum_score(pred_lsum, tgt_lsum)
            per_ref.append(scores)
        if accumulate == "best":
            first_key = rouge_keys_values[0]
            best_idx = int(np.argmax([r[first_key]["fmeasure"] for r in per_ref]))
            for key in rouge_keys_values:
                results[key].append(per_ref[best_idx][key])
        else:  # avg
            for key in rouge_keys_values:
                avg = {
                    typ: float(np.mean([r[key][typ] for r in per_ref]))
                    for typ in ("precision", "recall", "fmeasure")
                }
                results[key].append(avg)
    return results


def _stemmer_or_none(use_stemmer: bool):
    if not use_stemmer:
        return None
    import nltk.stem.porter

    return nltk.stem.porter.PorterStemmer()


def rouge_score(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    accumulate: str = "best",
    use_stemmer: bool = False,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
    rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
):
    """ROUGE-N / ROUGE-L / ROUGE-LSum (reference ``rouge.py:421-524``).

    Example:
        >>> from torchmetrics_tpu.functional import rouge_score
        >>> score = rouge_score('the cat sat', 'the cat sat down', rouge_keys='rouge1')
        >>> print(f"{float(score['rouge1_fmeasure']):.4f}")
        0.8571
    """
    import jax.numpy as jnp

    if not isinstance(rouge_keys, tuple):
        rouge_keys = (rouge_keys,)
    for key in rouge_keys:
        if key not in ALLOWED_ROUGE_KEYS:
            raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}")
    if accumulate not in ALLOWED_ACCUMULATE_VALUES:
        raise ValueError(
            f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
        )
    key_values = [ALLOWED_ROUGE_KEYS[k] for k in rouge_keys]

    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [[target]]
    elif target and all(isinstance(t, str) for t in target):
        target = [[t] for t in target] if len(preds) > 1 else [list(target)]

    stemmer = _stemmer_or_none(use_stemmer)
    sentence_results = _rouge_score_update(
        preds, target, key_values, accumulate, stemmer, normalizer, tokenizer
    )
    output = {}
    for key_val, key_name in zip(key_values, rouge_keys):
        scores = sentence_results[key_val]
        for typ in ("precision", "recall", "fmeasure"):
            output[f"{key_name}_{typ}"] = jnp.asarray(
                float(np.mean([s[typ] for s in scores])) if scores else 0.0, jnp.float32
            )
    return output
