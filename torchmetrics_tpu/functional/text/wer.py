"""Word/char/match error rates + word-information metrics.

Reference: ``src/torchmetrics/functional/text/{wer,cer,mer,wil,wip}.py``. All five share the
batched device Levenshtein kernel (``_edit.edit_distance_batch``) instead of the reference's
per-pair host DP loop (``helper.py:329``); state is two-to-four sum scalars.
"""
from __future__ import annotations

from typing import List, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.text._edit import _word_batch_stats, edit_distance_batch


def _as_list(x: Union[str, List[str]]) -> List[str]:
    return [x] if isinstance(x, str) else list(x)


def _wer_update(preds, target) -> Tuple[Array, Array]:
    """Summed edit operations + reference word count (reference ``wer.py:23``)."""
    preds, target = _as_list(preds), _as_list(target)
    d, _, t_len = _word_batch_stats(preds, target, str.split)
    return jnp.asarray(d.sum(), jnp.float32), jnp.asarray(t_len.sum(), jnp.float32)


def _wer_compute(errors: Array, total: Array) -> Array:
    """Reference ``wer.py:52``."""
    return errors / total


def word_error_rate(preds, target) -> Array:
    """Word error rate (reference ``wer.py:66``).

    Example:
        >>> from torchmetrics_tpu.functional import word_error_rate
        >>> print(f"{float(word_error_rate(['the cat sat'], ['the cat sat down'])):.4f}")
        0.2500
    """
    return _wer_compute(*_wer_update(preds, target))


def _cer_update(preds, target) -> Tuple[Array, Array]:
    """Char-level errors + reference char count (reference ``cer.py:23``)."""
    preds, target = _as_list(preds), _as_list(target)
    d = edit_distance_batch([list(p) for p in preds], [list(t) for t in target])
    total = sum(len(t) for t in target)
    return jnp.asarray(d.sum(), jnp.float32), jnp.asarray(float(total), jnp.float32)


def _cer_compute(errors: Array, total: Array) -> Array:
    """Reference ``cer.py:52``."""
    return errors / total


def char_error_rate(preds, target) -> Array:
    """Character error rate (reference ``cer.py:66``).

    Example:
        >>> from torchmetrics_tpu.functional import char_error_rate
        >>> print(f"{float(char_error_rate(['abcd'], ['abce'])):.4f}")
        0.2500
    """
    return _cer_compute(*_cer_update(preds, target))


def _mer_update(preds, target) -> Tuple[Array, Array]:
    """Errors + max(len_t, len_p) totals (reference ``mer.py:23``)."""
    preds, target = _as_list(preds), _as_list(target)
    d, p_len, t_len = _word_batch_stats(preds, target, str.split)
    total = np.maximum(p_len, t_len).sum()
    return jnp.asarray(d.sum(), jnp.float32), jnp.asarray(total, jnp.float32)


def _mer_compute(errors: Array, total: Array) -> Array:
    """Reference ``mer.py:55``."""
    return errors / total


def match_error_rate(preds, target) -> Array:
    """Match error rate (reference ``mer.py:69``).

    Example:
        >>> from torchmetrics_tpu.functional import match_error_rate
        >>> print(f"{float(match_error_rate(['the cat sat'], ['the cat sat down'])):.4f}")
        0.2500
    """
    return _mer_compute(*_mer_update(preds, target))


def _word_info_update(preds, target) -> Tuple[Array, Array, Array]:
    """Shared WIL/WIP statistics (reference ``wil.py:20``, ``wip.py:21``)."""
    preds, target = _as_list(preds), _as_list(target)
    d, p_len, t_len = _word_batch_stats(preds, target, str.split)
    total = np.maximum(p_len, t_len).sum()
    errors_minus_total = d.sum() - total
    return (
        jnp.asarray(errors_minus_total, jnp.float32),
        jnp.asarray(t_len.sum(), jnp.float32),
        jnp.asarray(p_len.sum(), jnp.float32),
    )


def _word_info_lost_compute(errors: Array, target_total: Array, preds_total: Array) -> Array:
    """Reference ``wil.py:55``."""
    return 1 - (errors / target_total) * (errors / preds_total)


def word_information_lost(preds, target) -> Array:
    """Word information lost (reference ``wil.py:70``).

    Example:
        >>> from torchmetrics_tpu.functional import word_information_lost
        >>> print(f"{float(word_information_lost(['the cat sat'], ['the cat sat down'])):.4f}")
        0.2500
    """
    return _word_info_lost_compute(*_word_info_update(preds, target))


def _wip_compute(errors: Array, target_total: Array, preds_total: Array) -> Array:
    """Reference ``wip.py:55``."""
    return (errors / target_total) * (errors / preds_total)


def word_information_preserved(preds, target) -> Array:
    """Word information preserved (reference ``wip.py:68``).

    Example:
        >>> from torchmetrics_tpu.functional import word_information_preserved
        >>> print(f"{float(word_information_preserved(['the cat sat'], ['the cat sat down'])):.4f}")
        0.7500
    """
    return _wip_compute(*_word_info_update(preds, target))
