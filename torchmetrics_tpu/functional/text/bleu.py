"""BLEU score (reference ``src/torchmetrics/functional/text/bleu.py``).

State is TPU-shaped by construction (reference ``text/bleu.py:91-94``): fixed-size
``(n_gram,)`` numerator/denominator count vectors plus two length scalars — n-gram counting is
host string work, everything after lives on device. The compute kernel is trace-safe jnp.
"""
from __future__ import annotations

from collections import Counter
from typing import Callable, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array


def _count_ngram(ngram_input_list: Sequence[str], n_gram: int) -> Counter:
    """Counter of 1..n grams (reference ``bleu.py:24-45``)."""
    ngram_counter: Counter = Counter()
    for i in range(1, n_gram + 1):
        for j in range(len(ngram_input_list) - i + 1):
            ngram_counter[tuple(ngram_input_list[j : i + j])] += 1
    return ngram_counter


def _tokenize_fn(sentence: str) -> Sequence[str]:
    """Whitespace tokenizer (reference ``bleu.py:48-58``)."""
    return sentence.split()


def _bleu_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    numerator: np.ndarray,
    denominator: np.ndarray,
    preds_len: float,
    target_len: float,
    n_gram: int = 4,
    tokenizer: Callable[[str], Sequence[str]] = _tokenize_fn,
) -> Tuple[float, float]:
    """Accumulate clipped n-gram counts into host numpy buffers (reference ``bleu.py:60-105``).

    Mutates ``numerator``/``denominator`` in place and returns updated lengths.
    """
    target_tok = [[tokenizer(line) if line else [] for line in t] for t in target]
    preds_tok = [tokenizer(line) if line else [] for line in preds]
    for pred, targets in zip(preds_tok, target_tok):
        preds_len += len(pred)
        target_len_list = [len(tgt) for tgt in targets]
        target_len_diff = [abs(len(pred) - x) for x in target_len_list]
        target_len += target_len_list[target_len_diff.index(min(target_len_diff))]
        preds_counter = _count_ngram(pred, n_gram)
        target_counter: Counter = Counter()
        for tgt in targets:
            target_counter |= _count_ngram(tgt, n_gram)
        clipped = preds_counter & target_counter
        for key in clipped:
            numerator[len(key) - 1] += clipped[key]
        for key in preds_counter:
            denominator[len(key) - 1] += preds_counter[key]
    return preds_len, target_len


def _bleu_score_compute(
    preds_len: Array,
    target_len: Array,
    numerator: Array,
    denominator: Array,
    n_gram: int,
    weights: Sequence[float],
    smooth: bool,
) -> Array:
    """Trace-safe BLEU compute (reference ``bleu.py:119-156``)."""
    numerator = jnp.asarray(numerator, jnp.float32)
    denominator = jnp.asarray(denominator, jnp.float32)
    preds_len = jnp.asarray(preds_len, jnp.float32)
    target_len = jnp.asarray(target_len, jnp.float32)

    if smooth:
        precision_scores = (numerator + 1.0) / (denominator + 1.0)
        precision_scores = precision_scores.at[0].set(
            numerator[0] / jnp.maximum(denominator[0], 1e-38)
        )
    else:
        precision_scores = numerator / jnp.maximum(denominator, 1e-38)

    safe_precision = jnp.maximum(precision_scores, 1e-38)
    log_precision = jnp.asarray(list(weights), jnp.float32) * jnp.log(safe_precision)
    geometric_mean = jnp.exp(jnp.sum(log_precision))
    brevity_penalty = jnp.where(
        preds_len > target_len, 1.0, jnp.exp(1 - target_len / jnp.maximum(preds_len, 1e-38))
    )
    return jnp.where(jnp.min(numerator) == 0.0, 0.0, brevity_penalty * geometric_mean)


def bleu_score(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_gram: int = 4,
    smooth: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> Array:
    """BLEU score of translated text vs one or more references (reference ``bleu.py:149``).

    Example:
        >>> from torchmetrics_tpu.functional import bleu_score
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat']]
        >>> print(f"{float(bleu_score(preds, target)):.4f}")
        0.0000
    """
    preds_ = [preds] if isinstance(preds, str) else preds
    target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
    if len(preds_) != len(target_):
        raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    if weights is None:
        weights = [1.0 / n_gram] * n_gram

    numerator = np.zeros(n_gram)
    denominator = np.zeros(n_gram)
    preds_len, target_len = _bleu_score_update_batched(preds_, target_, numerator, denominator, 0.0, 0.0, n_gram)
    return _bleu_score_compute(
        preds_len, target_len, jnp.asarray(numerator), jnp.asarray(denominator), n_gram, weights, smooth
    )


def _bleu_score_update_batched(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    numerator: np.ndarray,
    denominator: np.ndarray,
    preds_len: float,
    target_len: float,
    n_gram: int = 4,
    tokenizer: Callable[[str], Sequence[str]] = _tokenize_fn,
) -> Tuple[float, float]:
    """Vectorised corpus n-gram counting: intern tokens -> compacted rolling codes ->
    np.unique group counts, instead of one Python ``Counter`` pass per sentence (semantics of
    ``_bleu_score_update`` preserved exactly; fuzz-pinned against it in the text tests).

    Mutates ``numerator``/``denominator`` in place and returns updated lengths.
    """
    preds_tok = [tokenizer(line) if line else [] for line in preds]
    target_tok = [[tokenizer(line) if line else [] for line in t] for t in target]

    # sentence lengths and closest-reference lengths (first minimum wins, like list.index)
    for pred, refs in zip(preds_tok, target_tok):
        preds_len += len(pred)
        diffs = [abs(len(pred) - len(r)) for r in refs]
        target_len += len(refs[diffs.index(min(diffs))])

    # flatten pred and ref streams with owner ids (shared machinery with chrF)
    from torchmetrics_tpu.functional.text._ngram import intern_streams, iter_ngram_levels

    all_streams = preds_tok + [r for refs in target_tok for r in refs]
    n_pred = len(preds_tok)
    stream_sent = np.asarray(
        list(range(n_pred)) + [i for i, refs in enumerate(target_tok) for _ in refs], np.int64
    )
    is_pred = np.asarray([True] * n_pred + [False] * (len(all_streams) - n_pred))
    ids_flat, stream_of, vocab_size = intern_streams(all_streams)

    for n, codes, valid in iter_ngram_levels(ids_flat, stream_of, vocab_size, n_gram):
        sel = valid
        if not sel.any():
            continue
        # compact the (sentence, gram) keys before any further composition: keeps every
        # subsequent key bounded by the number of DISTINCT pairs, never by products of ranges
        n_codes = int(codes[sel].max()) + 1
        sent = stream_sent[stream_of[sel]]
        _, key = np.unique(sent * n_codes + codes[sel], return_inverse=True)
        pred_mask = is_pred[stream_of[sel]]
        # per-(sentence, gram) pred counts
        pk, pc = np.unique(key[pred_mask], return_counts=True)
        denominator[n - 1] += int(pc.sum())
        if pk.size == 0:
            continue
        # per-(sentence, ref, gram) counts -> max over refs per (sentence, gram). key is dense
        # (< total positions) so composing with the stream index stays far below int64 range.
        ref_stream = stream_of[sel][~pred_mask]
        rkey = key[~pred_mask]
        rk, rc = np.unique(rkey * (len(all_streams) + 1) + ref_stream, return_counts=True)
        rk_gram = rk // (len(all_streams) + 1)
        boundaries = np.flatnonzero(np.r_[True, rk_gram[1:] != rk_gram[:-1]])
        ref_max = np.maximum.reduceat(rc, boundaries)
        ref_gram = rk_gram[boundaries]
        # clipped counts: min(pred count, ref max) over grams present in both
        common, pi, ri = np.intersect1d(pk, ref_gram, assume_unique=True, return_indices=True)
        numerator[n - 1] += int(np.minimum(pc[pi], ref_max[ri]).sum())
    return preds_len, target_len
