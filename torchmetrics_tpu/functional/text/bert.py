"""BERTScore (reference ``src/torchmetrics/functional/text/bert.py``).

Pluggable-encoder design (the library's standard contract for model-based metrics): the
reference hard-loads a HuggingFace checkpoint; here the model is a callable

    ``encoder(sentences: List[str]) -> (embeddings (N, L, D), mask (N, L))``

where ``mask`` is 1 for real (non-special) token positions. A HuggingFace model id still works
when the checkpoint is in the local cache (transformers is installed). The greedy cosine
matching itself — the actual metric — runs as jnp MXU matmuls.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

Encoder = Callable[[List[str]], Tuple[Array, Array]]


def _hf_encoder(model_name_or_path: str, num_layers: Optional[int] = None, max_length: int = 512) -> Encoder:
    """Build an encoder from a locally cached HuggingFace checkpoint."""
    try:
        import torch
        from transformers import AutoModel, AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(model_name_or_path)
        model = AutoModel.from_pretrained(model_name_or_path)
        model.eval()
    except Exception as err:
        raise ModuleNotFoundError(
            f"Loading checkpoint {model_name_or_path!r} failed (no local cache and no network egress"
            " in this build). Pass an `encoder` callable `(sentences) -> (embeddings, mask)` instead."
        ) from err

    def encoder(sentences: List[str]) -> Tuple[Array, Array]:
        with torch.no_grad():
            batch = tokenizer(
                sentences, return_tensors="pt", padding=True, truncation=True, max_length=max_length,
                return_special_tokens_mask=True,
            )
            special = batch.pop("special_tokens_mask")
            # keyword-only call: positional binding differs across architectures, and BERT-style
            # tokenizers also emit token_type_ids that must be forwarded
            out = model(**batch, output_hidden_states=True)
            hidden = out.hidden_states[num_layers if num_layers is not None else -1]
        mask = batch["attention_mask"] * (1 - special)
        return jnp.asarray(hidden.numpy()), jnp.asarray(mask.numpy())

    return encoder


def _bert_score_from_embeddings(
    preds_emb: Array, preds_mask: Array, target_emb: Array, target_mask: Array,
    preds_weights: Optional[Array] = None, target_weights: Optional[Array] = None,
) -> Dict[str, Array]:
    """Greedy-matched precision/recall/F1 (reference ``bert.py:134-168``), jnp kernels.

    Weights default to uniform over real tokens (the reference's non-idf path); pass idf
    weights to reproduce ``idf=True``.
    """
    def _norm(e, m):
        e = jnp.asarray(e, jnp.float32)
        e = e / jnp.clip(jnp.linalg.norm(e, axis=-1, keepdims=True), 1e-12)
        return e * jnp.asarray(m, jnp.float32)[..., None]

    p = _norm(preds_emb, preds_mask)
    t = _norm(target_emb, target_mask)
    cos_sim = jnp.einsum("bpd,brd->bpr", p, t)
    # padded positions must not clamp negative best-matches to 0 (and must not win the max)
    pm = jnp.asarray(preds_mask, jnp.float32) > 0
    tm = jnp.asarray(target_mask, jnp.float32) > 0
    neg = jnp.asarray(-1e9, jnp.float32)
    cos_sim = jnp.where(pm[:, :, None] & tm[:, None, :], cos_sim, neg)

    def _weights(explicit, mask):
        mask = jnp.asarray(mask, jnp.float32)
        w = jnp.asarray(explicit, jnp.float32) * mask if explicit is not None else mask
        return w / jnp.clip(jnp.sum(w, axis=-1, keepdims=True), 1e-12)

    pw = _weights(preds_weights, preds_mask)
    tw = _weights(target_weights, target_mask)
    any_t = jnp.any(tm, axis=-1, keepdims=True)
    any_p = jnp.any(pm, axis=-1, keepdims=True)
    best_p = jnp.where(any_t, jnp.max(cos_sim, axis=2), 0.0)
    best_t = jnp.where(any_p, jnp.max(cos_sim, axis=1), 0.0)
    precision = jnp.sum(best_p * pw, axis=-1)
    recall = jnp.sum(best_t * tw, axis=-1)
    f1 = 2 * precision * recall / (precision + recall)
    f1 = jnp.where(jnp.isnan(f1), 0.0, f1)
    return {"precision": precision, "recall": recall, "f1": f1}


def bert_score(
    preds: Union[str, List[str]],
    target: Union[str, List[str]],
    model_name_or_path: Optional[str] = None,
    encoder: Optional[Encoder] = None,
    num_layers: Optional[int] = None,
    max_length: int = 512,
    idf: bool = False,
    rescale_with_baseline: bool = False,
    **unsupported,
) -> Dict[str, Array]:
    """BERTScore (reference ``bert.py:243``): greedy contextual-embedding matching P/R/F1.

    Provide either ``encoder`` (see module docstring) or a cached HF ``model_name_or_path``.
    """
    if idf or rescale_with_baseline or any(unsupported.values()):
        bad = [k for k, v in {"idf": idf, "rescale_with_baseline": rescale_with_baseline, **unsupported}.items() if v]
        raise NotImplementedError(
            f"bert_score options {bad} are not supported in this build (idf needs tokenizer-level"
            " document frequencies; baselines need downloaded tables). Use the default scores."
        )
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if len(preds) != len(target):
        raise ValueError(f"Number of predicted and reference sentences must match: {len(preds)} != {len(target)}")
    if encoder is None:
        if model_name_or_path is None:
            raise ModuleNotFoundError(
                "bert_score needs a model: pass `encoder` as a callable `(sentences) -> (embeddings,"
                " mask)` or a locally cached HuggingFace `model_name_or_path`."
            )
        encoder = _hf_encoder(model_name_or_path, num_layers=num_layers, max_length=max_length)
    p_emb, p_mask = encoder(list(preds))
    t_emb, t_mask = encoder(list(target))
    # pad to a common sequence length so the cosine matrix is rectangular
    lp, lt = p_emb.shape[1], t_emb.shape[1]
    if lp != lt:
        pad = max(lp, lt)
        p_emb = jnp.pad(p_emb, ((0, 0), (0, pad - lp), (0, 0)))
        p_mask = jnp.pad(p_mask, ((0, 0), (0, pad - lp)))
        t_emb = jnp.pad(t_emb, ((0, 0), (0, pad - lt), (0, 0)))
        t_mask = jnp.pad(t_mask, ((0, 0), (0, pad - lt)))
    return _bert_score_from_embeddings(p_emb, p_mask, t_emb, t_mask)
