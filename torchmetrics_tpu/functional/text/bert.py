"""BERTScore (reference ``src/torchmetrics/functional/text/bert.py``).

Pluggable-encoder design (the library's standard contract for model-based metrics): the
reference hard-loads a HuggingFace checkpoint; here the model is a callable

    ``encoder(sentences: List[str]) -> (embeddings (N, L, D), mask (N, L))``

where ``mask`` is 1 for real (non-special) token positions. A HuggingFace model id still works
when the checkpoint is in the local cache (transformers is installed). The greedy cosine
matching itself — the actual metric — runs as jnp MXU matmuls.
"""
from __future__ import annotations

import csv
import math
from collections import Counter
from typing import Callable, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.utils.prints import rank_zero_warn

Encoder = Callable[[List[str]], Tuple[Array, Array]]
Tokenize = Callable[[List[str]], Tuple[np.ndarray, np.ndarray]]

_DEFAULT_MODEL = "roberta-large"


def _hf_encoder(model_name_or_path: str, num_layers: Optional[int] = None, max_length: int = 512) -> Encoder:
    """Build an encoder from a locally cached HuggingFace checkpoint."""
    from torchmetrics_tpu.utils.pretrained import bert_encoder

    encoder, _ = bert_encoder(model_name_or_path, num_layers=num_layers, max_length=max_length)
    return encoder


def _tokens_idf(ids: np.ndarray, mask: np.ndarray) -> Dict[int, float]:
    """Inverse document frequencies over the reference corpus (reference
    ``helper_embedding_metric.py:240-259``): idf(t) = log((N+1)/(df(t)+1)), with log(N+1) for
    unseen tokens. ``ids``/``mask`` are (N, L); masked positions are ignored."""
    n_sentences = ids.shape[0]
    df: Counter = Counter()
    for row, m in zip(ids, mask):
        df.update(set(row[m > 0].tolist()))
    default = math.log(n_sentences + 1)
    idf = {tok: math.log((n_sentences + 1) / (occ + 1)) for tok, occ in df.items()}
    return {"__default__": default, **idf}


def _idf_weights(ids: np.ndarray, idf: Dict[int, float]) -> np.ndarray:
    default = idf["__default__"]
    return np.vectorize(lambda t: idf.get(int(t), default), otypes=[np.float32])(ids)


def _load_baseline_file(path: str) -> np.ndarray:
    """Parse a bert-score baseline csv/tsv: header row, then ``layer,P,R,F`` rows
    (reference ``bert.py:175-184``). Returns (num_layers+1, 3) float array."""
    with open(path, newline="") as f:
        sample = f.read(4096)
        f.seek(0)
        dialect = csv.Sniffer().sniff(sample, delimiters=",\t")
        rows = [
            [float(x) for x in row]
            for idx, row in enumerate(csv.reader(f, dialect))
            if idx > 0 and row
        ]
    return np.asarray(rows, np.float32)[:, 1:]


def _bert_score_from_embeddings(
    preds_emb: Array, preds_mask: Array, target_emb: Array, target_mask: Array,
    preds_weights: Optional[Array] = None, target_weights: Optional[Array] = None,
) -> Dict[str, Array]:
    """Greedy-matched precision/recall/F1 (reference ``bert.py:134-168``), jnp kernels.

    Weights default to uniform over real tokens (the reference's non-idf path); pass idf
    weights to reproduce ``idf=True``.
    """
    def _norm(e, m):
        e = jnp.asarray(e, jnp.float32)
        e = e / jnp.clip(jnp.linalg.norm(e, axis=-1, keepdims=True), 1e-12)
        return e * jnp.asarray(m, jnp.float32)[..., None]

    p = _norm(preds_emb, preds_mask)
    t = _norm(target_emb, target_mask)
    cos_sim = jnp.einsum("bpd,brd->bpr", p, t)
    # padded positions must not clamp negative best-matches to 0 (and must not win the max)
    pm = jnp.asarray(preds_mask, jnp.float32) > 0
    tm = jnp.asarray(target_mask, jnp.float32) > 0
    neg = jnp.asarray(-1e9, jnp.float32)
    cos_sim = jnp.where(pm[:, :, None] & tm[:, None, :], cos_sim, neg)

    def _weights(explicit, mask):
        mask = jnp.asarray(mask, jnp.float32)
        w = jnp.asarray(explicit, jnp.float32) * mask if explicit is not None else mask
        return w / jnp.clip(jnp.sum(w, axis=-1, keepdims=True), 1e-12)

    pw = _weights(preds_weights, preds_mask)
    tw = _weights(target_weights, target_mask)
    any_t = jnp.any(tm, axis=-1, keepdims=True)
    any_p = jnp.any(pm, axis=-1, keepdims=True)
    best_p = jnp.where(any_t, jnp.max(cos_sim, axis=2), 0.0)
    best_t = jnp.where(any_p, jnp.max(cos_sim, axis=1), 0.0)
    precision = jnp.sum(best_p * pw, axis=-1)
    recall = jnp.sum(best_t * tw, axis=-1)
    f1 = 2 * precision * recall / (precision + recall)
    f1 = jnp.where(jnp.isnan(f1), 0.0, f1)
    return {"precision": precision, "recall": recall, "f1": f1}


def bert_score(
    preds: Union[str, List[str]],
    target: Union[str, List[str]],
    model_name_or_path: Optional[str] = None,
    encoder: Optional[Encoder] = None,
    tokenize: Optional[Tokenize] = None,
    num_layers: Optional[int] = None,
    max_length: int = 512,
    idf: bool = False,
    rescale_with_baseline: bool = False,
    baseline_path: Optional[str] = None,
    lang: str = "en",
    **reference_kwargs,
) -> Dict[str, Array]:
    """BERTScore (reference ``bert.py:243``): greedy contextual-embedding matching P/R/F1.

    Provide either ``encoder`` (see module docstring) or a HF ``model_name_or_path`` resolved
    through the installed transformers stack; with neither, the reference's recommended default
    (``roberta-large``) is used with the reference's warning (``text/bert.py:184-188``).

    ``idf=True`` weights token matches by inverse document frequency computed over the target
    corpus (reference ``helper_embedding_metric.py:240-259``); it needs token ids, so it works
    with HF-resolved models out of the box, or with a custom ``encoder`` when ``tokenize`` is
    also given. ``rescale_with_baseline=True`` linearly rescales all three scores with a
    baseline table loaded from ``baseline_path`` (csv/tsv in the published bert-score layout —
    no network egress in this build, so the reference's auto-download is path-only; ``lang`` is
    accepted for reference API parity but only participates in the reference's auto-download
    URL, so it has no effect here).
    """
    # reference-API kwargs with no effect here (batching/device/progress knobs) are accepted
    # with any value; anything unknown is a typo and must never be silently swallowed
    _inert = {"verbose", "batch_size", "num_threads", "device"}
    _supported = {"all_layers", "user_forward_fn", "user_tokenizer", "own_model", "return_hash"}
    unknown = sorted(set(reference_kwargs) - _inert - _supported)
    if unknown:
        raise TypeError(f"bert_score() got unexpected keyword arguments {unknown}")
    all_layers = bool(reference_kwargs.get("all_layers", False))
    return_hash = bool(reference_kwargs.get("return_hash", False))
    own_model = reference_kwargs.get("own_model")
    user_tokenizer = reference_kwargs.get("user_tokenizer")
    user_forward_fn = reference_kwargs.get("user_forward_fn")
    if all_layers and (
        (encoder is not None and not getattr(encoder, "layer_stacked", False))
        or user_forward_fn is not None
    ):
        # reference functional/text/bert.py:108-110; an encoder built by
        # utils.pretrained.bert_encoder(all_layers=True) is tagged `layer_stacked` and already
        # returns the (N, Λ, L, D) stack, so it composes (lets BERTScore cache it in __init__)
        raise ValueError("The option `all_layers=True` can be used only with default `transformers` models.")
    if encoder is not None and (own_model is not None or user_tokenizer is not None or user_forward_fn is not None):
        raise ValueError(
            "Pass either `encoder` or the `own_model`/`user_tokenizer`/`user_forward_fn` hooks,"
            " not both — silently preferring one of them would misreport which model was scored."
        )
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if len(preds) != len(target):
        raise ValueError(f"Number of predicted and reference sentences must match: {len(preds)} != {len(target)}")
    if encoder is None and (own_model is not None or user_tokenizer is not None or user_forward_fn is not None):
        # reference own_model/user_tokenizer/user_forward_fn path (bert.py:95-115): any of the
        # three hooks may be combined with an HF-resolved model/tokenizer for the others
        from torchmetrics_tpu.utils.pretrained import hf_bert_model_and_tokenizer, torch_bert_encoder

        model, tok = own_model, user_tokenizer
        if model is None or tok is None:  # resolve ONLY the missing pieces from the checkpoint id
            if own_model is not None and model_name_or_path is None:
                raise ValueError("`own_model` requires `user_tokenizer` (no checkpoint id to resolve one from).")
            model_name_or_path = model_name_or_path or _DEFAULT_MODEL  # keep return_hash truthful
            hf_model, hf_tok = hf_bert_model_and_tokenizer(
                model_name_or_path, load_model=model is None, load_tokenizer=tok is None,
            )
            model = model if model is not None else hf_model
            tok = tok if tok is not None else hf_tok
        encoder, tokenize = torch_bert_encoder(
            model, tok, forward_fn=user_forward_fn, num_layers=num_layers,
            max_length=max_length, all_layers=all_layers,
        )
    elif encoder is None:
        if model_name_or_path is None:
            rank_zero_warn(
                "The argument `model_name_or_path` was not specified while it is required when the default"
                " `transformers` model is used."
                f" It will use the default recommended model - {_DEFAULT_MODEL!r}."
            )
            model_name_or_path = _DEFAULT_MODEL
        from torchmetrics_tpu.utils.pretrained import bert_encoder as _build

        encoder, tokenize = _build(
            model_name_or_path, num_layers=num_layers, max_length=max_length, all_layers=all_layers
        )

    p_weights = t_weights = None
    if idf:
        if tokenize is None:
            raise ValueError(
                "`idf=True` needs token ids: pass `tokenize` alongside a custom `encoder`, or use a"
                " HuggingFace `model_name_or_path` so the tokenizer is resolved automatically."
            )
        t_ids, t_idf_mask = tokenize(list(target))
        p_ids, p_idf_mask = tokenize(list(preds))
        idf_table = _tokens_idf(t_ids, t_idf_mask)
        p_weights = jnp.asarray(_idf_weights(p_ids, idf_table))
        t_weights = jnp.asarray(_idf_weights(t_ids, idf_table))

    p_emb, p_mask = encoder(list(preds))
    t_emb, t_mask = encoder(list(target))
    # pad to a common sequence length so the cosine matrix is rectangular; with all_layers the
    # embeddings carry a layer axis at dim 1: (N, Λ, L, D)
    seq_ax = 2 if p_emb.ndim == 4 else 1
    lp, lt = p_emb.shape[seq_ax], t_emb.shape[seq_ax]
    if lp != lt:
        pad = max(lp, lt)

        def _pad_emb(e, n):
            widths = [(0, 0)] * e.ndim
            widths[seq_ax] = (0, n)
            return jnp.pad(e, widths)

        p_emb = _pad_emb(p_emb, pad - lp)
        p_mask = jnp.pad(p_mask, ((0, 0), (0, pad - lp)))
        t_emb = _pad_emb(t_emb, pad - lt)
        t_mask = jnp.pad(t_mask, ((0, 0), (0, pad - lt)))
    if p_weights is not None:
        # tokenize() and encoder() pad independently; align the idf grids to the embedding grid
        def _fit(w, L):
            w = jnp.asarray(w)
            if w.shape[1] < L:
                w = jnp.pad(w, ((0, 0), (0, L - w.shape[1])))
            return w[:, :L]

        p_weights = _fit(p_weights, p_mask.shape[1])
        t_weights = _fit(t_weights, t_mask.shape[1])

    if p_emb.ndim == 4:  # all_layers: vmap the matcher over the layer axis -> (Λ, N) scores
        import jax

        out = jax.vmap(
            lambda pe, te: _bert_score_from_embeddings(pe, p_mask, te, t_mask, p_weights, t_weights),
            in_axes=1,
        )(p_emb, t_emb)
    else:
        out = _bert_score_from_embeddings(p_emb, p_mask, t_emb, t_mask, p_weights, t_weights)

    if rescale_with_baseline:
        if baseline_path is None:
            rank_zero_warn("Baseline was not successfully loaded. No baseline is going to be used.")
        else:
            baseline = _load_baseline_file(baseline_path)
            if all_layers:  # per-layer rows, broadcast over sentences (reference bert.py:231-240)
                row = jnp.asarray(baseline)[: out["precision"].shape[0], :, None]
                rows = (row[:, 0], row[:, 1], row[:, 2])
            else:
                raw = baseline[num_layers if num_layers is not None else -1]
                rows = (raw[0], raw[1], raw[2])
            out = {
                "precision": (out["precision"] - rows[0]) / (1 - rows[0]),
                "recall": (out["recall"] - rows[1]) / (1 - rows[1]),
                "f1": (out["f1"] - rows[2]) / (1 - rows[2]),
            }
    if return_hash:  # reference bert.py:389-390 / _get_hash at :170-172
        # a caller-supplied encoder has no resolved checkpoint name; "None_L..." would
        # misreport which model produced the scores
        name = model_name_or_path if model_name_or_path is not None else "custom-encoder"
        out["hash"] = f"{name}_L{num_layers}{'_idf' if idf else '_no-idf'}"
    return out
