"""Levenshtein edit distance between character sequences (reference
``src/torchmetrics/functional/text/edit.py``)."""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.text._edit import edit_distance_batch


def _edit_distance_update(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    substitution_cost: int = 1,
) -> Array:
    """Per-pair distances (reference ``edit.py:21``) via the batched device DP."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if not all(isinstance(x, str) for x in preds):
        raise ValueError(f"All values in argument `preds` must be strings, but got {preds}")
    if not all(isinstance(x, str) for x in target):
        raise ValueError(f"All values in argument `target` must be strings, but got {target}")
    if len(preds) != len(target):
        raise ValueError(
            f"Expected argument `preds` and `target` to have same length, but got {len(preds)} and {len(target)}"
        )
    d = edit_distance_batch([list(p) for p in preds], [list(t) for t in target], float(substitution_cost))
    return jnp.asarray(d, jnp.int32)


def _edit_distance_compute(
    edit_scores: Array,
    num_elements: Union[Array, int],
    reduction: Optional[str] = "mean",
) -> Array:
    """Batch reduction (reference ``edit.py:49``)."""
    if edit_scores.size == 0:
        return jnp.asarray(0, jnp.int32)
    if reduction == "mean":
        return jnp.sum(edit_scores) / num_elements
    if reduction == "sum":
        return jnp.sum(edit_scores)
    if reduction is None or reduction == "none":
        return edit_scores
    raise ValueError("Argument `reduction` must be either 'sum', 'mean', 'none' or None")


def edit_distance(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    substitution_cost: int = 1,
    reduction: Optional[str] = "mean",
) -> Array:
    """Levenshtein edit distance (reference ``edit.py:80``).

    Example:
        >>> from torchmetrics_tpu.functional import edit_distance
        >>> print(f"{float(edit_distance(['kitten'], ['sitting'])):.4f}")
        3.0000
    """
    distance = _edit_distance_update(preds, target, substitution_cost)
    return _edit_distance_compute(distance, num_elements=distance.size, reduction=reduction)
