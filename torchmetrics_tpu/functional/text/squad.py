"""SQuAD exact-match / F1 (reference ``src/torchmetrics/functional/text/squad.py``)."""
from __future__ import annotations

import re
import string
from collections import Counter
from typing import Any, Callable, Dict, List, Tuple, Union

import jax.numpy as jnp
from jax import Array

SQuAD_FORMAT = {
    "answers": {"answer_start": [1], "text": ["This is a test text"]},
    "context": "This is a test context.",
    "id": "1",
    "question": "Is this a test?",
    "title": "train test",
}


def _normalize_text(s: str) -> str:
    """Lowercase, strip punctuation/articles/extra whitespace (reference ``squad.py:41``)."""
    s = s.lower()
    s = "".join(ch for ch in s if ch not in set(string.punctuation))
    s = re.sub(r"\b(a|an|the)\b", " ", s)
    return " ".join(s.split())


def _get_tokens(s: str) -> List[str]:
    """Reference ``squad.py:60``."""
    return _normalize_text(s).split() if s else []


def _compute_f1_score(predicted_answer: str, target_answer: str) -> float:
    """Token-overlap F1 (reference ``squad.py:65``)."""
    target_tokens = _get_tokens(target_answer)
    predicted_tokens = _get_tokens(predicted_answer)
    common = Counter(target_tokens) & Counter(predicted_tokens)
    num_same = sum(common.values())
    if len(target_tokens) == 0 or len(predicted_tokens) == 0:
        return float(target_tokens == predicted_tokens)
    if num_same == 0:
        return 0.0
    precision = num_same / len(predicted_tokens)
    recall = num_same / len(target_tokens)
    return 2 * precision * recall / (precision + recall)


def _compute_exact_match_score(prediction: str, ground_truth: str) -> float:
    """Reference ``squad.py:81``."""
    return float(_normalize_text(prediction) == _normalize_text(ground_truth))


def _metric_max_over_ground_truths(metric_fn: Callable, prediction: str, ground_truths: List[str]) -> float:
    """Reference ``squad.py:86``."""
    return max(metric_fn(prediction, truth) for truth in ground_truths)


def _squad_input_check(preds, targets) -> Tuple[Dict[str, str], List[Dict[str, Any]]]:
    """Validate + canonicalize inputs (reference ``squad.py:93``)."""
    if isinstance(preds, dict):
        preds = [preds]
    if isinstance(targets, dict):
        targets = [targets]
    for pred in preds:
        if "prediction_text" not in pred or "id" not in pred:
            raise KeyError(
                "A single prediction must carry the keys 'prediction_text' (the answer string) and 'id'"
                " (the key string)."
            )
    for target in targets:
        if "answers" not in target or "id" not in target:
            raise KeyError(
                "A single target must carry the keys 'answers' (a `SQuAD` format dictionary) and 'id'"
                " (the key string).\n"
                f"SQuAD Format: {SQuAD_FORMAT}"
            )
        if "text" not in target["answers"]:
            raise KeyError(
                "The 'answers' entry must carry a 'text' key mapping to a `SQuAD` format dictionary.\n"
                f"SQuAD Format: {SQuAD_FORMAT}"
            )
    preds_dict = {p["id"]: p["prediction_text"] for p in preds}
    targets_dict = [
        {
            "paragraphs": [
                {
                    "qas": [
                        {"answers": [{"text": txt} for txt in t["answers"]["text"]], "id": t["id"]}
                        for t in targets
                    ]
                }
            ]
        }
    ]
    return preds_dict, targets_dict


def _squad_update(preds: Dict[str, str], target: List[Dict[str, Any]]) -> Tuple[Array, Array, Array]:
    """(f1 sum, exact-match sum, total) — reference ``squad.py:136``."""
    f1 = 0.0
    exact_match = 0.0
    total = 0
    for article in target:
        for paragraph in article["paragraphs"]:
            for qa in paragraph["qas"]:
                total += 1
                if qa["id"] not in preds:
                    continue
                ground_truths = [answer["text"] for answer in qa["answers"]]
                prediction = preds[qa["id"]]
                exact_match += _metric_max_over_ground_truths(_compute_exact_match_score, prediction, ground_truths)
                f1 += _metric_max_over_ground_truths(_compute_f1_score, prediction, ground_truths)
    return jnp.asarray(f1, jnp.float32), jnp.asarray(exact_match, jnp.float32), jnp.asarray(total, jnp.float32)


def _squad_compute(f1: Array, exact_match: Array, total: Array) -> Dict[str, Array]:
    """Reference ``squad.py:183``."""
    return {"exact_match": 100.0 * exact_match / total, "f1": 100.0 * f1 / total}


def squad(preds, target) -> Dict[str, Array]:
    """SQuAD EM/F1 (reference ``squad.py:195``).

    Example:
        >>> from torchmetrics_tpu.functional import squad
        >>> preds = [{'prediction_text': 'the cat', 'id': '1'}]
        >>> target = [{'answers': {'answer_start': [0], 'text': ['the cat']}, 'id': '1'}]
        >>> out = squad(preds, target)
        >>> print(f"{float(out['exact_match']):.1f} {float(out['f1']):.1f}")
        100.0 100.0
    """
    preds_dict, target_dict = _squad_input_check(preds, target)
    f1, exact_match, total = _squad_update(preds_dict, target_dict)
    return _squad_compute(f1, exact_match, total)
