"""SacreBLEU (reference ``src/torchmetrics/functional/text/sacre_bleu.py``).

Same count-vector state as BLEU; the sacrebleu-style tokenizers (``_SacreBLEUTokenizer``,
reference ``sacre_bleu.py:98``) are reimplemented for the supported variants. Tokenizers needing
external segmenters (``ja-mecab``, ``ko-mecab``, ``flores101/200`` sentencepiece) raise with a
clear message — this image has no mecab/sentencepiece and SURVEY §7 marks them host-dep.
"""
from __future__ import annotations

import re
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update_batched

AVAILABLE_TOKENIZERS = ("none", "13a", "zh", "intl", "char")
_UNSUPPORTED_TOKENIZERS = ("ja-mecab", "ko-mecab", "flores101", "flores200")

# CJK codepoint ranges used by the `zh` tokenizer (sacrebleu convention; reference
# ``sacre_bleu.py:63-87``)
_UCODE_RANGES = (
    ("\u3400", "\u4db5"),  # CJK Unified Ideographs Extension A
    ("\u4e00", "\u9fa5"),  # CJK Unified Ideographs
    ("\u9fa6", "\u9fbb"),
    ("\uf900", "\ufa2d"),  # CJK Compatibility Ideographs
    ("\ufa30", "\ufa6a"),
    ("\ufa70", "\ufad9"),
    ("\U00020000", "\U0002a6d6"),  # CJK Unified Ideographs Extension B
    ("\U0002f800", "\U0002fa1d"),  # CJK Compatibility Supplement
    ("\uff00", "\uffef"),  # full-width ASCII / half-width kana / Korean alphabet
    ("\u2e80", "\u2eff"),  # CJK radicals supplement
    ("\u3000", "\u303f"),  # CJK punctuation
    ("\u31c0", "\u31ef"),  # CJK stroke
    ("\u2f00", "\u2fdf"),  # Kangxi radicals
    ("\u2ff0", "\u2fff"),  # Chinese character structure
    ("\u3100", "\u312f"),  # phonetic symbols
    ("\u31a0", "\u31bf"),
    ("\ufe10", "\ufe1f"),
    ("\ufe30", "\ufe4f"),
    ("\u2600", "\u26ff"),
    ("\u2700", "\u27bf"),
    ("\u3200", "\u32ff"),
    ("\u3300", "\u33ff"),
)


class _SacreBLEUTokenizer:
    """Sacrebleu-style tokenizers (reference ``sacre_bleu.py:98``)."""

    _REGEX = (
        (re.compile(r"([\{-\~\[-\` -\&\(-\+\:-\@\/])"), r" \1 "),
        (re.compile(r"([^0-9])([\.,])"), r"\1 \2 "),
        (re.compile(r"([\.,])([^0-9])"), r" \1 \2"),
        (re.compile(r"([0-9])(-)"), r"\1 \2 "),
    )

    try:
        import regex

        _INT_REGEX = (
            (regex.compile(r"(\P{N})(\p{P})"), r"\1 \2 "),
            (regex.compile(r"(\p{P})(\P{N})"), r" \1 \2"),
            (regex.compile(r"(\p{S})"), r" \1 "),
        )
        _REGEX_AVAILABLE = True
    except ImportError:  # pragma: no cover
        _REGEX_AVAILABLE = False

    _TOKENIZE_FN = {
        "none": "_tokenize_base",
        "13a": "_tokenize_13a",
        "zh": "_tokenize_zh",
        "intl": "_tokenize_international",
        "char": "_tokenize_char",
    }

    def __init__(self, tokenize: str, lowercase: bool = False) -> None:
        self._check_tokenizers_validity(tokenize)
        self.tokenize_fn = getattr(self, self._TOKENIZE_FN[tokenize])
        self.lowercase = lowercase

    def __call__(self, line: str) -> Sequence[str]:
        tokenized_line = self.tokenize_fn(line)
        return self._lower(tokenized_line, self.lowercase).split()

    @classmethod
    def tokenize(cls, line: str, tokenize: str, lowercase: bool = False) -> Sequence[str]:
        cls._check_tokenizers_validity(tokenize)
        tokenized_line = getattr(cls, cls._TOKENIZE_FN[tokenize])(line)
        return cls._lower(tokenized_line, lowercase).split()

    @classmethod
    def _tokenize_regex(cls, line: str) -> str:
        for _re, repl in cls._REGEX:
            line = _re.sub(repl, line)
        return " ".join(line.split())

    @staticmethod
    def _is_chinese_char(uchar: str) -> bool:
        return any(start <= uchar <= end for start, end in _UCODE_RANGES)

    @classmethod
    def _tokenize_base(cls, line: str) -> str:
        return line

    @classmethod
    def _tokenize_13a(cls, line: str) -> str:
        line = line.replace("<skipped>", "")
        line = line.replace("-\n", "")
        line = line.replace("\n", " ")
        if "&" in line:
            line = line.replace("&quot;", '"')
            line = line.replace("&amp;", "&")
            line = line.replace("&lt;", "<")
            line = line.replace("&gt;", ">")
        return cls._tokenize_regex(f" {line} ")

    @classmethod
    def _tokenize_zh(cls, line: str) -> str:
        line = line.strip()
        line_in_chars = ""
        for char in line:
            if cls._is_chinese_char(char):
                line_in_chars += f" {char} "
            else:
                line_in_chars += char
        return cls._tokenize_regex(line_in_chars)

    @classmethod
    def _tokenize_international(cls, line: str) -> str:
        if not cls._REGEX_AVAILABLE:  # pragma: no cover
            raise ModuleNotFoundError("The `intl` tokenizer requires the `regex` package.")
        for _re, repl in cls._INT_REGEX:
            line = _re.sub(repl, line)
        return " ".join(line.split())

    @classmethod
    def _tokenize_char(cls, line: str) -> str:
        return " ".join(char for char in line)

    @staticmethod
    def _lower(line: str, lowercase: bool) -> str:
        return line.lower() if lowercase else line

    @classmethod
    def _check_tokenizers_validity(cls, tokenize: str) -> None:
        if tokenize in _UNSUPPORTED_TOKENIZERS:
            raise ValueError(
                f"Tokenizer {tokenize!r} needs an external segmenter (mecab/sentencepiece) that is not"
                f" available in this build; supported: {AVAILABLE_TOKENIZERS}."
            )
        if tokenize not in cls._TOKENIZE_FN:
            raise ValueError(f"Unsupported tokenizer selected. Please, choose one of {AVAILABLE_TOKENIZERS}")


def sacre_bleu_score(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    n_gram: int = 4,
    smooth: bool = False,
    tokenize: str = "13a",
    lowercase: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> Array:
    """SacreBLEU score (reference ``sacre_bleu.py:389``).

    Example:
        >>> from torchmetrics_tpu.functional import sacre_bleu_score
        >>> preds = ["the cat is on the mat"]
        >>> target = [["the cat is on the mat"]]
        >>> print(f"{float(sacre_bleu_score(preds, target)):.4f}")
        1.0000
    """
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    if weights is None:
        weights = [1.0 / n_gram] * n_gram
    tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)
    numerator = np.zeros(n_gram)
    denominator = np.zeros(n_gram)
    preds_len, target_len = _bleu_score_update_batched(
        preds, [[t] if isinstance(t, str) else t for t in target], numerator, denominator, 0.0, 0.0,
        n_gram, tokenizer,
    )
    return _bleu_score_compute(
        preds_len, target_len, jnp.asarray(numerator), jnp.asarray(denominator), n_gram, weights, smooth
    )
