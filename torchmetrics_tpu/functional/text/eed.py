"""Extended Edit Distance (reference ``src/torchmetrics/functional/text/eed.py``).

The CDER-grid DP runs vectorised over the hypothesis axis in numpy: the deletion chain inside a
row is a prefix-min (same trick as the TER row kernel), so each reference character costs one
vector pass instead of a Python loop.
"""
from __future__ import annotations

import re
import unicodedata
from math import inf
from typing import List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.text.ter import _validate_inputs


def _eed_function(
    hyp: str,
    ref: str,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> float:
    """EED over character sequences (reference ``eed.py:117-172``)."""
    h = len(hyp)
    hyp_chars = np.frombuffer(hyp.encode("utf-32-le"), np.uint32) if h else np.zeros(0, np.uint32)
    number_of_visits = np.full(h + 1, -1, np.int64)
    row = np.ones(h + 1)
    row[0] = 0.0

    for w in range(1, len(ref) + 1):
        ref_char = np.uint32(ord(ref[w - 1]))
        # substitution/insertion candidates, vectorised over the hypothesis axis
        base = np.empty(h + 1)
        base[0] = row[0] + 1.0
        if h:
            subst = row[:-1] + (hyp_chars != ref_char)
            base[1:] = np.minimum(subst, row[1:] + insertion)
        # deletion chain stays sequential: the reference accumulates `+deletion` one step at a
        # time, and a closed-form k*deletion differs in the last ulp — enough to flip argmin
        # ties and change the coverage term
        next_row = base
        prev = next_row[0]
        for i in range(1, h + 1):
            cand = prev + deletion
            if cand < next_row[i]:
                next_row[i] = cand
            prev = next_row[i]
        min_index = int(np.argmin(next_row))
        number_of_visits[min_index] += 1
        if ref[w - 1] == " ":
            jump = alpha + next_row[min_index]
            next_row = np.minimum(next_row, jump)
        row = next_row

    coverage = rho * float(np.where(number_of_visits >= 0, number_of_visits, 1).sum())
    return min(1.0, (row[-1] + coverage) / (float(len(ref)) + coverage))


def _preprocess_en(sentence: str) -> str:
    """English preprocessing rules (reference ``eed.py:175-215``)."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    sentence = sentence.rstrip()
    for pattern, replacement in ((".", " ."), ("!", " !"), ("?", " ?"), (",", " ,")):
        sentence = sentence.replace(pattern, replacement)
    rules_re = [
        (r"\s+", r" "),
        (r"(\d) ([.,]) (\d)", r"\1\2\3"),
        (r"(Dr|Jr|Prof|Rev|Gen|Mr|Mt|Mrs|Ms) .", r"\1."),
    ]
    for pattern, replacement in rules_re:
        sentence = re.sub(pattern, replacement, sentence)
    for pattern, replacement in (("e . g .", "e.g."), ("i . e .", "i.e."), ("U . S .", "U.S.")):
        sentence = sentence.replace(pattern, replacement)
    return " " + sentence + " "


def _preprocess_ja(sentence: str) -> str:
    """Japanese preprocessing (reference ``eed.py:218-233``)."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    return unicodedata.normalize("NFKC", sentence.rstrip())


def _eed_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
    sentence_eed: Optional[List[float]] = None,
) -> List[float]:
    """Per-sentence best-over-references EED scores (reference ``eed.py:300-341``)."""
    target, preds = _validate_inputs(target, preds)
    if language == "en":
        preprocess = _preprocess_en
    elif language == "ja":
        preprocess = _preprocess_ja
    else:
        raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
    if sentence_eed is None:
        sentence_eed = []
    for pred, refs in zip(preds, target):
        pred_p = preprocess(pred)
        best = inf
        for ref in refs:
            score = _eed_function(pred_p, preprocess(ref), alpha, rho, deletion, insertion)
            best = min(best, score)
        sentence_eed.append(best)
    return sentence_eed


def extended_edit_distance(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    return_sentence_level_score: bool = False,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
):
    """EED (reference ``eed.py:344-414``).

    Example:
        >>> from torchmetrics_tpu.functional import extended_edit_distance
        >>> preds = ["this is the prediction"]
        >>> target = ["this is the reference"]
        >>> print(f"{float(extended_edit_distance(preds, target)):.4f}")
        0.3835
    """
    for name, val in (("alpha", alpha), ("rho", rho), ("deletion", deletion), ("insertion", insertion)):
        if not isinstance(val, float) or val < 0:
            raise ValueError(f"Parameter `{name}` must be a non-negative float.")
    sentence_eed = _eed_update(preds, target, language, alpha, rho, deletion, insertion)
    if not sentence_eed:
        return jnp.asarray(0.0, jnp.float32)
    avg = jnp.asarray(float(np.mean(sentence_eed)), jnp.float32)
    if return_sentence_level_score:
        return avg, [jnp.asarray([s], jnp.float32) for s in sentence_eed]
    return avg
