"""Perplexity (reference ``src/torchmetrics/functional/text/perplexity.py``).

Fully on-device: one fused log-softmax + gather + masked sum per batch; ``ignore_index`` is a
mask-and-weight (the reference's boolean indexing is dynamic-shape).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import is_traced


def _check_shape_and_type_consistency(preds: Array, target: Array) -> None:
    """Host-side validation (reference ``perplexity.py:20``)."""
    if jnp.ndim(preds) != 3:
        raise ValueError(
            "Input tensor `preds` is expected to have 3 dimensions, [batch_size, seq_len, vocab_size],"
            f" but got {jnp.ndim(preds)}."
        )
    if jnp.ndim(target) != 2:
        raise ValueError(
            "Input tensor `target` is expected to have 2 dimensions, [batch_size, seq_len],"
            f" but got {jnp.ndim(target)}."
        )
    if jnp.shape(preds)[:2] != jnp.shape(target):
        raise ValueError(
            "Input tensors `preds` and `target` are expected to have equaling first two dimensions,"
            f" [batch_size, seq_len], but got {jnp.shape(preds)[:2]} and {jnp.shape(target)}."
        )
    if not is_traced(preds) and not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        raise TypeError(
            f"Input tensor `preds` must be of floating point type but got {jnp.asarray(preds).dtype}."
        )
    if not is_traced(target) and not jnp.issubdtype(jnp.asarray(target).dtype, jnp.integer):
        raise TypeError(
            f"Input tensor `target` is expected to be of integer type but got {jnp.asarray(target).dtype}."
        )


def _perplexity_update(preds: Array, target: Array, ignore_index: Optional[int] = None) -> Tuple[Array, Array]:
    """(summed token log-probs, token count) — reference ``perplexity.py:65``."""
    log_probs = jax.nn.log_softmax(jnp.asarray(preds, jnp.float32).reshape(-1, preds.shape[-1]), axis=-1)
    target = jnp.asarray(target).reshape(-1)
    if ignore_index is not None:
        mask = (target != ignore_index).astype(jnp.float32)
        target = jnp.where(target == ignore_index, 0, target)
    else:
        mask = jnp.ones_like(target, jnp.float32)
    token_lp = jnp.take_along_axis(log_probs, target[:, None], axis=1)[:, 0]
    return -jnp.sum(token_lp * mask), jnp.sum(mask)


def _perplexity_compute(total: Array, count: Array) -> Array:
    """exp(mean NLL) — reference ``perplexity.py:101``."""
    return jnp.exp(total / count)


def perplexity(preds: Array, target: Array, ignore_index: Optional[int] = None) -> Array:
    """Perplexity of a language-model output (reference ``perplexity.py:109``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import perplexity
        >>> logits = np.log(np.array([[[0.6, 0.4], [0.3, 0.7]]], np.float32))
        >>> target = np.array([[0, 1]])
        >>> print(f"{float(perplexity(logits, target)):.3f}")
        1.543
    """
    _check_shape_and_type_consistency(preds, target)
    total, count = _perplexity_update(preds, target, ignore_index)
    return _perplexity_compute(total, count)
