"""chrF / chrF++ score (reference ``src/torchmetrics/functional/text/chrf.py``).

TPU-first state layout: the reference keeps 6 dicts of per-order scalar tensors
(``chrf.py:48-79``); here each is ONE fixed-shape vector indexed by ``n-1`` — char orders in a
``(n_char_order,)`` array, word orders in ``(n_word_order,)`` — so the whole metric state is six
psum-able device arrays. n-gram counting stays host string work (inherently so), the F-score
compute is trace-safe jnp.
"""
from __future__ import annotations

from collections import Counter, defaultdict
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

_EPS_SMOOTHING = 1e-16
_PUNCTUATIONS = set("!\"#$%&'()*+,-./:;<=>?@[\\]^_`{|}~")


def _get_characters(sentence: str, whitespace: bool) -> List[str]:
    """Reference ``chrf.py:81``."""
    if whitespace:
        return list(sentence)
    return list(sentence.strip().replace(" ", ""))


def _separate_word_and_punctuation(word: str) -> List[str]:
    """Reference ``chrf.py:97``."""
    if len(word) == 1:
        return [word]
    if word[-1] in _PUNCTUATIONS:
        return [word[:-1], word[-1]]
    if word[0] in _PUNCTUATIONS:
        return [word[0], word[1:]]
    return [word]


def _get_words_and_punctuation(sentence: str) -> List[str]:
    """Reference ``chrf.py:120``."""
    return sum((_separate_word_and_punctuation(word) for word in sentence.strip().split()), [])


def _ngram_counts(char_or_word_list: List[str], n_gram_order: int) -> Dict[int, Counter]:
    """Counter per order 1..n (reference ``chrf.py:133``)."""
    ngrams: Dict[int, Counter] = defaultdict(Counter)
    for n in range(1, n_gram_order + 1):
        for ngram in (tuple(char_or_word_list[i : i + n]) for i in range(len(char_or_word_list) - n + 1)):
            ngrams[n][ngram] += 1
    return ngrams


def _get_n_grams_counts_and_total_ngrams(
    sentence: str, n_char_order: int, n_word_order: int, lowercase: bool, whitespace: bool
) -> Tuple[Dict[int, Counter], Dict[int, Counter], np.ndarray, np.ndarray]:
    """Reference ``chrf.py:151`` with vector totals."""
    if lowercase:
        sentence = sentence.lower()
    char_n_grams_counts = _ngram_counts(_get_characters(sentence, whitespace), n_char_order)
    word_n_grams_counts = _ngram_counts(_get_words_and_punctuation(sentence), n_word_order)
    char_totals = np.array(
        [sum(char_n_grams_counts[n].values()) for n in range(1, n_char_order + 1)], np.float32
    )
    word_totals = np.array(
        [sum(word_n_grams_counts[n].values()) for n in range(1, n_word_order + 1)], np.float32
    )
    return char_n_grams_counts, word_n_grams_counts, char_totals, word_totals


def _get_ngram_matches(hyp: Dict[int, Counter], ref: Dict[int, Counter], order: int) -> np.ndarray:
    """Clipped matches per order as a vector (reference ``chrf.py:202``)."""
    return np.array(
        [sum((hyp[n] & ref[n]).values()) for n in range(1, order + 1)], np.float32
    )


def _calculate_fscore(
    matching_char_n_grams: Array,
    matching_word_n_grams: Array,
    hyp_char_n_grams: Array,
    hyp_word_n_grams: Array,
    ref_char_n_grams: Array,
    ref_word_n_grams: Array,
    n_order: float,
    beta: float,
) -> Array:
    """Vectorized masked F-beta over all orders at once (reference ``chrf.py:243``)."""

    def _fscore(match, hyp, ref):
        match = jnp.asarray(match, jnp.float32)
        hyp = jnp.asarray(hyp, jnp.float32)
        ref = jnp.asarray(ref, jnp.float32)
        precision = jnp.where(hyp > 0, match / jnp.maximum(hyp, 1e-38), 0.0)
        recall = jnp.where(ref > 0, match / jnp.maximum(ref, 1e-38), 0.0)
        denominator = jnp.maximum(beta**2 * precision + recall, _EPS_SMOOTHING)
        return (1 + beta**2) * precision * recall / denominator

    char_f = _fscore(matching_char_n_grams, hyp_char_n_grams, ref_char_n_grams)
    word_f = _fscore(matching_word_n_grams, hyp_word_n_grams, ref_word_n_grams)
    return (jnp.sum(char_f) + jnp.sum(word_f)) / n_order


def _chrf_score_update(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    totals: Dict[str, np.ndarray],
    n_char_order: int,
    n_word_order: int,
    n_order: float,
    beta: float,
    lowercase: bool,
    whitespace: bool,
    sentence_chrf_score: Optional[List[float]] = None,
) -> Optional[List[float]]:
    """Accumulate corpus-level vectors in ``totals`` (reference ``chrf.py:386``), mutating in place.

    ``totals`` keys: preds_char/preds_word/target_char/target_word/matching_char/matching_word.
    Per sentence, the best-matching reference (by sentence F-score) contributes its statistics.
    """
    if isinstance(preds, str):
        preds = [preds]
    target_corpus = [[t] if isinstance(t, str) else t for t in target]
    if len(preds) != len(target_corpus):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target_corpus)}")

    for pred, targets in zip(preds, target_corpus):
        p_char_counts, p_word_counts, p_char_tot, p_word_tot = _get_n_grams_counts_and_total_ngrams(
            pred, n_char_order, n_word_order, lowercase, whitespace
        )
        totals["preds_char"] += p_char_tot
        totals["preds_word"] += p_word_tot

        # Strict-greater vs an initial best of 0.0 (reference ``chrf.py:344-372``): a sentence
        # whose F-score is 0 against every reference accumulates NO reference statistics.
        best = (0.0, None)
        for tgt in targets:
            t_char_counts, t_word_counts, t_char_tot, t_word_tot = _get_n_grams_counts_and_total_ngrams(
                tgt, n_char_order, n_word_order, lowercase, whitespace
            )
            m_char = _get_ngram_matches(p_char_counts, t_char_counts, n_char_order)
            m_word = _get_ngram_matches(p_word_counts, t_word_counts, n_word_order)
            f_score = float(
                _calculate_fscore(m_char, m_word, p_char_tot, p_word_tot, t_char_tot, t_word_tot, n_order, beta)
            )
            if f_score > best[0]:
                best = (f_score, (m_char, m_word, t_char_tot, t_word_tot))
        f_best, stats = best
        if stats is None:  # no references, or zero F against all of them -> zero contribution
            stats = (
                np.zeros(n_char_order, np.float32),
                np.zeros(n_word_order, np.float32),
                np.zeros(n_char_order, np.float32),
                np.zeros(n_word_order, np.float32),
            )
            f_best = 0.0
        m_char, m_word, t_char_tot, t_word_tot = stats
        totals["matching_char"] += m_char
        totals["matching_word"] += m_word
        totals["target_char"] += t_char_tot
        totals["target_word"] += t_word_tot
        if sentence_chrf_score is not None:
            sentence_chrf_score.append(max(f_best, 0.0))
    return sentence_chrf_score


def _chrf_score_compute(totals: Dict[str, Array], n_order: float, beta: float) -> Array:
    """Corpus-level score from the six vectors (reference ``chrf.py:497``)."""
    return _calculate_fscore(
        totals["matching_char"],
        totals["matching_word"],
        totals["preds_char"],
        totals["preds_word"],
        totals["target_char"],
        totals["target_word"],
        n_order,
        beta,
    )


def _validate_chrf_args(n_char_order: int, n_word_order: int, beta: float) -> None:
    if not isinstance(n_char_order, int) or n_char_order < 1:
        raise ValueError('Argument `n_char_order` must be an integer greater than or equal to 1.')
    if not isinstance(n_word_order, int) or n_word_order < 0:
        raise ValueError('Argument `n_word_order` must be an integer greater than or equal to 0.')
    if beta < 0:
        raise ValueError('Argument `beta` must be greater than 0.')


def chrf_score(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    n_char_order: int = 6,
    n_word_order: int = 2,
    beta: float = 2.0,
    lowercase: bool = False,
    whitespace: bool = False,
    return_sentence_level_score: bool = False,
):
    """chrF/chrF++ score (reference ``chrf.py:536``). ``n_word_order=2`` gives chrF++, 0 gives chrF.

    Example:
        >>> from torchmetrics_tpu.functional import chrf_score
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat']]
        >>> print(f"{float(chrf_score(preds, target)):.4f}")
        0.4942
    """
    _validate_chrf_args(n_char_order, n_word_order, beta)
    n_order = float(n_char_order + n_word_order)
    totals = {
        "preds_char": np.zeros(n_char_order, np.float32),
        "preds_word": np.zeros(n_word_order, np.float32),
        "target_char": np.zeros(n_char_order, np.float32),
        "target_word": np.zeros(n_word_order, np.float32),
        "matching_char": np.zeros(n_char_order, np.float32),
        "matching_word": np.zeros(n_word_order, np.float32),
    }
    sentence_scores: Optional[List[float]] = [] if return_sentence_level_score else None
    _chrf_score_update_batched(
        preds, target, totals, n_char_order, n_word_order, n_order, beta, lowercase, whitespace, sentence_scores
    )
    score = _chrf_score_compute({k: jnp.asarray(v) for k, v in totals.items()}, n_order, beta)
    if return_sentence_level_score:
        return score, jnp.asarray(sentence_scores, jnp.float32)
    return score


def _domain_stats_batched(
    pred_streams: List[List[str]],
    ref_streams: List[List[str]],
    ref_sent: np.ndarray,
    max_n: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorised per-domain (char or word) n-gram statistics.

    Returns ``(pred_totals (S, N), ref_totals (R, N), matches (R, N))`` where ``matches[r, n]``
    is the clipped n-gram intersection of ref ``r`` with ITS sentence's prediction.
    """
    from torchmetrics_tpu.functional.text._ngram import intern_streams, iter_ngram_levels

    n_pred = len(pred_streams)
    n_ref = len(ref_streams)
    pred_totals = np.zeros((n_pred, max_n), np.float32)
    ref_totals = np.zeros((n_ref, max_n), np.float32)
    matches = np.zeros((n_ref, max_n), np.float32)
    if max_n == 0:
        return pred_totals, ref_totals, matches

    ids_flat, stream_of, vocab = intern_streams(pred_streams + ref_streams)
    for n, codes, valid in iter_ngram_levels(ids_flat, stream_of, vocab, max_n):
        sel = valid
        if not sel.any():
            continue
        streams = stream_of[sel]
        n_codes = int(codes[sel].max()) + 1
        is_pred = streams < n_pred
        # totals: number of n-gram positions per stream
        pred_totals[:, n - 1] = np.bincount(streams[is_pred], minlength=n_pred)[:n_pred]
        ref_totals[:, n - 1] = np.bincount(streams[~is_pred] - n_pred, minlength=n_ref)[:n_ref]
        # per-(pred sentence, gram) counts, keys sorted by np.unique
        pkeys, pcounts = np.unique(streams[is_pred] * n_codes + codes[sel][is_pred], return_counts=True)
        # per-(ref, gram) counts
        rstreams = streams[~is_pred] - n_pred
        rk, rc = np.unique(rstreams * n_codes + codes[sel][~is_pred], return_counts=True)
        r_of = rk // n_codes
        gram = rk % n_codes
        # look up each ref gram in its sentence's prediction counts
        lookup = ref_sent[r_of] * n_codes + gram
        pos = np.searchsorted(pkeys, lookup)
        pos_c = np.minimum(pos, len(pkeys) - 1) if len(pkeys) else np.zeros_like(pos)
        hit = (len(pkeys) > 0) & (pkeys[pos_c] == lookup) if len(pkeys) else np.zeros_like(pos, bool)
        clipped = np.where(hit, np.minimum(rc, pcounts[pos_c] if len(pkeys) else 0), 0)
        np.add.at(matches[:, n - 1], r_of, clipped)
    return pred_totals, ref_totals, matches


def _fscore_np(m_char, m_word, h_char, h_word, r_char, r_word, n_order: float, beta: float) -> np.ndarray:
    """Vectorised numpy twin of ``_calculate_fscore`` over leading batch dims."""

    def _f(match, hyp, ref):
        precision = np.where(hyp > 0, match / np.maximum(hyp, 1e-38), 0.0).astype(np.float32)
        recall = np.where(ref > 0, match / np.maximum(ref, 1e-38), 0.0).astype(np.float32)
        denominator = np.maximum(beta**2 * precision + recall, _EPS_SMOOTHING).astype(np.float32)
        return ((1 + beta**2) * precision * recall / denominator).astype(np.float32)

    char_f = _f(m_char, h_char, r_char).sum(axis=-1)
    word_f = _f(m_word, h_word, r_word).sum(axis=-1)
    return ((char_f + word_f) / n_order).astype(np.float32)


def _chrf_score_update_batched(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    totals: Dict[str, np.ndarray],
    n_char_order: int,
    n_word_order: int,
    n_order: float,
    beta: float,
    lowercase: bool,
    whitespace: bool,
    sentence_chrf_score: Optional[List[float]] = None,
) -> Optional[List[float]]:
    """Vectorised twin of ``_chrf_score_update``: intern → dense-code counting → per-(sentence,
    ref) clipped matches → best-reference selection, all as numpy array passes (fuzz-pinned
    equal to the loop implementation in the text tests)."""
    if isinstance(preds, str):
        preds = [preds]
    target_corpus = [[t] if isinstance(t, str) else t for t in target]
    if len(preds) != len(target_corpus):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target_corpus)}")
    n_sent = len(preds)

    def _prep(s: str) -> str:
        return s.lower() if lowercase else s

    # the whitespace flag only affects the char stream; words always go through the
    # punctuation-separating tokenizer (same as _get_n_grams_counts_and_total_ngrams)
    pred_chars = [_get_characters(_prep(p), whitespace) for p in preds]
    pred_words = [_get_words_and_punctuation(_prep(p)) for p in preds]
    refs_flat: List[str] = [r for refs in target_corpus for r in refs]
    ref_sent = np.asarray([i for i, refs in enumerate(target_corpus) for _ in refs], np.int64)
    ref_chars = [_get_characters(_prep(r), whitespace) for r in refs_flat]
    ref_words = [_get_words_and_punctuation(_prep(r)) for r in refs_flat]

    pc_tot, rc_tot, mc = _domain_stats_batched(pred_chars, ref_chars, ref_sent, n_char_order)
    pw_tot, rw_tot, mw = _domain_stats_batched(pred_words, ref_words, ref_sent, n_word_order)

    totals["preds_char"] += pc_tot.sum(axis=0)
    totals["preds_word"] += pw_tot.sum(axis=0)

    if len(refs_flat):
        f = _fscore_np(
            mc, mw, pc_tot[ref_sent], pw_tot[ref_sent], rc_tot, rw_tot, n_order, beta
        )  # (R,)
        # first ref with the max f per sentence (strictly-greater update rule of the loop)
        ref_order = np.arange(len(refs_flat))
        order = np.lexsort((ref_order, -f, ref_sent))
        first = order[np.flatnonzero(np.r_[True, ref_sent[order][1:] != ref_sent[order][:-1]])]
        best_sent = ref_sent[first]
    else:
        first = np.zeros(0, np.int64)
        best_sent = np.zeros(0, np.int64)

    best_f = np.zeros(n_sent, np.float32)
    if len(first):
        # zero-F sentences contribute no reference stats (strict-greater rule, see loop twin)
        contributing = first[f[first] > 0]
        totals["matching_char"] += mc[contributing].sum(axis=0)
        totals["matching_word"] += mw[contributing].sum(axis=0)
        totals["target_char"] += rc_tot[contributing].sum(axis=0)
        totals["target_word"] += rw_tot[contributing].sum(axis=0)
        best_f[best_sent] = f[first]
    if sentence_chrf_score is not None:
        sentence_chrf_score.extend(float(x) for x in best_f)
    return sentence_chrf_score
