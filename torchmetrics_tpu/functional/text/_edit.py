"""Batched Levenshtein edit distance as a jitted XLA kernel.

Reference: ``src/torchmetrics/functional/text/helper.py`` (``_edit_distance:329`` — a per-pair
Python DP loop; ``_LevenshteinEditDistance:69`` — a cached row DP, also host Python).

TPU-first redesign: tokens are interned to int ids on the host (the only string-dependent step),
sentences are padded to a ``(B, L)`` rectangle (pow2-bucketed to bound recompiles), and the DP
runs as ONE device program for the whole batch:

- ``lax.scan`` over prediction positions carries the DP row for all B pairs at once,
- the insertion recurrence along the row — ``new[j] = min(c[j], min_{k<j} c[k] + (j-k))`` — is
  solved in closed form with a cumulative min of ``c[k] - k`` (min-plus prefix scan), so each
  scan step is O(L) vectorized work with no inner Python loop.

Cost: O(B * Lp * Lt) FLOPs, O(log) scan depth per row — embarrassingly parallel over the batch
where the reference is strictly sequential per pair.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

_BIG = 1e9


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1)).bit_length()


def _levenshtein_rows(
    pred_ids: Array, pred_len: Array, tgt_ids: Array, tgt_len: Array, substitution_cost: float
) -> Array:
    """Edit distance for ONE (padded) pair; vmapped over the batch by the caller."""
    l_t = tgt_ids.shape[0]
    j = jnp.arange(l_t + 1, dtype=jnp.float32)
    init_row = j  # distance from empty prediction = j insertions... (deletions of target prefix)

    def step(row, x):
        pid, i = x  # i is the 1-based prediction position
        active = i <= pred_len
        sub_cost = jnp.where(pid == tgt_ids, 0.0, substitution_cost)
        # candidate costs before resolving the along-row insertion dependency
        c = jnp.concatenate(
            [
                jnp.asarray([i], jnp.float32),  # j=0 boundary: i deletions
                jnp.minimum(row[:-1] + sub_cost, row[1:] + 1.0),
            ]
        )
        # new[j] = j + cummin(c[k] - k)  solves new[j] = min(c[j], new[j-1] + 1)
        new_row = j + jax.lax.associative_scan(jnp.minimum, c - j)
        return jnp.where(active, new_row, row), None

    ids_and_pos = (pred_ids, jnp.arange(1, pred_ids.shape[0] + 1, dtype=jnp.float32))
    final_row, _ = jax.lax.scan(step, init_row, ids_and_pos)
    return final_row[tgt_len]


@jax.jit
def _levenshtein_batch_kernel(pred_ids, pred_len, tgt_ids, tgt_len, substitution_cost):
    return jax.vmap(_levenshtein_rows, in_axes=(0, 0, 0, 0, None))(
        pred_ids, pred_len, tgt_ids, tgt_len, substitution_cost
    )


def _intern(batch: Sequence[Sequence[str]], vocab: dict) -> List[List[int]]:
    out = []
    for seq in batch:
        row = []
        for tok in seq:
            idx = vocab.get(tok)
            if idx is None:
                idx = len(vocab)
                vocab[tok] = idx
            row.append(idx)
        out.append(row)
    return out


def edit_distance_batch(
    preds_tokens: Sequence[Sequence[str]],
    target_tokens: Sequence[Sequence[str]],
    substitution_cost: float = 1.0,
) -> np.ndarray:
    """Per-pair Levenshtein distances for a batch of tokenized sentences (host entry point)."""
    if len(preds_tokens) != len(target_tokens):
        raise ValueError(
            f"Expected argument `preds` and `target` to have same length, but got {len(preds_tokens)} and {len(target_tokens)}"
        )
    if not preds_tokens:
        return np.zeros((0,), np.float32)
    vocab: dict = {}
    p_ids = _intern(preds_tokens, vocab)
    t_ids = _intern(target_tokens, vocab)
    b = len(p_ids)
    l_p = _next_pow2(max(1, max(len(r) for r in p_ids)))
    l_t = _next_pow2(max(1, max(len(r) for r in t_ids)))
    b_pad = _next_pow2(b)
    # -1/-2 pads never match each other, so padded positions cost substitution but are masked by
    # (pred_len, tgt_len) indexing anyway
    pp = np.full((b_pad, l_p), -1, np.int32)
    tt = np.full((b_pad, l_t), -2, np.int32)
    pl = np.zeros((b_pad,), np.int32)
    tl = np.zeros((b_pad,), np.int32)
    for i, (pr, tr) in enumerate(zip(p_ids, t_ids)):
        pp[i, : len(pr)] = pr
        tt[i, : len(tr)] = tr
        pl[i] = len(pr)
        tl[i] = len(tr)
    out = _levenshtein_batch_kernel(
        jnp.asarray(pp), jnp.asarray(pl), jnp.asarray(tt), jnp.asarray(tl), float(substitution_cost)
    )
    return np.asarray(out)[:b]


def _edit_distance_one(prediction_tokens: Sequence[str], reference_tokens: Sequence[str]) -> int:
    """Single-pair convenience (reference ``helper.py:329`` signature)."""
    return int(edit_distance_batch([list(prediction_tokens)], [list(reference_tokens)])[0])


def _word_batch_stats(
    preds: Sequence[str], target: Sequence[str], tokenize
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(distances, pred_lens, target_lens) for a batch of raw strings."""
    p_tok = [tokenize(p) for p in preds]
    t_tok = [tokenize(t) for t in target]
    d = edit_distance_batch(p_tok, t_tok)
    return d, np.asarray([len(x) for x in p_tok], np.float32), np.asarray([len(x) for x in t_tok], np.float32)
