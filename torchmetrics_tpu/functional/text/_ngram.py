"""Shared vectorised n-gram machinery for corpus counting metrics (BLEU, chrF).

Tokens are interned to dense int ids once; n-gram identities are built level by level as rolling
codes, compacted with ``np.unique`` at every level so values stay dense (bounded by the number
of positions — no int64 overflow regardless of vocabulary or order). All per-group counting is
``np.unique`` over composed dense keys: vectorised C loops instead of per-sentence Python
``Counter`` passes.
"""
from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple

import numpy as np


def intern_streams(streams: Sequence[Sequence[str]]) -> Tuple[np.ndarray, np.ndarray, int]:
    """Flatten token streams into (ids, owner-stream index, vocab size)."""
    vocab: dict = {}
    ids_list = [
        np.fromiter((vocab.setdefault(t, len(vocab)) for t in toks), np.int64, len(toks))
        for toks in streams
    ]
    ids_flat = np.concatenate(ids_list) if ids_list else np.zeros(0, np.int64)
    lens = np.asarray([len(x) for x in ids_list], np.int64)
    stream_of = np.repeat(np.arange(len(ids_list)), lens)
    return ids_flat, stream_of, max(len(vocab), 1)


def iter_ngram_levels(
    ids_flat: np.ndarray, stream_of: np.ndarray, vocab_size: int, max_n: int
) -> Iterator[Tuple[int, np.ndarray, np.ndarray]]:
    """Yield ``(n, codes, valid)`` for n = 1..max_n.

    ``codes[i]`` identifies the n-gram starting at position ``i`` (dense ids, comparable only
    within a level); ``valid[i]`` marks windows that fit inside their stream.
    """
    n_tokens = len(ids_flat)
    codes = ids_flat.copy()
    for n in range(1, max_n + 1):
        if n_tokens < n:
            break
        if n > 1:
            valid = np.zeros(n_tokens, bool)
            valid[: n_tokens - (n - 1)] = stream_of[: n_tokens - (n - 1)] == stream_of[n - 1 :]
            raw = np.where(valid, codes * vocab_size, 0)
            raw[: n_tokens - (n - 1)] += np.where(
                valid[: n_tokens - (n - 1)], ids_flat[n - 1 :] + 1, 0
            )
            _, codes = np.unique(raw, return_inverse=True)
        else:
            valid = np.ones(n_tokens, bool)
        yield n, codes, valid
