"""Box-overlap kernels: IoU / GIoU / DIoU / CIoU (reference ``src/torchmetrics/functional/detection/{iou,giou,diou,ciou}.py``).

The reference delegates to torchvision's box ops; here the pairwise kernels are native jnp —
broadcasted corner min/max and area algebra, one fused XLA program per call, batch-friendly.
Formulas follow the published definitions (torchvision semantics, eps=1e-7 for the
distance/complete variants).
"""
from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
from jax import Array

_EPS = 1e-7


def box_convert(boxes: Array, in_fmt: str, out_fmt: str = "xyxy") -> Array:
    """Convert between ``xyxy``, ``xywh`` and ``cxcywh`` box formats."""
    boxes = jnp.asarray(boxes, jnp.float32)
    if in_fmt == out_fmt:
        return boxes
    if out_fmt != "xyxy":
        raise ValueError(f"Only conversion to 'xyxy' is supported, got {out_fmt}")
    if in_fmt == "xywh":
        x, y, w, h = jnp.split(boxes, 4, axis=-1)
        return jnp.concatenate([x, y, x + w, y + h], axis=-1)
    if in_fmt == "cxcywh":
        cx, cy, w, h = jnp.split(boxes, 4, axis=-1)
        return jnp.concatenate([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
    raise ValueError(f"Unknown box format {in_fmt}")


def box_area(boxes: Array) -> Array:
    boxes = jnp.asarray(boxes, jnp.float32)
    return (boxes[..., 2] - boxes[..., 0]) * (boxes[..., 3] - boxes[..., 1])


def _pairwise_inter_union(preds: Array, target: Array):
    lt = jnp.maximum(preds[..., :, None, :2], target[..., None, :, :2])
    rb = jnp.minimum(preds[..., :, None, 2:], target[..., None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = box_area(preds)[..., :, None] + box_area(target)[..., None, :] - inter
    return inter, union


def box_iou(preds: Array, target: Array) -> Array:
    """Pairwise IoU matrix ``(N, M)`` for ``xyxy`` boxes."""
    inter, union = _pairwise_inter_union(jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32))
    return inter / union


def generalized_box_iou(preds: Array, target: Array) -> Array:
    """Pairwise GIoU: IoU minus the non-covered fraction of the enclosing box."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    inter, union = _pairwise_inter_union(preds, target)
    iou = inter / union
    lt = jnp.minimum(preds[..., :, None, :2], target[..., None, :, :2])
    rb = jnp.maximum(preds[..., :, None, 2:], target[..., None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    enclose = wh[..., 0] * wh[..., 1]
    return iou - (enclose - union) / enclose


def _diou_terms(preds: Array, target: Array):
    """Shared DIoU geometry: (eps-stabilised iou, center-distance penalty)."""
    inter, union = _pairwise_inter_union(preds, target)
    iou = inter / (union + _EPS)
    lt = jnp.minimum(preds[..., :, None, :2], target[..., None, :, :2])
    rb = jnp.maximum(preds[..., :, None, 2:], target[..., None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    diag_sq = jnp.square(wh[..., 0]) + jnp.square(wh[..., 1]) + _EPS
    cp = (preds[..., :2] + preds[..., 2:]) / 2
    ct = (target[..., :2] + target[..., 2:]) / 2
    dist_sq = jnp.sum(jnp.square(cp[..., :, None, :] - ct[..., None, :, :]), axis=-1)
    return iou, dist_sq / diag_sq


def distance_box_iou(preds: Array, target: Array) -> Array:
    """Pairwise DIoU: IoU minus normalised center distance."""
    iou, penalty = _diou_terms(jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32))
    return iou - penalty


def complete_box_iou(preds: Array, target: Array) -> Array:
    """Pairwise CIoU: DIoU minus the aspect-ratio consistency term."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    iou, penalty = _diou_terms(preds, target)
    wp = preds[..., 2] - preds[..., 0]
    hp = preds[..., 3] - preds[..., 1]
    wt = target[..., 2] - target[..., 0]
    ht = target[..., 3] - target[..., 1]
    v = (4 / math.pi**2) * jnp.square(
        jnp.arctan(wt / ht)[..., None, :] - jnp.arctan(wp / hp)[..., :, None]
    )
    alpha = v / (1 - iou + v + _EPS)
    return iou - penalty - alpha * v


def _masked_mean_diag(iou: Array) -> Array:
    if iou.size == 0:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.mean(jnp.diagonal(iou))


def _make_functional(pairwise_fn, name: str):
    def fn(
        preds: Array,
        target: Array,
        iou_threshold: Optional[float] = None,
        replacement_val: float = 0,
        aggregate: bool = True,
    ) -> Array:
        iou = pairwise_fn(preds, target)
        if iou_threshold is not None:
            iou = jnp.where(iou < iou_threshold, replacement_val, iou)
        return _masked_mean_diag(iou) if aggregate else iou

    fn.__name__ = name
    fn.__doc__ = (
        f"{name} over xyxy box pairs (reference ``functional/detection/``): mean of the matrix"
        " diagonal, or the full matrix with ``aggregate=False``."
    )
    return fn


intersection_over_union = _make_functional(box_iou, "intersection_over_union")
generalized_intersection_over_union = _make_functional(generalized_box_iou, "generalized_intersection_over_union")
distance_intersection_over_union = _make_functional(distance_box_iou, "distance_intersection_over_union")
complete_intersection_over_union = _make_functional(complete_box_iou, "complete_intersection_over_union")
