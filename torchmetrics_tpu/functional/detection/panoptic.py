"""Panoptic Quality kernels (reference ``src/torchmetrics/functional/detection/_panoptic_quality_common.py``).

Boundary decision: segment-area bookkeeping is ragged (data-dependent segment counts), so the
per-sample matching runs as *vectorised numpy on the host* — one ``np.unique`` over fused
(pred, target) color codes replaces the reference's Python dict-of-areas loops
(``_panoptic_quality_common.py:50-63,313-394``) — while the per-category accumulator states stay
``psum``-able device arrays. Input preprocessing (stuff-instance reset, void remap) is pure
elementwise and stays in jnp.
"""
from __future__ import annotations

from typing import Collection, Dict, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array


def _parse_categories(things: Collection[int], stuffs: Collection[int]) -> Tuple[Set[int], Set[int]]:
    """Reference ``_panoptic_quality_common.py:65-93``."""
    things_parsed = set(int(t) for t in things)
    stuffs_parsed = set(int(s) for s in stuffs)
    if not things_parsed and not stuffs_parsed:
        raise ValueError("At least one of `things` and `stuffs` must be non-empty.")
    if things_parsed & stuffs_parsed:
        raise ValueError(
            f"Expected arguments `things` and `stuffs` to have distinct keys, but got {things} and {stuffs}"
        )
    return things_parsed, stuffs_parsed


def _get_void_color(things: Set[int], stuffs: Set[int]) -> Tuple[int, int]:
    """An unused (category, instance) color (reference ``:124-137``)."""
    return 1 + max([0, *things, *stuffs]), 0


def _get_category_id_to_continuous_id(things: Set[int], stuffs: Set[int]) -> Dict[int, int]:
    """Things first, then stuffs (reference ``:139-158``)."""
    mapping = {thing_id: idx for idx, thing_id in enumerate(things)}
    mapping.update({stuff_id: idx + len(things) for idx, stuff_id in enumerate(stuffs)})
    return mapping


def _validate_inputs(preds: Array, target: Array) -> None:
    """Reference ``:96-122``."""
    if preds.shape != target.shape:
        raise ValueError(
            f"Expected argument `preds` and `target` to have the same shape, but got {preds.shape} and {target.shape}"
        )
    if preds.ndim < 3:
        raise ValueError(
            "Expected argument `preds` to have at least one spatial dimension (B, *spatial_dims, 2),"
            f" got {preds.shape}"
        )
    if preds.shape[-1] != 2:
        raise ValueError(
            f"Expected argument `preds` to have exactly 2 channels in the last dimension, got {preds.shape}"
        )


def _preprocess_inputs(
    things: Set[int],
    stuffs: Set[int],
    inputs: Array,
    void_color: Tuple[int, int],
    allow_unknown_category: bool,
) -> Array:
    """Flatten spatial dims, zero stuff instance ids, remap unknowns to void (reference ``:175-212``)."""
    out = jnp.asarray(inputs, jnp.int32)
    out = out.reshape(out.shape[0], -1, 2)
    cats = out[:, :, 0]
    stuffs_arr = jnp.asarray(sorted(stuffs) or [-(2**31)], jnp.int32)
    things_arr = jnp.asarray(sorted(things) or [-(2**31)], jnp.int32)
    mask_stuffs = jnp.any(cats[..., None] == stuffs_arr, axis=-1)
    mask_things = jnp.any(cats[..., None] == things_arr, axis=-1)
    known = mask_things | mask_stuffs
    if not allow_unknown_category and not bool(jax.device_get(jnp.all(known))):
        raise ValueError(f"Unknown categories found: {np.unique(np.asarray(cats)[~np.asarray(known)])}")
    inst = jnp.where(mask_stuffs, 0, out[:, :, 1])
    cats = jnp.where(known, cats, void_color[0])
    inst = jnp.where(known, inst, void_color[1])
    return jnp.stack([cats, inst], axis=-1)


def _panoptic_quality_update_sample(
    pred: np.ndarray,
    target: np.ndarray,
    cat_id_to_continuous_id: Dict[int, int],
    void_color: Tuple[int, int],
    stuffs_modified_metric: Optional[Set[int]] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Vectorised single-sample stat scores (reference ``:313-394``).

    One ``np.unique`` over fused 64-bit (pred_cat, pred_inst, tgt_cat, tgt_inst) codes yields all
    pairwise intersection areas; segment areas and the >0.5-IoU matching are then pure array ops.
    """
    stuffs_modified_metric = stuffs_modified_metric or set()
    num_categories = len(cat_id_to_continuous_id)
    iou_sum = np.zeros(num_categories)
    tp = np.zeros(num_categories, np.int64)
    fp = np.zeros(num_categories, np.int64)
    fn = np.zeros(num_categories, np.int64)

    # fuse each (cat, inst) pair into one int64 code; raw ids can be arbitrarily large (COCO
    # RGB-encoded instances), so codes are first compacted to dense indices via np.unique —
    # the pair fusion below then uses a base bounded by the number of distinct colors, which
    # cannot overflow int64
    id_base = 1 + int(
        max(
            pred[:, 0].max(initial=0), pred[:, 1].max(initial=0),
            target[:, 0].max(initial=0), target[:, 1].max(initial=0),
            void_color[0], void_color[1],
        )
    )
    p_raw = pred[:, 0].astype(np.int64) * id_base + pred[:, 1]
    t_raw = target[:, 0].astype(np.int64) * id_base + target[:, 1]
    void_raw = void_color[0] * id_base + void_color[1]
    palette = np.unique(np.concatenate([p_raw, t_raw, [void_raw]]))
    base = len(palette)  # dense color ids in [0, base)
    cat_of_dense = palette // id_base  # original category per dense id
    p_code = np.searchsorted(palette, p_raw)
    t_code = np.searchsorted(palette, t_raw)
    void_code = int(np.searchsorted(palette, void_raw))

    p_colors, p_areas = np.unique(p_code, return_counts=True)
    t_colors, t_areas = np.unique(t_code, return_counts=True)
    pair_codes, pair_areas = np.unique(p_code * base + t_code, return_counts=True)
    pair_p = pair_codes // base
    pair_t = pair_codes % base

    p_area_of = dict(zip(p_colors.tolist(), p_areas.tolist()))
    t_area_of = dict(zip(t_colors.tolist(), t_areas.tolist()))
    # void overlap per segment
    p_void = {int(p): int(a) for p, t, a in zip(pair_p, pair_t, pair_areas) if t == void_code}
    t_void = {int(t): int(a) for p, t, a in zip(pair_p, pair_t, pair_areas) if p == void_code}

    pred_matched: set = set()
    target_matched: set = set()
    for p_c, t_c, inter in zip(pair_p.tolist(), pair_t.tolist(), pair_areas.tolist()):
        if t_c == void_code or p_c == void_code:
            continue
        p_cat, t_cat = int(cat_of_dense[p_c]), int(cat_of_dense[t_c])
        if p_cat != t_cat:
            continue
        union = (
            p_area_of[p_c] - p_void.get(p_c, 0) + t_area_of[t_c] - t_void.get(t_c, 0) - inter
        )
        iou = inter / union
        cid = cat_id_to_continuous_id[t_cat]
        if t_cat not in stuffs_modified_metric and iou > 0.5:
            pred_matched.add(p_c)
            target_matched.add(t_c)
            iou_sum[cid] += iou
            tp[cid] += 1
        elif t_cat in stuffs_modified_metric and iou > 0:
            iou_sum[cid] += iou

    for t_c, area in zip(t_colors.tolist(), t_areas.tolist()):
        if t_c == void_code or t_c in target_matched:
            continue
        cat = int(cat_of_dense[t_c])
        if cat in stuffs_modified_metric:
            continue
        if t_void.get(t_c, 0) / area <= 0.5:
            fn[cat_id_to_continuous_id[cat]] += 1

    for p_c, area in zip(p_colors.tolist(), p_areas.tolist()):
        if p_c == void_code or p_c in pred_matched:
            continue
        cat = int(cat_of_dense[p_c])
        if cat in stuffs_modified_metric:
            continue
        if p_void.get(p_c, 0) / area <= 0.5:
            fp[cat_id_to_continuous_id[cat]] += 1

    # modified-PQ stuffs: TP slot counts target segments (reference :383-387)
    for t_c in t_colors.tolist():
        if t_c == void_code:
            continue
        cat = int(cat_of_dense[t_c])
        if cat in stuffs_modified_metric:
            tp[cat_id_to_continuous_id[cat]] += 1

    return iou_sum, tp, fp, fn


def _panoptic_quality_update(
    flatten_preds: Array,
    flatten_target: Array,
    cat_id_to_continuous_id: Dict[int, int],
    void_color: Tuple[int, int],
    modified_metric_stuffs: Optional[Set[int]] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Batch stat scores; per-sample matching (segments never match across frames)."""
    preds_np = np.asarray(flatten_preds)
    target_np = np.asarray(flatten_target)
    num_categories = len(cat_id_to_continuous_id)
    iou_sum = np.zeros(num_categories)
    tp = np.zeros(num_categories, np.int64)
    fp = np.zeros(num_categories, np.int64)
    fn = np.zeros(num_categories, np.int64)
    for p, t in zip(preds_np, target_np):
        r = _panoptic_quality_update_sample(
            p, t, cat_id_to_continuous_id, void_color, stuffs_modified_metric=modified_metric_stuffs
        )
        iou_sum += r[0]
        tp += r[1]
        fp += r[2]
        fn += r[3]
    return (
        jnp.asarray(iou_sum, jnp.float32),
        jnp.asarray(tp, jnp.int32),
        jnp.asarray(fp, jnp.int32),
        jnp.asarray(fn, jnp.int32),
    )


def _panoptic_quality_compute(iou_sum: Array, tp: Array, fp: Array, fn: Array) -> Array:
    """PQ = mean over observed categories of iou_sum / (TP + FP/2 + FN/2) (reference ``:448-470``)."""
    denominator = jnp.asarray(tp, jnp.float32) + 0.5 * fp + 0.5 * fn
    pq = jnp.where(denominator > 0, iou_sum / jnp.where(denominator > 0, denominator, 1.0), 0.0)
    observed = denominator > 0
    return jnp.sum(pq * observed) / jnp.sum(observed)


def panoptic_quality(
    preds: Array,
    target: Array,
    things: Collection[int],
    stuffs: Collection[int],
    allow_unknown_preds_category: bool = False,
) -> Array:
    """PQ (reference ``functional/detection/panoptic_qualities.py:25``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import panoptic_quality
        >>> preds = np.array([[[6, 0], [0, 0], [6, 0], [7, 0]]])
        >>> target = np.array([[[6, 0], [0, 1], [6, 0], [7, 0]]])
        >>> print(f"{float(panoptic_quality(preds, target, things={6, 7}, stuffs={0})):.4f}")
        1.0000
    """
    things_p, stuffs_p = _parse_categories(things, stuffs)
    _validate_inputs(jnp.asarray(preds), jnp.asarray(target))
    void_color = _get_void_color(things_p, stuffs_p)
    cat_map = _get_category_id_to_continuous_id(things_p, stuffs_p)
    fp_preds = _preprocess_inputs(things_p, stuffs_p, preds, void_color, allow_unknown_preds_category)
    fp_target = _preprocess_inputs(things_p, stuffs_p, target, void_color, True)
    iou_sum, tp, fps, fns = _panoptic_quality_update(fp_preds, fp_target, cat_map, void_color)
    return _panoptic_quality_compute(iou_sum, tp, fps, fns)


def modified_panoptic_quality(
    preds: Array,
    target: Array,
    things: Collection[int],
    stuffs: Collection[int],
    allow_unknown_preds_category: bool = False,
) -> Array:
    """Modified PQ: stuff classes scored by IoU sum over target segments (reference ``panoptic_qualities.py:102``)."""
    things_p, stuffs_p = _parse_categories(things, stuffs)
    _validate_inputs(jnp.asarray(preds), jnp.asarray(target))
    void_color = _get_void_color(things_p, stuffs_p)
    cat_map = _get_category_id_to_continuous_id(things_p, stuffs_p)
    fp_preds = _preprocess_inputs(things_p, stuffs_p, preds, void_color, allow_unknown_preds_category)
    fp_target = _preprocess_inputs(things_p, stuffs_p, target, void_color, True)
    iou_sum, tp, fps, fns = _panoptic_quality_update(
        fp_preds, fp_target, cat_map, void_color, modified_metric_stuffs=stuffs_p
    )
    return _panoptic_quality_compute(iou_sum, tp, fps, fns)
