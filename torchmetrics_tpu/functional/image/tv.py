"""Total-variation kernels (reference ``src/torchmetrics/functional/image/tv.py``)."""
from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array


def _total_variation_update(img: Array) -> Tuple[Array, int]:
    """Reference ``tv.py:21-32``."""
    if img.ndim != 4:
        raise RuntimeError(f"Input `img` must be an 4D tensor, but got {img.shape}")
    img = jnp.asarray(img, jnp.float32)
    diff1 = img[..., 1:, :] - img[..., :-1, :]
    diff2 = img[..., :, 1:] - img[..., :, :-1]
    score = jnp.sum(jnp.abs(diff1), axis=(1, 2, 3)) + jnp.sum(jnp.abs(diff2), axis=(1, 2, 3))
    return score, img.shape[0]


def _total_variation_compute(
    score: Array, num_elements: Union[int, Array], reduction: Optional[str]
) -> Array:
    """Reference ``tv.py:35-45``."""
    if reduction == "mean":
        return jnp.sum(score) / num_elements
    if reduction == "sum":
        return jnp.sum(score)
    if reduction is None or reduction == "none":
        return score
    raise ValueError("Argument `reduction` must be either 'sum', 'mean', 'none' or None")


def total_variation(img: Array, reduction: Optional[str] = "sum") -> Array:
    """Total variation (reference ``tv.py:48-87``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import total_variation
        >>> x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        >>> print(f"{float(total_variation(x)):.1f}")
        60.0
    """
    score, num_elements = _total_variation_update(jnp.asarray(img))
    return _total_variation_compute(score, num_elements, reduction)
