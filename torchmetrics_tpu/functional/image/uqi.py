"""Universal Image Quality Index kernels (reference ``src/torchmetrics/functional/image/uqi.py``).

Same one-conv-for-five-moments layout as SSIM (see ``ssim.py`` in this package).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.image.helpers import (
    _depthwise_conv2d,
    _gaussian_kernel_2d,
    _reflect_pad_2d,
    reduce,
)
from torchmetrics_tpu.utils.checks import _check_same_shape


def _uqi_check_inputs(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Reference ``uqi.py:25-44``."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(
            "Expected `preds` and `target` to have BxCxHxW shape."
            f" Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


def _uqi_map(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
) -> Array:
    """Full cropped per-pixel UQI map (core of reference ``uqi.py:47-117``)."""
    channel = preds.shape[1]
    kernel = _gaussian_kernel_2d(channel, kernel_size, sigma)
    pad_h = (kernel_size[0] - 1) // 2
    pad_w = (kernel_size[1] - 1) // 2
    preds = _reflect_pad_2d(preds, pad_h, pad_w)
    target = _reflect_pad_2d(target, pad_h, pad_w)

    stacked = jnp.concatenate(
        (preds, target, preds * preds, target * target, preds * target), axis=0
    )
    mu_p, mu_t, e_pp, e_tt, e_pt = jnp.split(_depthwise_conv2d(stacked, kernel), 5, axis=0)

    mu_pred_sq = mu_p * mu_p
    mu_target_sq = mu_t * mu_t
    mu_pred_target = mu_p * mu_t
    sigma_pred_sq = e_pp - mu_pred_sq
    sigma_target_sq = e_tt - mu_target_sq
    sigma_pred_target = e_pt - mu_pred_target

    upper = 2 * sigma_pred_target
    lower = sigma_pred_sq + sigma_target_sq
    eps = jnp.finfo(jnp.float32).eps
    uqi_idx = ((2 * mu_pred_target) * upper) / ((mu_pred_sq + mu_target_sq) * lower + eps)
    return uqi_idx[..., pad_h:-pad_h, pad_w:-pad_w]


def _uqi_compute(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """Reference ``uqi.py:47-117``."""
    if len(kernel_size) != 2 or len(sigma) != 2:
        raise ValueError(
            "Expected `kernel_size` and `sigma` to have the length of two."
            f" Got kernel_size: {len(kernel_size)} and sigma: {len(sigma)}."
        )
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"`kernel_size` must have odd positive number. Got {kernel_size}.")
    if any(y <= 0 for y in sigma):
        raise ValueError(f"`sigma` must have positive number. Got {sigma}.")
    return reduce(_uqi_map(preds, target, kernel_size, sigma), reduction)


def universal_image_quality_index(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """UQI (reference ``uqi.py:120-177``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import universal_image_quality_index
        >>> rng = np.random.RandomState(0)
        >>> preds = rng.rand(1, 1, 16, 16).astype(np.float32)
        >>> print(f"{float(universal_image_quality_index(preds, preds)):.4f}")
        1.0000
    """
    preds, target = _uqi_check_inputs(preds, target)
    return _uqi_compute(preds, target, kernel_size, sigma, reduction)
