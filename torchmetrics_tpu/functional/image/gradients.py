"""Image-gradient kernels (reference ``src/torchmetrics/functional/image/gradients.py``)."""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import Array


def image_gradients(img: Array) -> Tuple[Array, Array]:
    """Finite-difference (dy, dx), zero-padded at the far edge (reference ``gradients.py:47-81``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import image_gradients
        >>> img = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        >>> dy, dx = image_gradients(img)
        >>> np.asarray(dy)[0, 0].tolist()
        [[4.0, 4.0, 4.0, 4.0], [4.0, 4.0, 4.0, 4.0], [4.0, 4.0, 4.0, 4.0], [0.0, 0.0, 0.0, 0.0]]
    """
    img = jnp.asarray(img)
    if img.ndim != 4:
        raise RuntimeError(f"The `img` expects a 4D tensor but got {img.ndim}D tensor")
    dy = img[..., 1:, :] - img[..., :-1, :]
    dx = img[..., :, 1:] - img[..., :, :-1]
    dy = jnp.pad(dy, ((0, 0), (0, 0), (0, 1), (0, 0)))
    dx = jnp.pad(dx, ((0, 0), (0, 0), (0, 0), (0, 1)))
    return dy, dx
