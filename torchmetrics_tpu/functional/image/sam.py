"""Spectral Angle Mapper kernels (reference ``src/torchmetrics/functional/image/sam.py``)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.image.helpers import reduce
from torchmetrics_tpu.utils.checks import _check_same_shape


def _sam_check_inputs(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Reference ``sam.py:24-48``."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(
            "Expected `preds` and `target` to have BxCxHxW shape."
            f" Got preds: {preds.shape} and target: {target.shape}."
        )
    if preds.shape[1] <= 1:
        raise ValueError(
            "Expected channel dimension of `preds` and `target` to be larger than 1."
            f" Got preds: {preds.shape[1]} and target: {target.shape[1]}."
        )
    return preds, target


def _sam_compute(
    preds: Array, target: Array, reduction: Optional[str] = "elementwise_mean"
) -> Array:
    """Per-pixel spectral angle over the channel axis (reference ``sam.py:51-81``)."""
    dot_product = jnp.sum(preds * target, axis=1)
    preds_norm = jnp.linalg.norm(preds, axis=1)
    target_norm = jnp.linalg.norm(target, axis=1)
    sam_score = jnp.arccos(jnp.clip(dot_product / (preds_norm * target_norm), -1, 1))
    return reduce(sam_score, reduction)


def spectral_angle_mapper(
    preds: Array, target: Array, reduction: Optional[str] = "elementwise_mean"
) -> Array:
    """SAM (reference ``sam.py:84-125``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import spectral_angle_mapper
        >>> rng = np.random.RandomState(0)
        >>> preds = rng.rand(1, 3, 8, 8).astype(np.float32)
        >>> target = rng.rand(1, 3, 8, 8).astype(np.float32)
        >>> print(f"{float(spectral_angle_mapper(preds, target)):.4f}")
        0.6032
    """
    preds, target = _sam_check_inputs(preds, target)
    return _sam_compute(preds, target, reduction)
