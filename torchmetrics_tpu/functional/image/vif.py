"""Pixel-domain Visual Information Fidelity (reference ``src/torchmetrics/functional/image/vif.py``).

TPU redesign: the reference loops image channels in Python (``vif.py:113``); here all channels
are folded into the batch axis so each of the four static scales is ONE conv program over
``(N*C, 1, H, W)`` — the scale pyramid itself stays a static unrolled loop (shapes halve).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.image.helpers import _depthwise_conv2d


def _vif_filter(win_size: int, sigma: float) -> Array:
    """Non-separable normalised 2D gaussian ``(1, 1, k, k)`` (reference ``vif.py:21-31``)."""
    coords = jnp.arange(win_size, dtype=jnp.float32) - (win_size - 1) / 2
    g = jnp.square(coords)
    g = jnp.exp(-(g[None, :] + g[:, None]) / (2.0 * sigma**2))
    g = g / jnp.sum(g)
    return g[None, None]


def _vif_per_image_channel(preds: Array, target: Array, sigma_n_sq: float) -> Array:
    """VIF ratio per (image, channel) slice; input ``(M, 1, H, W)`` (reference ``vif.py:33-85``)."""
    eps = jnp.asarray(1e-10, jnp.float32)
    preds_vif = jnp.zeros((preds.shape[0],), jnp.float32)
    target_vif = jnp.zeros((preds.shape[0],), jnp.float32)
    for scale in range(4):
        n = int(2.0 ** (4 - scale) + 1)
        kernel = _vif_filter(n, n / 5)
        if scale > 0:
            target = _depthwise_conv2d(target, kernel)[:, :, ::2, ::2]
            preds = _depthwise_conv2d(preds, kernel)[:, :, ::2, ::2]

        mu_target = _depthwise_conv2d(target, kernel)
        mu_preds = _depthwise_conv2d(preds, kernel)
        mu_target_sq = jnp.square(mu_target)
        mu_preds_sq = jnp.square(mu_preds)
        mu_target_preds = mu_target * mu_preds

        sigma_target_sq = jnp.clip(_depthwise_conv2d(jnp.square(target), kernel) - mu_target_sq, 0.0)
        sigma_preds_sq = jnp.clip(_depthwise_conv2d(jnp.square(preds), kernel) - mu_preds_sq, 0.0)
        sigma_target_preds = _depthwise_conv2d(target * preds, kernel) - mu_target_preds

        g = sigma_target_preds / (sigma_target_sq + eps)
        sigma_v_sq = sigma_preds_sq - g * sigma_target_preds

        mask = sigma_target_sq < eps
        g = jnp.where(mask, 0.0, g)
        sigma_v_sq = jnp.where(mask, sigma_preds_sq, sigma_v_sq)
        sigma_target_sq = jnp.where(mask, 0.0, sigma_target_sq)

        mask = sigma_preds_sq < eps
        g = jnp.where(mask, 0.0, g)
        sigma_v_sq = jnp.where(mask, 0.0, sigma_v_sq)

        mask = g < 0
        sigma_v_sq = jnp.where(mask, sigma_preds_sq, sigma_v_sq)
        g = jnp.where(mask, 0.0, g)
        sigma_v_sq = jnp.clip(sigma_v_sq, eps)

        preds_vif_scale = jnp.log10(1.0 + jnp.square(g) * sigma_target_sq / (sigma_v_sq + sigma_n_sq))
        preds_vif = preds_vif + jnp.sum(preds_vif_scale, axis=(1, 2, 3))
        target_vif = target_vif + jnp.sum(jnp.log10(1.0 + sigma_target_sq / sigma_n_sq), axis=(1, 2, 3))
    return preds_vif / target_vif


def visual_information_fidelity(preds: Array, target: Array, sigma_n_sq: float = 2.0) -> Array:
    """VIF-p (reference ``vif.py:88-114``)."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    if preds.shape[-1] < 41 or preds.shape[-2] < 41:
        raise ValueError(
            f"Invalid size of preds. Expected at least 41x41, but got {preds.shape[-1]}x{preds.shape[-2]}!"
        )
    if target.shape[-1] < 41 or target.shape[-2] < 41:
        raise ValueError(
            f"Invalid size of target. Expected at least 41x41, but got {target.shape[-1]}x{target.shape[-2]}!"
        )
    n, c, h, w = preds.shape
    # channels → batch: (N, C, H, W) -> (C*N, 1, H, W), ordered channel-major to match the
    # reference's per-channel concatenation before the mean
    p = jnp.moveaxis(preds, 1, 0).reshape(c * n, 1, h, w)
    t = jnp.moveaxis(target, 1, 0).reshape(c * n, 1, h, w)
    return jnp.mean(_vif_per_image_channel(p, t, sigma_n_sq))
