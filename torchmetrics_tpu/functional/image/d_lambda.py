"""Spectral Distortion Index (D-lambda) kernels (reference ``src/torchmetrics/functional/image/d_lambda.py``).

TPU redesign: the reference computes the inter-band UQI matrices with a Python double loop of
separate conv calls (``d_lambda.py:77-98``); here every unordered band pair of BOTH inputs is
folded into one batch, so the whole matrix is a single five-moment depthwise-conv program.
"""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.image.helpers import reduce
from torchmetrics_tpu.functional.image.uqi import _uqi_map


def _spectral_distortion_index_check_inputs(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Reference ``d_lambda.py:25-47``."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    if preds.ndim != 4 or target.ndim != 4:
        raise ValueError(
            f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape} and target: {target.shape}."
        )
    if preds.shape[:2] != target.shape[:2]:
        raise ValueError(
            "Expected `preds` and `target` to have same batch and channel sizes."
            f"Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


def _pairwise_band_uqi(x: Array, pairs: list) -> Array:
    """Mean UQI between band pairs of ``x``: one stacked single-channel conv for all pairs."""
    b, _, h, w = x.shape
    left = jnp.concatenate([x[:, k : k + 1] for k, _ in pairs], axis=0)
    right = jnp.concatenate([x[:, r : r + 1] for _, r in pairs], axis=0)
    uqi_map = _uqi_map(left, right)  # (P*B, 1, H', W')
    per_pair = uqi_map.reshape(len(pairs), -1)
    return jnp.mean(per_pair, axis=1)


def _spectral_distortion_index_compute(
    preds: Array, target: Array, p: int = 1, reduction: str = "elementwise_mean"
) -> Array:
    """Reference ``d_lambda.py:50-111``."""
    length = preds.shape[1]
    if length == 1:
        # single band: both matrices are empty → score 0 (reference special case, d_lambda.py:105)
        return reduce(jnp.asarray(0.0, jnp.float32), reduction)
    pairs = [(k, r) for k in range(length) for r in range(k + 1, length)]
    m1_vals = _pairwise_band_uqi(target, pairs)
    m2_vals = _pairwise_band_uqi(preds, pairs)
    diff = jnp.abs(m1_vals - m2_vals) ** p
    # each unordered pair appears twice in the symmetric matrices (d_lambda.py:99-100)
    output = (2 * jnp.sum(diff) / (length * (length - 1))) ** (1.0 / p)
    return reduce(output, reduction)


def spectral_distortion_index(
    preds: Array, target: Array, p: int = 1, reduction: str = "elementwise_mean"
) -> Array:
    """D-lambda (reference ``d_lambda.py:114-160``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import spectral_distortion_index
        >>> rng = np.random.RandomState(42)
        >>> preds = rng.rand(2, 3, 32, 32).astype(np.float32)
        >>> target = rng.rand(2, 3, 32, 32).astype(np.float32)
        >>> print(f"{float(spectral_distortion_index(preds, target)):.4f}")
        0.0404
    """
    if not isinstance(p, int) or p <= 0:
        raise ValueError(f"`p` must be a positive integer. Got p: {p}.")
    preds, target = _spectral_distortion_index_check_inputs(preds, target)
    return _spectral_distortion_index_compute(preds, target, p, reduction)
