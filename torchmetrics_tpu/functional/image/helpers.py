"""Shared image-kernel helpers (reference ``src/torchmetrics/functional/image/helper.py``).

TPU-first design notes: every filter here is a *depthwise* convolution expressed through
``lax.conv_general_dilated`` with ``feature_group_count=channels`` so XLA lowers it onto the MXU
as one batched conv per call (the reference loops channels in Python for the uniform filter,
``helper.py:118-133``). All shapes are static; everything is jit-safe.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
from jax import Array, lax


def _gaussian_1d(kernel_size: int, sigma: float, dtype=jnp.float32) -> Array:
    """Normalised 1D gaussian window (reference ``helper.py:8-25``)."""
    dist = jnp.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, 1.0, dtype=dtype)
    gauss = jnp.exp(-jnp.square(dist / sigma) / 2)
    return gauss / jnp.sum(gauss)


def _gaussian_kernel_2d(
    channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype=jnp.float32
) -> Array:
    """Separable 2D gaussian as a depthwise-conv weight ``(C, 1, kh, kw)`` (reference ``helper.py:27-58``)."""
    kx = _gaussian_1d(kernel_size[0], sigma[0], dtype)
    ky = _gaussian_1d(kernel_size[1], sigma[1], dtype)
    kernel = jnp.outer(kx, ky)
    return jnp.broadcast_to(kernel, (channel, 1, *kernel.shape))


def _gaussian_kernel_3d(
    channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype=jnp.float32
) -> Array:
    """3D gaussian depthwise-conv weight ``(C, 1, kh, kw, kd)`` (reference ``helper.py:137-157``)."""
    kx = _gaussian_1d(kernel_size[0], sigma[0], dtype)
    ky = _gaussian_1d(kernel_size[1], sigma[1], dtype)
    kz = _gaussian_1d(kernel_size[2], sigma[2], dtype)
    kernel = jnp.einsum("i,j,k->ijk", kx, ky, kz)
    return jnp.broadcast_to(kernel, (channel, 1, *kernel.shape))


def _depthwise_conv2d(x: Array, kernel: Array) -> Array:
    """Valid-mode depthwise conv: ``x`` is ``(N, C, H, W)``, ``kernel`` is ``(C, 1, kh, kw)``."""
    channels = x.shape[1]
    return lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=channels,
    )


def _depthwise_conv3d(x: Array, kernel: Array) -> Array:
    """Valid-mode depthwise conv: ``x`` is ``(N, C, D, H, W)``-like, ``kernel`` ``(C, 1, k1, k2, k3)``."""
    channels = x.shape[1]
    return lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1, 1),
        padding="VALID",
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=channels,
    )


def _reflect_pad_2d(x: Array, pad_h: int, pad_w: int) -> Array:
    """Edge-excluding reflection pad of the two trailing dims (torch ``F.pad(mode='reflect')``)."""
    return jnp.pad(x, ((0, 0), (0, 0), (pad_h, pad_h), (pad_w, pad_w)), mode="reflect")


def _reflect_pad_3d(x: Array, pad_d: int, pad_h: int, pad_w: int) -> Array:
    return jnp.pad(
        x, ((0, 0), (0, 0), (pad_d, pad_d), (pad_h, pad_h), (pad_w, pad_w)), mode="reflect"
    )


def _symmetric_pad_2d(x: Array, pad: int, outer_pad: int) -> Array:
    """Edge-including reflection pad, asymmetric on the right (reference ``helper.py:80-113``).

    The reference pads ``pad`` rows/cols on the left and ``pad + outer_pad - 1`` on the right of
    each spatial dim (scipy ``uniform_filter`` alignment for even windows); numpy's
    ``mode='symmetric'`` has exactly the edge-including semantics.
    """
    right = pad + outer_pad - 1
    return jnp.pad(x, ((0, 0), (0, 0), (pad, right), (pad, right)), mode="symmetric")


def _uniform_filter(x: Array, window_size: int) -> Array:
    """Sliding-window mean matching scipy's ``uniform_filter`` (reference ``helper.py:116-133``)."""
    x = _symmetric_pad_2d(x, window_size // 2, window_size % 2)
    channels = x.shape[1]
    kernel = jnp.full((channels, 1, window_size, window_size), 1.0 / window_size**2, x.dtype)
    return _depthwise_conv2d(x, kernel)


def _avg_pool(x: Array, spatial_dims: int) -> Array:
    """2x downsample by mean (torch ``avg_pool{2,3}d(kernel=2, stride=2)``, floor semantics)."""
    window = (1, 1) + (2,) * spatial_dims
    summed = lax.reduce_window(x, 0.0, lax.add, window, window, "VALID")
    return summed / (2**spatial_dims)


def reduce(x: Array, reduction: str = "elementwise_mean") -> Array:
    """Reference ``utilities/distributed.py:22-43``: elementwise_mean / sum / none."""
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction is None or reduction == "none":
        return x
    raise ValueError("Expected reduction to be one of `elementwise_mean`, `sum`, `none`, None")
