"""PSNR-B kernels (reference ``src/torchmetrics/functional/image/psnrb.py``).

The block/off-block column and row index sets are static functions of the image shape, so they
are built with numpy at trace time and the whole blocking-effect factor compiles to gathered
squared differences — no data-dependent shapes.
"""
from __future__ import annotations

import math
from typing import Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array


def _compute_bef(x: Array, block_size: int = 8) -> Array:
    """Blocking-effect factor (reference ``psnrb.py:33-78``)."""
    _, channels, height, width = x.shape
    if channels > 1:
        raise ValueError(f"`psnrb` metric expects grayscale images, but got images with {channels} channels.")

    h = np.arange(width - 1)
    h_b = np.arange(block_size - 1, width - 1, block_size)
    h_bc = np.setdiff1d(h, h_b)

    v = np.arange(height - 1)
    v_b = np.arange(block_size - 1, height - 1, block_size)
    v_bc = np.setdiff1d(v, v_b)

    d_b = jnp.sum(jnp.square(x[:, :, :, h_b] - x[:, :, :, h_b + 1]))
    d_bc = jnp.sum(jnp.square(x[:, :, :, h_bc] - x[:, :, :, h_bc + 1]))
    d_b += jnp.sum(jnp.square(x[:, :, v_b, :] - x[:, :, v_b + 1, :]))
    d_bc += jnp.sum(jnp.square(x[:, :, v_bc, :] - x[:, :, v_bc + 1, :]))

    n_hb = height * (width / block_size) - 1
    n_hbc = (height * (width - 1)) - n_hb
    n_vb = width * (height / block_size) - 1
    n_vbc = (width * (height - 1)) - n_vb
    d_b = d_b / (n_hb + n_vb)
    d_bc = d_bc / (n_hbc + n_vbc)
    t_on = math.log2(block_size) / math.log2(min(height, width))
    t = jnp.where(d_b > d_bc, t_on, 0.0)
    return t * (d_b - d_bc)


def _psnrb_update(preds: Array, target: Array, block_size: int = 8) -> Tuple[Array, Array, Array]:
    """Reference ``psnrb.py:89-101``."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff)
    num_obs = jnp.asarray(target.size, jnp.float32)
    bef = _compute_bef(preds, block_size=block_size)
    return sum_squared_error, bef, num_obs


def _psnrb_compute(
    sum_squared_error: Array, bef: Array, num_obs: Array, data_range: Array
) -> Array:
    """Reference ``psnrb.py:66-86``."""
    mse_b = sum_squared_error / num_obs + bef
    return jnp.where(
        data_range > 2,
        10 * jnp.log10(jnp.square(data_range) / mse_b),
        10 * jnp.log10(1.0 / mse_b),
    )


def peak_signal_noise_ratio_with_blocked_effect(
    preds: Array, target: Array, block_size: int = 8
) -> Array:
    """PSNR-B (reference ``psnrb.py:104-136``)."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    data_range = jnp.max(target) - jnp.min(target)
    sum_squared_error, bef, num_obs = _psnrb_update(preds, target, block_size=block_size)
    return _psnrb_compute(sum_squared_error, bef, num_obs, data_range)
