"""ERGAS kernels (reference ``src/torchmetrics/functional/image/ergas.py``)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.image.helpers import reduce
from torchmetrics_tpu.utils.checks import _check_same_shape


def _ergas_check_inputs(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Reference ``ergas.py:24-43``."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(
            "Expected `preds` and `target` to have BxCxHxW shape."
            f" Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


def _ergas_compute(
    preds: Array,
    target: Array,
    ratio: float = 4,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """Per-image ERGAS over per-band RMSE (reference ``ergas.py:46-83``)."""
    b, c, h, w = preds.shape
    preds = preds.reshape(b, c, h * w)
    target = target.reshape(b, c, h * w)
    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=2)
    rmse_per_band = jnp.sqrt(sum_squared_error / (h * w))
    mean_target = jnp.mean(target, axis=2)
    ergas_score = 100 * ratio * jnp.sqrt(jnp.sum(jnp.square(rmse_per_band / mean_target), axis=1) / c)
    return reduce(ergas_score, reduction)


def error_relative_global_dimensionless_synthesis(
    preds: Array,
    target: Array,
    ratio: float = 4,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """ERGAS (reference ``ergas.py:86-131``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import error_relative_global_dimensionless_synthesis
        >>> rng = np.random.RandomState(42)
        >>> preds = rng.rand(2, 3, 32, 32).astype(np.float32)
        >>> target = rng.rand(2, 3, 32, 32).astype(np.float32)
        >>> print(f"{float(error_relative_global_dimensionless_synthesis(preds, target)):.1f}")
        331.2
    """
    preds, target = _ergas_check_inputs(preds, target)
    return _ergas_compute(preds, target, ratio, reduction)
