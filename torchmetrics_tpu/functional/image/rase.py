"""RASE kernels (reference ``src/torchmetrics/functional/image/rase.py``)."""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.image.helpers import _uniform_filter
from torchmetrics_tpu.functional.image.rmse_sw import _rmse_sw_compute, _rmse_sw_update


def _rase_update(
    preds: Array,
    target: Array,
    window_size: int,
    rmse_map: Array,
    target_sum: Array,
    total_images: Array,
) -> Tuple[Array, Array, Array]:
    """Reference ``rase.py:24-46``.

    The extra division of the local target mean by ``window_size**2`` replicates the reference
    exactly (``rase.py:45`` — the uniform filter already normalises, so RASE values carry this
    double scaling; parity over plausibility).
    """
    _, rmse_map, total_images = _rmse_sw_update(
        preds, target, window_size, rmse_val_sum=None, rmse_map=rmse_map, total_images=total_images
    )
    target = jnp.asarray(target, jnp.float32)
    target_sum = target_sum + jnp.sum(_uniform_filter(target, window_size) / window_size**2, axis=0)
    return rmse_map, target_sum, total_images


def _rase_compute(
    rmse_map: Array, target_sum: Array, total_images: Array, window_size: int
) -> Array:
    """Reference ``rase.py:49-68``."""
    _, rmse_map = _rmse_sw_compute(rmse_val_sum=None, rmse_map=rmse_map, total_images=total_images)
    target_mean = target_sum / total_images
    target_mean = jnp.mean(target_mean, axis=0)  # mean over channels
    rase_map = 100 / target_mean * jnp.sqrt(jnp.mean(jnp.square(rmse_map), axis=0))
    crop_slide = round(window_size / 2)
    return jnp.mean(rase_map[crop_slide:-crop_slide, crop_slide:-crop_slide])


def relative_average_spectral_error(preds: Array, target: Array, window_size: int = 8) -> Array:
    """RASE (reference ``rase.py:71-103``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import relative_average_spectral_error
        >>> rng = np.random.RandomState(42)
        >>> preds = rng.rand(2, 3, 32, 32).astype(np.float32)
        >>> target = rng.rand(2, 3, 32, 32).astype(np.float32)
        >>> print(f"{float(relative_average_spectral_error(preds, target)):.1f}")
        5278.6
    """
    if not isinstance(window_size, int) or window_size < 1:
        raise ValueError('Argument `window_size` must be a positive integer.')
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    img_shape = target.shape[1:]
    rmse_map = jnp.zeros(img_shape, jnp.float32)
    target_sum = jnp.zeros(img_shape, jnp.float32)
    total_images = jnp.asarray(0.0, jnp.float32)
    rmse_map, target_sum, total_images = _rase_update(
        preds, target, window_size, rmse_map, target_sum, total_images
    )
    return _rase_compute(rmse_map, target_sum, total_images, window_size)
