"""Sliding-window RMSE kernels (reference ``src/torchmetrics/functional/image/rmse_sw.py``)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.image.helpers import _uniform_filter
from torchmetrics_tpu.utils.checks import _check_same_shape


def _rmse_sw_checks(preds: Array, target: Array, window_size: int) -> None:
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. But got {preds.shape}.")
    if round(window_size / 2) >= target.shape[2] or round(window_size / 2) >= target.shape[3]:
        raise ValueError(
            f"Parameter `round(window_size / 2)` is expected to be smaller than"
            f" {min(target.shape[2], target.shape[3])} but got {round(window_size / 2)}."
        )


def _rmse_sw_update(
    preds: Array,
    target: Array,
    window_size: int,
    rmse_val_sum: Optional[Array],
    rmse_map: Optional[Array],
    total_images: Optional[Array],
) -> Tuple[Optional[Array], Array, Array]:
    """Accumulate the per-window RMSE map over a batch (reference ``rmse_sw.py:24-89``).

    ``crop_slide`` uses Python's banker's rounding of ``window_size / 2`` to match the
    reference/scipy alignment exactly.
    """
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    _rmse_sw_checks(preds, target, window_size)

    batch = jnp.asarray(target.shape[0], jnp.float32)
    total_images = batch if total_images is None else total_images + batch
    error = jnp.square(target - preds)
    _rmse_map = jnp.sqrt(_uniform_filter(error, window_size))
    crop_slide = round(window_size / 2)

    batch_val = jnp.mean(
        jnp.sum(_rmse_map[:, :, crop_slide:-crop_slide, crop_slide:-crop_slide], axis=0)
    )
    if rmse_val_sum is not None:
        rmse_val_sum = rmse_val_sum + batch_val
    else:
        rmse_val_sum = batch_val

    batch_map = jnp.sum(_rmse_map, axis=0)
    rmse_map = batch_map if rmse_map is None else rmse_map + batch_map
    return rmse_val_sum, rmse_map, total_images


def _rmse_sw_compute(
    rmse_val_sum: Optional[Array], rmse_map: Array, total_images: Array
) -> Tuple[Optional[Array], Array]:
    """Reference ``rmse_sw.py:92-109``."""
    rmse = rmse_val_sum / total_images if rmse_val_sum is not None else None
    return rmse, rmse_map / total_images


def root_mean_squared_error_using_sliding_window(
    preds: Array, target: Array, window_size: int = 8, return_rmse_map: bool = False
):
    """Sliding-window RMSE (reference ``rmse_sw.py:112-151``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import root_mean_squared_error_using_sliding_window
        >>> rng = np.random.RandomState(22)
        >>> preds = rng.rand(1, 1, 16, 16).astype(np.float32)
        >>> target = rng.rand(1, 1, 16, 16).astype(np.float32)
        >>> print(f"{float(root_mean_squared_error_using_sliding_window(preds, target, window_size=8)):.4f}")
        0.4143
    """
    if not isinstance(window_size, int) or window_size < 1:
        raise ValueError('Argument `window_size` must be a positive integer.')
    rmse_val_sum, rmse_map, total_images = _rmse_sw_update(
        preds, target, window_size, rmse_val_sum=None, rmse_map=None, total_images=None
    )
    rmse, rmse_map = _rmse_sw_compute(rmse_val_sum, rmse_map, total_images)
    return (rmse, rmse_map) if return_rmse_map else rmse
