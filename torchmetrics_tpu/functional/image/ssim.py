"""SSIM / MS-SSIM kernels (reference ``src/torchmetrics/functional/image/ssim.py``).

TPU shape: the five filtered moments (mu_p, mu_t, E[p^2], E[t^2], E[pt]) are produced by ONE
depthwise conv over a ``(5·B, C, ...)`` stack — a single MXU-friendly program per scale instead
of five kernel launches (mirrors the reference's batching trick at ``ssim.py:147-149`` but with
grouped ``lax.conv_general_dilated``). All control flow (scales, kernel sizes) is static.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.image.helpers import (
    _avg_pool,
    _depthwise_conv2d,
    _depthwise_conv3d,
    _gaussian_kernel_2d,
    _gaussian_kernel_3d,
    _reflect_pad_2d,
    _reflect_pad_3d,
    reduce,
)
from torchmetrics_tpu.utils.checks import _check_same_shape


def _ssim_check_inputs(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Reference ``ssim.py:26-42``."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    _check_same_shape(preds, target)
    if preds.ndim not in (4, 5):
        raise ValueError(
            "Expected `preds` and `target` to have BxCxHxW or BxCxDxHxW shape."
            f" Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


def _ssim_validate_args(kernel_size: Sequence[int], sigma: Sequence[float], ndim: int) -> None:
    if len(kernel_size) != ndim - 2:
        raise ValueError(
            f"`kernel_size` has dimension {len(kernel_size)}, but expected to be two less that target dimensionality,"
            f" which is: {ndim}"
        )
    if len(kernel_size) not in (2, 3):
        raise ValueError(
            f"`kernel_size` dimension must be 2 or 3. `kernel_size` dimensionality: {len(kernel_size)}"
        )
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"`kernel_size` must have odd positive number. Got {kernel_size}.")
    if any(y <= 0 for y in sigma):
        raise ValueError(f"`sigma` must have positive number. Got {sigma}.")


def _ssim_update(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_full_image: bool = False,
    return_contrast_sensitivity: bool = False,
):
    """Per-image SSIM (reference ``ssim.py:45-184``)."""
    is_3d = preds.ndim == 5
    if not isinstance(kernel_size, Sequence):
        kernel_size = (3 if is_3d else 2) * [kernel_size]
    if not isinstance(sigma, Sequence):
        sigma = (3 if is_3d else 2) * [sigma]
    _ssim_validate_args(kernel_size, sigma, preds.ndim)
    if return_full_image and return_contrast_sensitivity:
        raise ValueError("Arguments `return_full_image` and `return_contrast_sensitivity` are mutually exclusive.")

    if data_range is None:
        data_range = jnp.maximum(
            jnp.max(preds) - jnp.min(preds), jnp.max(target) - jnp.min(target)
        )
    elif isinstance(data_range, tuple):
        preds = jnp.clip(preds, data_range[0], data_range[1])
        target = jnp.clip(target, data_range[0], data_range[1])
        data_range = data_range[1] - data_range[0]

    c1 = (k1 * data_range) ** 2
    c2 = (k2 * data_range) ** 2
    channel = preds.shape[1]
    # padding is always derived from the sigma-sized gaussian support, even for the uniform
    # kernel (reference quirk, ssim.py:125-128)
    gauss_kernel_size = [int(3.5 * s + 0.5) * 2 + 1 for s in sigma]
    pad_h = (gauss_kernel_size[0] - 1) // 2
    pad_w = (gauss_kernel_size[1] - 1) // 2

    if is_3d:
        pad_d = (gauss_kernel_size[2] - 1) // 2
        preds = _reflect_pad_3d(preds, pad_h, pad_w, pad_d)
        target = _reflect_pad_3d(target, pad_h, pad_w, pad_d)
        kernel = (
            _gaussian_kernel_3d(channel, gauss_kernel_size, sigma)
            if gaussian_kernel
            else jnp.full((channel, 1, *kernel_size), 1.0 / jnp.prod(jnp.asarray(kernel_size)), jnp.float32)
        )
        conv = _depthwise_conv3d
    else:
        preds = _reflect_pad_2d(preds, pad_h, pad_w)
        target = _reflect_pad_2d(target, pad_h, pad_w)
        kernel = (
            _gaussian_kernel_2d(channel, gauss_kernel_size, sigma)
            if gaussian_kernel
            else jnp.full((channel, 1, *kernel_size), 1.0 / jnp.prod(jnp.asarray(kernel_size)), jnp.float32)
        )
        conv = _depthwise_conv2d

    batch = preds.shape[0]
    stacked = jnp.concatenate(
        (preds, target, preds * preds, target * target, preds * target), axis=0
    )
    mu_p, mu_t, e_pp, e_tt, e_pt = jnp.split(conv(stacked, kernel), 5, axis=0)

    mu_pred_sq = mu_p * mu_p
    mu_target_sq = mu_t * mu_t
    mu_pred_target = mu_p * mu_t

    sigma_pred_sq = e_pp - mu_pred_sq
    sigma_target_sq = e_tt - mu_target_sq
    sigma_pred_target = e_pt - mu_pred_target

    upper = 2 * sigma_pred_target + c2
    lower = sigma_pred_sq + sigma_target_sq + c2

    ssim_full = ((2 * mu_pred_target + c1) * upper) / ((mu_pred_sq + mu_target_sq + c1) * lower)

    if is_3d:
        crop = lambda im: im[..., pad_h:-pad_h, pad_w:-pad_w, pad_d:-pad_d]
    else:
        crop = lambda im: im[..., pad_h:-pad_h, pad_w:-pad_w]
    ssim_idx = crop(ssim_full)
    per_image = jnp.mean(ssim_idx.reshape(batch, -1), axis=-1)

    if return_contrast_sensitivity:
        cs = crop(upper / lower)
        return per_image, jnp.mean(cs.reshape(batch, -1), axis=-1)
    if return_full_image:
        return per_image, ssim_full
    return per_image


def structural_similarity_index_measure(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_full_image: bool = False,
    return_contrast_sensitivity: bool = False,
):
    """SSIM (reference ``ssim.py:208-290``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import structural_similarity_index_measure
        >>> rng = np.random.RandomState(0)
        >>> preds = rng.rand(1, 1, 16, 16).astype(np.float32)
        >>> print(f"{float(structural_similarity_index_measure(preds, preds, data_range=1.0)):.4f}")
        1.0000
    """
    preds, target = _ssim_check_inputs(preds, target)
    pack = _ssim_update(
        preds, target, gaussian_kernel, sigma, kernel_size, data_range, k1, k2,
        return_full_image, return_contrast_sensitivity,
    )
    if isinstance(pack, tuple):
        similarity, image = pack
        return reduce(similarity, reduction), image
    return reduce(pack, reduction)


def _multiscale_ssim_update(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = None,
) -> Array:
    """Per-image MS-SSIM (reference ``ssim.py:321-423``): static unrolled scale pyramid."""
    is_3d = preds.ndim == 5
    if not isinstance(kernel_size, Sequence):
        kernel_size = (3 if is_3d else 2) * [kernel_size]
    if not isinstance(sigma, Sequence):
        sigma = (3 if is_3d else 2) * [sigma]

    if preds.shape[-1] < 2 ** len(betas) or preds.shape[-2] < 2 ** len(betas):
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)}, the image height and width dimensions must be"
            f" larger than or equal to {2 ** len(betas)}."
        )
    betas_div = max(1, (len(betas) - 1)) ** 2
    if preds.shape[-2] // betas_div <= kernel_size[0] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)} and kernel size {kernel_size[0]},"
            f" the image height must be larger than {(kernel_size[0] - 1) * betas_div}."
        )
    if preds.shape[-1] // betas_div <= kernel_size[1] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)} and kernel size {kernel_size[1]},"
            f" the image width must be larger than {(kernel_size[1] - 1) * betas_div}."
        )

    mcs_list = []
    sim = None
    for scale in range(len(betas)):
        sim, cs = _ssim_update(
            preds, target, gaussian_kernel, sigma, kernel_size, data_range, k1, k2,
            return_contrast_sensitivity=True,
        )
        if normalize == "relu":
            sim = jnp.maximum(sim, 0.0)
            cs = jnp.maximum(cs, 0.0)
        mcs_list.append(cs)
        if scale != len(betas) - 1:
            preds = _avg_pool(preds, 3 if is_3d else 2)
            target = _avg_pool(target, 3 if is_3d else 2)
    mcs_list[-1] = sim
    mcs_stack = jnp.stack(mcs_list)
    if normalize == "simple":
        mcs_stack = (mcs_stack + 1) / 2
    weighted = mcs_stack ** jnp.asarray(betas, jnp.float32)[:, None]
    return jnp.prod(weighted, axis=0)


def multiscale_structural_similarity_index_measure(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = "relu",
) -> Array:
    """MS-SSIM (reference ``ssim.py:447-527``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import multiscale_structural_similarity_index_measure
        >>> rng = np.random.RandomState(42)
        >>> preds = rng.rand(1, 1, 48, 48).astype(np.float32)
        >>> target = rng.rand(1, 1, 48, 48).astype(np.float32)
        >>> v = multiscale_structural_similarity_index_measure(preds, target, data_range=1.0,
        ...                                                    betas=(0.5, 0.5))
        >>> print(f"{float(v):.4f}")
        0.0258
    """
    if not isinstance(betas, tuple):
        raise ValueError("Argument `betas` is expected to be of a type tuple.")
    if not all(isinstance(beta, float) for beta in betas):
        raise ValueError("Argument `betas` is expected to be a tuple of floats.")
    if normalize and normalize not in ("relu", "simple"):
        raise ValueError("Argument `normalize` to be expected either `None` or one of 'relu' or 'simple'")
    preds, target = _ssim_check_inputs(preds, target)
    mcs = _multiscale_ssim_update(
        preds, target, gaussian_kernel, sigma, kernel_size, data_range, k1, k2, betas, normalize
    )
    return reduce(mcs, reduction)
