"""PSNR kernels (reference ``src/torchmetrics/functional/image/psnr.py``)."""
from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.image.helpers import reduce
from torchmetrics_tpu.utils.prints import rank_zero_warn


def _psnr_update(
    preds: Array, target: Array, dim: Optional[Union[int, Tuple[int, ...]]] = None
) -> Tuple[Array, Array]:
    """Sum of squared error + observation count, optionally per-`dim` (reference ``psnr.py:58-88``)."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    if dim is None:
        diff = preds - target
        return jnp.sum(diff * diff), jnp.asarray(target.size, jnp.float32)
    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=dim)
    dim_list = [dim] if isinstance(dim, int) else list(dim)
    if not dim_list:
        num_obs = jnp.asarray(target.size, jnp.float32)
    else:
        n = 1
        for d in dim_list:
            n *= target.shape[d]
        num_obs = jnp.broadcast_to(jnp.asarray(n, jnp.float32), sum_squared_error.shape)
    return sum_squared_error, num_obs


def _psnr_compute(
    sum_squared_error: Array,
    num_obs: Array,
    data_range: Array,
    base: float = 10.0,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """Reference ``psnr.py:23-55``."""
    psnr_base_e = 2 * jnp.log(data_range) - jnp.log(sum_squared_error / num_obs)
    psnr_vals = psnr_base_e * (10 / jnp.log(jnp.asarray(base)))
    return reduce(psnr_vals, reduction)


def peak_signal_noise_ratio(
    preds: Array,
    target: Array,
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    base: float = 10.0,
    reduction: Optional[str] = "elementwise_mean",
    dim: Optional[Union[int, Tuple[int, ...]]] = None,
) -> Array:
    """PSNR (reference ``psnr.py:91-155``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import peak_signal_noise_ratio
        >>> preds = np.array([[0.0, 1.0], [2.0, 3.0]], np.float32)
        >>> target = np.array([[3.0, 2.0], [1.0, 0.0]], np.float32)
        >>> print(f"{float(peak_signal_noise_ratio(preds, target, data_range=3.0)):.2f}")
        2.55
    """
    if dim is None and reduction != "elementwise_mean":
        rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    if data_range is None:
        if dim is not None:
            raise ValueError("The `data_range` must be given when `dim` is not None.")
        data_range = jnp.max(target) - jnp.min(target)
    elif isinstance(data_range, tuple):
        preds = jnp.clip(preds, data_range[0], data_range[1])
        target = jnp.clip(target, data_range[0], data_range[1])
        data_range = jnp.asarray(data_range[1] - data_range[0], jnp.float32)
    else:
        data_range = jnp.asarray(float(jax.device_get(data_range)), jnp.float32)
    sum_squared_error, num_obs = _psnr_update(preds, target, dim=dim)
    return _psnr_compute(sum_squared_error, num_obs, data_range, base=base, reduction=reduction)
