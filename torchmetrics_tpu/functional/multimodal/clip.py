"""CLIP-based multimodal metrics (reference ``src/torchmetrics/functional/multimodal/{clip_score,clip_iqa}.py``).

Pluggable-encoder design (same contract as the image generative metrics): the reference
hard-loads HuggingFace CLIP checkpoints; this build has no network egress, so the model is a
pair of callables

    ``image_encoder(images) -> (N, d)``   and   ``text_encoder(list_of_strings) -> (M, d)``

— any JAX/flax CLIP port, or a host callback into transformers. Passing a HuggingFace model id
string still works when the checkpoint is in the local cache (transformers is installed); it
raises the reference's ``ModuleNotFoundError`` contract otherwise. All similarity math
(normalise → cosine → softmax over prompt pairs) is jnp on device.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

EncoderPair = Tuple[Callable, Callable]

_PROMPTS: Dict[str, Tuple[str, str]] = {
    "quality": ("Good photo.", "Bad photo."),
    "brightness": ("Bright photo.", "Dark photo."),
    "noisiness": ("Clean photo.", "Noisy photo."),
    "colorfullness": ("Colorful photo.", "Dull photo."),
    "sharpness": ("Sharp photo.", "Blurry photo."),
    "contrast": ("High contrast photo.", "Low contrast photo."),
    "complexity": ("Complex photo.", "Simple photo."),
    "natural": ("Natural photo.", "Synthetic photo."),
    "happy": ("Happy photo.", "Sad photo."),
    "scary": ("Scary photo.", "Peaceful photo."),
    "new": ("New photo.", "Old photo."),
    "warm": ("Warm photo.", "Cold photo."),
    "real": ("Real photo.", "Abstract photo."),
    "beautiful": ("Beautiful photo.", "Ugly photo."),
    "lonely": ("Lonely photo.", "Sociable photo."),
    "relaxing": ("Relaxing photo.", "Stressful photo."),
}


def _resolve_encoders(model_name_or_path: Union[str, EncoderPair], rescale_uint8: bool = True) -> EncoderPair:
    """Map the model argument to (image_encoder, text_encoder) callables.

    ``rescale_uint8`` controls the HF processor's /255 rescale: clip_score feeds raw [0, 255]
    images (keep True, the reference contract); clip_iqa pre-divides by ``data_range`` so its
    encoder must not rescale again.
    """
    if isinstance(model_name_or_path, (tuple, list)) and len(model_name_or_path) == 2 and all(
        callable(f) for f in model_name_or_path
    ):
        return tuple(model_name_or_path)
    if not isinstance(model_name_or_path, str):
        raise ValueError(
            "Expected `model_name_or_path` to be a HuggingFace CLIP model id or a pair of callables"
            f" (image_encoder, text_encoder), got {model_name_or_path!r}"
        )
    from torchmetrics_tpu.utils.pretrained import clip_encoders

    return clip_encoders(model_name_or_path, rescale_uint8=rescale_uint8)


def _normalize(x: Array) -> Array:
    x = jnp.asarray(x, jnp.float32)
    return x / jnp.linalg.norm(x, axis=-1, keepdims=True)


def _clip_score_update(
    images: Union[Array, List[Array]],
    text: Union[str, List[str]],
    image_encoder: Callable,
    text_encoder: Callable,
) -> Tuple[Array, int]:
    """Per-sample 100·cosine(image, caption) (reference ``clip_score.py:44-90``)."""
    if not isinstance(images, list):
        images = [images] if jnp.ndim(images) == 3 else list(images)
    if not all(jnp.ndim(i) == 3 for i in images):
        raise ValueError('All images must be 3d, but found an image with a different number of dimensions')
    if not isinstance(text, list):
        text = [text]
    if len(text) != len(images):
        raise ValueError(
            f"Expected the number of images and text examples to be the same but got {len(images)} and {len(text)}"
        )
    img_features = _normalize(image_encoder(images))
    txt_features = _normalize(text_encoder(text))
    score = 100 * jnp.sum(img_features * txt_features, axis=-1)
    return score, len(text)


def clip_score(
    images: Union[Array, List[Array]],
    text: Union[str, List[str]],
    model_name_or_path: Union[str, EncoderPair] = "openai/clip-vit-large-patch14",
) -> Array:
    """CLIPScore = max(100·cos(E_I, E_C), 0) averaged over samples (reference ``clip_score.py:115``)."""
    image_encoder, text_encoder = _resolve_encoders(model_name_or_path)
    score, _ = _clip_score_update(images, text, image_encoder, text_encoder)
    return jnp.maximum(jnp.mean(score), 0.0)


def _clip_iqa_format_prompts(prompts: Tuple[Union[str, Tuple[str, str]], ...] = ("quality",)):
    """Expand prompt keywords / custom pairs (reference ``clip_iqa.py:92-142``)."""
    if not isinstance(prompts, tuple):
        raise ValueError("Argument `prompts` must be a tuple")
    prompts_names: List[str] = []
    prompts_list: List[str] = []
    count = 0
    for p in prompts:
        if not isinstance(p, (str, tuple)):
            raise ValueError("Argument `prompts` must be a tuple containing strings or nested tuples of strings")
        if isinstance(p, str):
            if p not in _PROMPTS:
                raise ValueError(
                    f"All elements of `prompts` must be one of {list(_PROMPTS.keys())} if not custom tuple"
                    f" prompts, got {p}."
                )
            prompts_names.append(p)
            prompts_list.extend(_PROMPTS[p])
        else:
            if len(p) != 2:
                raise ValueError("If a tuple is provided in argument `prompts`, it must be of length 2")
            prompts_names.append(f"user_defined_{count}")
            prompts_list.extend(p)
            count += 1
    return prompts_names, prompts_list


def _clip_iqa_compute(
    img_features: Array,
    anchors: Array,
    prompts_names: List[str],
    format_as_dict: bool = True,
):
    """Softmax over (positive, negative) anchor pairs (reference ``clip_iqa.py:202-215``)."""
    logits_per_image = 100 * img_features @ anchors.T
    logits = logits_per_image.reshape(logits_per_image.shape[0], -1, 2)
    probs = jnp.exp(logits - jnp.max(logits, -1, keepdims=True))
    probs = (probs / jnp.sum(probs, -1, keepdims=True))[:, :, 0]
    if len(prompts_names) == 1:
        return jnp.squeeze(probs)
    if format_as_dict:
        return {p: probs[:, i] for i, p in enumerate(prompts_names)}
    return probs


def clip_image_quality_assessment(
    images: Array,
    model_name_or_path: Union[str, EncoderPair] = "clip_iqa",
    data_range: float = 1.0,
    prompts: Tuple[Union[str, Tuple[str, str]], ...] = ("quality",),
):
    """CLIP-IQA (reference ``clip_iqa.py:218``): anchor-pair softmax probabilities per prompt."""
    prompts_names, prompts_list = _clip_iqa_format_prompts(prompts)
    if isinstance(model_name_or_path, str) and model_name_or_path == "clip_iqa":
        raise ModuleNotFoundError(
            "The 'clip_iqa' checkpoint (piq) is not bundled in this build; pass `model_name_or_path`"
            " as (image_encoder, text_encoder) callables or a cached HuggingFace CLIP id."
        )
    if not (isinstance(data_range, (int, float)) and data_range > 0):
        raise ValueError('Argument `data_range` must be a positive number.')
    images = jnp.asarray(images, jnp.float32)
    if images.ndim != 4:
        raise ValueError(f"Expected `images` to be a batched 4d tensor (N, C, H, W), got shape {images.shape}")
    image_encoder, text_encoder = _resolve_encoders(model_name_or_path, rescale_uint8=False)
    images = images / float(data_range)
    img_features = _normalize(image_encoder(images))
    anchors = _normalize(text_encoder(prompts_list))
    return _clip_iqa_compute(img_features, anchors, prompts_names)
