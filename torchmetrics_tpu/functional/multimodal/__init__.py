"""Functional multimodal metrics (reference ``src/torchmetrics/functional/multimodal/``)."""
from torchmetrics_tpu.functional.multimodal.clip import clip_image_quality_assessment, clip_score

__all__ = ["clip_image_quality_assessment", "clip_score"]
