"""Flat segment-reduce retrieval engine.

The rectangle path (``retrieval/base.py``) must first fetch two group statistics to the host to
size its padded ``(Q, L_max)`` batch — a blocking device→host round-trip that dominates wall
time on tunneled/remote accelerators (~134ms each here vs ~4ms for a pipelined launch). This
module removes the round-trip entirely: every metric is expressed over the *flat* sorted doc
stream with ``jax.ops.segment_*`` reductions, so all shapes are static in the input length and
the whole compute (sort → group → kernel → empty-action → aggregation) is ONE jitted launch.

This is the segment-reduce design SURVEY §3.4 prescribes for the reference's per-query Python
loop (``src/torchmetrics/retrieval/base.py:165-182``).

Layout: docs are sorted by (query id asc, score desc) with one ``lax.sort`` over two key
operands. Invalid (``ignore_index``) docs get score −inf so they sink to the end of their
query and are masked out of every reduction. Queries are dense segment ids ``0..q−1`` with
``q`` a *traced* value — ``num_segments`` is the static doc count, so segments ``≥ q`` are
empty and carry ``n_valid == 0``, which excludes them everywhere.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array, lax

_NEG = -1e30  # effective -inf for masked score positions (matches _kernels._NEG)


def host_sort_perm(indexes: Array, preds: Array, valid: Array) -> Optional[Array]:
    """Precompute the flat-engine sort permutation EAGERLY on the CPU backend; None elsewhere.

    XLA:CPU's comparator-based variadic sort is the entire retrieval bottleneck there
    (~666 ms of a ~760 ms 1M-doc cycle vs 65 ms for the packed numpy argsort). The host sort
    must run OUTSIDE the compiled program: an in-graph ``pure_callback`` can deadlock
    nondeterministically against XLA:CPU's thread pool on few-core hosts (observed hanging
    ~1 in 3 runs on a 1-core box). Callers pass the result into ``build_context(perm=...)``;
    on TPU (None) the in-graph 3-key ``lax.sort`` is used and everything stays on device.
    """
    if jax.default_backend() != "cpu":
        return None
    try:
        idx_np = np.asarray(indexes)
        # keep the key's native dtype: an early f32 cast would defeat _sort_perm_host's
        # f64 exact-path guard (sub-f32-ulp score differences must not collapse into ties)
        score_np = np.where(np.asarray(valid) > 0, np.asarray(preds), _NEG)
    except Exception:  # traced values (inside someone else's jit) — stay on the device sort
        return None
    return jnp.asarray(_sort_perm_host(idx_np, score_np))


def host_ideal_perm(
    indexes: Array, target: Array, valid: Array, perm: Optional[Array]
) -> Optional[Array]:
    """Eager permutation for NDCG's ideal-DCG re-sort (relevance desc within query).

    Operates on the main-perm-ordered stream (so segment layout is unchanged); None when the
    main perm is None (TPU: the kernel's in-graph ``lax.sort`` is used instead).
    """
    if perm is None:
        return None
    perm_np = np.asarray(perm)
    idx_s = np.asarray(indexes)[perm_np]
    # native key dtype (see host_sort_perm): graded f64 relevance must keep exact ordering
    tgt_s = (np.asarray(target) * np.asarray(valid))[perm_np]
    val_s = np.asarray(valid, np.float32)[perm_np]
    rel_key = np.where(val_s > 0, tgt_s, _NEG)
    return jnp.asarray(_sort_perm_host(idx_s, rel_key))


def _sort_perm_host(indexes: np.ndarray, key_desc: np.ndarray) -> np.ndarray:
    """Host permutation for (query asc, key desc, reversed-input-order ties).

    Packs (query, descending-sortable score bits) into ONE uint64 and runs a single stable
    argsort over the REVERSED array (stability on the reversal yields the reversed-input tie
    order) — ~10x faster than XLA:CPU's comparator sort at 1M docs. Negative ids or NaN keys
    fall back to an equivalent ``np.lexsort``.
    """
    n = indexes.shape[0]
    raw_key = np.asarray(key_desc)
    key_desc = raw_key.astype(np.float32)
    indexes = np.asarray(indexes)
    if n == 0:
        return np.zeros((0,), np.int32)
    if (
        (indexes < 0).any()
        or np.isnan(key_desc).any()
        # ids >= 2^32 would wrap in the uint64 pack; f64 keys would change tie structure
        # when downcast to f32 — both route to the (slower, exact) lexsort
        or int(indexes.max(initial=0)) >= (1 << 32)
        or raw_key.dtype == np.float64
    ):
        rev = np.arange(n, dtype=np.int64)[::-1]
        return np.lexsort((rev, -raw_key, indexes)).astype(np.int32)
    bits = key_desc.view(np.uint32)
    # order-preserving f32 -> uint32 (ascending), inverted for descending-score order
    sortable = np.where(key_desc >= 0, bits | np.uint32(0x80000000), ~bits)
    packed = (indexes.astype(np.uint64) << np.uint64(32)) | (~sortable).astype(np.uint64)
    perm_rev = np.argsort(packed[::-1], kind="stable")
    return ((n - 1) - perm_rev).astype(np.int32)


def _sort_by_query_then(indexes: Array, key_desc: Array, *payload: Array):
    """Sort by (query id asc, key desc), ties in REVERSED input order; returns sorted
    (indexes, key, *payload).

    The tertiary key reproduces the rectangle engine's tie order exactly
    (``_kernels._ranked_target`` does a stable ascending argsort then reverses, which leaves
    equal scores in reversed input order) — the two paths must agree on tied scores or the
    same metric instance would return different values for string vs callable aggregations.
    """
    n = indexes.shape[0]
    rev_rank = jnp.arange(n, dtype=jnp.int32)[::-1]
    sorted_all = lax.sort((indexes, -key_desc, rev_rank) + payload, num_keys=3, is_stable=True)
    return sorted_all[:2] + sorted_all[3:]


def dense_groups(idx_sorted: Array):
    """(is_new, gid, start) for a SORTED id stream — the one copy of the segment-boundary
    index math every retrieval grouping path shares: ``is_new`` marks segment starts, ``gid``
    is the dense 0-based segment id, ``start`` the flat index of each element's segment start."""
    n = idx_sorted.shape[0]
    ar = jnp.arange(n)
    is_new = jnp.concatenate([jnp.ones((1,), bool), idx_sorted[1:] != idx_sorted[:-1]])
    gid = jnp.cumsum(is_new) - 1
    start = lax.cummax(jnp.where(is_new, ar, 0))
    return is_new, gid, start


def build_context(
    indexes: Array, preds: Array, target: Array, valid: Array, top_k: Optional[int],
    perm: Optional[Array] = None, ideal_perm: Optional[Array] = None,
) -> Dict[str, Array]:
    """Shared per-doc/per-segment quantities every flat kernel consumes.

    All arrays are length-N (per sorted doc) or length-N (per segment id; segments >= q empty).
    ``perm``: optional precomputed sort permutation (``host_sort_perm``, CPU backend) — only
    cheap gathers run in the compiled program; None keeps the in-graph ``lax.sort``.
    """
    n = indexes.shape[0]
    score = jnp.where(valid > 0, preds, _NEG)
    if perm is not None:
        idx_s = jnp.take(indexes, perm)
        neg_score = jnp.take(-score, perm)
        tgt_s = jnp.take(target * valid, perm)
        val_s = jnp.take(valid.astype(jnp.float32), perm)
    else:
        idx_s, neg_score, tgt_s, val_s = _sort_by_query_then(
            indexes, score, target * valid, valid.astype(jnp.float32)
        )
    is_new, gid, start = dense_groups(idx_s)
    rank = (jnp.arange(n) - start).astype(jnp.float32) + 1.0  # 1-based within-query rank

    n_valid_seg = jax.ops.segment_sum(val_s, gid, num_segments=n)
    n_valid = n_valid_seg[gid]
    if top_k is None:
        k_eff = n_valid
    else:
        k_eff = jnp.minimum(jnp.asarray(top_k, jnp.float32), n_valid)
    in_k = (rank <= k_eff) & (val_s > 0)

    # within-query cumulative relevance: global cumsum re-based at each segment start
    c = jnp.cumsum(tgt_s)
    within_cum = c - c[start] + tgt_s[start]

    pos_seg = jax.ops.segment_sum(tgt_s, gid, num_segments=n)
    return {
        "n": n,
        "idx_s": idx_s,
        "score_s": -neg_score,
        "tgt_s": tgt_s,
        "val_s": val_s,
        "gid": gid,
        "is_new": is_new,
        "rank": rank,
        "n_valid": n_valid,
        "n_valid_seg": n_valid_seg,
        "k_eff": k_eff,
        "in_k": in_k.astype(jnp.float32),
        "within_cum": within_cum,
        "pos_seg": pos_seg,  # per-segment total relevance (graded sum for NDCG inputs)
        "top_k": top_k,
        "ideal_perm": ideal_perm,  # NDCG's eager ideal-DCG re-sort (CPU backend), else None
    }


def _seg(ctx: Dict[str, Array], values: Array) -> Array:
    return jax.ops.segment_sum(values, ctx["gid"], num_segments=ctx["n"])


def average_precision_flat(ctx: Dict[str, Array]) -> Array:
    """AP per query: mean over relevant in-top-k docs of precision@rank (``_kernels.py:38``)."""
    prec = ctx["within_cum"] / ctx["rank"]
    w = ctx["tgt_s"] * ctx["in_k"]
    n_rel = _seg(ctx, w)
    return jnp.where(n_rel > 0, _seg(ctx, prec * w) / jnp.maximum(n_rel, 1.0), 0.0)


def reciprocal_rank_flat(ctx: Dict[str, Array]) -> Array:
    first = jax.ops.segment_min(
        jnp.where((ctx["tgt_s"] > 0) & (ctx["in_k"] > 0), ctx["rank"], jnp.inf),
        ctx["gid"], num_segments=ctx["n"],
    )
    return jnp.where(jnp.isfinite(first), 1.0 / jnp.maximum(first, 1.0), 0.0)


def make_precision_flat(top_k: Optional[int], adaptive_k: bool = False) -> Callable:
    """precision@k per query (rectangle twin ``_kernels.py:61``): hits bounded by
    ``min(k, n_valid)``; the denominator is the fixed ``k`` unless adaptive/None, where it is
    ``min(k, n_valid)`` (or ``n_valid`` for None)."""

    def precision_flat(ctx: Dict[str, Array]) -> Array:
        if top_k is None:
            k_doc, k_seg = ctx["n_valid"], ctx["n_valid_seg"]
        else:
            kf = jnp.asarray(top_k, jnp.float32)
            k_doc = jnp.minimum(kf, ctx["n_valid"])
            k_seg = jnp.minimum(kf, ctx["n_valid_seg"]) if adaptive_k else jnp.full((ctx["n"],), kf)
        in_k = (ctx["rank"] <= k_doc) & (ctx["val_s"] > 0)
        hits = _seg(ctx, ctx["tgt_s"] * in_k)
        return jnp.where(ctx["pos_seg"] > 0, hits / jnp.maximum(k_seg, 1.0), 0.0)

    return precision_flat


def make_recall_flat(top_k: Optional[int]) -> Callable:
    """recall@k per query with an explicit k (curve metrics sweep k in one launch)."""

    def recall_at_k(ctx: Dict[str, Array]) -> Array:
        if top_k is None:
            in_k = ctx["in_k"]
        else:
            k_doc = jnp.minimum(jnp.asarray(top_k, jnp.float32), ctx["n_valid"])
            in_k = ((ctx["rank"] <= k_doc) & (ctx["val_s"] > 0)).astype(jnp.float32)
        hits = _seg(ctx, ctx["tgt_s"] * in_k)
        total = ctx["pos_seg"]
        return jnp.where(total > 0, hits / jnp.maximum(total, 1.0), 0.0)

    return recall_at_k


recall_flat = make_recall_flat(None)


def curve_counts(ctx: Dict[str, Array], max_k: int, adaptive_k: bool, k_tile: int = 128):
    """(precision (N, K), recall (N, K)) for every k in 1..max_k via batched segment-reduces.

    Replaces a per-k Python loop (2*K kernel instantiations traced into the program) with a
    (docs, k) membership product scattered per query — constant kernel count. The k axis is
    processed in ``k_tile``-wide tiles under ``lax.map`` so the per-doc transient is bounded
    at ``n_docs * k_tile`` floats regardless of how large the k sweep is (an unchunked
    (n_docs, K) product reaches multi-GB when K tracks the longest query of a large corpus).
    """
    k_vec = jnp.arange(1, max_k + 1, dtype=jnp.float32)  # (K,)

    def _hits_for(kv: Array) -> Array:  # kv (T,) -> per-query hit counts (N, T)
        k_doc = jnp.minimum(kv[None, :], ctx["n_valid"][:, None])  # (docs, T)
        in_k = (ctx["rank"][:, None] <= k_doc) & (ctx["val_s"][:, None] > 0)
        return jax.ops.segment_sum(ctx["tgt_s"][:, None] * in_k, ctx["gid"], num_segments=ctx["n"])

    if max_k <= k_tile:
        hits = _hits_for(k_vec)  # (N, K)
    else:
        n_tiles = -(-max_k // k_tile)
        pad = n_tiles * k_tile - max_k
        k_tiles = jnp.pad(k_vec, (0, pad)).reshape(n_tiles, k_tile)
        tiled = jax.lax.map(_hits_for, k_tiles)  # (n_tiles, N, k_tile), sequential tiles
        hits = jnp.moveaxis(tiled, 0, 1).reshape(ctx["n"], n_tiles * k_tile)[:, :max_k]
    if adaptive_k:
        prec_den = jnp.minimum(k_vec[None, :], ctx["n_valid_seg"][:, None])
    else:
        prec_den = jnp.broadcast_to(k_vec[None, :], hits.shape)
    has_pos = (ctx["pos_seg"] > 0)[:, None]
    precision = jnp.where(has_pos, hits / jnp.maximum(prec_den, 1.0), 0.0)
    recall = jnp.where(has_pos, hits / jnp.maximum(ctx["pos_seg"][:, None], 1.0), 0.0)
    return precision, recall


def fall_out_flat(ctx: Dict[str, Array]) -> Array:
    irrel = ctx["val_s"] - ctx["tgt_s"]
    hits = _seg(ctx, irrel * ctx["in_k"])
    total = ctx["n_valid_seg"] - ctx["pos_seg"]
    return jnp.where(total > 0, hits / jnp.maximum(total, 1.0), 0.0)


def hit_rate_flat(ctx: Dict[str, Array]) -> Array:
    return (_seg(ctx, ctx["tgt_s"] * ctx["in_k"]) > 0).astype(jnp.float32)


def r_precision_flat(ctx: Dict[str, Array]) -> Array:
    r = ctx["pos_seg"]
    in_r = (ctx["rank"] <= r[ctx["gid"]]) & (ctx["val_s"] > 0)
    hits = _seg(ctx, ctx["tgt_s"] * in_r)
    return jnp.where(r > 0, hits / jnp.maximum(r, 1.0), 0.0)


def ndcg_flat(ctx: Dict[str, Array]) -> Array:
    """NDCG with tie-averaged DCG (sklearn semantics; rectangle twin ``_kernels.py:121``)."""
    n = ctx["n"]
    discount = jnp.where(ctx["in_k"] > 0, 1.0 / jnp.log2(ctx["rank"] + 1.0), 0.0)
    # tie groups: runs of equal score within a query
    score = ctx["score_s"]
    tie_new = ctx["is_new"] | jnp.concatenate([jnp.ones((1,), bool), score[1:] != score[:-1]])
    tie_gid = jnp.cumsum(tie_new) - 1
    tie_disc = jax.ops.segment_sum(discount, tie_gid, num_segments=n)
    tie_cnt = jax.ops.segment_sum(jnp.ones((n,), jnp.float32), tie_gid, num_segments=n)
    avg_disc = (tie_disc / jnp.maximum(tie_cnt, 1.0))[tie_gid]
    dcg = _seg(ctx, ctx["tgt_s"] * avg_disc)

    # ideal DCG: docs re-sorted by true relevance within the query, plain discounts.
    # On the CPU backend the permutation was precomputed eagerly (host_ideal_perm) — the
    # in-graph variadic sort it replaces is the same ~10x bottleneck as the main sort.
    if ctx.get("ideal_perm") is not None:
        ideal_tgt = jnp.take(ctx["tgt_s"], ctx["ideal_perm"])
        ideal_val = jnp.take(ctx["val_s"], ctx["ideal_perm"])
    else:
        rel_key = jnp.where(ctx["val_s"] > 0, ctx["tgt_s"], _NEG)
        _, _, ideal_tgt, ideal_val = _sort_by_query_then(
            ctx["idx_s"], rel_key, ctx["tgt_s"], ctx["val_s"]
        )
    # within-query positions are identical to the first sort's (same segment layout)
    ideal_disc = jnp.where(
        (ctx["rank"] <= ctx["k_eff"]) & (ideal_val > 0), 1.0 / jnp.log2(ctx["rank"] + 1.0), 0.0
    )
    idcg = _seg(ctx, ideal_tgt * ideal_disc)
    return jnp.where(idcg > 0, dcg / jnp.maximum(idcg, 1e-38), 0.0)
