"""Masked single-query retrieval kernels.

Every kernel takes ``(preds (L,), target (L,), mask (L,))`` and returns a scalar for ONE query;
invalid (padded) positions have ``mask == 0``. All are pure, shape-static, and vmap/jit-safe —
the module layer vmaps them over a padded ``(num_queries, L_max)`` batch, replacing the
reference's per-query Python loop (``src/torchmetrics/retrieval/base.py:165-182``) with one
fused kernel launch.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import Array

_NEG = -1e30  # effective -inf for masked score positions


def _ranked_target(preds: Array, target: Array, mask: Array) -> Array:
    """Relevance values sorted by descending score (masked entries last)."""
    order = jnp.argsort(jnp.where(mask > 0, preds, _NEG))[::-1]
    return (target * mask)[order]


def _n_valid(mask: Array) -> Array:
    return jnp.sum(mask)


def _effective_k(top_k: Optional[int], mask: Array) -> Array:
    """k limited to the number of valid docs (None = all valid docs)."""
    n = _n_valid(mask)
    if top_k is None:
        return n
    return jnp.minimum(jnp.asarray(top_k, jnp.float32), n)


def average_precision_kernel(
    preds: Array, target: Array, mask: Array, top_k: Optional[int] = None
) -> Array:
    """AP = mean over relevant docs of precision@rank (reference ``average_precision.py``)."""
    rel = _ranked_target(preds, target, mask)
    pos = jnp.arange(1, rel.shape[0] + 1, dtype=jnp.float32)
    in_k = pos <= _effective_k(top_k, mask)
    prec_at_rank = jnp.cumsum(rel) / pos
    n_rel = jnp.sum(rel * in_k)
    return jnp.where(n_rel > 0, jnp.sum(prec_at_rank * rel * in_k) / jnp.maximum(n_rel, 1.0), 0.0)


def reciprocal_rank_kernel(
    preds: Array, target: Array, mask: Array, top_k: Optional[int] = None
) -> Array:
    """MRR contribution: 1/rank of the first relevant document."""
    rel = _ranked_target(preds, target, mask)
    pos = jnp.arange(1, rel.shape[0] + 1, dtype=jnp.float32)
    in_k = pos <= _effective_k(top_k, mask)
    first = jnp.min(jnp.where((rel > 0) & in_k, pos, jnp.inf))
    return jnp.where(jnp.isfinite(first), 1.0 / jnp.maximum(first, 1.0), 0.0)


def precision_kernel(
    preds: Array, target: Array, mask: Array, top_k: Optional[int] = None, adaptive_k: bool = False
) -> Array:
    """precision@k (reference ``precision.py``): relevant-in-top-k / k."""
    rel = _ranked_target(preds, target, mask)
    pos = jnp.arange(1, rel.shape[0] + 1, dtype=jnp.float32)
    n = _n_valid(mask)
    if top_k is None or adaptive_k:
        k = _effective_k(top_k, mask)
    else:
        k = jnp.asarray(top_k, jnp.float32)
    in_k = pos <= jnp.minimum(k, n)
    return jnp.where(jnp.sum(target * mask) > 0, jnp.sum(rel * in_k) / jnp.maximum(k, 1.0), 0.0)


def recall_kernel(
    preds: Array, target: Array, mask: Array, top_k: Optional[int] = None
) -> Array:
    """recall@k: relevant-in-top-k / total relevant."""
    rel = _ranked_target(preds, target, mask)
    pos = jnp.arange(1, rel.shape[0] + 1, dtype=jnp.float32)
    in_k = pos <= _effective_k(top_k, mask)
    total_rel = jnp.sum(target * mask)
    return jnp.where(total_rel > 0, jnp.sum(rel * in_k) / jnp.maximum(total_rel, 1.0), 0.0)


def fall_out_kernel(
    preds: Array, target: Array, mask: Array, top_k: Optional[int] = None
) -> Array:
    """fall-out@k: irrelevant-in-top-k / total irrelevant."""
    rel = _ranked_target(preds, target, mask)
    pos = jnp.arange(1, rel.shape[0] + 1, dtype=jnp.float32)
    in_k = pos <= _effective_k(top_k, mask)
    # irrelevant indicator among the ranked valid docs: ranked mask minus ranked relevance
    order = jnp.argsort(jnp.where(mask > 0, preds, _NEG))[::-1]
    valid_ranked = mask[order]
    irrel = valid_ranked - rel
    total_irrel = jnp.sum(mask) - jnp.sum(target * mask)
    return jnp.where(total_irrel > 0, jnp.sum(irrel * in_k) / jnp.maximum(total_irrel, 1.0), 0.0)


def hit_rate_kernel(
    preds: Array, target: Array, mask: Array, top_k: Optional[int] = None
) -> Array:
    """hit-rate@k: 1 if any relevant doc in the top k."""
    rel = _ranked_target(preds, target, mask)
    pos = jnp.arange(1, rel.shape[0] + 1, dtype=jnp.float32)
    in_k = pos <= _effective_k(top_k, mask)
    return (jnp.sum(rel * in_k) > 0).astype(jnp.float32)


def r_precision_kernel(preds: Array, target: Array, mask: Array) -> Array:
    """R-precision: relevant-in-top-R / R, with R = number of relevant docs."""
    rel = _ranked_target(preds, target, mask)
    pos = jnp.arange(1, rel.shape[0] + 1, dtype=jnp.float32)
    r = jnp.sum(target * mask)
    in_r = pos <= r
    return jnp.where(r > 0, jnp.sum(rel * in_r) / jnp.maximum(r, 1.0), 0.0)


def ndcg_kernel(
    preds: Array, target: Array, mask: Array, top_k: Optional[int] = None
) -> Array:
    """NDCG@k with tie-averaged DCG (sklearn semantics, reference ``ndcg.py``).

    Graded relevance supported: gain = target value, discount = 1/log2(rank+1).
    """
    length = preds.shape[0]
    pos = jnp.arange(length, dtype=jnp.float32)
    discount = 1.0 / jnp.log2(pos + 2.0)
    k = _effective_k(top_k, mask)
    discount = jnp.where(pos < k, discount, 0.0)

    scores = jnp.where(mask > 0, preds, _NEG)
    tgt = target * mask

    # tie-averaged DCG: every doc in a tie group gets the mean discount of the group's positions
    order = jnp.argsort(scores)[::-1]
    s_sorted = scores[order]
    t_sorted = tgt[order]
    is_new = jnp.concatenate([jnp.ones((1,), bool), s_sorted[1:] != s_sorted[:-1]])
    group_id = jnp.cumsum(is_new) - 1
    group_disc = jax.ops.segment_sum(discount, group_id, num_segments=length)
    group_cnt = jax.ops.segment_sum(jnp.ones(length, jnp.float32), group_id, num_segments=length)
    avg_disc = group_disc / jnp.maximum(group_cnt, 1.0)
    dcg = jnp.sum(t_sorted * avg_disc[group_id])

    # ideal DCG: sorted by true relevance, no tie handling (sklearn)
    ideal = jnp.sort(tgt)[::-1]
    idcg = jnp.sum(ideal * jnp.where(pos < k, 1.0 / jnp.log2(pos + 2.0), 0.0))
    return jnp.where(idcg > 0, dcg / jnp.maximum(idcg, 1e-38), 0.0)
