"""Functional retrieval API — single-query metrics (reference
``src/torchmetrics/functional/retrieval/``)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.retrieval._kernels import (
    average_precision_kernel,
    fall_out_kernel,
    hit_rate_kernel,
    ndcg_kernel,
    precision_kernel,
    r_precision_kernel,
    recall_kernel,
    reciprocal_rank_kernel,
)
from torchmetrics_tpu.utils.checks import _check_retrieval_functional_inputs


def _prep(preds: Array, target: Array, graded: bool = False) -> Tuple[Array, Array, Array]:
    preds, target = _check_retrieval_functional_inputs(preds, target, allow_non_binary_target=graded)
    mask = jnp.ones(preds.shape, jnp.float32)
    return preds, target.astype(jnp.float32), mask


def _check_top_k(top_k: Optional[int]) -> None:
    if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")


def retrieval_average_precision(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """AP for a single query (reference ``functional/retrieval/average_precision.py``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import retrieval_average_precision
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([True, False, True])
        >>> print(f"{float(retrieval_average_precision(preds, target)):.4f}")
        0.8333
    """
    _check_top_k(top_k)
    preds, target, mask = _prep(preds, target)
    return average_precision_kernel(preds, target, mask, top_k)


def retrieval_reciprocal_rank(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """Reciprocal rank for a single query (reference ``reciprocal_rank.py``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import retrieval_reciprocal_rank
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([True, False, True])
        >>> print(f"{float(retrieval_reciprocal_rank(preds, target)):.4f}")
        1.0000
    """
    _check_top_k(top_k)
    preds, target, mask = _prep(preds, target)
    return reciprocal_rank_kernel(preds, target, mask, top_k)


def retrieval_precision(
    preds: Array, target: Array, top_k: Optional[int] = None, adaptive_k: bool = False
) -> Array:
    """precision@k for a single query (reference ``precision.py``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import retrieval_precision
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([True, False, True])
        >>> print(f"{float(retrieval_precision(preds, target, top_k=2)):.4f}")
        0.5000
    """
    _check_top_k(top_k)
    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    preds, target, mask = _prep(preds, target)
    return precision_kernel(preds, target, mask, top_k, adaptive_k)


def retrieval_recall(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """recall@k for a single query (reference ``recall.py``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import retrieval_recall
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([True, False, True])
        >>> print(f"{float(retrieval_recall(preds, target, top_k=2)):.4f}")
        0.5000
    """
    _check_top_k(top_k)
    preds, target, mask = _prep(preds, target)
    return recall_kernel(preds, target, mask, top_k)


def retrieval_fall_out(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """fall-out@k for a single query (reference ``fall_out.py``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import retrieval_fall_out
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([True, False, True])
        >>> print(f"{float(retrieval_fall_out(preds, target)):.4f}")
        1.0000
    """
    _check_top_k(top_k)
    preds, target, mask = _prep(preds, target)
    return fall_out_kernel(preds, target, mask, top_k)


def retrieval_hit_rate(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """hit-rate@k for a single query (reference ``hit_rate.py``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import retrieval_hit_rate
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([True, False, True])
        >>> print(f"{float(retrieval_hit_rate(preds, target)):.4f}")
        1.0000
    """
    _check_top_k(top_k)
    preds, target, mask = _prep(preds, target)
    return hit_rate_kernel(preds, target, mask, top_k)


def retrieval_r_precision(preds: Array, target: Array) -> Array:
    """R-precision for a single query (reference ``r_precision.py``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import retrieval_r_precision
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([True, False, True])
        >>> print(f"{float(retrieval_r_precision(preds, target)):.4f}")
        0.5000
    """
    preds, target, mask = _prep(preds, target)
    return r_precision_kernel(preds, target, mask)


def retrieval_normalized_dcg(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """NDCG@k for a single query, graded relevance allowed (reference ``ndcg.py``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import retrieval_normalized_dcg
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([True, False, True])
        >>> print(f"{float(retrieval_normalized_dcg(preds, target)):.4f}")
        0.9197
    """
    _check_top_k(top_k)
    preds, target, mask = _prep(preds, target, graded=True)
    return ndcg_kernel(preds, target, mask, top_k)


def retrieval_precision_recall_curve(
    preds: Array, target: Array, max_k: Optional[int] = None, adaptive_k: bool = False
) -> Tuple[Array, Array, Array]:
    """(precisions, recalls, top_k values) for k = 1..max_k (reference ``precision_recall_curve.py``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import retrieval_precision_recall_curve
        >>> preds = np.array([0.9, 0.8, 0.7, 0.6, 0.5], np.float32)
        >>> target = np.array([1, 0, 1, 0, 1])
        >>> prec, rec, top_k = retrieval_precision_recall_curve(preds, target, max_k=4)
        >>> np.asarray(prec, np.float64).round(4).tolist()
        [1.0, 0.5, 0.6667, 0.5]
        >>> np.asarray(top_k).tolist()
        [1, 2, 3, 4]
    """
    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    preds, target, mask = _prep(preds, target)
    n = preds.shape[0]
    if max_k is None:
        max_k = n
    if not (isinstance(max_k, int) and max_k > 0):
        raise ValueError('`max_k` must be a positive integer or None')
    if not adaptive_k:
        ks = list(range(1, max_k + 1))
    else:
        ks = list(range(1, min(max_k, n) + 1))
    precisions = jnp.stack([precision_kernel(preds, target, mask, k, adaptive_k) for k in ks])
    recalls = jnp.stack([recall_kernel(preds, target, mask, k) for k in ks])
    return precisions, recalls, jnp.asarray(ks)


__all__ = [
    "retrieval_average_precision",
    "retrieval_fall_out",
    "retrieval_hit_rate",
    "retrieval_normalized_dcg",
    "retrieval_precision",
    "retrieval_precision_recall_curve",
    "retrieval_r_precision",
    "retrieval_recall",
    "retrieval_reciprocal_rank",
]
