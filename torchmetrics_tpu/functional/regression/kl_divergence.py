"""KL-divergence kernels (reference ``src/torchmetrics/functional/regression/kl_divergence.py``)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape
from torchmetrics_tpu.utils.compute import _safe_xlogy


def _kld_update(p: Array, q: Array, log_prob: bool) -> Tuple[Array, Array]:
    _check_same_shape(p, q)
    if p.ndim != 2 or q.ndim != 2:
        raise ValueError(f"Both p and q distribution must be 2D but got {p.ndim} and {q.ndim} respectively")
    p = p.astype(jnp.float32)
    q = q.astype(jnp.float32)
    if log_prob:
        measures = jnp.sum(jnp.exp(p) * (p - q), axis=-1)
    else:
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        q = q / jnp.sum(q, axis=-1, keepdims=True)
        measures = jnp.sum(_safe_xlogy(p, p / jnp.where(q == 0, 1e-38, q)), axis=-1)
    return measures, jnp.asarray(p.shape[0], jnp.float32)


def _kld_compute(measures: Array, total: Array, reduction: Optional[str] = "mean") -> Array:
    if reduction == "sum":
        return jnp.sum(measures)
    if reduction == "mean":
        return jnp.sum(measures) / total
    if reduction in ("none", None):
        return measures
    raise ValueError(f"Expected reduction to be one of `['mean', 'sum', 'none', None]` but got {reduction}")


def kl_divergence(
    p: Array, q: Array, log_prob: bool = False, reduction: Optional[str] = "mean"
) -> Array:
    """KL(P||Q) (reference ``kl_divergence.py:58``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import kl_divergence
        >>> p = np.array([[0.5, 0.5], [0.8, 0.2]], np.float32)
        >>> q = np.array([[0.4, 0.6], [0.6, 0.4]], np.float32)
        >>> print(f"{float(kl_divergence(p, q)):.4f}")
        0.0560
    """
    p = jnp.asarray(p)
    q = jnp.asarray(q)
    measures, total = _kld_update(p, q, log_prob)
    return _kld_compute(measures, total, reduction)
