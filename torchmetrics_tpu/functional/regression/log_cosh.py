"""LogCosh error kernels (reference ``src/torchmetrics/functional/regression/log_cosh.py``)."""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.regression.utils import _check_data_shape_to_num_outputs


def _unsqueeze_tensors(preds: Array, target: Array) -> tuple:
    if preds.ndim == 1:
        return preds[:, None], target[:, None]
    return preds, target


def _log_cosh_error_update(preds: Array, target: Array, num_outputs: int) -> Tuple[Array, Array]:
    _check_data_shape_to_num_outputs(preds, target, num_outputs)
    preds, target = _unsqueeze_tensors(preds.astype(jnp.float32), target.astype(jnp.float32))
    diff = preds - target
    # log(cosh(x)) computed stably: |x| + log1p(exp(-2|x|)) - log(2)
    a = jnp.abs(diff)
    vals = a + jnp.log1p(jnp.exp(-2 * a)) - jnp.log(2.0)
    return jnp.sum(vals, axis=0), jnp.asarray(preds.shape[0], jnp.float32)


def _log_cosh_error_compute(sum_log_cosh_error: Array, total: Array) -> Array:
    return jnp.squeeze(sum_log_cosh_error / total)


def log_cosh_error(preds: Array, target: Array) -> Array:
    """LogCosh error (reference ``log_cosh.py:53``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import log_cosh_error
        >>> preds = np.array([2.5, 1.0, 2.0, 8.0], np.float32)
        >>> target = np.array([3.0, 0.5, 2.0, 7.0], np.float32)
        >>> print(f"{float(log_cosh_error(preds, target)):.4f}")
        0.1685
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    num_outputs = 1 if preds.ndim == 1 else preds.shape[1]
    s, n = _log_cosh_error_update(preds, target, num_outputs)
    return _log_cosh_error_compute(s, n)
