"""R² kernels (reference ``src/torchmetrics/functional/regression/r2.py``)."""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape, is_traced
from torchmetrics_tpu.utils.prints import rank_zero_warn


def _r2_score_update(preds: Array, target: Array) -> Tuple[Array, Array, Array, Array]:
    """(Σy, Σy², Σ(y-ŷ)², n) per output column."""
    _check_same_shape(preds, target)
    if preds.ndim > 2:
        raise ValueError(
            f"Expected both prediction and target to be 1D or 2D tensors, but received tensors with"
            f" dimension {preds.shape}"
        )
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    if preds.ndim == 1:
        preds = preds[:, None]
        target = target[:, None]
    sum_obs = jnp.sum(target, axis=0)
    sum_squared_obs = jnp.sum(target * target, axis=0)
    diff = target - preds
    rss = jnp.sum(diff * diff, axis=0)
    return sum_squared_obs, sum_obs, rss, jnp.asarray(target.shape[0], jnp.float32)


def _r2_score_compute(
    sum_squared_obs: Array,
    sum_obs: Array,
    rss: Array,
    num_obs: Array,
    adjusted: int = 0,
    multioutput: str = "uniform_average",
) -> Array:
    """Reference ``r2.py:53``: tss from moments, multioutput reductions, adjusted correction."""
    if not is_traced(num_obs) and float(num_obs) < 2:
        raise ValueError("Needs at least two samples to calculate r2 score.")
    mean_obs = sum_obs / num_obs
    tss = sum_squared_obs - sum_obs * mean_obs
    cond = tss != 0
    raw_scores = 1 - rss / jnp.where(cond, tss, 1.0)
    raw_scores = jnp.where(cond, raw_scores, 0.0)
    if multioutput == "raw_values":
        r2 = raw_scores
    elif multioutput == "uniform_average":
        r2 = jnp.mean(raw_scores)
    elif multioutput == "variance_weighted":
        tss_sum = jnp.sum(tss)
        r2 = jnp.sum(tss / jnp.where(tss_sum == 0, 1.0, tss_sum) * raw_scores)
    else:
        raise ValueError(
            "Argument `multioutput` must be either `raw_values`,"
            f" `uniform_average` or `variance_weighted`. Received {multioutput}."
        )
    if adjusted < 0 or not isinstance(adjusted, int):
        raise ValueError('`adjusted` parameter must be an integer larger or equal to 0.')
    if adjusted != 0:
        if not is_traced(num_obs) and adjusted > float(num_obs) - 1:
            rank_zero_warn(
                "More independent regressions than data points in adjusted r2 score. Falls back to standard r2 score.",
                UserWarning,
            )
        elif not is_traced(num_obs) and adjusted == float(num_obs) - 1:
            rank_zero_warn("Division by zero in adjusted r2 score. Falls back to standard r2 score.", UserWarning)
        else:
            return 1 - (1 - r2) * (num_obs - 1) / (num_obs - adjusted - 1)
    return r2


def r2_score(
    preds: Array,
    target: Array,
    adjusted: int = 0,
    multioutput: str = "uniform_average",
) -> Array:
    """R² score (reference ``r2.py:99``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import r2_score
        >>> preds = np.array([2.5, 1.0, 2.0, 8.0], np.float32)
        >>> target = np.array([3.0, 0.5, 2.0, 7.0], np.float32)
        >>> print(f"{float(r2_score(preds, target)):.4f}")
        0.9353
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    return _r2_score_compute(*_r2_score_update(preds, target), adjusted, multioutput)
