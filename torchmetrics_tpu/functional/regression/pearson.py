"""Pearson correlation kernels (reference ``src/torchmetrics/functional/regression/pearson.py``).

Running mean/var/cov state with the pairwise (Chan et al.) parallel-merge for distributed
aggregation — the reference's ``_final_aggregation`` (``pearson.py:28-71``) re-expressed as a
vectorised fold over the replica axis (jit/psum friendly, no Python loop over devices needed when
used inside ``shard_map``; the eager multi-process path folds a leading world axis).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.regression.utils import _check_data_shape_to_num_outputs


def _pearson_corrcoef_update(
    preds: Array,
    target: Array,
    mean_x: Array,
    mean_y: Array,
    var_x: Array,
    var_y: Array,
    corr_xy: Array,
    num_prior: Array,
    num_outputs: int,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Welford-style batch fold (reference ``pearson.py:74-118``)."""
    _check_data_shape_to_num_outputs(preds, target, num_outputs)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    if num_outputs == 1:
        preds = jnp.reshape(preds, (-1,))
        target = jnp.reshape(target, (-1,))
    n_obs = jnp.asarray(preds.shape[0], jnp.float32)
    total = num_prior + n_obs
    mx_new = (num_prior * mean_x + preds.sum(axis=0)) / total
    my_new = (num_prior * mean_y + target.sum(axis=0)) / total
    # incremental cross-terms use the OLD running mean (reference pearson.py:104-110); with
    # zero-initialised means the first-batch special case reduces to the same formula
    # (sum((x - x_bar)(x - c)) == sum((x - x_bar)^2) for any constant c), so no data-dependent
    # branch is needed under jit
    var_x = var_x + jnp.sum((preds - mx_new) * (preds - mean_x), axis=0)
    var_y = var_y + jnp.sum((target - my_new) * (target - mean_y), axis=0)
    corr_xy = corr_xy + jnp.sum((preds - mx_new) * (target - mean_y), axis=0)
    return mx_new, my_new, var_x, var_y, corr_xy, total


def _pearson_corrcoef_compute(
    var_x: Array, var_y: Array, corr_xy: Array, nb: Array
) -> Array:
    """corr = cov / (σx σy) (reference ``pearson.py:121``)."""
    var_x = var_x / (nb - 1)
    var_y = var_y / (nb - 1)
    corr_xy = corr_xy / (nb - 1)
    corrcoef = jnp.clip(corr_xy / jnp.sqrt(var_x * var_y), -1.0, 1.0)
    return jnp.squeeze(corrcoef)


def _final_aggregation(
    means_x: Array,
    means_y: Array,
    vars_x: Array,
    vars_y: Array,
    corrs_xy: Array,
    nbs: Array,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Merge per-replica (mean, var, cov, n) along a leading world axis (reference ``pearson.py:28``).

    Vectorised pairwise merge fold — mathematically Chan et al.'s parallel variance update.
    """

    def merge(a, b):
        mx1, my1, vx1, vy1, cxy1, n1 = a
        mx2, my2, vx2, vy2, cxy2, n2 = b
        nb = n1 + n2
        safe_nb = jnp.where(nb == 0, 1.0, nb)
        mean_x = (n1 * mx1 + n2 * mx2) / safe_nb
        mean_y = (n1 * my1 + n2 * my2) / safe_nb
        # var_x
        element_x1 = (n1 + 1) * mean_x - n1 * mx1
        vx = (
            vx1
            + (element_x1 - mx1) * (element_x1 - mean_x)
            - (element_x1 - mean_x) ** 2
        )
        element_x2 = (n2 + 1) * mean_x - n2 * mx2
        vx = (
            vx
            + vx2
            + (element_x2 - mx2) * (element_x2 - mean_x)
            - (element_x2 - mean_x) ** 2
        )
        element_y1 = (n1 + 1) * mean_y - n1 * my1
        vy = (
            vy1
            + (element_y1 - my1) * (element_y1 - mean_y)
            - (element_y1 - mean_y) ** 2
        )
        element_y2 = (n2 + 1) * mean_y - n2 * my2
        vy = (
            vy
            + vy2
            + (element_y2 - my2) * (element_y2 - mean_y)
            - (element_y2 - mean_y) ** 2
        )
        cxy = cxy1 + (element_x1 - mx1) * (element_y1 - mean_y) - (element_x1 - mean_x) * (element_y1 - mean_y)
        cxy = (
            cxy
            + cxy2
            + (element_x2 - mx2) * (element_y2 - mean_y)
            - (element_x2 - mean_x) * (element_y2 - mean_y)
        )
        return mean_x, mean_y, vx, vy, cxy, nb

    state = (means_x[0], means_y[0], vars_x[0], vars_y[0], corrs_xy[0], nbs[0])
    for i in range(1, means_x.shape[0]):
        state = merge(state, (means_x[i], means_y[i], vars_x[i], vars_y[i], corrs_xy[i], nbs[i]))
    return state


def pearson_corrcoef(preds: Array, target: Array) -> Array:
    """Pearson correlation coefficient (reference ``pearson.py:141``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import pearson_corrcoef
        >>> preds = np.array([2.5, 1.0, 2.0, 8.0], np.float32)
        >>> target = np.array([3.0, 0.5, 2.0, 7.0], np.float32)
        >>> print(f"{float(pearson_corrcoef(preds, target)):.4f}")
        0.9838
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    d = preds.shape[1] if preds.ndim == 2 else 1
    shape = (d,) if d > 1 else ()
    zeros = jnp.zeros(shape, jnp.float32)
    _, _, var_x, var_y, corr_xy, nb = _pearson_corrcoef_update(
        preds, target, zeros, zeros, zeros, zeros, zeros, jnp.zeros((), jnp.float32), num_outputs=d
    )
    return _pearson_corrcoef_compute(var_x, var_y, corr_xy, nb)
