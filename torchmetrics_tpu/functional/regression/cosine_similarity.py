"""Cosine-similarity kernels (reference
``src/torchmetrics/functional/regression/cosine_similarity.py``)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape


def _cosine_similarity_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    if preds.ndim != 2:
        raise ValueError(f"Expected input to cosine similarity to be 2D tensors of shape `[N,D]`,"
                         f" but got {preds.ndim}D")
    return preds.astype(jnp.float32), target.astype(jnp.float32)


def _cosine_similarity_compute(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    dot = jnp.sum(preds * target, axis=-1)
    norm = jnp.linalg.norm(preds, axis=-1) * jnp.linalg.norm(target, axis=-1)
    sim = dot / jnp.where(norm == 0, 1.0, norm)
    if reduction == "sum":
        return jnp.sum(sim)
    if reduction == "mean":
        return jnp.mean(sim)
    if reduction in ("none", None):
        return sim
    raise ValueError(f"Expected reduction to be one of `['sum', 'mean', 'none', None]` but got {reduction}")


def cosine_similarity(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    """Cosine similarity (reference ``cosine_similarity.py:62``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import cosine_similarity
        >>> preds = np.array([[1.0, 0.0], [1.0, 1.0]], np.float32)
        >>> target = np.array([[1.0, 0.0], [0.0, 1.0]], np.float32)
        >>> print(f"{float(cosine_similarity(preds, target, reduction='mean')):.4f}")
        0.8536
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    preds, target = _cosine_similarity_update(preds, target)
    return _cosine_similarity_compute(preds, target, reduction)
