"""Kendall rank-correlation kernels (reference
``src/torchmetrics/functional/regression/kendall.py``).

τ-a / τ-b / τ-c with optional p-value. Pair statistics are computed with an O(N²) vectorised
comparison matrix — a single fused XLA kernel; fine for the cat-state sizes metrics see (the
reference's merge-sort discordance count is an inherently sequential host algorithm).
"""
from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.regression.utils import _check_data_shape_to_num_outputs

_ALLOWED_VARIANTS = ("a", "b", "c")


def _kendall_stats_1d(preds: Array, target: Array) -> Tuple[Array, Array, Array, Array, Array]:
    """(concordant, discordant, ties_x_only, ties_y_only, n) over all pairs i<j."""
    dx = preds[:, None] - preds[None, :]
    dy = target[:, None] - target[None, :]
    mask = jnp.triu(jnp.ones((preds.shape[0], preds.shape[0]), bool), k=1)
    sx = jnp.sign(dx)
    sy = jnp.sign(dy)
    prod = sx * sy
    con = jnp.sum((prod > 0) & mask)
    dis = jnp.sum((prod < 0) & mask)
    tx = jnp.sum((sx == 0) & (sy != 0) & mask)  # ties only in x
    ty = jnp.sum((sy == 0) & (sx != 0) & mask)
    return (
        con.astype(jnp.float32),
        dis.astype(jnp.float32),
        tx.astype(jnp.float32),
        ty.astype(jnp.float32),
        jnp.asarray(preds.shape[0], jnp.float32),
    )


def _kendall_tau_1d(preds: Array, target: Array, variant: str) -> Array:
    con, dis, tx, ty, n = _kendall_stats_1d(preds, target)
    if variant == "a":
        tot = n * (n - 1) / 2
        return (con - dis) / tot
    if variant == "b":
        denom = jnp.sqrt((con + dis + tx) * (con + dis + ty))
        return (con - dis) / jnp.where(denom == 0, 1.0, denom)
    # tau-c: needs distinct-value counts; computed trace-unsafe only via host path in practice,
    # approximate with min(unique_x, unique_y) via sorted comparison (static shapes)
    ux = jnp.sum(jnp.concatenate([jnp.ones((1,), bool), jnp.sort(preds)[1:] != jnp.sort(preds)[:-1]]))
    uy = jnp.sum(jnp.concatenate([jnp.ones((1,), bool), jnp.sort(target)[1:] != jnp.sort(target)[:-1]]))
    m = jnp.minimum(ux, uy).astype(jnp.float32)
    return 2 * (con - dis) / (n * n * (m - 1) / jnp.where(m == 0, 1.0, m))


def _tie_moments_1d(x: Array) -> Tuple[Array, Array, Array]:
    """(Σt(t-1)/2, Σt(t-1)(t-2), Σt(t-1)(2t+5)) over tie groups of ``x`` (jit-safe)."""
    import jax

    n = x.shape[0]
    s = jnp.sort(x)
    is_new = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    group_id = jnp.cumsum(is_new) - 1
    t = jax.ops.segment_sum(jnp.ones(n, jnp.float32), group_id, num_segments=n)
    return (
        jnp.sum(t * (t - 1)) / 2,
        jnp.sum(t * (t - 1) * (t - 2)),
        jnp.sum(t * (t - 1) * (2 * t + 5)),
    )


def _kendall_pvalue_1d(
    preds: Array, target: Array, variant: str = "b", alternative: str = "two-sided"
) -> Array:
    """Asymptotic normal-approximation p-value with tie corrections (reference
    ``kendall.py:192-223``); ``alternative`` picks the tail."""
    from jax.scipy.stats import norm

    con, dis, _, _, n = _kendall_stats_1d(preds, target)
    con_min_dis = con - dis
    base = n * (n - 1) * (2 * n + 5)
    if variant == "a":
        t_value = 3 * con_min_dis / jnp.sqrt(base / 2)
    else:
        xtie, x1, x2 = _tie_moments_1d(preds)
        ytie, y1, y2 = _tie_moments_1d(target)
        m = n * (n - 1)
        denom = (base - x2 - y2) / 18
        denom = denom + (2 * xtie * ytie) / m
        denom = denom + x1 * y1 / (9 * m * (n - 2))
        t_value = con_min_dis / jnp.sqrt(denom)
    if alternative == "two-sided":
        return 2 * norm.cdf(-jnp.abs(t_value))
    if alternative == "greater":
        return norm.cdf(-t_value)
    return norm.cdf(t_value)  # "less"


def kendall_rank_corrcoef(
    preds: Array,
    target: Array,
    variant: str = "b",
    t_test: bool = False,
    alternative: Optional[str] = "two-sided",
) -> Union[Array, Tuple[Array, Array]]:
    """Kendall rank correlation (reference ``kendall.py:270``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import kendall_rank_corrcoef
        >>> preds = np.array([2.5, 1.0, 2.0, 8.0], np.float32)
        >>> target = np.array([3.0, 0.5, 2.0, 7.0], np.float32)
        >>> print(f"{float(kendall_rank_corrcoef(preds, target)):.4f}")
        1.0000
    """
    if variant not in _ALLOWED_VARIANTS:
        raise ValueError(f"Argument `variant` is expected to be one of {_ALLOWED_VARIANTS}, but got {variant}")
    if not isinstance(t_test, bool):
        raise ValueError(f"Argument `t_test` must be of a type `bool`, but got {t_test}.")
    if t_test and alternative not in ("two-sided", "less", "greater"):
        raise ValueError("Argument `alternative` is expected to be one of 'two-sided', 'less' or 'greater'.")
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    num_outputs = 1 if preds.ndim == 1 else preds.shape[1]
    _check_data_shape_to_num_outputs(preds, target, num_outputs)
    if preds.ndim == 1:
        tau = _kendall_tau_1d(preds, target, variant)
        if t_test:
            return tau, _kendall_pvalue_1d(preds, target, variant, alternative)
        return tau
    taus = jnp.stack([_kendall_tau_1d(preds[:, i], target[:, i], variant) for i in range(preds.shape[1])])
    if t_test:
        ps = jnp.stack(
            [_kendall_pvalue_1d(preds[:, i], target[:, i], variant, alternative) for i in range(preds.shape[1])]
        )
        return taus, ps
    return taus
