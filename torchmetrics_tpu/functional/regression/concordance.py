"""Concordance correlation coefficient kernels (reference
``src/torchmetrics/functional/regression/concordance.py``).

CCC = 2·ρ·σx·σy / (σx² + σy² + (μx − μy)²), computed from the Pearson running state.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.regression.pearson import _pearson_corrcoef_update


def _concordance_corrcoef_compute(
    mean_x: Array, mean_y: Array, var_x: Array, var_y: Array, corr_xy: Array, nb: Array
) -> Array:
    """Reference ``concordance.py:24`` — unbiased (n-1) variances (the reference's in-place
    ``/=`` inside its pearson compute normalises var/cov by nb-1 before the CCC formula)."""
    vx = var_x / (nb - 1)
    vy = var_y / (nb - 1)
    cxy = corr_xy / (nb - 1)
    return jnp.squeeze(2.0 * cxy / (vx + vy + (mean_x - mean_y) ** 2))


def concordance_corrcoef(preds: Array, target: Array) -> Array:
    """Concordance correlation coefficient (reference ``concordance.py:58``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import concordance_corrcoef
        >>> preds = np.array([2.5, 1.0, 2.0, 8.0], np.float32)
        >>> target = np.array([3.0, 0.5, 2.0, 7.0], np.float32)
        >>> print(f"{float(concordance_corrcoef(preds, target)):.4f}")
        0.9729
    """
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    d = preds.shape[1] if preds.ndim == 2 else 1
    shape = (d,) if d > 1 else ()
    zeros = jnp.zeros(shape, jnp.float32)
    mean_x, mean_y, var_x, var_y, corr_xy, nb = _pearson_corrcoef_update(
        preds, target, zeros, zeros, zeros, zeros, zeros, jnp.zeros((), jnp.float32), num_outputs=d
    )
    return _concordance_corrcoef_compute(mean_x, mean_y, var_x, var_y, corr_xy, nb)
