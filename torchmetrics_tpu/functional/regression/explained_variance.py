"""Explained-variance kernels (reference
``src/torchmetrics/functional/regression/explained_variance.py``).

State = first/second moments of target + error sums — O(num_outputs) memory, single psum sync.
"""
from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape

ALLOWED_MULTIOUTPUT = ("raw_values", "uniform_average", "variance_weighted")


def _explained_variance_update(preds: Array, target: Array) -> Tuple[Array, Array, Array, Array, Array]:
    """(n, Σerr, Σerr², Σy, Σy²) per output column."""
    _check_same_shape(preds, target)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    if preds.ndim == 1:
        preds = preds[:, None]
        target = target[:, None]
    diff = target - preds
    n_obs = jnp.asarray(preds.shape[0], jnp.float32)
    return (
        n_obs,
        jnp.sum(diff, axis=0),
        jnp.sum(diff * diff, axis=0),
        jnp.sum(target, axis=0),
        jnp.sum(target * target, axis=0),
    )


def _explained_variance_compute(
    n_obs: Array,
    sum_error: Array,
    sum_squared_error: Array,
    sum_target: Array,
    sum_squared_target: Array,
    multioutput: str = "uniform_average",
) -> Array:
    diff_avg = sum_error / n_obs
    numerator = sum_squared_error / n_obs - diff_avg * diff_avg
    target_avg = sum_target / n_obs
    denominator = sum_squared_target / n_obs - target_avg * target_avg
    nonzero_numerator = numerator != 0
    nonzero_denominator = denominator != 0
    valid = nonzero_numerator & nonzero_denominator
    output_scores = jnp.where(
        valid,
        1.0 - numerator / jnp.where(valid, denominator, 1.0),
        jnp.where(nonzero_numerator, 0.0, 1.0),
    )
    output_scores = jnp.squeeze(output_scores) if output_scores.shape == (1,) else output_scores
    if multioutput == "raw_values":
        return output_scores
    if multioutput == "uniform_average":
        return jnp.mean(output_scores)
    denom_sum = jnp.sum(denominator)
    return jnp.sum(jnp.atleast_1d(output_scores) * denominator) / jnp.where(denom_sum == 0, 1.0, denom_sum)


def explained_variance(
    preds: Array, target: Array, multioutput: str = "uniform_average"
) -> Array:
    """Explained variance (reference ``explained_variance.py:84``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import explained_variance
        >>> preds = np.array([2.5, 1.0, 2.0, 8.0], np.float32)
        >>> target = np.array([3.0, 0.5, 2.0, 7.0], np.float32)
        >>> print(f"{float(explained_variance(preds, target)):.4f}")
        0.9461
    """
    if multioutput not in ALLOWED_MULTIOUTPUT:
        raise ValueError(f"Invalid input to argument `multioutput`. Choose one of {ALLOWED_MULTIOUTPUT}")
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    return _explained_variance_compute(*_explained_variance_update(preds, target), multioutput)
