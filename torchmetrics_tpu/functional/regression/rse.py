"""Relative-squared-error kernels (reference ``src/torchmetrics/functional/regression/rse.py``).

RSE = Σ(y−ŷ)² / Σ(y−ȳ)², with ȳ the GLOBAL target mean — the denominator is reconstructed from
(Σy², Σy, n) moments so the state stays O(num_outputs).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.regression.r2 import _r2_score_update


def _relative_squared_error_compute(
    sum_squared_obs: Array,
    sum_obs: Array,
    rss: Array,
    num_obs: Array,
    squared: bool = True,
) -> Array:
    """Reference ``rse.py:22``."""
    epsilon = jnp.finfo(jnp.float32).eps
    tss = sum_squared_obs - sum_obs * sum_obs / num_obs
    rse = rss / jnp.clip(tss, min=epsilon)
    if not squared:
        rse = jnp.sqrt(rse)
    return jnp.mean(rse)


def relative_squared_error(preds: Array, target: Array, squared: bool = True) -> Array:
    """Relative squared error (reference ``rse.py:49``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import relative_squared_error
        >>> preds = np.array([2.5, 1.0, 2.0, 8.0], np.float32)
        >>> target = np.array([3.0, 0.5, 2.0, 7.0], np.float32)
        >>> print(f"{float(relative_squared_error(preds, target)):.4f}")
        0.0647
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    sum_squared_obs, sum_obs, rss, num_obs = _r2_score_update(preds, target)
    return _relative_squared_error_compute(sum_squared_obs, sum_obs, rss, num_obs, squared)
