"""Spearman rank-correlation kernels (reference
``src/torchmetrics/functional/regression/spearman.py``).

Ranks (average-tie) computed with a double argsort + tie segment-mean — O(N log N), jit-safe.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.regression.utils import _check_data_shape_to_num_outputs


def _rank_data(data: Array) -> Array:
    """Average-tie ranks of a 1-D array (1-based), matching scipy's 'average' method."""
    n = data.shape[0]
    order = jnp.argsort(data)
    sorted_data = data[order]
    ranks_sorted = jnp.arange(1, n + 1, dtype=jnp.float32)
    # average ranks over tie groups: group id = index of first equal element
    is_new = jnp.concatenate([jnp.ones((1,), bool), sorted_data[1:] != sorted_data[:-1]])
    group_id = jnp.cumsum(is_new) - 1
    import jax

    group_sum = jax.ops.segment_sum(ranks_sorted, group_id, num_segments=n)
    group_cnt = jax.ops.segment_sum(jnp.ones(n, jnp.float32), group_id, num_segments=n)
    avg = group_sum / jnp.maximum(group_cnt, 1.0)
    ranks_avg_sorted = avg[group_id]
    out = jnp.zeros(n, jnp.float32).at[order].set(ranks_avg_sorted)
    return out


def _spearman_corrcoef_compute(preds: Array, target: Array, eps: float = 1.17e-06) -> Array:
    """Pearson over ranks (reference ``spearman.py:54``)."""
    if preds.ndim == 1:
        rp = _rank_data(preds)
        rt = _rank_data(target)
    else:
        rp = jnp.stack([_rank_data(preds[:, i]) for i in range(preds.shape[1])], axis=1)
        rt = jnp.stack([_rank_data(target[:, i]) for i in range(target.shape[1])], axis=1)
    pd = rp - jnp.mean(rp, axis=0)
    td = rt - jnp.mean(rt, axis=0)
    cov = jnp.mean(pd * td, axis=0)
    corr = cov / jnp.clip(jnp.sqrt(jnp.mean(pd * pd, axis=0) * jnp.mean(td * td, axis=0)), min=eps)
    return jnp.squeeze(jnp.clip(corr, -1.0, 1.0))


def spearman_corrcoef(preds: Array, target: Array) -> Array:
    """Spearman rank correlation (reference ``spearman.py:80``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import spearman_corrcoef
        >>> preds = np.array([2.5, 1.0, 2.0, 8.0], np.float32)
        >>> target = np.array([3.0, 0.5, 2.0, 7.0], np.float32)
        >>> print(f"{float(spearman_corrcoef(preds, target)):.4f}")
        1.0000
    """
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    num_outputs = 1 if preds.ndim == 1 else preds.shape[1]
    _check_data_shape_to_num_outputs(preds, target, num_outputs)
    return _spearman_corrcoef_compute(preds, target)
