"""Minkowski distance kernels (reference ``src/torchmetrics/functional/regression/minkowski.py``)."""
from __future__ import annotations

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError


def _minkowski_distance_update(preds: Array, target: Array, p: float) -> Array:
    _check_same_shape(preds, target)
    if not (isinstance(p, (float, int)) and p >= 1):
        raise TorchMetricsUserError(f"Argument ``p`` must be a float or int greater than 1, but got {p}")
    diff = jnp.abs(preds.astype(jnp.float32) - target.astype(jnp.float32))
    return jnp.sum(jnp.power(diff, p))


def _minkowski_distance_compute(distance: Array, p: float) -> Array:
    return jnp.power(distance, 1.0 / p)


def minkowski_distance(preds: Array, targets: Array, p: float) -> Array:
    """Minkowski distance (reference ``minkowski.py:44`` — which names the second argument
    ``targets``, unlike the rest of the API).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import minkowski_distance
        >>> preds = np.array([1.0, 2.0, 3.0], np.float32)
        >>> targets = np.array([1.5, 2.5, 4.0], np.float32)
        >>> print(f"{float(minkowski_distance(preds, targets, p=3)):.4f}")
        1.0772
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(targets)
    distance = _minkowski_distance_update(preds, target, p)
    return _minkowski_distance_compute(distance, p)
