"""Mean-squared-log-error kernels (reference ``src/torchmetrics/functional/regression/log_mse.py``)."""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape


def _mean_squared_log_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    d = jnp.log1p(preds) - jnp.log1p(target)
    return jnp.sum(d * d), jnp.asarray(preds.size, jnp.float32)


def mean_squared_log_error(preds: Array, target: Array) -> Array:
    """MSLE (reference ``log_mse.py:47``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    s, n = _mean_squared_log_error_update(preds, target)
    return s / n
