"""Mean-squared-log-error kernels (reference ``src/torchmetrics/functional/regression/log_mse.py``)."""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape


def _mean_squared_log_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    d = jnp.log1p(preds) - jnp.log1p(target)
    return jnp.sum(d * d), jnp.asarray(preds.size, jnp.float32)


def mean_squared_log_error(preds: Array, target: Array) -> Array:
    """MSLE (reference ``log_mse.py:47``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import mean_squared_log_error
        >>> preds = np.array([2.5, 1.0, 2.0, 8.0], np.float32)
        >>> target = np.array([3.0, 0.5, 2.0, 7.0], np.float32)
        >>> print(f"{float(mean_squared_log_error(preds, target)):.4f}")
        0.0286
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    s, n = _mean_squared_log_error_update(preds, target)
    return s / n
