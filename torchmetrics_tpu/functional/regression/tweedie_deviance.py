"""Tweedie deviance kernels (reference
``src/torchmetrics/functional/regression/tweedie_deviance.py``)."""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape
from torchmetrics_tpu.utils.compute import _safe_xlogy


def _domain_check(preds: Array, target: Array, power: float) -> None:
    """Eager-only domain validation (reference ``tweedie_deviance.py:51-73``); no-op under trace."""
    import numpy as np

    from torchmetrics_tpu.utils.checks import is_traced

    if is_traced(preds, target):
        return
    p = np.asarray(preds)
    t = np.asarray(target)
    if 0 < power < 1:
        raise ValueError(f"Deviance Score is not defined for power={power}.")
    if power < 0 and np.any(p <= 0):
        raise ValueError(f"For power={power}, 'preds' has to be strictly positive.")
    if 1 <= power < 2 and (np.any(t < 0) or np.any(p <= 0)):
        raise ValueError(f"For power={power}, 'preds' must be strictly positive and 'targets' cannot be negative.")
    if power >= 2 and (np.any(t <= 0) or np.any(p <= 0)):
        raise ValueError(f"For power={power}, both 'preds' and 'targets' must be strictly positive.")


def _tweedie_deviance_score_update(preds: Array, target: Array, power: float = 0.0) -> Tuple[Array, Array]:
    """Reference ``tweedie_deviance.py:26``; branches on the static ``power`` argument."""
    _check_same_shape(preds, target)
    _domain_check(preds, target, power)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    if power < 0:  # extreme stable distribution: any power < 0 is valid
        deviance_score = 2 * (
            jnp.power(jnp.maximum(target, 0), 2 - power) / ((1 - power) * (2 - power))
            - target * jnp.power(preds, 1 - power) / (1 - power)
            + jnp.power(preds, 2 - power) / (2 - power)
        )
    elif power == 0:
        deviance_score = jnp.power(target - preds, 2)
    elif power == 1:
        deviance_score = 2 * (_safe_xlogy(target, target / preds) - target + preds)
    elif power == 2:
        deviance_score = 2 * (jnp.log(preds / target) + target / preds - 1)
    elif (1 < power < 2) or power > 2:
        deviance_score = 2 * (
            jnp.power(target, 2 - power) / ((1 - power) * (2 - power))
            - target * jnp.power(preds, 1 - power) / (1 - power)
            + jnp.power(preds, 2 - power) / (2 - power)
        )
    else:
        raise ValueError(f"Deviance Score is not defined for power={power}.")
    return jnp.sum(deviance_score), jnp.asarray(target.size, jnp.float32)


def _tweedie_deviance_score_compute(sum_deviance_score: Array, num_observations: Array) -> Array:
    return sum_deviance_score / num_observations


def tweedie_deviance_score(preds: Array, targets: Array, power: float = 0.0) -> Array:
    """Tweedie deviance score (reference ``tweedie_deviance.py:100`` — which names the second
    argument ``targets``, unlike the rest of the API).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import tweedie_deviance_score
        >>> preds = np.array([1.0, 2.0, 3.0], np.float32)
        >>> targets = np.array([1.5, 2.5, 4.0], np.float32)
        >>> print(f"{float(tweedie_deviance_score(preds, targets, power=1.5)):.4f}")
        0.1489
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(targets)
    s, n = _tweedie_deviance_score_update(preds, target, power)
    return _tweedie_deviance_score_compute(s, n)
