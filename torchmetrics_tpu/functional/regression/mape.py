"""MAPE / SMAPE / weighted-MAPE kernels (reference
``src/torchmetrics/functional/regression/{mape,symmetric_mape,wmape}.py``)."""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape

_EPS = 1.17e-06  # the reference's epsilon for zero-denominator clamping


def _mean_abs_percentage_error_update(
    preds: Array, target: Array, epsilon: float = _EPS
) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    abs_per_error = jnp.abs(preds - target) / jnp.clip(jnp.abs(target), min=epsilon)
    return jnp.sum(abs_per_error), jnp.asarray(target.size, jnp.float32)


def _mean_abs_percentage_error_compute(sum_abs_per_error: Array, num_obs: Array) -> Array:
    return sum_abs_per_error / num_obs


def mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """MAPE (reference ``mape.py:54``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import mean_absolute_percentage_error
        >>> preds = np.array([2.5, 1.0, 2.0, 8.0], np.float32)
        >>> target = np.array([3.0, 0.5, 2.0, 7.0], np.float32)
        >>> print(f"{float(mean_absolute_percentage_error(preds, target)):.4f}")
        0.3274
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    s, n = _mean_abs_percentage_error_update(preds, target)
    return _mean_abs_percentage_error_compute(s, n)


def _symmetric_mape_update(
    preds: Array, target: Array, epsilon: float = _EPS
) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    abs_per_error = jnp.abs(preds - target) / jnp.clip(jnp.abs(target) + jnp.abs(preds), min=epsilon)
    return jnp.sum(2 * abs_per_error), jnp.asarray(target.size, jnp.float32)


def symmetric_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """SMAPE (reference ``symmetric_mape.py:51``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import symmetric_mean_absolute_percentage_error
        >>> preds = np.array([2.5, 1.0, 2.0, 8.0], np.float32)
        >>> target = np.array([3.0, 0.5, 2.0, 7.0], np.float32)
        >>> print(f"{float(symmetric_mean_absolute_percentage_error(preds, target)):.4f}")
        0.2455
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    s, n = _symmetric_mape_update(preds, target)
    return s / n


def _weighted_mape_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    return jnp.sum(jnp.abs(preds - target)), jnp.sum(jnp.abs(target))


def _weighted_mape_compute(
    sum_abs_error: Array, sum_scale: Array, epsilon: float = _EPS
) -> Array:
    return sum_abs_error / jnp.clip(sum_scale, min=epsilon)


def weighted_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """WMAPE (reference ``wmape.py:50``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import weighted_mean_absolute_percentage_error
        >>> preds = np.array([2.5, 1.0, 2.0, 8.0], np.float32)
        >>> target = np.array([3.0, 0.5, 2.0, 7.0], np.float32)
        >>> print(f"{float(weighted_mean_absolute_percentage_error(preds, target)):.4f}")
        0.1600
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    s, scale = _weighted_mape_update(preds, target)
    return _weighted_mape_compute(s, scale)
