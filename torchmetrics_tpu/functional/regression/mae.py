"""Mean-absolute-error kernels (reference ``src/torchmetrics/functional/regression/mae.py``)."""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape


def _mean_absolute_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    sum_abs_error = jnp.sum(jnp.abs(preds - target))
    return sum_abs_error, jnp.asarray(preds.size, jnp.float32)


def _mean_absolute_error_compute(sum_abs_error: Array, total: Array) -> Array:
    return sum_abs_error / total


def mean_absolute_error(preds: Array, target: Array) -> Array:
    """MAE (reference ``mae.py:46``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    sum_abs_error, total = _mean_absolute_error_update(preds, target)
    return _mean_absolute_error_compute(sum_abs_error, total)
