"""Mean-absolute-error kernels (reference ``src/torchmetrics/functional/regression/mae.py``)."""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape


def _mean_absolute_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    sum_abs_error = jnp.sum(jnp.abs(preds - target))
    return sum_abs_error, jnp.asarray(preds.size, jnp.float32)


def _mean_absolute_error_compute(sum_abs_error: Array, total: Array) -> Array:
    return sum_abs_error / total


def mean_absolute_error(preds: Array, target: Array) -> Array:
    """MAE (reference ``mae.py:46``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import mean_absolute_error
        >>> preds = np.array([2.5, 0.0, 2.0, 8.0], np.float32)
        >>> target = np.array([3.0, -0.5, 2.0, 7.0], np.float32)
        >>> print(f"{float(mean_absolute_error(preds, target)):.4f}")
        0.5000
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    sum_abs_error, total = _mean_absolute_error_update(preds, target)
    return _mean_absolute_error_compute(sum_abs_error, total)
