"""Mean-squared-error kernels (reference ``src/torchmetrics/functional/regression/mse.py``)."""
from __future__ import annotations

from typing import Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape


def _mean_squared_error_update(preds: Array, target: Array, num_outputs: int) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    if num_outputs == 1:
        preds = jnp.reshape(preds, (-1,))
        target = jnp.reshape(target, (-1,))
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=0)
    return sum_squared_error, jnp.asarray(target.shape[0], jnp.float32)


def _mean_squared_error_compute(sum_squared_error: Array, total: Array, squared: bool = True) -> Array:
    mse = sum_squared_error / total
    return mse if squared else jnp.sqrt(mse)


def mean_squared_error(
    preds: Array, target: Array, squared: bool = True, num_outputs: int = 1
) -> Array:
    """MSE (or RMSE with ``squared=False``) — reference ``mse.py:53``.

    Example:
        >>> from torchmetrics_tpu.functional.regression.mse import mean_squared_error
        >>> round(float(mean_squared_error([0.0, 1.0, 2.0], [0.5, 1.0, 1.5])), 6)
        0.166667
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    sum_squared_error, total = _mean_squared_error_update(preds, target, num_outputs)
    return _mean_squared_error_compute(sum_squared_error, total, squared)
