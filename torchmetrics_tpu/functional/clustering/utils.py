"""Shared clustering kernels (reference ``src/torchmetrics/functional/clustering/utils.py``).

TPU-first redesign:

- ``calculate_contingency_matrix`` (reference ``utils.py:119``) relies on ``torch.unique`` +
  sparse scatter — dynamic shapes. Here the relabel step (the only inherently dynamic part) runs
  ONCE on the host (``np.unique``), and the O(N*R*C) counting runs on device as a
  ``one_hot(target).T @ one_hot(preds)`` matmul on the MXU (same trick as
  ``torchmetrics_tpu.ops.histogram``).
- Downstream computes replace the reference's ``nonzero``-gather (``mutual_info_score.py:54``)
  with mask-and-weight: zero entries contribute identity elements, which XLA fuses into the
  reduction. No dynamic shapes anywhere on device.
"""
from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array


def check_cluster_labels(preds, target) -> None:
    """Host-side validation (reference ``utils.py:185``)."""
    if jnp.ndim(preds) != 1 or jnp.ndim(target) != 1:
        raise ValueError(f"`preds` and `target` must be 1d, but got {jnp.ndim(preds)} and {jnp.ndim(target)}.")
    if jnp.shape(preds) != jnp.shape(target):
        raise ValueError(f"Expected `preds` and `target` to have the same shape, got {jnp.shape(preds)} and {jnp.shape(target)}.")
    for name, x in (("preds", preds), ("target", target)):
        xn = np.asarray(x)
        if xn.size and (np.iscomplexobj(xn) or (xn.dtype.kind == "f" and not np.all(xn == np.floor(xn)))):
            raise ValueError(f"Expected real, discrete values for `{name}` but received {xn.dtype}.")


def relabel(x) -> Tuple[Array, int]:
    """Map arbitrary labels to ``0..K-1`` (host ``np.unique``, the one dynamic step)."""
    _, inv = np.unique(np.asarray(x), return_inverse=True)
    n = int(inv.max()) + 1 if inv.size else 0
    return jnp.asarray(inv, jnp.int32), n


def contingency_from_indices(target_idx: Array, preds_idx: Array, num_target: int, num_preds: int) -> Array:
    """(R, C) contingency matrix of pre-relabelled indices via MXU one-hot matmul."""
    oh_t = jax.nn.one_hot(target_idx, num_target, dtype=jnp.float32)  # (N, R)
    oh_p = jax.nn.one_hot(preds_idx, num_preds, dtype=jnp.float32)  # (N, C)
    return jnp.matmul(oh_t.T, oh_p, precision="highest")


def calculate_contingency_matrix(preds, target) -> Array:
    """(n_classes_target, n_classes_preds) contingency matrix (reference ``utils.py:119``)."""
    t_idx, n_t = relabel(target)
    p_idx, n_p = relabel(preds)
    return contingency_from_indices(t_idx, p_idx, max(n_t, 1), max(n_p, 1))


def calculate_entropy(x) -> Array:
    """Entropy of a label array (reference ``utils.py:47``)."""
    if jnp.shape(x)[0] == 0:
        return jnp.asarray(1.0)
    idx, k = relabel(x)
    p = jnp.bincount(idx, length=k).astype(jnp.float32)
    if k == 1:
        return jnp.asarray(0.0)
    n = p.sum()
    # all p > 0 after relabel (every unique value occurs), so logs are finite
    return -jnp.sum((p / n) * (jnp.log(p) - jnp.log(n)))


def calculate_generalized_mean(x: Array, p: Union[int, float, str]) -> Array:
    """Generalized mean (reference ``utils.py:78``)."""
    if isinstance(p, str):
        if p == "min":
            return jnp.min(x)
        if p == "geometric":
            return jnp.exp(jnp.mean(jnp.log(x)))
        if p == "arithmetic":
            return jnp.mean(x)
        if p == "max":
            return jnp.max(x)
        raise ValueError("'method' must be 'min', 'geometric', 'arirthmetic', or 'max'")
    return jnp.mean(x**p) ** (1.0 / p)


def _validate_average_method_arg(average_method: str) -> None:
    if average_method not in ("min", "geometric", "arithmetic", "max"):
        raise ValueError("Expected argument `average_method` to be one of `min`, `geometric`, `arithmetic`, `max`")


def calculate_pair_cluster_confusion_matrix(
    preds=None, target=None, contingency: Array = None
) -> Array:
    """2x2 pair confusion matrix (reference ``utils.py:217``) — pure arithmetic, trace-safe.

    Layout matches the REFERENCE, which is the transpose of sklearn's ``pair_confusion_matrix``
    off-diagonal convention (reference docstring example ``utils.py:256-260`` gives
    ``[[8, 2], [0, 2]]`` where sklearn gives ``[[8, 0], [2, 2]]``): here ``[0, 1]`` counts pairs
    that are together in ``target`` but split in ``preds``.
    """
    if preds is None and target is None and contingency is None:
        raise ValueError('You must provide either `preds` and `target` or `contingency`.')
    if preds is not None and target is not None and contingency is not None:
        raise ValueError('You must provide either `preds` and `target` or `contingency`, not both.')
    if preds is not None and target is not None:
        contingency = calculate_contingency_matrix(preds, target)
    if contingency is None:
        raise ValueError('You must provide `contingency` if `preds` and `target` are not provided.')
    contingency = contingency.astype(jnp.float32)
    num_samples = contingency.sum()
    sum_c = contingency.sum(axis=1)
    sum_k = contingency.sum(axis=0)
    sum_squared = (contingency**2).sum()
    m11 = sum_squared - num_samples
    m10 = (contingency * sum_k[None, :]).sum() - sum_squared
    m01 = (contingency.T * sum_c[None, :]).sum() - sum_squared
    m00 = num_samples**2 - m01 - m10 - sum_squared
    return jnp.stack([jnp.stack([m00, m01]), jnp.stack([m10, m11])])


def _validate_intrinsic_cluster_data(data, labels) -> None:
    """Reference ``utils.py:198``."""
    if jnp.ndim(data) != 2:
        raise ValueError(f"Expected 2D data, got {jnp.ndim(data)}D data instead")
    if not jnp.issubdtype(jnp.asarray(data).dtype, jnp.floating):
        raise ValueError("Expected floating point data, got non-floating point data instead")
    if jnp.ndim(labels) != 1:
        raise ValueError(f"Expected 1D labels, got {jnp.ndim(labels)}D labels instead")


def _validate_intrinsic_labels_to_samples(num_labels: int, num_samples: int) -> None:
    """Reference ``utils.py:208``."""
    if not 1 < num_labels < num_samples:
        raise ValueError(
            "Number of detected clusters must be greater than one and less than the number of samples."
            f"Got {num_labels} clusters and {num_samples} samples."
        )
