"""Intrinsic (data + labels) clustering metrics: Calinski-Harabasz, Davies-Bouldin, Dunn.

Reference: ``src/torchmetrics/functional/clustering/{calinski_harabasz_score,
davies_bouldin_score,dunn_index}.py``.

The reference loops over clusters with boolean-mask gathers (``calinski_harabasz_score.py:54-58``)
— one dynamic-shape slice per cluster. Here cluster means/dispersions are segment reductions:
``one_hot(labels).T @ data`` puts the centroid computation on the MXU, and per-sample deviations
are a single gather + reduction, so the whole metric is one fused device program independent of
the number of clusters.
"""
from __future__ import annotations

from itertools import combinations
from typing import Tuple, Union

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.clustering.utils import (
    _validate_intrinsic_cluster_data,
    _validate_intrinsic_labels_to_samples,
    relabel,
)


def _cluster_stats(data: Array, labels_idx: Array, k: int) -> Tuple[Array, Array]:
    """Per-cluster (counts, centroids) via one-hot matmul — MXU path, no per-cluster loop."""
    oh = jax.nn.one_hot(labels_idx, k, dtype=jnp.float32)  # (N, K)
    counts = oh.sum(axis=0)  # (K,)
    sums = jnp.matmul(oh.T, data.astype(jnp.float32), precision="highest")  # (K, d)
    centroids = sums / jnp.maximum(counts, 1.0)[:, None]
    return counts, centroids


def calinski_harabasz_score(data, labels) -> Array:
    """Variance-ratio criterion (reference ``calinski_harabasz_score.py:23``)."""
    _validate_intrinsic_cluster_data(data, labels)
    labels_idx, k = relabel(labels)
    data = jnp.asarray(data, jnp.float32)
    n = data.shape[0]
    _validate_intrinsic_labels_to_samples(k, n)

    counts, centroids = _cluster_stats(data, labels_idx, k)
    mean = data.mean(axis=0)
    between = jnp.sum(((centroids - mean[None, :]) ** 2).sum(axis=1) * counts)
    within = jnp.sum((data - centroids[labels_idx]) ** 2)
    return jnp.where(within == 0, 1.0, between * (n - k) / (jnp.maximum(within, 1e-38) * (k - 1.0)))


def davies_bouldin_score(data, labels) -> Array:
    """Davies-Bouldin score (reference ``davies_bouldin_score.py:23``)."""
    _validate_intrinsic_cluster_data(data, labels)
    labels_idx, k = relabel(labels)
    data = jnp.asarray(data, jnp.float32)
    n = data.shape[0]
    _validate_intrinsic_labels_to_samples(k, n)

    counts, centroids = _cluster_stats(data, labels_idx, k)
    # mean intra-cluster distance per cluster: segment-mean of ||x - c_label||
    dists = jnp.sqrt(jnp.maximum(((data - centroids[labels_idx]) ** 2).sum(axis=1), 0.0))
    intra = jax.ops.segment_sum(dists, labels_idx, num_segments=k) / jnp.maximum(counts, 1.0)

    diff = centroids[:, None, :] - centroids[None, :, :]
    centroid_distances = jnp.sqrt(jnp.maximum((diff**2).sum(axis=-1), 0.0))

    degenerate = jnp.allclose(intra, 0.0) | jnp.allclose(centroid_distances, 0.0)
    safe_cd = jnp.where(centroid_distances == 0, jnp.inf, centroid_distances)
    combined = intra[None, :] + intra[:, None]
    scores = jnp.max(combined / safe_cd, axis=1)
    return jnp.where(degenerate, 0.0, scores.mean())


def _dunn_index_update(data, labels, p: Union[int, float]) -> Tuple[Array, Array]:
    """Centroid distances + max intra-cluster distances (reference ``dunn_index.py:21``)."""
    labels_idx, k = relabel(labels)
    data = jnp.asarray(data, jnp.float32)
    _, centroids = _cluster_stats(data, labels_idx, k)
    pairs = list(combinations(range(k), 2))
    a = jnp.asarray([i for i, _ in pairs], jnp.int32)
    b = jnp.asarray([j for _, j in pairs], jnp.int32)
    inter = jnp.linalg.norm(centroids[a] - centroids[b], ord=p, axis=1)
    per_sample = jnp.linalg.norm(data - centroids[labels_idx], ord=p, axis=1)
    max_intra = jax.ops.segment_max(per_sample, labels_idx, num_segments=k)
    return inter, max_intra


def _dunn_index_compute(intercluster_distance: Array, max_intracluster_distance: Array) -> Array:
    """Reference ``dunn_index.py:49``."""
    return intercluster_distance.min() / max_intracluster_distance.max()


def dunn_index(data, labels, p: Union[int, float] = 2) -> Array:
    """Dunn index (reference ``dunn_index.py:63``)."""
    inter, max_intra = _dunn_index_update(data, labels, p)
    return _dunn_index_compute(inter, max_intra)
