"""Extrinsic (label-vs-label) clustering metrics.

Reference: ``src/torchmetrics/functional/clustering/{mutual_info_score,rand_score,
adjusted_rand_score,adjusted_mutual_info_score,normalized_mutual_info_score,
fowlkes_mallows_index,homogeneity_completeness_v_measure}.py``.

All computes are masked reductions over a fixed-shape contingency matrix — the reference's
``nonzero`` gathers (``mutual_info_score.py:53-55``) and the EMI triple Python loop
(``adjusted_mutual_info_score.py:101-124``, ported from sklearn's Cython) are replaced by
vectorized mask-and-weight kernels that XLA fuses and tiles.
"""
from __future__ import annotations

from typing import Literal, Union

import jax
import jax.numpy as jnp
from jax import Array
from jax.scipy.special import gammaln

from torchmetrics_tpu.functional.clustering.utils import (
    _validate_average_method_arg,
    calculate_contingency_matrix,
    calculate_entropy,
    calculate_generalized_mean,
    calculate_pair_cluster_confusion_matrix,
    check_cluster_labels,
)


def _entropy_from_marginal(counts: Array) -> Array:
    """Entropy of a label distribution given its count vector (a contingency marginal).

    After relabelling every marginal count is > 0, so this equals ``calculate_entropy`` on the
    raw labels without re-running the host ``np.unique`` pass.
    """
    counts = counts.astype(jnp.float32)
    if counts.shape[0] <= 1:
        return jnp.asarray(0.0)
    n = counts.sum()
    safe = jnp.maximum(counts, 1e-38)
    return -jnp.sum((counts / n) * (jnp.log(safe) - jnp.log(n)))


def _mutual_info_from_contingency(contingency: Array) -> Array:
    """MI from a contingency matrix — masked form of reference ``mutual_info_score.py:35``."""
    contingency = contingency.astype(jnp.float32)
    n = contingency.sum()
    u = contingency.sum(axis=1)
    v = contingency.sum(axis=0)
    if u.shape[0] == 1 or v.shape[0] == 1:  # single cluster on either side
        return jnp.asarray(0.0)
    pos = contingency > 0
    safe = jnp.where(pos, contingency, 1.0)
    log_outer = jnp.log(jnp.maximum(u, 1e-38))[:, None] + jnp.log(jnp.maximum(v, 1e-38))[None, :]
    terms = safe / n * (jnp.log(n) + jnp.log(safe) - log_outer)
    return jnp.sum(jnp.where(pos, terms, 0.0))


def mutual_info_score(preds, target) -> Array:
    """Mutual information between two clusterings (reference ``mutual_info_score.py:63``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import mutual_info_score
        >>> preds = np.array([0, 0, 1, 1, 2])
        >>> target = np.array([0, 0, 1, 2, 2])
        >>> print(f"{float(mutual_info_score(preds, target)):.4f}")
        0.7777
    """
    check_cluster_labels(preds, target)
    return _mutual_info_from_contingency(calculate_contingency_matrix(preds, target))


def rand_score(preds, target) -> Array:
    """Rand score (reference ``rand_score.py:62``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import rand_score
        >>> preds = np.array([0, 0, 1, 1, 2])
        >>> target = np.array([0, 0, 1, 2, 2])
        >>> print(f"{float(rand_score(preds, target)):.4f}")
        0.8000
    """
    check_cluster_labels(preds, target)
    contingency = calculate_contingency_matrix(preds, target)
    pair = calculate_pair_cluster_confusion_matrix(contingency=contingency)
    numerator = pair[0, 0] + pair[1, 1]
    denominator = pair.sum()
    return jnp.where(
        (numerator == denominator) | (denominator == 0), 1.0, numerator / jnp.maximum(denominator, 1e-38)
    ).astype(jnp.float32)


def adjusted_rand_score(preds, target) -> Array:
    """Adjusted Rand score (reference ``adjusted_rand_score.py:55``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import adjusted_rand_score
        >>> preds = np.array([0, 0, 1, 1, 2])
        >>> target = np.array([0, 0, 1, 2, 2])
        >>> print(f"{float(adjusted_rand_score(preds, target)):.4f}")
        0.3750
    """
    check_cluster_labels(preds, target)
    contingency = calculate_contingency_matrix(preds, target)
    pair = calculate_pair_cluster_confusion_matrix(contingency=contingency)
    tn, fp, fn, tp = pair[0, 0], pair[0, 1], pair[1, 0], pair[1, 1]
    denom = (tp + fn) * (fn + tn) + (tp + fp) * (fp + tn)
    return jnp.where((fn == 0) & (fp == 0), 1.0, 2.0 * (tp * tn - fn * fp) / jnp.maximum(denom, 1e-38)).astype(
        jnp.float32
    )


def expected_mutual_info_score(contingency: Array, n_samples: int) -> Array:
    """Expected MI under the hypergeometric null (reference ``adjusted_mutual_info_score.py:64``).

    The reference ports sklearn's Cython triple loop over ``(i, j, nij)``; here the whole grid is
    one masked elementwise kernel of shape (R, C, M+1) — embarrassingly parallel on the VPU.
    """
    contingency = contingency.astype(jnp.float32)
    a = contingency.sum(axis=1)  # (R,)
    b = contingency.sum(axis=0)  # (C,)
    if a.shape[0] == 1 or b.shape[0] == 1:
        return jnp.asarray(0.0)
    n = jnp.asarray(float(n_samples))
    max_nij = int(max(float(a.max()), float(b.max()))) + 1

    ai = a[:, None, None]  # (R,1,1)
    bj = b[None, :, None]  # (1,C,1)

    def _emi_chunk(nij: Array) -> Array:
        nk = nij[None, None, :]  # (1,1,M_chunk)
        start = jnp.maximum(1.0, ai + bj - n)
        end = jnp.minimum(ai, bj) + 1.0
        mask = (nk >= start) & (nk < end)
        nk_safe = jnp.maximum(nk, 1.0)
        term1 = nk_safe / n
        term2 = jnp.log(n) + jnp.log(nk_safe) - jnp.log(jnp.maximum(ai, 1e-38)) - jnp.log(jnp.maximum(bj, 1e-38))
        gln = (
            gammaln(ai + 1)
            + gammaln(bj + 1)
            + gammaln(n - ai + 1)
            + gammaln(n - bj + 1)
            - gammaln(n + 1)
            - gammaln(nk_safe + 1)
            - gammaln(jnp.maximum(ai - nk_safe, 0.0) + 1)
            - gammaln(jnp.maximum(bj - nk_safe, 0.0) + 1)
            - gammaln(jnp.maximum(n - ai - bj + nk_safe, 0.0) + 1)
        )
        return jnp.sum(jnp.where(mask, term1 * term2 * jnp.exp(gln), 0.0))

    # bound peak memory: the eager elementwise chain materializes ~10 (R,C,M) temporaries, so cap
    # the chunk at ~4M grid cells (reference instead runs an O(R*C*M) host triple-loop)
    r, c = int(a.shape[0]), int(b.shape[0])
    chunk = max(1, (1 << 22) // max(r * c, 1))
    if max_nij <= chunk:
        return _emi_chunk(jnp.arange(max_nij, dtype=jnp.float32))
    emi = jnp.asarray(0.0)
    for lo in range(0, max_nij, chunk):
        emi = emi + _emi_chunk(jnp.arange(lo, min(lo + chunk, max_nij), dtype=jnp.float32))
    return emi


def adjusted_mutual_info_score(
    preds, target, average_method: Literal["min", "geometric", "arithmetic", "max"] = "arithmetic"
) -> Array:
    """Adjusted mutual information (reference ``adjusted_mutual_info_score.py:27``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import adjusted_mutual_info_score
        >>> preds = np.array([0, 0, 1, 1, 2])
        >>> target = np.array([0, 0, 1, 2, 2])
        >>> print(f"{float(adjusted_mutual_info_score(preds, target)):.4f}")
        0.3750
    """
    _validate_average_method_arg(average_method)
    check_cluster_labels(preds, target)
    contingency = calculate_contingency_matrix(preds, target)
    mutual_info = _mutual_info_from_contingency(contingency)
    n_samples = jnp.shape(target)[0]
    emi = expected_mutual_info_score(contingency, n_samples)
    normalizer = calculate_generalized_mean(
        jnp.stack(
            [_entropy_from_marginal(contingency.sum(axis=0)), _entropy_from_marginal(contingency.sum(axis=1))]
        ),
        average_method,
    )
    denominator = normalizer - emi
    eps = jnp.finfo(jnp.float32).eps
    denominator = jnp.where(denominator < 0, jnp.minimum(denominator, -eps), jnp.maximum(denominator, eps))
    return (mutual_info - emi) / denominator


def normalized_mutual_info_score(
    preds, target, average_method: Literal["min", "geometric", "arithmetic", "max"] = "arithmetic"
) -> Array:
    """Normalized mutual information (reference ``normalized_mutual_info_score.py:28``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import normalized_mutual_info_score
        >>> preds = np.array([0, 0, 1, 1, 2])
        >>> target = np.array([0, 0, 1, 2, 2])
        >>> print(f"{float(normalized_mutual_info_score(preds, target)):.4f}")
        0.7372
    """
    check_cluster_labels(preds, target)
    _validate_average_method_arg(average_method)
    contingency = calculate_contingency_matrix(preds, target)
    mutual_info = _mutual_info_from_contingency(contingency)
    if float(jax.device_get(jnp.abs(mutual_info))) <= float(jnp.finfo(jnp.float32).eps):
        return mutual_info
    normalizer = calculate_generalized_mean(
        jnp.stack(
            [_entropy_from_marginal(contingency.sum(axis=0)), _entropy_from_marginal(contingency.sum(axis=1))]
        ),
        average_method,
    )
    return mutual_info / normalizer


def fowlkes_mallows_index(preds, target) -> Array:
    """Fowlkes-Mallows index (reference ``fowlkes_mallows_index.py:58``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import fowlkes_mallows_index
        >>> preds = np.array([0, 0, 1, 1, 2])
        >>> target = np.array([0, 0, 1, 2, 2])
        >>> print(f"{float(fowlkes_mallows_index(preds, target)):.4f}")
        0.5000
    """
    check_cluster_labels(preds, target)
    contingency = calculate_contingency_matrix(preds, target)
    n = jnp.shape(preds)[0]
    tk = jnp.sum(contingency**2) - n
    pk = jnp.sum(contingency.sum(axis=0) ** 2) - n
    qk = jnp.sum(contingency.sum(axis=1) ** 2) - n
    fm = jnp.sqrt(tk / jnp.maximum(pk, 1e-38)) * jnp.sqrt(tk / jnp.maximum(qk, 1e-38))
    return jnp.where(jnp.abs(tk) < 1e-8, 0.0, fm).astype(jnp.float32)


def _homogeneity_score_compute(preds, target):
    """Reference ``homogeneity_completeness_v_measure.py:23``."""
    check_cluster_labels(preds, target)
    if jnp.shape(target)[0] == 0:
        zero = jnp.asarray(0.0)
        return zero, zero, zero, zero
    entropy_target = calculate_entropy(target)
    entropy_preds = calculate_entropy(preds)
    mutual_info = mutual_info_score(preds, target)
    homogeneity = jnp.where(entropy_target > 0, mutual_info / jnp.maximum(entropy_target, 1e-38), 1.0)
    return homogeneity, mutual_info, entropy_preds, entropy_target


def homogeneity_score(preds, target) -> Array:
    """Homogeneity (reference ``homogeneity_completeness_v_measure.py:46``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import homogeneity_score
        >>> preds = np.array([0, 0, 1, 1, 2])
        >>> target = np.array([0, 0, 1, 2, 2])
        >>> print(f"{float(homogeneity_score(preds, target)):.4f}")
        0.7372
    """
    return _homogeneity_score_compute(preds, target)[0]


def completeness_score(preds, target) -> Array:
    """Completeness (reference ``homogeneity_completeness_v_measure.py:69``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import completeness_score
        >>> preds = np.array([0, 0, 1, 1, 2])
        >>> target = np.array([0, 0, 1, 2, 2])
        >>> print(f"{float(completeness_score(preds, target)):.4f}")
        0.7372
    """
    _, mutual_info, entropy_preds, _ = _homogeneity_score_compute(preds, target)
    return jnp.where(entropy_preds > 0, mutual_info / jnp.maximum(entropy_preds, 1e-38), 1.0)


def v_measure_score(preds, target, beta: Union[int, float] = 1.0) -> Array:
    """V-measure (reference ``homogeneity_completeness_v_measure.py:92``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import v_measure_score
        >>> preds = np.array([0, 0, 1, 1, 2])
        >>> target = np.array([0, 0, 1, 2, 2])
        >>> print(f"{float(v_measure_score(preds, target)):.4f}")
        0.7372
    """
    homogeneity, mutual_info, entropy_preds, entropy_target = _homogeneity_score_compute(preds, target)
    completeness = jnp.where(entropy_preds > 0, mutual_info / jnp.maximum(entropy_preds, 1e-38), 1.0)
    numerator = (1 + beta) * homogeneity * completeness
    denominator = beta * homogeneity + completeness
    return jnp.where(denominator > 0, numerator / jnp.maximum(denominator, 1e-38), 0.0)
