"""Fleiss' kappa inter-rater agreement (reference ``src/torchmetrics/functional/nominal/fleiss_kappa.py``)."""
from __future__ import annotations

from typing import Literal

import jax
import jax.numpy as jnp
from jax import Array


def _fleiss_kappa_update(ratings: Array, mode: Literal["counts", "probs"] = "counts") -> Array:
    """Convert ratings to a (n_samples, n_categories) counts matrix (reference ``fleiss_kappa.py:24``)."""
    ratings = jnp.asarray(ratings)
    if mode == "probs":
        if ratings.ndim != 3 or not jnp.issubdtype(ratings.dtype, jnp.floating):
            raise ValueError(
                "If argument ``mode`` is 'probs', ratings must have 3 dimensions with the format"
                " [n_samples, n_categories, n_raters] and be floating point."
            )
        n_categories = ratings.shape[1]
        picked = jnp.argmax(ratings, axis=1)  # (n_samples, n_raters)
        counts = jax.nn.one_hot(picked, n_categories, dtype=jnp.float32).sum(axis=1)
        return counts
    if mode == "counts" and (ratings.ndim != 2 or jnp.issubdtype(ratings.dtype, jnp.floating)):
        raise ValueError(
            "If argument ``mode`` is `counts`, ratings must have 2 dimensions with the format"
            " [n_samples, n_categories] and be none floating point."
        )
    return ratings


def _fleiss_kappa_compute(counts: Array) -> Array:
    """Kappa from the counts matrix (reference ``fleiss_kappa.py:43``)."""
    counts = counts.astype(jnp.float32)
    total = counts.shape[0]
    num_raters = counts.sum(axis=1).max()
    p_i = counts.sum(axis=0) / (total * num_raters)
    p_j = ((counts**2).sum(axis=1) - num_raters) / (num_raters * (num_raters - 1))
    p_bar = p_j.mean()
    pe_bar = (p_i**2).sum()
    return (p_bar - pe_bar) / (1 - pe_bar + 1e-5)


def fleiss_kappa(ratings: Array, mode: Literal["counts", "probs"] = "counts") -> Array:
    """Fleiss' kappa (reference ``fleiss_kappa.py:61``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import fleiss_kappa
        >>> ratings = np.array([[3, 2, 5], [4, 4, 2], [5, 3, 2]])  # [n_samples, n_categories] counts
        >>> print(f"{float(fleiss_kappa(ratings, mode='counts')):.4f}")
        -0.0550
    """
    if mode not in ("counts", "probs"):
        raise ValueError("Argument ``mode`` must be one of 'counts' or 'probs'.")
    counts = _fleiss_kappa_update(ratings, mode)
    return _fleiss_kappa_compute(counts)
