"""Cramer's V (reference ``src/torchmetrics/functional/nominal/cramers.py``)."""
from __future__ import annotations

import itertools
from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.nominal.utils import (
    _compute_bias_corrected_values,
    _compute_chi_squared,
    _effective_shape,
    _joint_relabel,
    _nominal_confmat_update,
    _nominal_input_validation,
    _unable_to_use_bias_correction_warning,
)
from torchmetrics_tpu.utils.checks import is_traced


def _cramers_v_update(
    preds, target, num_classes: int, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0
) -> Array:
    """Reference ``cramers.py:32``."""
    return _nominal_confmat_update(preds, target, num_classes, nan_strategy, nan_replace_value)


def _cramers_v_compute(confmat: Array, bias_correction: bool) -> Array:
    """Reference ``cramers.py:58``, masked instead of row/col-dropped."""
    confmat = confmat.astype(jnp.float32)
    cm_sum = confmat.sum()
    chi_squared = _compute_chi_squared(confmat, bias_correction)
    phi_squared = chi_squared / jnp.maximum(cm_sum, 1e-38)
    num_rows, num_cols = _effective_shape(confmat)

    if bias_correction:
        phi_squared_corrected, rows_corrected, cols_corrected = _compute_bias_corrected_values(
            phi_squared, num_rows, num_cols, cm_sum
        )
        min_corrected = jnp.minimum(rows_corrected, cols_corrected)
        if not is_traced(min_corrected) and float(min_corrected) == 1.0:
            _unable_to_use_bias_correction_warning(metric_name="Cramer's V")
        value = jnp.sqrt(phi_squared_corrected / jnp.maximum(min_corrected - 1, 1e-38))
        value = jnp.where(min_corrected == 1.0, jnp.nan, value)
    else:
        value = jnp.sqrt(phi_squared / jnp.maximum(jnp.minimum(num_rows - 1, num_cols - 1), 1e-38))
    return jnp.clip(value, 0.0, 1.0)


def cramers_v(
    preds,
    target,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Cramer's V statistic between two categorical series (reference ``cramers.py:88``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import cramers_v
        >>> preds = np.array([0, 1, 1, 2, 2, 2])
        >>> target = np.array([0, 1, 1, 2, 1, 2])
        >>> print(f"{float(cramers_v(preds, target)):.4f}")
        0.7328
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    preds = jnp.argmax(jnp.asarray(preds), axis=1) if jnp.ndim(preds) == 2 else preds
    target = jnp.argmax(jnp.asarray(target), axis=1) if jnp.ndim(target) == 2 else target
    p_idx, t_idx, num_classes = _joint_relabel(preds, target, nan_strategy, nan_replace_value)
    confmat = _cramers_v_update(p_idx, t_idx, num_classes)
    return _cramers_v_compute(confmat, bias_correction)


def cramers_v_matrix(
    matrix,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Pairwise Cramer's V over the columns of a (N, V) categorical matrix (reference ``cramers.py:141``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import cramers_v_matrix
        >>> matrix = np.array([[0, 0], [1, 1], [0, 1], [1, 1], [2, 2], [2, 0], [0, 0], [1, 2]])
        >>> np.asarray(cramers_v_matrix(matrix), np.float64).round(4).tolist()
        [[1.0, 0.0913], [0.0913, 1.0]]
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    matrix = np.asarray(matrix)
    num_variables = matrix.shape[1]
    out = np.ones((num_variables, num_variables), np.float32)
    for i, j in itertools.combinations(range(num_variables), 2):
        x, y = matrix[:, i], matrix[:, j]
        out[i, j] = out[j, i] = float(cramers_v(x, y, bias_correction, nan_strategy, nan_replace_value))
    return jnp.asarray(out)
