"""Shared nominal-association kernels (reference ``src/torchmetrics/functional/nominal/utils.py``).

TPU-first: the reference's ``_drop_empty_rows_and_cols`` (``utils.py:62``) is a dynamic-shape
boolean gather; here empty rows/columns stay in place and every downstream quantity is computed
with mask-and-weight — the effective row/column counts are masked sums, expected frequencies of
empty cells are exactly zero and contribute nothing. NaN "drop" becomes a zero sample weight in
the confusion-matrix matmul instead of a dynamic filter, so the whole update is one jitted
device program.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.ops.histogram import confusion_matrix_update
from torchmetrics_tpu.utils.prints import rank_zero_warn


def _nominal_input_validation(nan_strategy: str, nan_replace_value: Optional[float]) -> None:
    """Reference ``utils.py:23``."""
    if nan_strategy not in ("replace", "drop"):
        raise ValueError(
            f"Argument `nan_strategy` is expected to be one of `['replace', 'drop']`, but got {nan_strategy}"
        )
    if nan_strategy == "replace" and not isinstance(nan_replace_value, (float, int)):
        raise ValueError(
            "Argument `nan_replace` is expected to be of a type `int` or `float` when `nan_strategy = 'replace`, "
            f"but got {nan_replace_value}"
        )


def _nominal_confmat_update(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """(C, C) confusion-matrix contribution with NaN handling fused in (reference pattern of
    ``_cramers_v_update``, ``cramers.py:32``: argmax-if-2D → NaN handle → confmat).

    Rows of the contingency matrix are ``target`` categories, columns ``preds`` (matching
    ``_multiclass_confusion_matrix_update``). "drop" zero-weights NaN pairs instead of filtering.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.ndim == 2:
        preds = jnp.argmax(preds, axis=1)
    if target.ndim == 2:
        target = jnp.argmax(target, axis=1)
    preds_f = preds.astype(jnp.float32)
    target_f = target.astype(jnp.float32)
    nan_mask = jnp.isnan(preds_f) | jnp.isnan(target_f)
    if nan_strategy == "replace":
        preds_f = jnp.where(jnp.isnan(preds_f), nan_replace_value, preds_f)
        target_f = jnp.where(jnp.isnan(target_f), nan_replace_value, target_f)
        weights = None
    else:  # drop -> zero weight
        preds_f = jnp.where(nan_mask, 0.0, preds_f)
        target_f = jnp.where(nan_mask, 0.0, target_f)
        weights = (~nan_mask).astype(jnp.float32)
    return confusion_matrix_update(
        preds_f.astype(jnp.int32), target_f.astype(jnp.int32), num_classes, weights=weights, dtype=jnp.float32
    )


def _row_col_masks(confmat: Array) -> Tuple[Array, Array]:
    """Boolean masks of non-empty rows/columns (the masked analog of ``_drop_empty_rows_and_cols``)."""
    return confmat.sum(axis=1) > 0, confmat.sum(axis=0) > 0


def _effective_shape(confmat: Array) -> Tuple[Array, Array]:
    """Non-empty (rows, cols) counts as traced f32 scalars."""
    row_mask, col_mask = _row_col_masks(confmat)
    return jnp.sum(row_mask).astype(jnp.float32), jnp.sum(col_mask).astype(jnp.float32)


def _expected_freqs(confmat: Array) -> Array:
    """Outer-product expected frequencies (reference ``utils.py:35``); zero for empty cells."""
    rows = confmat.sum(axis=1)
    cols = confmat.sum(axis=0)
    return rows[:, None] * cols[None, :] / jnp.maximum(confmat.sum(), 1e-38)


def _compute_chi_squared(confmat: Array, bias_correction: bool) -> Array:
    """Chi-squared over non-empty cells (reference ``utils.py:41``), trace-safe.

    The reference mutates the confmat for the ``df == 1`` Yates-style correction; here both the
    raw and corrected statistics are computed and selected by ``where`` on the traced df.
    """
    expected = _expected_freqs(confmat)
    valid = expected > 0
    n_rows, n_cols = _effective_shape(confmat)
    df = n_rows * n_cols - n_rows - n_cols + 1.0

    safe_e = jnp.where(valid, expected, 1.0)
    chi_raw = jnp.sum(jnp.where(valid, (confmat - expected) ** 2 / safe_e, 0.0))
    if bias_correction:
        diff = expected - confmat
        corrected = confmat + jnp.sign(diff) * jnp.minimum(0.5, jnp.abs(diff))
        chi_corr = jnp.sum(jnp.where(valid, (corrected - expected) ** 2 / safe_e, 0.0))
        chi = jnp.where(df == 1.0, chi_corr, chi_raw)
    else:
        chi = chi_raw
    return jnp.where(df == 0.0, 0.0, chi)


def _compute_phi_squared_corrected(phi_squared: Array, num_rows: Array, num_cols: Array, confmat_sum: Array) -> Array:
    """Reference ``utils.py:85``."""
    return jnp.maximum(0.0, phi_squared - ((num_rows - 1) * (num_cols - 1)) / jnp.maximum(confmat_sum - 1, 1e-38))


def _compute_rows_and_cols_corrected(num_rows: Array, num_cols: Array, confmat_sum: Array) -> Tuple[Array, Array]:
    """Reference ``utils.py:98``."""
    denom = jnp.maximum(confmat_sum - 1, 1e-38)
    return num_rows - (num_rows - 1) ** 2 / denom, num_cols - (num_cols - 1) ** 2 / denom


def _compute_bias_corrected_values(
    phi_squared: Array, num_rows: Array, num_cols: Array, confmat_sum: Array
) -> Tuple[Array, Array, Array]:
    """Reference ``utils.py:105``."""
    return (
        _compute_phi_squared_corrected(phi_squared, num_rows, num_cols, confmat_sum),
        *_compute_rows_and_cols_corrected(num_rows, num_cols, confmat_sum),
    )


def _unable_to_use_bias_correction_warning(metric_name: str) -> None:
    rank_zero_warn(
        f"Unable to compute {metric_name} using bias correction. Consider setting `bias_correction=False`."
    )


def _joint_relabel(preds, target, nan_strategy: str, nan_replace_value):
    """Host-side joint relabel to dense 0..C-1 codes + class count for the public functionals.

    The reference counts unique values of the concat (``cramers.py:137``) but then indexes the
    confmat with the RAW codes — gapped codes (e.g. {0, 2}) crash its bincount/reshape. Relabeling
    through one joint ``np.unique`` keeps the same statistic for dense codes and makes gapped or
    arbitrary category values work instead of failing.
    """
    import numpy as np

    p = np.asarray(preds, np.float32).reshape(-1)
    t = np.asarray(target, np.float32).reshape(-1)
    if nan_strategy == "replace":
        p = np.nan_to_num(p, nan=nan_replace_value)
        t = np.nan_to_num(t, nan=nan_replace_value)
    else:
        keep = ~(np.isnan(p) | np.isnan(t))
        p, t = p[keep], t[keep]
    uniq, inv = np.unique(np.concatenate([p, t]), return_inverse=True)
    num_classes = max(len(uniq), 1)
    return (
        jnp.asarray(inv[: len(p)], jnp.int32),
        jnp.asarray(inv[len(p) :], jnp.int32),
        num_classes,
    )
