"""Pearson's contingency coefficient (reference ``src/torchmetrics/functional/nominal/pearson.py``)."""
from __future__ import annotations

import itertools
from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.nominal.utils import (
    _compute_chi_squared,
    _joint_relabel,
    _nominal_confmat_update,
    _nominal_input_validation,
)


def _pearsons_contingency_coefficient_update(
    preds, target, num_classes: int, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0
) -> Array:
    """Reference ``pearson.py:29``."""
    return _nominal_confmat_update(preds, target, num_classes, nan_strategy, nan_replace_value)


def _pearsons_contingency_coefficient_compute(confmat: Array) -> Array:
    """Reference ``pearson.py:56``."""
    confmat = confmat.astype(jnp.float32)
    cm_sum = confmat.sum()
    chi_squared = _compute_chi_squared(confmat, bias_correction=False)
    phi_squared = chi_squared / jnp.maximum(cm_sum, 1e-38)
    return jnp.clip(jnp.sqrt(phi_squared / (1 + phi_squared)), 0.0, 1.0)


def pearsons_contingency_coefficient(
    preds, target, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0
) -> Array:
    """Pearson's contingency coefficient (reference ``pearson.py:75``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import pearsons_contingency_coefficient
        >>> preds = np.array([0, 1, 1, 2, 2, 2])
        >>> target = np.array([0, 1, 1, 2, 1, 2])
        >>> print(f"{float(pearsons_contingency_coefficient(preds, target)):.4f}")
        0.7687
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    preds = jnp.argmax(jnp.asarray(preds), axis=1) if jnp.ndim(preds) == 2 else preds
    target = jnp.argmax(jnp.asarray(target), axis=1) if jnp.ndim(target) == 2 else target
    p_idx, t_idx, num_classes = _joint_relabel(preds, target, nan_strategy, nan_replace_value)
    confmat = _pearsons_contingency_coefficient_update(p_idx, t_idx, num_classes)
    return _pearsons_contingency_coefficient_compute(confmat)


def pearsons_contingency_coefficient_matrix(
    matrix, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0
) -> Array:
    """Pairwise coefficient over columns (reference ``pearson.py:129``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import pearsons_contingency_coefficient_matrix
        >>> matrix = np.array([[0, 0], [1, 1], [0, 1], [1, 1], [2, 2], [2, 0], [0, 0], [1, 2]])
        >>> np.asarray(pearsons_contingency_coefficient_matrix(matrix), np.float64).round(4).tolist()
        [[1.0, 0.607], [0.607, 1.0]]
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    matrix = np.asarray(matrix)
    num_variables = matrix.shape[1]
    out = np.ones((num_variables, num_variables), np.float32)
    for i, j in itertools.combinations(range(num_variables), 2):
        out[i, j] = out[j, i] = float(
            pearsons_contingency_coefficient(matrix[:, i], matrix[:, j], nan_strategy, nan_replace_value)
        )
    return jnp.asarray(out)
