"""Theil's U uncertainty coefficient (reference ``src/torchmetrics/functional/nominal/theils_u.py``)."""
from __future__ import annotations

import itertools
from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.nominal.utils import (
    _joint_relabel,
    _nominal_confmat_update,
    _nominal_input_validation,
)


def _conditional_entropy_compute(confmat: Array) -> Array:
    """H(X|Y) from the contingency matrix (reference ``theils_u.py:30``), masked nansum form."""
    confmat = confmat.astype(jnp.float32)
    total = jnp.maximum(confmat.sum(), 1e-38)
    p_xy = confmat / total
    p_y = confmat.sum(axis=1) / total  # rows are target=Y categories
    pos = p_xy > 0
    safe_xy = jnp.where(pos, p_xy, 1.0)
    safe_y = jnp.maximum(p_y, 1e-38)[:, None]
    return jnp.sum(jnp.where(pos, p_xy * (jnp.log(safe_y) - jnp.log(safe_xy)), 0.0))


def _theils_u_update(
    preds, target, num_classes: int, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0
) -> Array:
    """Reference ``theils_u.py:55``."""
    return _nominal_confmat_update(preds, target, num_classes, nan_strategy, nan_replace_value)


def _theils_u_compute(confmat: Array) -> Array:
    """Reference ``theils_u.py:84``: U = (H(X) - H(X|Y)) / H(X) with X = preds (columns)."""
    confmat = confmat.astype(jnp.float32)
    s_xy = _conditional_entropy_compute(confmat)
    total = jnp.maximum(confmat.sum(), 1e-38)
    p_x = confmat.sum(axis=0) / total
    pos = p_x > 0
    safe_x = jnp.where(pos, p_x, 1.0)
    s_x = -jnp.sum(jnp.where(pos, safe_x * jnp.log(safe_x), 0.0))
    return jnp.where(s_x == 0, 0.0, (s_x - s_xy) / jnp.maximum(s_x, 1e-38))


def theils_u(
    preds, target, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0
) -> Array:
    """Theil's U of preds given target — asymmetric (reference ``theils_u.py:107``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import theils_u
        >>> preds = np.array([0, 1, 1, 2, 2, 2])
        >>> target = np.array([0, 1, 1, 2, 1, 2])
        >>> print(f"{float(theils_u(preds, target)):.4f}")
        0.6853
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    preds = jnp.argmax(jnp.asarray(preds), axis=1) if jnp.ndim(preds) == 2 else preds
    target = jnp.argmax(jnp.asarray(target), axis=1) if jnp.ndim(target) == 2 else target
    p_idx, t_idx, num_classes = _joint_relabel(preds, target, nan_strategy, nan_replace_value)
    confmat = _theils_u_update(p_idx, t_idx, num_classes)
    return _theils_u_compute(confmat)


def theils_u_matrix(
    matrix, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0
) -> Array:
    """Pairwise (asymmetric) Theil's U over columns (reference ``theils_u.py:147``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import theils_u_matrix
        >>> matrix = np.array([[0, 0], [1, 1], [0, 1], [1, 1], [2, 2], [2, 0], [0, 0], [1, 2]])
        >>> np.asarray(theils_u_matrix(matrix), np.float64).round(4).tolist()
        [[1.0, 0.3987], [0.3987, 1.0]]
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    matrix = np.asarray(matrix)
    num_variables = matrix.shape[1]
    out = np.ones((num_variables, num_variables), np.float32)
    for i, j in itertools.permutations(range(num_variables), 2):
        out[i, j] = float(theils_u(matrix[:, i], matrix[:, j], nan_strategy, nan_replace_value))
    return jnp.asarray(out)
