"""Speech-to-Reverberation Modulation Energy Ratio (reference ``functional/audio/srmr.py:37``).

Self-contained implementation of the SRMR pipeline — no ``gammatone``/``torchaudio`` dependency
(unlike the reference, which delegates its filterbank design and IIR filtering to those
packages):

1. cochlear decomposition with Slaney's 4th-order gammatone ERB filterbank (coefficient design
   from the published Apple TR #35 formulas, the same tables the ``gammatone`` package encodes),
2. temporal envelopes via the analytic (Hilbert) signal,
3. an 8-channel Q=2 modulation filterbank over each envelope,
4. windowed modulation energy, and the ratio of low (first 4) to high (5..k*) modulation bands.

Numerics note: the modulation filters sit at 4–128 Hz against sample rates of 8–16 kHz, so
their poles are within ~1e-3 of the unit circle — single-precision IIR recursion visibly
drifts. The reference runs the whole pipeline in float64 on torch-CPU; this build keeps the
same contract by running the sequential IIR recursions on the host in numpy/scipy float64
(exactly like the PESQ/STOI host delegation, ``deps.py``), since TPUs have no fast f64 and a
65 k-step sequential scan has no accelerator win. Only the final scores land on device.
"""
from __future__ import annotations

from functools import lru_cache
from math import ceil, pi
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.utils.prints import rank_zero_warn

_EAR_Q = 9.26449  # Glasberg & Moore
_MIN_BW = 24.7


def _erb_space(low_freq: float, fs: int, n: int) -> np.ndarray:
    """Slaney ERB-spaced centre frequencies, high→low (as the gammatone package returns them)."""
    hi = fs / 2.0
    c = _EAR_Q * _MIN_BW
    return -c + np.exp(np.arange(1, n + 1) * (-np.log(hi + c) + np.log(low_freq + c)) / n) * (hi + c)


@lru_cache(maxsize=100)
def _make_erb_coeffs(fs: int, n_filters: int, low_freq: float) -> np.ndarray:
    """Slaney gammatone filter coefficients, rows [A0,A11,A12,A13,A14,A2,B0,B1,B2,gain]."""
    t = 1.0 / fs
    cf = _erb_space(low_freq, fs, n_filters)
    erb = ((cf / _EAR_Q) ** 1 + _MIN_BW**1) ** 1.0
    b = 1.019 * 2 * pi * erb

    arg = 2 * cf * pi * t
    vec = np.exp(2j * arg)
    k = np.exp(-b * t)

    a0 = t
    a2 = 0.0
    b0 = 1.0
    b1 = -2 * np.cos(arg) * k
    b2 = np.exp(-2 * b * t)

    rt_pos = np.sqrt(3 + 2**1.5)
    rt_neg = np.sqrt(3 - 2**1.5)
    common = 2 * t * np.cos(arg) * k
    a11 = -(common + 2 * rt_pos * t * np.sin(arg) * k) / 2
    a12 = -(common - 2 * rt_pos * t * np.sin(arg) * k) / 2
    a13 = -(common + 2 * rt_neg * t * np.sin(arg) * k) / 2
    a14 = -(common - 2 * rt_neg * t * np.sin(arg) * k) / 2

    def _gain_term(sign_rt: float, rt: np.ndarray) -> np.ndarray:
        return -2 * vec * t + 2 * np.exp(-(b * t) + 1j * arg) * t * (np.cos(arg) + sign_rt * rt * np.sin(arg))

    gain = np.abs(
        _gain_term(-1, rt_neg)
        * _gain_term(+1, rt_neg)
        * _gain_term(-1, rt_pos)
        * _gain_term(+1, rt_pos)
        / (-2 / np.exp(2 * b * t) - 2 * vec + 2 * (1 + vec) / np.exp(b * t)) ** 4
    )

    ones = np.ones_like(cf)
    return np.stack(
        [a0 * ones, a11, a12, a13, a14, a2 * ones, b0 * ones, b1, b2, gain], axis=1
    )


def _erb_filterbank(wave: np.ndarray, coefs: np.ndarray) -> np.ndarray:
    """Cascade of four 3-tap sections per channel: (B, T) -> (B, N, T), float64."""
    from scipy.signal import lfilter

    a_den = coefs[:, 6:9]  # B0, B1, B2 (denominator in Slaney's naming)
    out = np.empty((wave.shape[0], coefs.shape[0], wave.shape[1]), np.float64)
    for ch in range(coefs.shape[0]):
        a0, a11, a12, a13, a14, a2 = coefs[ch, :6]
        den = a_den[ch]
        y = lfilter([a0, a11, a2], den, wave, axis=-1)
        y = lfilter([a0, a12, a2], den, y, axis=-1)
        y = lfilter([a0, a13, a2], den, y, axis=-1)
        y = lfilter([a0, a14, a2], den, y, axis=-1)
        out[:, ch] = y / coefs[ch, 9]
    return out


def _hilbert_envelope(x: np.ndarray) -> np.ndarray:
    """|analytic signal| along the last axis, FFT length padded to a multiple of 16 (the
    reference pads identically, ``srmr.py:92-103`` — the pad slightly changes the spectrum, so
    matching it is required for numerical parity)."""
    time = x.shape[-1]
    n = time if time % 16 == 0 else ceil(time / 16) * 16
    xf = np.fft.fft(x, n=n, axis=-1)
    h = np.zeros(n)
    if n % 2 == 0:
        h[0] = h[n // 2] = 1
        h[1 : n // 2] = 2
    else:
        h[0] = 1
        h[1 : (n + 1) // 2] = 2
    return np.abs(np.fft.ifft(xf * h, axis=-1)[..., :time])


@lru_cache(maxsize=100)
def _modulation_filterbank(min_cf: float, max_cf: float, n: int, fs: float, q: int):
    """n log-spaced 2nd-order modulation bandpasses; returns (coeffs (n,2,3), low-cutoffs (n,))."""
    spacing = (max_cf / min_cf) ** (1.0 / (n - 1))
    cfs = min_cf * spacing ** np.arange(n)
    w0 = 2 * pi * cfs / fs
    wt = np.tan(w0 / 2)
    b0 = wt / q
    num = np.stack([b0, np.zeros(n), -b0], axis=1)
    den = np.stack([1 + b0 + wt**2, 2 * wt**2 - 2, 1 - b0 + wt**2], axis=1)
    low_cutoff = cfs - b0 * fs / (2 * pi)
    return np.stack([num, den], axis=1), low_cutoff


def _frame_energy(mod_out: np.ndarray, w_length: int, w_inc: int, num_frames: int) -> np.ndarray:
    """Hamming-windowed frame energies: (..., T) -> (..., num_frames)."""
    time = mod_out.shape[-1]
    pad = max(ceil(time / w_inc) * w_inc - time, w_length - time)
    if pad > 0:
        mod_out = np.concatenate(
            [mod_out, np.zeros((*mod_out.shape[:-1], pad), mod_out.dtype)], axis=-1
        )
    # torch.hamming_window(L+1, periodic=True)[:-1] == np.hamming(L+2)[:L]
    window = np.hamming(w_length + 2)[:w_length]
    starts = np.arange(num_frames) * w_inc
    idx = starts[:, None] + np.arange(w_length)[None, :]
    frames = mod_out[..., idx]  # (..., num_frames, w_length)
    return ((frames * window) ** 2).sum(axis=-1)


def _normalize_energy(energy: np.ndarray, drange: float = 30.0) -> np.ndarray:
    """Clamp to a 30 dB dynamic range below the peak (reference ``srmr.py:147-160``)."""
    peak = energy.mean(axis=1, keepdims=True).max(axis=2, keepdims=True).max(axis=3, keepdims=True)
    floor = peak * 10.0 ** (-drange / 10.0)
    return np.clip(energy, floor, peak)


def _srmr_arg_validate(
    fs: int, n_cochlear_filters: int, low_freq: float, min_cf: float, max_cf: Optional[float], norm: bool, fast: bool
) -> None:
    if not (isinstance(fs, int) and fs > 0):
        raise ValueError(f"Expected argument `fs` to be an int larger than 0, but got {fs}")
    if not (isinstance(n_cochlear_filters, int) and n_cochlear_filters > 0):
        raise ValueError(
            f"Expected argument `n_cochlear_filters` to be an int larger than 0, but got {n_cochlear_filters}"
        )
    if not (isinstance(low_freq, (float, int)) and low_freq > 0):
        raise ValueError(f"Expected argument `low_freq` to be a float larger than 0, but got {low_freq}")
    if not (isinstance(min_cf, (float, int)) and min_cf > 0):
        raise ValueError(f"Expected argument `min_cf` to be a float larger than 0, but got {min_cf}")
    if max_cf is not None and not (isinstance(max_cf, (float, int)) and max_cf > 0):
        raise ValueError(f"Expected argument `max_cf` to be a float larger than 0, but got {max_cf}")
    if not isinstance(norm, bool):
        raise ValueError("Expected argument `norm` to be a bool value")
    if not isinstance(fast, bool):
        raise ValueError("Expected argument `fast` to be a bool value")


def speech_reverberation_modulation_energy_ratio(
    preds: Array,
    fs: int,
    n_cochlear_filters: int = 23,
    low_freq: float = 125,
    min_cf: float = 4,
    max_cf: Optional[float] = None,
    norm: bool = False,
    fast: bool = False,
) -> Array:
    """SRMR of ``preds`` with shape ``(..., time)`` (reference ``srmr.py:178-330``).

    ``fast=True`` delegates to the ``gammatone`` package's FFT gammatonegram when installed
    (matching the reference's behavior and its accuracy caveat); the default path is fully
    self-contained.
    """
    _srmr_arg_validate(fs, n_cochlear_filters, low_freq, min_cf, max_cf, norm, fast)

    shape = jnp.shape(preds)
    x = np.asarray(preds, np.float64).reshape(1, -1) if len(shape) == 1 else np.asarray(
        preds, np.float64
    ).reshape(-1, shape[-1])
    num_batch, time = x.shape

    # normalise to [-1, 1] when any sample exceeds it (reference srmr.py:258-266)
    max_vals = np.abs(x).max(axis=-1, keepdims=True)
    x = x / np.where(max_vals > 1, max_vals, 1.0)

    w_length_s, w_inc_s = 0.256, 0.064
    if fast:
        rank_zero_warn("`fast=True` uses the gammatonegram approximation; scores differ from the default path.")
        try:
            from gammatone.fftweight import fft_gtgram
        except ImportError as err:
            raise ModuleNotFoundError(
                "speech_reverberation_modulation_energy_ratio with `fast=True` requires the"
                " `gammatone` package. Install it with `pip install gammatone` or use `fast=False`."
            ) from err
        mfs = 400.0
        gt_env = np.stack(
            [np.asarray(fft_gtgram(x[b], fs, 0.010, 0.0025, n_cochlear_filters, low_freq)) for b in range(num_batch)],
            axis=0,
        ).astype(np.float64)
    else:
        coefs = _make_erb_coeffs(fs, n_cochlear_filters, float(low_freq))
        gt_env = _hilbert_envelope(_erb_filterbank(x, coefs))  # (B, N, T)
        mfs = float(fs)

    w_length = ceil(w_length_s * mfs)
    w_inc = ceil(w_inc_s * mfs)
    env_time = gt_env.shape[-1]

    if max_cf is None:
        max_cf = 30.0 if norm else 128.0
    mfb, low_cutoffs = _modulation_filterbank(float(min_cf), float(max_cf), 8, mfs, 2)

    from scipy.signal import lfilter

    # (B, N, 8, T): each envelope through each modulation bandpass
    mod_out = np.empty((num_batch, gt_env.shape[1], 8, env_time), np.float64)
    for m in range(8):
        mod_out[:, :, m] = lfilter(mfb[m, 0], mfb[m, 1], gt_env, axis=-1)

    num_frames = int(1 + (env_time - w_length) // w_inc)
    energy = _frame_energy(mod_out, w_length, w_inc, num_frames)  # (B, N, 8, F)
    if norm:
        energy = _normalize_energy(energy)

    erbs_ascending = (_erb_space(float(low_freq), fs, n_cochlear_filters) / _EAR_Q + _MIN_BW)[::-1]

    avg_energy = energy.mean(axis=-1)  # (B, N, 8)
    total_energy = avg_energy.reshape(num_batch, -1).sum(axis=-1)
    ac_energy = avg_energy.sum(axis=2)  # (B, N)
    ac_perc = ac_energy * 100 / total_energy[:, None]
    cumsum_low_to_high = np.cumsum(ac_perc[:, ::-1], axis=-1)
    k90_idx = np.argmax(cumsum_low_to_high > 90, axis=-1)
    bw = erbs_ascending[k90_idx]

    scores = np.empty(num_batch, np.float64)
    for b in range(num_batch):
        if low_cutoffs[4] <= bw[b] < low_cutoffs[5]:
            kstar = 5
        elif low_cutoffs[5] <= bw[b] < low_cutoffs[6]:
            kstar = 6
        elif low_cutoffs[6] <= bw[b] < low_cutoffs[7]:
            kstar = 7
        elif low_cutoffs[7] <= bw[b]:
            kstar = 8
        else:
            raise ValueError("Something wrong with the cutoffs compared to bw values.")
        scores[b] = avg_energy[b, :, :4].sum() / avg_energy[b, :, 4:kstar].sum()

    result = jnp.asarray(scores, jnp.float32)
    return result.reshape(shape[:-1]) if len(shape) > 1 else result
