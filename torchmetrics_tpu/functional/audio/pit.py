"""Permutation-invariant training kernels (reference ``src/torchmetrics/functional/audio/pit.py``).

TPU redesign: the reference fills the speaker-pair metric matrix with an S×S Python loop of
separate metric calls (``pit.py:190-200``) and ships large-S assignment to scipy on the host
(``pit.py:42-66``). Here the matrix comes from ONE batched metric call over all (target, pred)
speaker pairs folded into the batch axis, and the optimum is an exhaustive vmapped scan over the
(static) S! permutations — a single gather + argmax program, exact for the sizes PIT is used at
(the factorial table is static per S, so everything stays jittable).
"""
from __future__ import annotations

from itertools import permutations
from typing import Any, Callable, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.utils.prints import rank_zero_warn

_PERM_CACHE: dict = {}


def _gen_permutations(spk_num: int) -> Array:
    """All S! speaker permutations as a static ``(perm_num, S)`` table (reference ``pit.py:30-39``).

    Cached as numpy (jnp constants created under one trace must not leak into another).
    """
    if spk_num not in _PERM_CACHE:
        _PERM_CACHE[spk_num] = np.array(list(permutations(range(spk_num))), np.int32)
    return jnp.asarray(_PERM_CACHE[spk_num])


def permutation_invariant_training(
    preds: Array,
    target: Array,
    metric_func: Callable,
    mode: str = "speaker-wise",
    eval_func: str = "max",
    **kwargs: Any,
) -> Tuple[Array, Array]:
    """PIT (reference ``pit.py:108-215``): best metric + permutation per batch element.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import (permutation_invariant_training,
        ...     scale_invariant_signal_noise_ratio)
        >>> preds = np.array([[[0.6, 0.4, 0.2], [0.2, 0.4, 0.6]]], np.float32)
        >>> target = np.array([[[0.2, 0.4, 0.6], [0.6, 0.4, 0.2]]], np.float32)
        >>> best, perm = permutation_invariant_training(preds, target,
        ...     scale_invariant_signal_noise_ratio, eval_func='max')
        >>> np.asarray(perm).tolist()
        [[1, 0]]
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.shape[0:2] != target.shape[0:2]:
        raise RuntimeError(
            "Predictions and targets are expected to have the same shape at the batch and speaker dimensions"
        )
    if eval_func not in ["max", "min"]:
        raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
    if mode not in ["speaker-wise", "permutation-wise"]:
        raise ValueError(f'mode can only be "speaker-wise" or "permutation-wise" but got {mode}')
    if target.ndim < 2:
        raise ValueError(f"Inputs must be of shape [batch, spk, ...], got {target.shape} and {preds.shape} instead")

    batch_size, spk_num = target.shape[0:2]
    if spk_num > 8:
        rank_zero_warn(
            f"Exhaustive permutation search over {spk_num}! assignments is expensive; PIT is exact"
            " but consider fewer speakers."
        )
    perms = _gen_permutations(spk_num)  # (perm_num, S)
    perm_num = perms.shape[0]

    if mode == "permutation-wise":
        # evaluate metric_func once on all permuted stacks folded into the batch axis
        ppreds = preds[:, perms.reshape(-1)].reshape(batch_size * perm_num, *preds.shape[1:])
        ptarget = jnp.repeat(target, perm_num, axis=0)
        # kwargs forwarded here too (the reference drops them in this branch, pit.py:181 — a bug)
        metric_of_ps = metric_func(ppreds, ptarget, **kwargs)
        metric_of_ps = jnp.mean(metric_of_ps.reshape(batch_size, perm_num, -1), axis=-1)
    else:
        # ONE metric call over all S×S (target, pred) speaker pairs folded into the batch axis
        rest = preds.shape[2:]
        p = jnp.broadcast_to(preds[:, None, :], (batch_size, spk_num, spk_num, *rest))
        t = jnp.broadcast_to(target[:, :, None], (batch_size, spk_num, spk_num, *rest))
        flat = metric_func(p.reshape(batch_size * spk_num * spk_num, *rest),
                           t.reshape(batch_size * spk_num * spk_num, *rest), **kwargs)
        metric_mtx = jnp.reshape(flat, (batch_size, spk_num, spk_num))  # [b, target_idx, preds_idx]
        # score of each permutation: mean over target_idx of mtx[target_idx, perm[target_idx]]
        metric_of_ps = jnp.mean(
            metric_mtx[:, jnp.arange(spk_num)[None, :], perms], axis=-1
        ).reshape(batch_size, perm_num)

    if eval_func == "max":
        best_indexes = jnp.argmax(metric_of_ps, axis=1)
        best_metric = jnp.max(metric_of_ps, axis=1)
    else:
        best_indexes = jnp.argmin(metric_of_ps, axis=1)
        best_metric = jnp.min(metric_of_ps, axis=1)
    best_perm = perms[best_indexes]
    return best_metric, best_perm


def pit_permutate(preds: Array, perm: Array) -> Array:
    """Reorder ``preds`` speakers by the per-sample permutation (reference ``pit.py:218-229``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import pit_permutate
        >>> preds = np.array([[[0.6, 0.4, 0.2], [0.2, 0.4, 0.6]]], np.float32)
        >>> perm = np.array([[1, 0]])
        >>> np.asarray(pit_permutate(preds, perm), np.float64).round(1)[0].tolist()
        [[0.2, 0.4, 0.6], [0.6, 0.4, 0.2]]
    """
    preds = jnp.asarray(preds)
    perm = jnp.asarray(perm)
    return jnp.take_along_axis(preds, perm.reshape(*perm.shape, *([1] * (preds.ndim - 2))), axis=1)
