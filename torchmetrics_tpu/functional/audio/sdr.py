"""SDR kernel (reference ``src/torchmetrics/functional/audio/sdr.py``).

The optimal distortion filter is found by solving the Toeplitz normal equations built from
FFT-domain auto/cross-correlations — rfft, a gather-built symmetric Toeplitz matrix, and a
batched ``jnp.linalg.solve``, all of which lower to TPU. The reference promotes to float64
(``sdr.py:157-160``); TPUs have no fast fp64, so this kernel stays f32 and exposes
``load_diag`` for conditioning (add ~1e-6·r₀ when reference signals can be near-silent).
"""
from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape
from torchmetrics_tpu.utils.prints import rank_zero_warn

_warned_cg_iter = False


def _symmetric_toeplitz(r0: Array) -> Array:
    """Symmetric Toeplitz matrix from its first row: ``T[i, j] = r0[|i - j|]`` (reference ``sdr.py:28-54``)."""
    n = r0.shape[-1]
    idx = jnp.abs(jnp.arange(n)[:, None] - jnp.arange(n)[None, :])
    return r0[..., idx]


def _compute_autocorr_crosscorr(target: Array, preds: Array, corr_len: int):
    """FFT-domain autocorrelation of target and cross-correlation with preds (reference ``sdr.py:57-85``)."""
    n_fft = 2 ** math.ceil(math.log2(preds.shape[-1] + target.shape[-1] - 1))
    t_fft = jnp.fft.rfft(target, n=n_fft, axis=-1)
    r_0 = jnp.fft.irfft(jnp.square(t_fft.real) + jnp.square(t_fft.imag), n=n_fft)[..., :corr_len]
    p_fft = jnp.fft.rfft(preds, n=n_fft, axis=-1)
    b = jnp.fft.irfft(jnp.conj(t_fft) * p_fft, n=n_fft, axis=-1)[..., :corr_len]
    return r_0, b


def signal_distortion_ratio(
    preds: Array,
    target: Array,
    use_cg_iter: Optional[int] = None,
    filter_length: int = 512,
    zero_mean: bool = False,
    load_diag: Optional[float] = None,
) -> Array:
    """SDR in dB per sample (reference ``sdr.py:88-198``).

    ``use_cg_iter`` is accepted for API parity but the direct batched solve is always used —
    on TPU a single dense solve of the ``filter_length``² system is one fused kernel, which is
    the regime the reference's conjugate-gradient path exists to avoid on CPU.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import signal_distortion_ratio
        >>> rng = np.random.RandomState(1)
        >>> target = rng.randn(8000).astype(np.float32)
        >>> preds = target * 0.9 + 0.05 * rng.randn(8000).astype(np.float32)
        >>> print(f"{float(signal_distortion_ratio(preds, target)):.2f}")
        25.34
    """
    global _warned_cg_iter
    if use_cg_iter is not None and not _warned_cg_iter:
        _warned_cg_iter = True
        rank_zero_warn(
            "`use_cg_iter` is accepted for API parity but ignored on TPU: the direct batched "
            "Toeplitz solve is always used, so numerics may differ slightly from the reference's "
            "conjugate-gradient approximation."
        )
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    _check_same_shape(preds, target)

    if zero_mean:
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
        target = target - jnp.mean(target, axis=-1, keepdims=True)

    target = target / jnp.clip(jnp.linalg.norm(target, axis=-1, keepdims=True), 1e-6)
    preds = preds / jnp.clip(jnp.linalg.norm(preds, axis=-1, keepdims=True), 1e-6)

    r_0, b = _compute_autocorr_crosscorr(target, preds, corr_len=filter_length)
    if load_diag is not None:
        r_0 = r_0.at[..., 0].add(load_diag)

    r = _symmetric_toeplitz(r_0)
    sol = jnp.linalg.solve(r, b[..., None])[..., 0]

    coh = jnp.einsum("...l,...l->...", b, sol)
    ratio = coh / (1 - coh)
    return 10.0 * jnp.log10(ratio)
