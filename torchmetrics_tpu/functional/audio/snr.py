"""SNR-family kernels (reference ``src/torchmetrics/functional/audio/snr.py``)."""
from __future__ import annotations

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape

_EPS = float(jnp.finfo(jnp.float32).eps)


def signal_noise_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SNR in dB per sample over the trailing time axis (reference ``snr.py:21-63``).

    Example:
        >>> from torchmetrics_tpu.functional.audio import signal_noise_ratio
        >>> round(float(signal_noise_ratio([2.5, 0.0, 2.0, 8.0], [3.0, -0.5, 2.0, 7.0])), 2)
        16.18
    """
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    _check_same_shape(preds, target)
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    noise = target - preds
    snr_value = (jnp.sum(jnp.square(target), axis=-1) + _EPS) / (jnp.sum(jnp.square(noise), axis=-1) + _EPS)
    return 10 * jnp.log10(snr_value)


def scale_invariant_signal_distortion_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SI-SDR in dB per sample (reference ``sdr.py:200-240``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import scale_invariant_signal_distortion_ratio
        >>> target = np.array([3.0, -0.5, 2.0, 7.0], np.float32)
        >>> preds = np.array([2.5, 0.0, 2.0, 8.0], np.float32)
        >>> print(f"{float(scale_invariant_signal_distortion_ratio(preds, target)):.4f}")
        18.4030
    """
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    _check_same_shape(preds, target)
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    alpha = (jnp.sum(preds * target, axis=-1, keepdims=True) + _EPS) / (
        jnp.sum(jnp.square(target), axis=-1, keepdims=True) + _EPS
    )
    target_scaled = alpha * target
    noise = target_scaled - preds
    val = (jnp.sum(jnp.square(target_scaled), axis=-1) + _EPS) / (jnp.sum(jnp.square(noise), axis=-1) + _EPS)
    return 10 * jnp.log10(val)


def scale_invariant_signal_noise_ratio(preds: Array, target: Array) -> Array:
    """SI-SNR: SI-SDR with zero-mean inputs (reference ``snr.py:66-91``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import scale_invariant_signal_noise_ratio
        >>> target = np.array([3.0, -0.5, 2.0, 7.0], np.float32)
        >>> preds = np.array([2.5, 0.0, 2.0, 8.0], np.float32)
        >>> print(f"{float(scale_invariant_signal_noise_ratio(preds, target)):.4f}")
        15.0918
    """
    return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=True)


def complex_scale_invariant_signal_noise_ratio(
    preds: Array, target: Array, zero_mean: bool = False
) -> Array:
    """C-SI-SNR over ``(..., freq, time, 2)`` real-view spectrograms (reference ``snr.py:94-132``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if jnp.iscomplexobj(preds):
        preds = jnp.stack([preds.real, preds.imag], axis=-1)
    if jnp.iscomplexobj(target):
        target = jnp.stack([target.real, target.imag], axis=-1)
    if (preds.ndim < 3 or preds.shape[-1] != 2) or (target.ndim < 3 or target.shape[-1] != 2):
        raise RuntimeError(
            "Predictions and targets are expected to have the shape (..., frequency, time, 2),"
            f" but got {preds.shape} and {target.shape}."
        )
    preds = preds.reshape(*preds.shape[:-3], -1)
    target = target.reshape(*target.shape[:-3], -1)
    return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=zero_mean)


def source_aggregated_signal_distortion_ratio(
    preds: Array,
    target: Array,
    scale_invariant: bool = True,
    zero_mean: bool = False,
) -> Array:
    """SA-SDR over ``(..., spk, time)`` (reference ``sdr.py:243-330``)."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    _check_same_shape(preds, target)
    if preds.ndim < 2:
        raise RuntimeError(f"The preds and target should have the shape (..., spk, time), but {preds.shape} found")
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    if scale_invariant:
        alpha = (jnp.sum(preds * target, axis=(-2, -1), keepdims=True) + _EPS) / (
            jnp.sum(jnp.square(target), axis=(-2, -1), keepdims=True) + _EPS
        )
        target = alpha * target
    distortion = target - preds
    val = (jnp.sum(jnp.square(target), axis=(-2, -1)) + _EPS) / (
        jnp.sum(jnp.square(distortion), axis=(-2, -1)) + _EPS
    )
    return 10 * jnp.log10(val)
