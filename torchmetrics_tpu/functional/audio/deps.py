"""Host-library-backed audio metrics: PESQ and STOI (reference ``functional/audio/{pesq,stoi}.py``).

These wrap third-party native DSP packages (``pesq``, ``pystoi``) in the reference; the
algorithms are ITU-standard host-side signal processing, not accelerator math. Parity decision
(documented, VERDICT r2 item 3): when the host package is importable we delegate to it
sample-by-sample exactly like the reference; when it is not (this build ships neither) we raise
the same ``ModuleNotFoundError`` contract the reference raises. SRMR — which the reference also
backs with external packages (gammatone/torchaudio) — is implemented natively in ``srmr.py``.
"""
from __future__ import annotations

import importlib.util

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape

_PESQ_AVAILABLE = importlib.util.find_spec("pesq") is not None
_PYSTOI_AVAILABLE = importlib.util.find_spec("pystoi") is not None


def _require_pesq() -> None:
    if not _PESQ_AVAILABLE:
        raise ModuleNotFoundError(
            "PESQ metric requires that pesq is installed. Either install as `pip install"
            " torchmetrics[audio]` or `pip install pesq`."
        )


def _require_pystoi() -> None:
    if not _PYSTOI_AVAILABLE:
        raise ModuleNotFoundError(
            "STOI metric requires that pystoi is installed. Either install as `pip install"
            " torchmetrics[audio]` or `pip install pystoi`."
        )


def perceptual_evaluation_speech_quality(
    preds: Array,
    target: Array,
    fs: int,
    mode: str,
    keep_same_device: bool = False,
    n_processes: int = 1,
) -> Array:
    """PESQ via the host ``pesq`` package (reference ``functional/audio/pesq.py:28``).

    ``n_processes`` is accepted for API parity but evaluation is always serial here (the
    reference spawns a multiprocessing pool, ``pesq.py:110-115``).
    """
    _require_pesq()
    import pesq as pesq_backend

    if fs not in (8000, 16000):
        raise ValueError(f"Argument `fs` must be either 8000 or 16000 but got {fs}")
    if mode not in ("wb", "nb"):
        raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
    _check_same_shape(preds, target)
    preds_np = np.asarray(preds, np.float32).reshape(-1, preds.shape[-1])
    target_np = np.asarray(target, np.float32).reshape(-1, preds.shape[-1])
    pesq_val = np.empty(preds_np.shape[0], np.float32)
    for b in range(preds_np.shape[0]):
        try:
            pesq_val[b] = pesq_backend.pesq(fs, target_np[b], preds_np[b], mode)
        except pesq_backend.NoUtterancesError:  # silent sample → NaN (reference pesq.py:103-106)
            pesq_val[b] = np.nan
    return jnp.asarray(pesq_val.reshape(preds.shape[:-1]))


def short_time_objective_intelligibility(
    preds: Array, target: Array, fs: int, extended: bool = False, keep_same_device: bool = False
) -> Array:
    """STOI via the host ``pystoi`` package (reference ``functional/audio/stoi.py:25``)."""
    _require_pystoi()
    from pystoi import stoi as stoi_backend

    _check_same_shape(preds, target)
    preds_np = np.asarray(preds, np.float32).reshape(-1, preds.shape[-1])
    target_np = np.asarray(target, np.float32).reshape(-1, preds.shape[-1])
    stoi_val = np.empty(preds_np.shape[0], np.float32)
    for b in range(preds_np.shape[0]):
        stoi_val[b] = stoi_backend(target_np[b], preds_np[b], fs, extended=extended)
    return jnp.asarray(stoi_val.reshape(preds.shape[:-1]))


