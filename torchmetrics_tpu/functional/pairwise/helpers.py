"""Shared input handling for the pairwise distance kernels (reference
``src/torchmetrics/functional/pairwise/helpers.py:19-60``)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array


def _check_input(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Tuple[Array, Array, bool]:
    """Validate shapes and resolve the ``zero_diagonal`` default (reference ``helpers.py:19``).

    ``x``: ``[N, d]``; ``y``: ``[M, d]`` or ``None`` (self-comparison, diagonal zeroed by
    default).
    """
    x = jnp.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"Expected argument `x` to be a 2D tensor of shape `[N, d]` but got {x.shape}")
    if y is not None:
        y = jnp.asarray(y)
        if y.ndim != 2 or y.shape[1] != x.shape[1]:
            raise ValueError(
                "Expected argument `y` to be a 2D tensor of shape `[M, d]` where"
                " `d` should be same as the last dimension of `x`"
            )
        zero_diagonal = False if zero_diagonal is None else zero_diagonal
    else:
        y = x
        zero_diagonal = True if zero_diagonal is None else zero_diagonal
    return x, y, zero_diagonal


def _zero_diagonal(distance: Array, zero_diagonal: bool) -> Array:
    """Functional replacement for the reference's in-place ``fill_diagonal_(0)``."""
    if not zero_diagonal:
        return distance
    on_diag = jnp.arange(distance.shape[0])[:, None] == jnp.arange(distance.shape[1])[None, :]
    return jnp.where(on_diag, 0, distance)


def _reduce_distance_matrix(distmat: Array, reduction: Optional[str] = None) -> Array:
    """mean/sum/none over the last axis (reference ``helpers.py:46-60``)."""
    if reduction == "mean":
        return jnp.mean(distmat, axis=-1)
    if reduction == "sum":
        return jnp.sum(distmat, axis=-1)
    if reduction is None or reduction == "none":
        return distmat
    raise ValueError(f"Expected reduction to be one of `['mean', 'sum', None]` but got {reduction}")
