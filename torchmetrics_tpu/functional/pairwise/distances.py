"""Pairwise distance/similarity matrices (reference
``src/torchmetrics/functional/pairwise/{cosine,euclidean,linear,manhattan,minkowski}.py``).

TPU-first design: every kernel is a single jittable expression dominated by one ``[N, d] x [d, M]``
matmul (MXU) where the math allows it. The reference upcasts to float64 for euclidean/minkowski;
TPU f64 is emulated and slow, so euclidean uses the Gram expansion ``max(x² + y² - 2xy, 0)`` in
f32 (negative residuals from cancellation are clamped; documented tolerance ~1e-6 relative) and
minkowski broadcasts in f32.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.pairwise.helpers import (
    _check_input,
    _reduce_distance_matrix,
    _zero_diagonal,
)
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError


def _matmul_f32(x: Array, y: Array) -> Array:
    # TPU matmuls default to bf16 operands (~1e-3 relative error) — metrics need full f32:
    # "highest" keeps the MXU but runs the 6-pass f32 decomposition
    return jnp.matmul(x, y, precision="highest")


def _pairwise_cosine_similarity_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Row-normalise then one MXU matmul (reference ``cosine.py:25``)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    x = x / jnp.linalg.norm(x, axis=1, keepdims=True)
    y = y / jnp.linalg.norm(y, axis=1, keepdims=True)
    distance = _matmul_f32(x, y.T)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_cosine_similarity(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise cosine similarity ``<x,y> / (||x||·||y||)`` (reference ``cosine.py:48``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import pairwise_cosine_similarity
        >>> x = np.array([[1.0, 0.0], [0.0, 1.0]], np.float32)
        >>> print(np.round(np.asarray(pairwise_cosine_similarity(x)), 4))
        [[0. 0.]
         [0. 0.]]
    """
    distance = _pairwise_cosine_similarity_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)


def _pairwise_euclidean_distance_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Gram-expansion euclidean: ``sqrt(max(x² + y² - 2·x@yᵀ, 0))`` (reference ``euclidean.py:23``).

    The reference upcasts to f64; on TPU we stay f32 (one MXU matmul) and clamp the tiny negative
    residuals the expansion can produce for near-identical rows.
    """
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    x_norm = jnp.sum(x * x, axis=1, keepdims=True)
    y_norm = jnp.sum(y * y, axis=1)
    distance = jnp.maximum(x_norm + y_norm - 2 * _matmul_f32(x, y.T), 0.0)
    distance = _zero_diagonal(distance, zero_diagonal)
    return jnp.sqrt(distance)


def pairwise_euclidean_distance(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise euclidean distance matrix (reference ``euclidean.py:47``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import pairwise_euclidean_distance
        >>> x = np.array([[1.0, 0.0], [0.0, 1.0]], np.float32)
        >>> print(np.round(np.asarray(pairwise_euclidean_distance(x)), 4))
        [[0.     1.4142]
         [1.4142 0.    ]]
    """
    distance = _pairwise_euclidean_distance_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)


def _pairwise_linear_similarity_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Plain inner-product matrix — one MXU matmul (reference ``linear.py:23``)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = _matmul_f32(x, y.T)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_linear_similarity(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise linear (dot-product) similarity (reference ``linear.py:42``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import pairwise_linear_similarity
        >>> x = np.array([[1.0, 0.0], [0.0, 1.0]], np.float32)
        >>> print(np.round(np.asarray(pairwise_linear_similarity(x)), 4))
        [[0. 0.]
         [0. 0.]]
    """
    distance = _pairwise_linear_similarity_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)


def _pairwise_manhattan_distance_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Broadcast |xᵢ - yⱼ| sum (reference ``manhattan.py:22``); no matmul form exists for L1."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_manhattan_distance(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise manhattan (L1) distance (reference ``manhattan.py:41``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import pairwise_manhattan_distance
        >>> x = np.array([[1.0, 0.0], [0.0, 1.0]], np.float32)
        >>> print(np.round(np.asarray(pairwise_manhattan_distance(x)), 4))
        [[0. 2.]
         [2. 0.]]
    """
    distance = _pairwise_manhattan_distance_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)


def _pairwise_minkowski_distance_update(
    x: Array, y: Optional[Array] = None, exponent: float = 2, zero_diagonal: Optional[bool] = None
) -> Array:
    """Broadcast |xᵢ - yⱼ|^p sum ^(1/p) (reference ``minkowski.py:25``), f32 on TPU."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    if not (isinstance(exponent, (float, int)) and exponent >= 1):
        raise TorchMetricsUserError(f"Argument ``p`` must be a float or int greater than 1, but got {exponent}")
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    distance = jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]) ** exponent, axis=-1) ** (1.0 / exponent)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_minkowski_distance(
    x: Array,
    y: Optional[Array] = None,
    exponent: float = 2,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise minkowski (Lᵖ) distance (reference ``minkowski.py:49``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.functional import pairwise_minkowski_distance
        >>> x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        >>> np.asarray(pairwise_minkowski_distance(x, exponent=3), np.float64).round(4).tolist()
        [[0.0, 2.5198], [2.5198, 0.0]]
    """
    distance = _pairwise_minkowski_distance_update(x, y, exponent, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
