"""Stateful clustering metrics (reference ``src/torchmetrics/clustering/*.py``).

All extrinsic metrics share one state layout — ``preds``/``target`` label list states with
``dist_reduce_fx="cat"`` (reference e.g. ``clustering/mutual_info_score.py:77-78``) — and one
compute shape: concatenate, relabel on host, run the fused contingency kernel. Intrinsic metrics
(CH / DB / Dunn) store ``data``/``labels`` (reference ``calinski_harabasz_score.py:77-78``).
Compute is host-mediated (the relabel step is dynamic), so ``jit_compute=False``; the heavy
kernels inside the functionals are still jitted device programs.
"""
from __future__ import annotations

from typing import Any, Dict, Literal, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.clustering import (
    adjusted_mutual_info_score,
    adjusted_rand_score,
    calinski_harabasz_score,
    completeness_score,
    davies_bouldin_score,
    dunn_index,
    fowlkes_mallows_index,
    homogeneity_score,
    mutual_info_score,
    normalized_mutual_info_score,
    rand_score,
    v_measure_score,
)
from torchmetrics_tpu.functional.clustering.utils import _validate_average_method_arg
from torchmetrics_tpu.metric import Metric


class _LabelPairMetric(Metric):
    """Shared shell for extrinsic clustering metrics: two label list-states, host compute."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    jit_compute = False
    jit_update = False  # labels may be arbitrary ints; update just appends

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def _update(self, state: Dict[str, Any], preds: Array, target: Array) -> Dict[str, Any]:
        return {"preds": jnp.atleast_1d(preds), "target": jnp.atleast_1d(target)}

    def _functional(self, preds: Array, target: Array) -> Array:
        raise NotImplementedError

    def _compute(self, state: Dict[str, Any]) -> Array:
        return self._functional(state["preds"], state["target"])


class MutualInfoScore(_LabelPairMetric):
    """Mutual information between clusterings (reference ``clustering/mutual_info_score.py:30``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.clustering import MutualInfoScore
        >>> metric = MutualInfoScore()
        >>> metric.update(np.array([0, 0, 1, 1]), np.array([0, 0, 1, 2]))
        >>> print(f"{float(metric.compute()):.4f}")
        0.6931
    """

    plot_upper_bound = None

    def _functional(self, preds, target):
        return mutual_info_score(preds, target)


class RandScore(_LabelPairMetric):
    """Rand score (reference ``clustering/rand_score.py:29``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.clustering import RandScore
        >>> metric = RandScore()
        >>> metric.update(np.array([0, 0, 1, 1]), np.array([0, 0, 1, 2]))
        >>> print(f"{float(metric.compute()):.4f}")
        0.8333
    """

    def _functional(self, preds, target):
        return rand_score(preds, target)


class AdjustedRandScore(_LabelPairMetric):
    """Adjusted Rand score (reference ``clustering/adjusted_rand_score.py:29``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.clustering import AdjustedRandScore
        >>> metric = AdjustedRandScore()
        >>> metric.update(np.array([0, 0, 1, 1]), np.array([0, 0, 1, 2]))
        >>> print(f"{float(metric.compute()):.4f}")
        0.5714
    """

    plot_lower_bound = -0.5

    def _functional(self, preds, target):
        return adjusted_rand_score(preds, target)


class AdjustedMutualInfoScore(_LabelPairMetric):
    """Adjusted mutual info (reference ``clustering/adjusted_mutual_info_score.py:31``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0, 0, 1, 1])
        >>> target = np.array([0, 0, 1, 2])
        >>> from torchmetrics_tpu.clustering import AdjustedMutualInfoScore
        >>> metric = AdjustedMutualInfoScore()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.5714
    """

    plot_lower_bound = -1.0

    def __init__(
        self, average_method: Literal["min", "geometric", "arithmetic", "max"] = "arithmetic", **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        _validate_average_method_arg(average_method)
        self.average_method = average_method

    def _functional(self, preds, target):
        return adjusted_mutual_info_score(preds, target, self.average_method)


class NormalizedMutualInfoScore(_LabelPairMetric):
    """Normalized mutual info (reference ``clustering/normalized_mutual_info_score.py:30``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.clustering import NormalizedMutualInfoScore
        >>> metric = NormalizedMutualInfoScore()
        >>> metric.update(np.array([0, 0, 1, 1]), np.array([0, 0, 1, 2]))
        >>> print(f"{float(metric.compute()):.4f}")
        0.8000
    """

    def __init__(
        self, average_method: Literal["min", "geometric", "arithmetic", "max"] = "arithmetic", **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        _validate_average_method_arg(average_method)
        self.average_method = average_method

    def _functional(self, preds, target):
        return normalized_mutual_info_score(preds, target, self.average_method)


class FowlkesMallowsIndex(_LabelPairMetric):
    """Fowlkes-Mallows index (reference ``clustering/fowlkes_mallows_index.py:29``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0, 0, 1, 1])
        >>> target = np.array([0, 0, 1, 2])
        >>> from torchmetrics_tpu.clustering import FowlkesMallowsIndex
        >>> metric = FowlkesMallowsIndex()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.7071
    """

    def _functional(self, preds, target):
        return fowlkes_mallows_index(preds, target)


class HomogeneityScore(_LabelPairMetric):
    """Homogeneity score (reference ``clustering/homogeneity_completeness_v_measure.py:30``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0, 0, 1, 1])
        >>> target = np.array([0, 0, 1, 2])
        >>> from torchmetrics_tpu.clustering import HomogeneityScore
        >>> metric = HomogeneityScore()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.6667
    """

    def _functional(self, preds, target):
        return homogeneity_score(preds, target)


class CompletenessScore(_LabelPairMetric):
    """Completeness score (reference ``clustering/homogeneity_completeness_v_measure.py:126``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0, 0, 1, 1])
        >>> target = np.array([0, 0, 1, 2])
        >>> from torchmetrics_tpu.clustering import CompletenessScore
        >>> metric = CompletenessScore()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    def _functional(self, preds, target):
        return completeness_score(preds, target)


class VMeasureScore(_LabelPairMetric):
    """V-measure (reference ``clustering/homogeneity_completeness_v_measure.py:226``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0, 0, 1, 1])
        >>> target = np.array([0, 0, 1, 2])
        >>> from torchmetrics_tpu.clustering import VMeasureScore
        >>> metric = VMeasureScore()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.8000
    """

    def __init__(self, beta: Union[int, float] = 1.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(beta, (int, float)) and beta > 0):
            raise ValueError(f"Argument `beta` must be a positive float. Got {beta}.")
        self.beta = beta

    def _functional(self, preds, target):
        return v_measure_score(preds, target, self.beta)


class _DataLabelMetric(Metric):
    """Shared shell for intrinsic clustering metrics: data + labels list-states."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    jit_compute = False
    jit_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("data", default=[], dist_reduce_fx="cat")
        self.add_state("labels", default=[], dist_reduce_fx="cat")

    def _update(self, state: Dict[str, Any], data: Array, labels: Array) -> Dict[str, Any]:
        return {"data": jnp.atleast_2d(data), "labels": jnp.atleast_1d(labels)}


class CalinskiHarabaszScore(_DataLabelMetric):
    """Calinski-Harabasz score (reference ``clustering/calinski_harabasz_score.py:29``).

    Example:
        >>> import numpy as np
        >>> data = np.array([[0.0, 0.0], [0.5, 0.0], [8.0, 8.0], [8.5, 8.0]], np.float32)
        >>> labels = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu.clustering import CalinskiHarabaszScore
        >>> metric = CalinskiHarabaszScore()
        >>> metric.update(data, labels)
        >>> print(f"{float(metric.compute()):.4f}")
        1024.0000
    """

    def _compute(self, state):
        return calinski_harabasz_score(state["data"], state["labels"])


class DaviesBouldinScore(_DataLabelMetric):
    """Davies-Bouldin score (reference ``clustering/davies_bouldin_score.py:29``).

    Example:
        >>> import numpy as np
        >>> data = np.array([[0.0, 0.0], [0.5, 0.0], [8.0, 8.0], [8.5, 8.0]], np.float32)
        >>> labels = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu.clustering import DaviesBouldinScore
        >>> metric = DaviesBouldinScore()
        >>> metric.update(data, labels)
        >>> print(f"{float(metric.compute()):.4f}")
        0.0442
    """

    higher_is_better = False

    def _compute(self, state):
        return davies_bouldin_score(state["data"], state["labels"])


class DunnIndex(_DataLabelMetric):
    """Dunn index (reference ``clustering/dunn_index.py:29``).

    Example:
        >>> import numpy as np
        >>> data = np.array([[0.0, 0.0], [0.5, 0.0], [8.0, 8.0], [8.5, 8.0]], np.float32)
        >>> labels = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu.clustering import DunnIndex
        >>> metric = DunnIndex()
        >>> metric.update(data, labels)
        >>> print(f"{float(metric.compute()):.4f}")
        45.2548
    """

    def __init__(self, p: Union[int, float] = 2, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.p = p

    def _compute(self, state):
        return dunn_index(state["data"], state["labels"], self.p)
