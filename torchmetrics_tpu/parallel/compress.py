"""Wire codecs for compressed collectives: ``SyncOptions(compression=...)``.

Every eager ``process_sync`` gather used to ship full-precision state even though the
dominant payloads are (a) large float accumulator slabs whose consumers tolerate a
documented quantization error and (b) sketch states that are mostly ``+inf`` padding.
This module is the codec seam behind ``SyncOptions(compression="none"|"bf16"|"int8")``
(env ``TM_TPU_SYNC_COMPRESSION``), in the spirit of *EQuARX: Efficient Quantized
AllReduce in XLA* (PAPERS.md): block-scaled quantization with per-block scales packed
into ONE wire payload, plus error-feedback residuals so repeated syncs of a sum state
do not drift.

Exactness matrix (docs/distributed.md "Compressed collectives"):

=====================================  ==========  =================================
state / reduction                      wire        exactness
=====================================  ==========  =================================
int / bool dtype (counts)              raw         bit-identical by construction
``min`` / ``max`` reductions           raw         bit-identical by construction
``cat`` / ``None`` / plain callables   raw         bit-identical by construction
sketch states (kll / countmin / hist)  packed      LOSSLESS pack → merge bit-identical
f32 ``sum``                            bf16/int8   error-feedback, bounded (below)
f32 ``mean``                           bf16/int8   plain quantization, bounded
anything whose wire would be BIGGER    raw         bit-identical (never ship more)
=====================================  ==========  =================================

Wire format — a self-identifying 1-D ``uint8`` blob::

    [0:4)  magic b"TMCW"      [4]    kind      [5]    flags   [6:8)  reserved
    [8:12) n (u32 LE)         [12:16) extra (u32 LE)          [16:)  payload

- ``bf16`` (kind 1): payload = round-to-nearest-even bfloat16 halves (2 bytes/elem).
- ``int8`` (kind 2): payload = per-block f32 scales (``ceil(n/BLOCK)``) followed by the
  symmetric int8 quanta (``q = clip(round(x/scale), -127, 127)``, ``scale =
  max|block|/127``). Per-element abs error ≤ ``scale/2``.
- ``kll`` (kind 3): LOSSLESS pack of a KLL compactor state ``(levels, capacity+2)`` —
  per-level u16 counts + u8 parities, then only the ``count`` VALID leading items per
  level as verbatim f32 bytes (slots past the count are ``+inf`` by construction, so
  decode rebuilds the exact array). A state that violates the invariant (e.g. NaN
  samples sorted into the tail) falls back to a verbatim f32 payload (flags=0).
- ``counts`` (kind 4): LOSSLESS narrow-int pack of integral count grids (count-min
  rows, threshold-histogram pairs): u8/u16/u32 chosen by range (flags = byte width),
  verbatim dtype bytes when the values are non-integral/negative (flags=0).

Everything here is host numpy — the eager sync path already runs on the host, and the
codec must never add a device launch per state. jax is deliberately NOT imported.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, MutableMapping, Optional, Tuple

import numpy as np

#: recognised compression modes for ``SyncOptions(compression=...)``
MODES = ("none", "bf16", "int8")
ENV_SYNC_COMPRESSION = "TM_TPU_SYNC_COMPRESSION"

#: quantization block width for int8 (one f32 scale per block)
BLOCK = 256

_MAGIC = b"TMCW"
_HEADER = struct.Struct("<4sBBHII")  # magic, kind, flags, reserved, n, extra
HEADER_BYTES = _HEADER.size

KIND_BF16 = 1
KIND_INT8 = 2
KIND_KLL = 3
KIND_COUNTS = 4

#: sketch kind (SketchSpec.kind) -> wire codec kind
SKETCH_WIRE_KINDS: Dict[str, int] = {"kll": KIND_KLL, "countmin": KIND_COUNTS, "hist": KIND_COUNTS}

#: documented per-element relative quantization quantum per lossy mode (half-ulp):
#: bf16 keeps 8 significand bits, so round-to-nearest lands within ``2^-8`` of the
#: value relatively; int8 block-scaling bounds abs error by ``block_max/254`` per
#: element. Bound helpers below fold in the world size and a 2x slack for the
#: error-feedback carry (the shipped value is ``x + residual``).
LOSSY_EPS = {"bf16": 2.0 ** -8, "int8": 1.0 / 254.0}


def validate_mode(mode: Any) -> str:
    """Normalise + validate a compression mode string."""
    m = str(mode or "none").strip().lower()
    if m not in MODES:
        raise ValueError(f"unknown sync compression mode {mode!r}; expected one of {MODES}")
    return m


def _pack(kind: int, flags: int, n: int, extra: int, payload: bytes) -> np.ndarray:
    header = _HEADER.pack(_MAGIC, kind, flags, 0, n, extra)
    return np.frombuffer(header + payload, dtype=np.uint8).copy()


def is_wire(value: Any) -> bool:
    """True when ``value`` is (or wraps) a blob this module encoded."""
    arr = np.asarray(value)
    if arr.dtype != np.uint8 or arr.ndim != 1 or arr.size < HEADER_BYTES:
        return False
    return arr[:4].tobytes() == _MAGIC


def wire_nbytes(value: Any) -> int:
    """Byte size of one wire blob (or 0 for non-wire values)."""
    arr = np.asarray(value)
    return int(arr.size) if is_wire(arr) else 0


# ------------------------------------------------------------------- lossy float codecs
def _bf16_encode(x32: np.ndarray) -> bytes:
    u = np.ascontiguousarray(x32, np.float32).view(np.uint32)
    rounding = np.uint32(0x7FFF) + ((u >> np.uint32(16)) & np.uint32(1))
    bf = ((u + rounding) >> np.uint32(16)).astype(np.uint16)
    nan = np.isnan(x32)
    if nan.any():
        # round-to-nearest of a NaN mantissa can overflow into the exponent (-> inf);
        # truncate instead and force a quiet-NaN mantissa bit
        bf[nan] = ((u[nan] >> np.uint32(16)) | np.uint32(0x0040)).astype(np.uint16)
    return bf.tobytes()


def _bf16_decode(payload: bytes, n: int) -> np.ndarray:
    bf = np.frombuffer(payload, dtype=np.uint16, count=n).astype(np.uint32)
    return (bf << np.uint32(16)).view(np.float32)


def _int8_encode(x32: np.ndarray) -> Optional[Tuple[bytes, int]]:
    n = x32.size
    nb = max(1, -(-n // BLOCK))
    xp = np.zeros((nb * BLOCK,), np.float32)
    xp[:n] = x32.reshape(-1)
    xp = xp.reshape(nb, BLOCK)
    maxabs = np.max(np.abs(xp), axis=1)
    if not np.isfinite(maxabs).all():
        return None  # non-finite blocks cannot block-scale; caller ships raw
    scales = (maxabs / 127.0).astype(np.float32)
    safe = np.where(scales > 0.0, scales, np.float32(1.0))
    q = np.clip(np.rint(xp / safe[:, None]), -127, 127).astype(np.int8)
    # ship exactly n quanta — the last block's padding is reconstructed on decode
    return scales.tobytes() + q.reshape(-1)[:n].tobytes(), nb


def _int8_decode(payload: bytes, n: int, nb: int) -> np.ndarray:
    scales = np.frombuffer(payload, dtype=np.float32, count=nb)
    q = np.zeros((nb * BLOCK,), np.int8)
    q[:n] = np.frombuffer(payload, dtype=np.int8, offset=4 * nb, count=n)
    out = q.reshape(nb, BLOCK).astype(np.float32) * scales[:, None]
    return out.reshape(-1)[:n]


# -------------------------------------------------------------- lossless sketch codecs
def _kll_geometry(shape: Tuple[int, ...]) -> Tuple[int, int]:
    levels, width = int(shape[0]), int(shape[1])
    return levels, width - 2


def _kll_encode(state: np.ndarray) -> np.ndarray:
    """LOSSLESS pack of a KLL state: only the valid leading items per level ship."""
    levels, cap = _kll_geometry(state.shape)
    items, counts, pars = state[:, :cap], state[:, cap], state[:, cap + 1]
    cnt = counts.astype(np.int64)
    valid = (
        np.all(counts == cnt)
        and np.all((cnt >= 0) & (cnt <= cap))
        and np.all((pars == 0.0) | (pars == 1.0))
        and all(bool(np.all(np.isposinf(items[lvl, cnt[lvl]:]))) for lvl in range(levels))
    )
    n = int(state.size)
    extra = (levels << 16) | cap
    if not valid:
        # e.g. NaN samples sorted into the padding tail: ship the array verbatim so the
        # round-trip stays bit-identical no matter what (the never-bigger guard upstream
        # then prefers the raw array over this header-taxed copy)
        return _pack(KIND_KLL, 0, n, extra, np.ascontiguousarray(state, np.float32).tobytes())
    body = cnt.astype("<u2").tobytes() + pars.astype(np.uint8).tobytes()
    body += b"".join(
        np.ascontiguousarray(items[lvl, : cnt[lvl]], np.float32).tobytes() for lvl in range(levels)
    )
    return _pack(KIND_KLL, 1, n, extra, body)


def _kll_decode(blob: np.ndarray, flags: int, n: int, extra: int) -> np.ndarray:
    levels, cap = extra >> 16, extra & 0xFFFF
    payload = blob[HEADER_BYTES:].tobytes()
    if flags == 0:
        return np.frombuffer(payload, dtype=np.float32, count=n).reshape(levels, cap + 2).copy()
    cnt = np.frombuffer(payload, dtype="<u2", count=levels).astype(np.int64)
    pars = np.frombuffer(payload, dtype=np.uint8, offset=2 * levels, count=levels)
    state = np.full((levels, cap + 2), np.inf, np.float32)
    state[:, cap] = cnt.astype(np.float32)
    state[:, cap + 1] = pars.astype(np.float32)
    offset = 3 * levels
    for lvl in range(levels):
        k = int(cnt[lvl])
        if k:
            state[lvl, :k] = np.frombuffer(payload, dtype=np.float32, offset=offset, count=k)
            offset += 4 * k
    return state


def _counts_encode(arr: np.ndarray) -> np.ndarray:
    """LOSSLESS narrow-int pack of an integral count grid (count-min / hist pair)."""
    flat = np.ascontiguousarray(arr).reshape(-1)
    n = int(flat.size)
    as_int = flat.astype(np.int64, copy=False) if flat.dtype.kind in "iu" else None
    if as_int is None and flat.dtype.kind == "f" and np.isfinite(flat).all():
        cand = np.rint(flat)
        if np.array_equal(cand, flat):
            as_int = cand.astype(np.int64)
    if as_int is None or n == 0 or as_int.min() < 0 or as_int.max() > 0xFFFFFFFF:
        return _pack(KIND_COUNTS, 0, n, 0, flat.tobytes())
    top = int(as_int.max())
    width = 1 if top <= 0xFF else (2 if top <= 0xFFFF else 4)
    payload = as_int.astype(f"<u{width}").tobytes()
    return _pack(KIND_COUNTS, width, n, 0, payload)


def _counts_decode(blob: np.ndarray, flags: int, n: int, dtype: Any) -> np.ndarray:
    payload = blob[HEADER_BYTES:].tobytes()
    if flags == 0:
        return np.frombuffer(payload, dtype=np.dtype(dtype), count=n).copy()
    vals = np.frombuffer(payload, dtype=f"<u{flags}", count=n)
    return vals.astype(np.dtype(dtype))


# ------------------------------------------------------------------------- public codec
def encode_array(value: Any, mode: str) -> Optional[np.ndarray]:
    """Block-scaled lossy encode of a float array; None when the value can't compress
    (non-f32 dtype, non-finite int8 blocks) — the caller then ships raw."""
    arr = np.asarray(value)
    if arr.dtype != np.float32:
        return None
    flat = np.ascontiguousarray(arr, np.float32).reshape(-1)
    if mode == "bf16":
        return _pack(KIND_BF16, 0, flat.size, 0, _bf16_encode(flat))
    if mode == "int8":
        enc = _int8_encode(flat)
        if enc is None:
            return None
        payload, nb = enc
        return _pack(KIND_INT8, 0, flat.size, nb, payload)
    raise ValueError(f"not a lossy wire mode: {mode!r}")


def encode_sketch(value: Any, sketch_kind: str) -> Optional[np.ndarray]:
    """LOSSLESS pack of one sketch state (``SketchSpec.kind``); None for unknown kinds."""
    wire_kind = SKETCH_WIRE_KINDS.get(sketch_kind)
    arr = np.asarray(value)
    if wire_kind == KIND_KLL and arr.ndim == 2 and arr.shape[1] >= 3 and arr.dtype == np.float32:
        return _kll_encode(arr)
    if wire_kind == KIND_COUNTS:
        return _counts_encode(arr)
    return None


def decode(blob: Any, shape: Tuple[int, ...], dtype: Any) -> np.ndarray:
    """Decode one wire blob back to an array of the receiver's (known) shape/dtype."""
    arr = np.asarray(blob)
    magic, kind, flags, _res, n, extra = _HEADER.unpack(arr[:HEADER_BYTES].tobytes())
    if magic != _MAGIC:
        raise ValueError("not a TMCW wire blob")
    if kind == KIND_BF16:
        return _bf16_decode(arr[HEADER_BYTES:].tobytes(), n).reshape(shape).astype(np.dtype(dtype))
    if kind == KIND_INT8:
        return _int8_decode(arr[HEADER_BYTES:].tobytes(), n, extra).reshape(shape).astype(np.dtype(dtype))
    if kind == KIND_KLL:
        return _kll_decode(arr, flags, n, extra).reshape(shape)
    if kind == KIND_COUNTS:
        return _counts_decode(arr, flags, n, dtype).reshape(shape)
    raise ValueError(f"unknown wire kind {kind}")


def maybe_decode(value: Any, shape: Tuple[int, ...], dtype: Any) -> Any:
    """Decode when ``value`` is a wire blob; pass anything else through untouched.

    The wire is self-identifying (magic header), so a transport that ignored the
    encoded payload (a compression-unaware injected gather) degrades gracefully: its
    raw entries flow through and the sync is simply uncompressed for that state.
    """
    if is_wire(value):
        return decode(value, shape, dtype)
    return value


# --------------------------------------------------------------------- codec planning
def plan_state(value: Any, fx: Any, mode: str, sketch_kind: Optional[str] = None) -> str:
    """Pick the wire treatment for one state: ``raw | bf16 | int8 | sketch``.

    Exactness is preserved BY CONSTRUCTION for int/bool dtypes, ``min``/``max``
    reductions, ``cat``/``None``/callable reductions (raw wire), and sketch merges
    (lossless pack). Lossy block-scaled quantization applies only to float32 ``sum`` /
    ``mean`` slabs. The caller additionally enforces the never-bigger guard (a wire
    blob that does not beat the raw bytes ships raw).
    """
    if mode == "none":
        return "raw"
    if sketch_kind is not None and sketch_kind in SKETCH_WIRE_KINDS:
        return "sketch"
    if isinstance(value, (list, tuple)):
        return "raw"
    dtype = getattr(value, "dtype", None)
    if dtype is None or np.dtype(dtype) != np.float32:
        return "raw"
    if fx in ("sum", "mean"):
        return mode
    return "raw"


def encode_with_feedback(
    value: Any,
    mode: str,
    residuals: Optional[MutableMapping[str, np.ndarray]] = None,
    key: Optional[str] = None,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Quantize ``value`` with error-feedback: ship ``Q(x + r)``, keep ``r' = x + r − Q``.

    The residual lives HOST-side in ``residuals[key]`` (per state, per metric) so
    repeated syncs of a growing sum do not drift: whatever one epoch's quantization
    dropped is re-injected into the next epoch's payload. Returns ``(wire, decoded)``
    — ``decoded`` is exactly what every receiver reconstructs — or None when the value
    cannot compress (the caller ships raw and leaves the residual untouched).
    """
    base = np.asarray(value)
    if base.dtype != np.float32:
        return None
    carry = base
    if residuals is not None and key is not None:
        prev = residuals.get(key)
        if prev is not None and prev.shape == base.shape:
            carry = base + prev
    blob = encode_array(carry, mode)
    if blob is None:
        return None
    approx = decode(blob, base.shape, base.dtype)
    if residuals is not None and key is not None:
        residuals[key] = (carry - approx).astype(np.float32)
    return blob, approx


def encode_for_wire(
    value: Any,
    fx: Any,
    mode: str,
    sketch_kind: Optional[str] = None,
    residuals: Optional[MutableMapping[str, np.ndarray]] = None,
    key: Optional[str] = None,
) -> Tuple[Any, str]:
    """The whole shipping policy for one state: plan, encode, never-bigger guard.

    Returns ``(wire_or_original, plan)`` where ``plan`` is the treatment that was
    ACTUALLY applied — a blob that fails to beat the raw bytes (scalars, tiny vectors,
    non-finite int8 blocks) degrades to ``"raw"`` and, because raw ships exact, any
    stored error-feedback residual for the state is cleared rather than carried.
    Shared by ``process_sync`` and the simulated transports so every simulated rank
    applies byte-for-byte the policy the local rank does.
    """
    plan = plan_state(value, fx, mode, sketch_kind)
    if plan == "raw":
        return value, "raw"
    arr = np.asarray(value)
    blob: Optional[np.ndarray] = None
    if plan == "sketch":
        blob = encode_sketch(arr, sketch_kind or "")
    elif fx == "sum":
        enc = encode_with_feedback(arr, plan, residuals, key)
        if enc is not None:
            blob = enc[0]
    else:
        blob = encode_array(arr, plan)
    if blob is None or blob.nbytes >= arr.nbytes:
        if residuals is not None and key is not None:
            residuals.pop(key, None)
        return value, "raw"
    return blob, plan


def sum_error_bound(mode: str, per_rank_maxabs: Any, world: Optional[int] = None) -> float:
    """Documented abs-error bound for a ``sum`` synced under lossy compression.

    Per rank, per element: bf16 rounds at ≤ ``2^-8`` relative, int8 block-scaling at
    ≤ ``block_max/254`` absolute. Summing ``world`` quantized contributions adds the
    per-rank bounds; the error-feedback carry can push one epoch's shipped magnitude
    up to one quantum past the raw value, covered by the 2x slack. ``per_rank_maxabs``
    is a scalar (shared bound) or one max-abs per rank.
    """
    eps = LOSSY_EPS[validate_mode(mode)] if mode != "none" else 0.0
    maxes = np.atleast_1d(np.asarray(per_rank_maxabs, np.float64))
    if world is not None and maxes.size == 1:
        maxes = np.repeat(maxes, world)
    return float(2.0 * eps * np.sum(maxes))


def reset_residuals(store: MutableMapping[str, np.ndarray]) -> None:
    """Drop accumulated error-feedback residuals (tests / after a state reset)."""
    store.clear()
