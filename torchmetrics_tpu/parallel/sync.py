"""Reduce-fx → XLA-collective mapping.

Parity map (reference ``src/torchmetrics/utilities/distributed.py`` + ``metric.py:426-456``):

==================  =========================================  =============================
reference            semantics                                  TPU-native lowering
==================  =========================================  =============================
gather+``sum``       all_gather → stack → sum                   ``lax.psum`` (fused all-reduce)
gather+``mean``      all_gather → stack → mean                  ``lax.pmean``
gather+``max/min``   all_gather → stack → max/min               ``lax.pmax/pmin``
gather+``cat``        all_gather → concat dim0                  ``lax.all_gather(tiled=True)``
``None``             all_gather → list of replicas              ``lax.all_gather`` (new axis)
uneven shapes        gather sizes → pad → gather → trim         static pad-to-capacity + mask
==================  =========================================  =============================

Elastic degraded modes (docs/robustness.md "Quorum sync and rank health"): a bounded
``process_sync`` no longer collapses straight to local-only state on timeout. With
``SyncOptions(quorum=...)`` it aggregates over the ranks that DID respond (sum rescaled
``world/k``, mean over responders, min/max/cat exact over the responding subset), reports
``SyncedState.responding_ranks``, and grades ``world_consistent`` as a tri-state
(``full | quorum | local``). A process-global :class:`HealthLedger` tracks per-rank
consecutive timeouts and latency EWMA, evicts a flapping rank from the gather group after
``evict_after`` failures (circuit breaker), probes it with exponential backoff, and
re-admits it on the first successful probe — re-admission state reconciliation rides
``torchmetrics_tpu.robust.checkpoint`` blobs.
"""
from __future__ import annotations

import dataclasses
import functools
import math
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array, lax

from torchmetrics_tpu import obs
from torchmetrics_tpu.parallel import compress as _compress
from torchmetrics_tpu.utils.exceptions import SyncTimeoutError
from torchmetrics_tpu.utils.prints import rank_zero_warn

ReduceFx = Union[str, Callable, None]


@functools.lru_cache(maxsize=None)
def _empty_payload() -> Array:
    """Shared zero-length gather payload for empty list states.

    Built once per process: constructing it inline in ``process_sync`` re-uploads the
    same constant to the device on every sync (jaxlint TPU006)."""
    return jnp.zeros((0,))

# ------------------------------------------------------------------ bounded-sync options
ENV_SYNC_TIMEOUT = "TM_TPU_SYNC_TIMEOUT_S"
ENV_SYNC_RETRIES = "TM_TPU_SYNC_RETRIES"
ENV_SYNC_BACKOFF = "TM_TPU_SYNC_BACKOFF_S"
ENV_SYNC_DEGRADED = "TM_TPU_SYNC_DEGRADED"
ENV_SYNC_QUORUM = "TM_TPU_SYNC_QUORUM"
ENV_SYNC_EVICT_AFTER = "TM_TPU_SYNC_EVICT_AFTER"
ENV_SYNC_PROBE_BACKOFF = "TM_TPU_SYNC_PROBE_BACKOFF_S"
ENV_SYNC_JITTER = "TM_TPU_SYNC_JITTER"
ENV_SYNC_COMPRESSION = _compress.ENV_SYNC_COMPRESSION  # "TM_TPU_SYNC_COMPRESSION"

#: retry-backoff jitter RNG, seeded from the chaos harness's fixed seed when one is
#: pinned (``TM_TPU_CHAOS_SEED``, ``make chaos``) so jittered retry schedules stay
#: deterministic under fault injection; free-running entropy otherwise
_BACKOFF_RNG: Optional[Any] = None


def _backoff_rng() -> Any:
    global _BACKOFF_RNG
    if _BACKOFF_RNG is None:
        import random

        seed = os.environ.get("TM_TPU_CHAOS_SEED", "")
        _BACKOFF_RNG = random.Random(int(seed)) if seed.lstrip("-").isdigit() else random.Random()
    return _BACKOFF_RNG


def reset_backoff_rng() -> None:
    """Re-derive the jitter RNG from the current env (tests re-pinning the chaos seed)."""
    global _BACKOFF_RNG
    _BACKOFF_RNG = None


class ConsistencyLevel(str):
    """Tri-state world-consistency grade of a sync: ``full | quorum | local``.

    A ``str`` subclass so the grade serialises/compares naturally (``level == "quorum"``),
    with boolean semantics preserved from the PR-4 bool era: ``bool(level)`` is True ONLY
    for ``full`` — code that did ``if not synced.world_consistent: ...`` still treats any
    degraded sync (quorum OR local) as non-world-consistent.
    """

    __slots__ = ()

    def __bool__(self) -> bool:
        return str.__eq__(self, "full")


FULL = ConsistencyLevel("full")
QUORUM = ConsistencyLevel("quorum")
LOCAL = ConsistencyLevel("local")


def as_consistency(value: Any) -> ConsistencyLevel:
    """Coerce a legacy bool (or raw string) consistency flag to its tri-state grade."""
    if isinstance(value, ConsistencyLevel):
        return value
    if isinstance(value, str):
        if value == "quorum":
            return QUORUM
        return FULL if value == "full" else LOCAL
    return FULL if value else LOCAL


@dataclasses.dataclass(frozen=True)
class SyncOptions:
    """Bounding + elasticity policy for the eager multi-process sync path (``process_sync``).

    ``timeout_s == 0`` (the default) disables bounding entirely — gathers run inline on
    the calling thread with zero added overhead, exactly the pre-PR-4 behaviour. With a
    positive timeout each gather runs on a worker thread against a *whole-sync* deadline;
    a timed-out or crashed gather is retried up to ``retries`` times with exponential
    backoff (``backoff_s * 2**attempt``). On exhaustion, in order of preference:

    1. **quorum** (``quorum`` set, and the partial responses the gather attached to its
       :class:`SyncTimeoutError` cover at least the quorum): aggregate over the responding
       ranks — ``sum`` rescaled by ``world/k`` (``quorum_rescale=False`` keeps the exact
       partial sum), ``mean`` over responders, ``min``/``max``/``cat`` exact over the
       responding subset. The result grades ``world_consistent="quorum"``.
    2. **local fallback** (``degraded_mode=True``): the state keeps its LOCAL value and the
       result grades ``world_consistent="local"``.
    3. **strict** (``degraded_mode=False``): :class:`SyncTimeoutError` propagates.

    ``quorum`` is an absolute rank count (int ≥ 1) or a world fraction (float in (0, 1]).
    ``world`` overrides ``jax.process_count()`` — for simulated worlds driven through an
    injected ``gather_fn`` (tests, chaos harness); leave None in real deployments.
    ``evict_after``/``probe_backoff_s`` configure the per-rank circuit breaker
    (:class:`HealthLedger`): a rank missing ``evict_after`` consecutive syncs is evicted
    from the gather group and probed with exponential backoff until it answers again.

    ``compression`` (``"none" | "bf16" | "int8"``, env ``TM_TPU_SYNC_COMPRESSION``)
    selects the wire codec for every eager gather (docs/distributed.md "Compressed
    collectives"): block-scaled lossy quantization of float32 sum/mean slabs with
    error-feedback residuals, LOSSLESS packed blobs for sketch states, raw (exact)
    wire for int/bool dtypes, min/max/cat/None/callable reductions, and anything the
    blob would not shrink. ``"none"`` is byte-for-byte the pre-codec behaviour.
    """

    timeout_s: float = 0.0
    retries: int = 2
    backoff_s: float = 0.05
    #: decorrelated jitter on the retry backoff (AWS-style: next pause drawn uniformly
    #: from [backoff_s, 3*previous]). Plain exponential backoff SYNCHRONIZES retry
    #: storms: after a shared stall (one straggler chip, one slow switch) every rank
    #: retries on the same 2^k schedule and the collective thunders in lockstep; jitter
    #: decorrelates the herd. Deterministic under chaos via the seeded-injector RNG
    #: (``TM_TPU_CHAOS_SEED`` seeds the jitter stream too).
    backoff_jitter: bool = True
    degraded_mode: bool = True
    quorum: Optional[Union[int, float]] = None
    quorum_rescale: bool = True
    world: Optional[int] = None
    evict_after: int = 3
    probe_backoff_s: float = 1.0
    compression: str = "none"

    def __post_init__(self) -> None:
        # normalise + validate eagerly so a typo'd mode fails at the construction site,
        # not on the first (possibly degraded) sync deep inside compute()
        object.__setattr__(self, "compression", _compress.validate_mode(self.compression))

    @property
    def bounded(self) -> bool:
        return self.timeout_s > 0


def _parse_quorum(raw: Optional[str]) -> Optional[Union[int, float]]:
    """``"0.5"`` → fraction of world, ``"2"`` → absolute rank count, unset/invalid → None."""
    if not raw:
        return None
    try:
        val = float(raw) if "." in raw else int(raw)
    except (TypeError, ValueError):
        return None
    return val if val > 0 else None


def _parse_compression(raw: Optional[str]) -> str:
    """Env-lenient mode parse: unset/invalid values fall back to ``"none"``."""
    try:
        return _compress.validate_mode(raw)
    except ValueError:
        return "none"


def sync_options_from_env() -> SyncOptions:
    """Build :class:`SyncOptions` from the ``TM_TPU_SYNC_*`` environment knobs."""

    def _f(name: str, default: float) -> float:
        try:
            return float(os.environ.get(name, default))
        except (TypeError, ValueError):
            return default

    return SyncOptions(
        timeout_s=_f(ENV_SYNC_TIMEOUT, 0.0),
        retries=int(_f(ENV_SYNC_RETRIES, 2)),
        backoff_s=_f(ENV_SYNC_BACKOFF, 0.05),
        backoff_jitter=str(os.environ.get(ENV_SYNC_JITTER, "1")).strip().lower()
        not in ("0", "false", "no", "off"),
        degraded_mode=str(os.environ.get(ENV_SYNC_DEGRADED, "1")).strip().lower()
        not in ("0", "false", "no", "off"),
        quorum=_parse_quorum(os.environ.get(ENV_SYNC_QUORUM)),
        evict_after=int(_f(ENV_SYNC_EVICT_AFTER, 3)),
        probe_backoff_s=_f(ENV_SYNC_PROBE_BACKOFF, 1.0),
        compression=_parse_compression(os.environ.get(ENV_SYNC_COMPRESSION)),
    )


class SyncedState(dict):
    """``process_sync`` result: a plain state dict plus world-consistency metadata.

    ``world_consistent`` is the tri-state :class:`ConsistencyLevel` — ``full`` when every
    state gathered from the whole world, ``quorum`` when at least one state aggregated
    over a responding subset (quorum fallback or a circuit-broken gather group), ``local``
    when any state fell back to its purely local value. Bool contexts keep the PR-4
    meaning: truthy only for ``full``. ``degraded_states`` names the local-fallback
    states, ``quorum_states`` the subset-aggregated ones, ``responding_ranks`` maps each
    state to the ranks whose contribution its value covers, and ``readmitted_ranks``
    lists circuit-broken ranks that answered their probe during THIS sync.
    ``gather_latency_us`` maps each state name to the wall time its gather took on THIS
    rank — the raw material of the cross-rank skew report (:func:`skew_report`).
    ``bytes_shipped``/``bytes_received`` account the sync's communication volume on this
    rank — TRUE wire bytes: when a state ships as a quantized slab or a packed sketch
    blob, the blob's bytes are counted, not the raw array's. ``sharded_states`` names
    the states that synced through the reduce-scatter shard path instead of a full
    allgather; ``compression`` tags the wire mode the sync ran under,
    ``compressed_states`` the states whose payloads actually shrank, and
    ``bytes_saved`` the bytes this sync avoided versus a full-precision allgather
    (shard-path savings + codec savings combined).
    """

    world_consistent: ConsistencyLevel = FULL
    degraded_states: Tuple[str, ...] = ()
    quorum_states: Tuple[str, ...] = ()
    responding_ranks: Dict[str, Tuple[int, ...]] = {}
    readmitted_ranks: Tuple[int, ...] = ()
    gather_latency_us: Dict[str, float] = {}
    bytes_shipped: int = 0
    bytes_received: int = 0
    bytes_saved: int = 0
    sharded_states: Tuple[str, ...] = ()
    compression: str = "none"
    compressed_states: Tuple[str, ...] = ()


# ------------------------------------------------------------------ rank health ledger
@dataclasses.dataclass
class RankHealth:
    """Per-rank health record: consecutive-timeout breaker state + latency EWMA."""

    rank: int
    consecutive_failures: int = 0
    total_failures: int = 0
    successes: int = 0
    latency_ewma_us: Optional[float] = None
    evicted: bool = False
    evicted_at: float = 0.0  # monotonic timestamp of eviction / last failed probe
    failed_probes: int = 0  # probe attempts since eviction (backoff exponent)
    readmissions: int = 0

    def as_dict(self) -> Dict[str, Any]:
        return {
            "rank": self.rank,
            "consecutive_failures": self.consecutive_failures,
            "total_failures": self.total_failures,
            "successes": self.successes,
            "latency_ewma_us": None if self.latency_ewma_us is None else round(self.latency_ewma_us, 1),
            "evicted": self.evicted,
            "failed_probes": self.failed_probes,
            "readmissions": self.readmissions,
        }


class HealthLedger:
    """Process-global per-rank health: circuit breakers over the eager gather group.

    A rank that misses ``evict_after`` consecutive syncs is **evicted**: subsequent
    gathers exclude it (so one flapping peer stops stalling every sync at the full
    deadline) and grade ``quorum``. Evicted ranks are **probed** by re-including them in
    the gather group once their backoff (``probe_backoff_s * 2**failed_probes``, capped)
    expires — a successful probe **re-admits** the rank (``sync.rank_readmissions``); a
    failed one deepens the backoff. Latency EWMA per rank is fed by :func:`skew_report`'s
    cross-rank mean gathers and surfaced in its output and ``obs.summary()``.

    Rank attribution requires a gather that can name responders (partial ``responses`` on
    its :class:`SyncTimeoutError`, or a ``ranks=...``-aware subgroup gather). The stock
    ``multihost_utils.process_allgather`` path is all-or-nothing, so with it the ledger
    simply never accumulates failures — behaviour is unchanged.

    Threading contract: the ledger is main-thread-only today (the tmrace static pass
    confirms no concurrent writer reaches it), and it carries no locks on that basis.
    The ``health_ledger_evict_vs_probe`` racerun scenario (``make jaxlint-race``) pins
    the invariants any future multi-threaded caller (per-tier ledgers, ROADMAP item 5)
    must preserve: a fixed rank population never resizes ``ranks`` mid-iteration, and
    the eviction/probe partition stays consistent under interleaved readers.
    """

    EWMA_ALPHA = 0.2

    def __init__(self, evict_after: int = 3, probe_backoff_s: float = 1.0, probe_backoff_cap_s: float = 60.0) -> None:
        self.evict_after = evict_after
        self.probe_backoff_s = probe_backoff_s
        self.probe_backoff_cap_s = probe_backoff_cap_s
        self.ranks: Dict[int, RankHealth] = {}

    def configure(self, opts: "SyncOptions") -> None:
        """Adopt the breaker thresholds of the sync options driving the current sync."""
        self.evict_after = max(1, int(opts.evict_after)) if opts.evict_after else 0
        self.probe_backoff_s = max(0.0, float(opts.probe_backoff_s))

    def _get(self, rank: int) -> RankHealth:
        h = self.ranks.get(rank)
        if h is None:
            h = self.ranks[rank] = RankHealth(rank=int(rank))
        return h

    def record_success(self, rank: int, latency_us: Optional[float] = None) -> bool:
        """Mark a responding rank healthy; returns True when this re-admitted an evictee."""
        h = self._get(rank)
        h.successes += 1
        h.consecutive_failures = 0
        if latency_us is not None:
            if h.latency_ewma_us is None:
                h.latency_ewma_us = float(latency_us)
            else:
                h.latency_ewma_us += self.EWMA_ALPHA * (float(latency_us) - h.latency_ewma_us)
        if h.evicted:
            h.evicted = False
            h.failed_probes = 0
            h.readmissions += 1
            obs.telemetry.counter("sync.rank_readmissions").inc()
            obs.telemetry.event("sync.rank_readmitted", cat="sync", args={"rank": h.rank})
            obs.flightrec.record("rank.readmitted", rank=h.rank, readmissions=h.readmissions)
            rank_zero_warn(
                f"process_sync: rank {h.rank} answered its health probe and was re-admitted"
                " to the gather group. Reconcile its state before trusting full-world"
                " results (docs/robustness.md, 'Re-admission handshake').",
                UserWarning,
            )
            return True
        return False

    def record_failure(self, rank: int) -> bool:
        """Mark a missing rank; returns True when this call tripped its circuit breaker."""
        h = self._get(rank)
        h.total_failures += 1
        h.consecutive_failures += 1
        now = time.monotonic()
        if h.evicted:
            # a failed probe: deepen the backoff, restart its clock
            h.failed_probes += 1
            h.evicted_at = now
            return False
        if self.evict_after and h.consecutive_failures >= self.evict_after:
            h.evicted = True
            h.evicted_at = now
            h.failed_probes = 0
            obs.telemetry.counter("sync.rank_evictions").inc()
            obs.telemetry.event(
                "sync.rank_evicted", cat="sync",
                args={"rank": h.rank, "consecutive_failures": h.consecutive_failures},
            )
            obs.flightrec.record(
                "rank.evicted", rank=h.rank, consecutive_failures=h.consecutive_failures
            )
            rank_zero_warn(
                f"process_sync: rank {h.rank} missed {h.consecutive_failures} consecutive"
                " sync(s) and was evicted from the gather group (circuit breaker). It will"
                f" be probed with exponential backoff (base {self.probe_backoff_s:g}s) and"
                " re-admitted when it answers.",
                UserWarning,
            )
            return True
        return False

    def _probe_due(self, h: RankHealth, now: float) -> bool:
        wait = min(self.probe_backoff_s * (2 ** h.failed_probes), self.probe_backoff_cap_s)
        return now - h.evicted_at >= wait

    def gather_group(self, world: int) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """(ranks to gather from, subset of those that are backoff-due probes)."""
        now = time.monotonic()
        group: List[int] = []
        probes: List[int] = []
        for r in range(world):
            h = self.ranks.get(r)
            if h is None or not h.evicted:
                group.append(r)
            elif self._probe_due(h, now):
                group.append(r)
                probes.append(r)
        return tuple(group), tuple(probes)

    def evicted_ranks(self) -> Tuple[int, ...]:
        return tuple(sorted(r for r, h in self.ranks.items() if h.evicted))

    def observe_latencies(self, per_rank_mean_us: Sequence[float]) -> None:
        """Fold a cross-rank latency gather (``skew_report``) into the per-rank EWMAs."""
        for rank, us in enumerate(per_rank_mean_us):
            h = self._get(rank)
            if h.latency_ewma_us is None:
                h.latency_ewma_us = float(us)
            else:
                h.latency_ewma_us += self.EWMA_ALPHA * (float(us) - h.latency_ewma_us)

    def report(self) -> Dict[int, Dict[str, Any]]:
        return {r: h.as_dict() for r, h in sorted(self.ranks.items())}

    def reset(self) -> None:
        self.ranks.clear()


_HEALTH: Optional[HealthLedger] = None


def health_ledger() -> HealthLedger:
    """The process-global rank health ledger (created on first use)."""
    global _HEALTH
    if _HEALTH is None:
        _HEALTH = HealthLedger()
    return _HEALTH


def reset_health_state() -> None:
    """Drop all per-rank health records (tests)."""
    if _HEALTH is not None:
        _HEALTH.reset()


# ------------------------------------------------------------------ cross-rank skew report
#: recent per-gather latencies on this rank (bounded; feeds skew_report / obs.summary)
_GATHER_LATENCIES_US: "deque" = deque(maxlen=1024)
_LAST_SKEW: Optional[Dict[str, Any]] = None


def _record_gather_latency(dur_s: float) -> None:
    us = dur_s * 1e6
    _GATHER_LATENCIES_US.append(us)
    obs.telemetry.histogram("sync.gather.latency_us").record(us)
    # always-on live series (docs/observability.md "Live time series"): windowed
    # gather rate + all-time KLL quantiles, addressable by SLO specs (e.g. a gather
    # p99 objective) and rendered by the OpenMetrics exposition
    obs.telemetry.series("sync.gather_latency_us").record(us)


def local_gather_stats() -> Optional[Dict[str, Any]]:
    """Mean/p50/max of this rank's recent gather latencies; None before any sync ran."""
    if not _GATHER_LATENCIES_US:
        return None
    vals = sorted(_GATHER_LATENCIES_US)
    n = len(vals)
    return {
        "count": n,
        "mean_us": round(sum(vals) / n, 1),
        "p50_us": round(vals[n // 2], 1),
        "max_us": round(vals[-1], 1),
    }


def skew_report(gather_fn: Optional[Callable] = None) -> Optional[Dict[str, Any]]:
    """Cross-rank gather-latency skew: per-rank mean latencies → a straggler index.

    Each rank contributes the mean of its recent gather latencies; the report gathers
    them (ONE tiny extra collective at world > 1 — or ``gather_fn`` injected for tests)
    and computes ``straggler_index = max / median`` with the offending rank named. An
    index near 1.0 means the mesh gathers in lockstep; a rank whose collectives
    consistently take N× the median holds every sync back by the same factor. The
    per-rank means also feed the :class:`HealthLedger` latency EWMAs, and the ledger's
    breaker states ride along under ``health``. The result is cached module-wide and
    surfaced by ``obs.summary()`` and ``Metric.telemetry``. Returns None when no gather
    latency has been recorded yet.
    """
    global _LAST_SKEW
    local = local_gather_stats()
    if local is None:
        return None
    try:
        world = jax.process_count()
        rank = jax.process_index()
    except Exception:  # jaxlint: disable=TPU019 - capability probe: no backend = single-process defaults, nothing absorbed
        world, rank = 1, 0
    payload = np.asarray([local["mean_us"]], np.float32)
    if gather_fn is not None:
        gathered = [np.asarray(g).reshape(-1) for g in gather_fn(payload, None)]
    elif world > 1:
        gathered = [np.asarray(g).reshape(-1) for g in gather_all_arrays(jnp.asarray(payload))]
    else:
        gathered = [payload]
    per_rank = [round(float(g[0]), 1) for g in gathered]
    ranked = sorted(per_rank)
    median = ranked[len(ranked) // 2] or 1.0
    worst = max(per_rank)
    ledger = health_ledger()
    ledger.observe_latencies(per_rank)
    report = {
        "world": len(per_rank),
        "rank": rank,
        "per_rank_mean_us": per_rank,
        "straggler_rank": int(per_rank.index(worst)),
        "straggler_index": round(worst / median, 3) if median else 1.0,
        "local": local,
    }
    if ledger.ranks:
        report["health"] = ledger.report()
        report["evicted_ranks"] = ledger.evicted_ranks()
    _LAST_SKEW = report
    obs.telemetry.event("sync.skew_report", cat="sync", args=report)
    return report


def last_skew_report() -> Optional[Dict[str, Any]]:
    """The most recent :func:`skew_report` result (no collective); None if never run."""
    return _LAST_SKEW


def reset_skew_state() -> None:
    """Drop recorded gather latencies and the cached skew report (tests)."""
    global _LAST_SKEW
    _GATHER_LATENCIES_US.clear()
    _LAST_SKEW = None


def _bounded_gather(
    gather: Callable, value: Any, group: Optional[str], kw: Dict[str, Any],
    opts: SyncOptions, deadline: float, state_name: str,
) -> List[Any]:
    """Run one gather against the sync deadline, retrying with exponential backoff.

    The gather runs on a daemon worker thread so a peer that never answers cannot wedge
    the training process — the thread is abandoned at timeout (there is no portable way
    to cancel a blocked collective; abandonment + retry/degrade is the honest contract).
    Raises :class:`SyncTimeoutError` when the deadline/retry budget is exhausted,
    carrying any partial per-rank ``responses`` the last failed gather attached so the
    caller can attempt quorum aggregation.
    """
    attempt = 0
    last_error: Optional[BaseException] = None
    prev_pause = opts.backoff_s
    while True:
        # deadline arithmetic, not metric semantics: the clock decides when to STOP
        # waiting on a straggler, never which batch lands where (values are identical
        # on every timing path — degraded mode is flagged, not silent)
        remaining = deadline - time.monotonic()  # jaxlint: disable=TPU017
        if remaining <= 0:
            raise SyncTimeoutError(
                f"sync of state {state_name!r} exhausted its {opts.timeout_s:g}s deadline"
                f" after {attempt} attempt(s)",
                responses=getattr(last_error, "responses", None),
            )
        result: List[Any] = []
        error: List[BaseException] = []
        done = threading.Event()

        def _work() -> None:
            try:
                result.append(gather(value, group, **kw))
            except BaseException as err:  # noqa: BLE001  # jaxlint: disable=TPU019 - not a swallow: the error crosses the thread boundary and re-raises in the caller
                error.append(err)
            finally:
                done.set()

        worker = threading.Thread(target=_work, daemon=True, name="tm-tpu-sync-gather")
        worker.start()
        finished = done.wait(remaining)
        if finished and result:
            return result[0]
        if finished and error:
            last_error = error[0]
        attempt += 1
        obs.telemetry.counter("robust.sync_retries").inc()
        if attempt > opts.retries:
            detail = f"last error: {last_error!r}" if last_error is not None else "gather hung past the deadline"
            raise SyncTimeoutError(
                f"sync of state {state_name!r} failed after {attempt} attempt(s)"
                f" within its {opts.timeout_s:g}s deadline ({detail})",
                responses=getattr(last_error, "responses", None),
            )
        # backoff capped so the sleep never outlives the deadline. Default: decorrelated
        # jitter (pause ~ U[base, 3*prev]) — pure exponential backoff puts every rank
        # that shared a stall on the SAME 2^k schedule, so the retries storm the
        # interconnect in lockstep; the jittered schedule spreads them out while keeping
        # the same expected growth. Deterministic under `make chaos` (the RNG seeds from
        # TM_TPU_CHAOS_SEED, like the fault injectors).
        if opts.backoff_jitter:
            pause = _backoff_rng().uniform(opts.backoff_s, max(opts.backoff_s, prev_pause * 3.0))
        else:
            pause = opts.backoff_s * (2 ** (attempt - 1))
        prev_pause = pause
        pause = min(pause, max(0.0, deadline - time.monotonic()))  # jaxlint: disable=TPU017 - deadline clamp, not semantics
        if pause > 0:
            time.sleep(pause)


def _axis_size(axis_name: str) -> Optional[int]:
    """Static size of a mesh axis from inside a traced computation; None if unresolvable.

    ``lax.axis_size`` only exists on newer JAX; ``psum(1, axis)`` constant-folds to the axis
    size as a concrete int on every release this repo supports.
    """
    try:
        # static mesh metadata, constant-folds at trace time — no runtime sync
        return int(lax.axis_size(axis_name))  # jaxlint: disable=TPU001
    except Exception:  # jaxlint: disable=TPU019 - capability probe: older JAX lacks axis_size, the psum fold below answers
        pass
    try:
        size = lax.psum(1, axis_name)
        # the isinstance guard admits only the constant-folded (host int) case
        return int(size) if isinstance(size, int) else None  # jaxlint: disable=TPU001
    except Exception:
        return None


def _reduce_one(value: Array, reduce_fx: ReduceFx, axis_name: str) -> Array:
    """Synchronise a single tensor state across ``axis_name`` inside jit/shard_map/pmap."""
    if reduce_fx == "sum":
        return lax.psum(value, axis_name)
    if reduce_fx == "mean":
        return lax.pmean(value, axis_name)
    if reduce_fx == "max":
        return lax.pmax(value, axis_name)
    if reduce_fx == "min":
        return lax.pmin(value, axis_name)
    if reduce_fx == "cat":
        return lax.all_gather(value, axis_name, axis=0, tiled=True)
    if reduce_fx is None:
        # gather replicas along a fresh leading axis (caller applies its own reduction)
        return lax.all_gather(value, axis_name, axis=0, tiled=False)
    if callable(reduce_fx):
        gathered = lax.all_gather(value, axis_name, axis=0, tiled=False)
        return reduce_fx(gathered)
    raise ValueError(f"Unsupported dist_reduce_fx: {reduce_fx!r}")


def sync_state(
    state: Dict[str, Any],
    reductions: Dict[str, ReduceFx],
    axis_name: str,
) -> Dict[str, Any]:
    """Synchronise a metric state pytree across a mesh axis, inside a compiled computation.

    List states (Python lists of arrays) are pre-concatenated along dim 0 — mirroring
    ``metric.py:431-432`` — then treated as ``cat``.

    Telemetry: this body runs at TRACE time (the collectives execute inside the compiled
    program, where wall-clock timing is impossible), so the recorded event carries what IS
    known at trace time — state names, reduce-fx, payload bytes, and mesh-axis size. Executed
    latency is measured by the eager paths (``process_sync``) and the bench sync probes.
    """
    obs.telemetry.counter("sync.sync_state.traces").inc()  # jaxlint: disable=TPU009 — counts TRACES on purpose (see docstring)
    obs.telemetry.event(  # jaxlint: disable=TPU009 — trace-time record by design: collectives cannot be timed in-program
        "sync.sync_state", cat="sync",
        args={
            "axis": axis_name,
            "mesh_size": _axis_size(axis_name),
            "states": sorted(state),
            "bytes": obs.tree_bytes(state),
            "reductions": {k: getattr(v, "__name__", str(v)) for k, v in reductions.items()},
        },
    )
    out: Dict[str, Any] = {}
    for name, value in state.items():
        fx = reductions.get(name, "sum")
        if isinstance(value, (list, tuple)):
            if len(value) == 0:
                out[name] = value
                continue
            cat = jnp.concatenate([jnp.atleast_1d(v) for v in value], axis=0)
            out[name] = [_reduce_one(cat, "cat" if fx in (None, "cat") else fx, axis_name)]
        else:
            out[name] = _reduce_one(value, fx, axis_name)
    return out


# ---------------------------------------------------------------------------
# Multi-process eager path (one metric replica per host process, à la DDP)
# ---------------------------------------------------------------------------

def all_gather_object_shapes(local_shape: tuple) -> List[tuple]:
    """Gather dim-0 sizes from every process (reference ``distributed.py:118-127``)."""
    from jax.experimental import multihost_utils

    sizes = multihost_utils.process_allgather(jnp.asarray(local_shape, jnp.int32))
    return [tuple(int(d) for d in row) for row in np.asarray(sizes)]


def gather_all_arrays(value: Array, group: Optional[str] = None) -> List[Array]:
    """All-gather an array from every process, handling uneven dim-0 sizes by pad+trim.

    Returns a list of per-process arrays (reference ``gather_all_tensors``,
    ``distributed.py:97-147``). No-op single-element list when world size is 1.
    """
    del group
    obs.telemetry.counter("sync.gather.calls").inc()
    if jax.process_count() == 1:
        return [value]
    from jax.experimental import multihost_utils

    shapes = all_gather_object_shapes(tuple(value.shape))
    max_dim0 = max((s[0] if s else 0) for s in shapes)
    pad = max_dim0 - (value.shape[0] if value.ndim else 0)
    padded = jnp.pad(value, [(0, pad)] + [(0, 0)] * (value.ndim - 1)) if value.ndim else value
    gathered = multihost_utils.process_allgather(padded)  # (world, max_dim0, ...)
    return [jnp.asarray(gathered[i][: shapes[i][0]] if value.ndim else gathered[i]) for i in range(len(shapes))]


# ------------------------------------------------------------------ quorum aggregation
def _world_size(opts: SyncOptions) -> int:
    if opts.world is not None:
        return max(1, int(opts.world))
    try:
        return jax.process_count()
    except Exception:
        return 1


def _local_rank() -> int:
    try:
        return jax.process_index()
    except Exception:
        return 0


def quorum_threshold(quorum: Optional[Union[int, float]], world: int) -> int:
    """Minimum responding-rank count for a quorum; 0 when quorum mode is off.

    A float in (0, 1] is a world fraction (ceil), an int an absolute count (clamped to
    world). Single-rank worlds never quorum — the semantics are a no-op at world 1.
    """
    if not quorum or world <= 1:
        return 0
    if isinstance(quorum, float) and quorum <= 1.0:
        return max(1, math.ceil(quorum * world))
    return max(1, min(int(quorum), world))


def _rescale_sum(value: Array, world: int, k: int) -> Array:
    """Estimate the full-world sum from ``k`` of ``world`` contributions (``* world/k``).

    The registered state dtype is preserved: integer (count-like) sums are rounded back,
    float sums cast back from the weak-promoted product. ``k >= world`` is the identity.
    """
    if k >= world:
        return value
    scaled = value * (world / k)
    dtype = value.dtype if hasattr(value, "dtype") else jnp.float32
    if jnp.issubdtype(dtype, jnp.integer):
        return jnp.round(scaled).astype(dtype)
    return scaled.astype(dtype)


def _reduce_gathered(fx: ReduceFx, vals: List[Any], world: int, opts: SyncOptions) -> Any:
    """Host-side reduction of per-rank contributions, quorum-aware for partial worlds.

    With ``k = len(vals) < world``: ``sum`` is rescaled to a full-world estimate (unless
    ``quorum_rescale=False``), ``mean`` is the responders' mean (its divisor already
    adapts to ``k``), and ``min``/``max``/``cat``/``None``/callable are exact over the
    responding subset — partial extremes and concatenations are true statements about the
    ranks that answered, so no rescaling is applied.
    """
    k = len(vals)
    if fx == "sum":
        total = jnp.sum(jnp.stack(vals), axis=0)
        return _rescale_sum(total, world, k) if opts.quorum_rescale else total
    if fx == "mean":
        return jnp.mean(jnp.stack(vals), axis=0)
    if fx == "max":
        return jnp.max(jnp.stack(vals), axis=0)
    if fx == "min":
        return jnp.min(jnp.stack(vals), axis=0)
    if fx == "cat":
        return jnp.concatenate(vals, axis=0)
    if fx is None:
        return jnp.stack(vals)
    if callable(fx):
        return fx(jnp.stack(vals))
    raise ValueError(f"Unsupported dist_reduce_fx: {fx!r}")


def _nbytes(value: Any) -> int:
    """Byte size of one gather payload as it ACTUALLY travels (arrays via
    size×itemsize, lists summed).

    Wire blobs (``parallel.compress`` packed/quantized payloads) are 1-D uint8 arrays,
    so ``size × itemsize`` IS their true wire size — the ledger counts what shipped,
    never the raw array a sketch blob or quantized slab stands in for. (Before the
    codec layer the sketch states' ~12 KB arrays were charged at full f32 bytes even
    though only the packed blob need ship; ``bytes_saved`` is honest in every mode
    now that the accounting runs on the encoded payloads themselves.)
    """
    if isinstance(value, (list, tuple)):
        return sum(_nbytes(v) for v in value)
    size = getattr(value, "size", None)
    itemsize = getattr(getattr(value, "dtype", None), "itemsize", None)
    if size is None or itemsize is None:
        return 0
    return int(size) * int(itemsize)


def shardable_state(value: Any, fx: ReduceFx, world: int) -> bool:
    """Can this state sync via the reduce-scatter shard path in a ``world``-rank world?

    Needs an elementwise named reduction (sum/mean/max/min — slab-wise reduction of those
    is the SAME elementwise op sequence as the full reduction, so the result is
    bit-identical to the allgather path) and a leading axis that splits evenly across the
    ranks. ``cat``/``None``/callable reductions and scalars keep the full gather.
    """
    if fx not in ("sum", "mean", "max", "min"):
        return False
    shape = getattr(value, "shape", None)
    if not shape or world <= 1:
        return False
    return shape[0] >= world and shape[0] % world == 0


def simulate_mesh_world(
    rank_states: Sequence[Dict[str, Any]],
    reductions: Dict[str, ReduceFx],
    options: Optional[SyncOptions] = None,
    sketch_kinds: Optional[Dict[str, str]] = None,
) -> Callable:
    """A shard-aware ``gather_fn`` over a simulated multi-rank world (tests, bench).

    ``rank_states`` holds one state dict per simulated rank. The returned gather speaks
    the full sharded-sync contract of :func:`process_sync`:

    - plain call → every rank's full value (the replicated allgather),
    - ``shard_slice=(lo, hi)`` → every rank's ``value[lo:hi]`` (the reduce-scatter
      request: "ship me everyone's copy of MY rows"),
    - ``shard_assemble=rows`` → every rank's REDUCED owned slab (what each rank's own
      reduce-scatter phase produced), for the assembly allgather.

    This is the eager twin of a real reduce-scatter backend — on actual multihost
    deployments the same contract is implemented over the wire; here it reads the
    simulated ranks directly, so single-process tests and the ``bench.py --sharded``
    lane can drive the exact code path (and byte accounting) of a sharded sync.

    With ``options.compression != "none"`` the transport is codec-aware: every
    simulated rank's contribution travels as the SAME wire payload the local rank
    ships (block-scaled quantized slabs with per-rank host-side error-feedback
    residuals for sums, packed sketch blobs per ``sketch_kinds`` — a
    ``{state_name: SketchSpec.kind}`` map — exact raw wire everywhere else), and the
    shard phases quantize slab exchanges exactly as a real compressed reduce-scatter
    would (reduce over DECODED values, re-encode the reduced slab for assembly).
    """
    opts = options or SyncOptions()
    mode = _compress.validate_mode(getattr(opts, "compression", "none"))
    active = mode != "none" and len(rank_states) > 1
    kinds = dict(sketch_kinds or {})
    # per-simulated-rank error-feedback residuals, persistent across syncs (epochs)
    rank_residuals: List[Dict[str, Any]] = [{} for _ in rank_states]

    def _enc(rank: int, arr: Any, fx: ReduceFx, key: str, slab: bool = False) -> Any:
        if not active:
            return arr
        if slab and key in kinds:
            # a partitioned sum-merged sketch keeps RAW slabs: lossy quantization would
            # break the sketch-merge exactness promise, and the packed codecs are
            # whole-state formats
            return arr
        payload, _plan = _compress.encode_for_wire(
            arr, fx, mode,
            sketch_kind=None if slab else kinds.get(key),
            # slab exchanges re-quantize fresh sub-ranges per sync; residual feedback
            # is a full-state contract (see docs/distributed.md)
            residuals=None if slab else rank_residuals[rank],
            key=key,
        )
        return payload

    def gather(
        value: Any,
        group: Optional[str] = None,
        *,
        name: Optional[str] = None,
        shard_slice: Optional[Tuple[int, int]] = None,
        shard_assemble: Optional[int] = None,
    ) -> List[Any]:
        del group
        vals = [jnp.asarray(s[name]) for s in rank_states]
        fx = reductions.get(name, "sum")
        if shard_slice is not None:
            lo, hi = shard_slice
            return [_enc(i, v[lo:hi], fx, name, slab=True) for i, v in enumerate(vals)]
        if shard_assemble is not None:
            rows, world = int(shard_assemble), len(vals)

            def _assembled(r: int) -> Any:
                slabs = [v[r * rows:(r + 1) * rows] for v in vals]
                if active:
                    # faithful compressed reduce-scatter: rank r receives each peer's
                    # QUANTIZED slab, reduces the decoded values, then re-encodes its
                    # reduced slab for the assembly allgather
                    contrib = [_enc(i, s, fx, name, slab=True) for i, s in enumerate(slabs)]
                    slabs = [
                        _compress.maybe_decode(c, tuple(s.shape), s.dtype)
                        for c, s in zip(contrib, slabs)
                    ]
                reduced = _reduce_gathered(fx, [jnp.asarray(s) for s in slabs], world, opts)
                return _enc(r, reduced, fx, name, slab=True)

            return [_assembled(r) for r in range(world)]
        out = [_enc(i, v, fx, name) for i, v in enumerate(vals)]
        if active and _compress.is_wire(value):
            # the calling rank already encoded its payload (with ITS residual store);
            # echo that exact wire back so the round-trip matches what it shipped
            out[0] = value
        return out

    return gather


def process_sync(
    state: Dict[str, Any],
    reductions: Dict[str, ReduceFx],
    gather_fn: Optional[Callable] = None,
    group: Optional[str] = None,
    options: Optional[SyncOptions] = None,
    sharded_states: Optional[Sequence[str]] = None,
    sketch_wire: Optional[Dict[str, str]] = None,
    residuals: Optional[Dict[str, Any]] = None,
) -> "SyncedState":
    """Eager cross-process sync of a state dict; identity when world size is 1.

    A ``gather_fn`` that accepts a ``name`` keyword receives the state's name — gathers are then
    keyed by identity instead of having to match tensors by value (the reference's injected
    test gathers need this; value matching can mis-map states that happen to be equal). A
    ``gather_fn`` that accepts a ``ranks`` keyword receives the circuit-broken gather group
    (evicted ranks excluded, due probes included) and must answer with one entry per
    requested rank, in order — the subgroup-gather seam of the :class:`HealthLedger`.

    With a bounded :class:`SyncOptions` (explicit argument, or the ``TM_TPU_SYNC_*`` env
    knobs) each gather races a deadline with retry+backoff; exhausted states aggregate
    over the quorum of responding ranks when the options and partial responses allow,
    falling back to their LOCAL value under degraded mode otherwise — the returned
    :class:`SyncedState` grades the result ``full | quorum | local`` and names the
    degraded/quorum states — or raise :class:`SyncTimeoutError` when degraded mode is
    off. See ``docs/robustness.md``.

    ``sharded_states`` (set by ``Metric._sync_dist`` for states with a partitioned
    ``NamedSharding`` — docs/distributed.md "Sharded state") switches those states from
    the full allgather to **reduce-scatter + slab assembly** when the gather speaks the
    shard contract (accepts ``shard_slice``/``shard_assemble`` keywords, e.g.
    :func:`simulate_mesh_world` or a real reduce-scatter backend): this rank gathers only
    its OWNED ``1/world`` slab from every rank (received ``≈ state_bytes``), reduces it
    with the state's fx — slab-wise reduction is elementwise identical to the full
    reduction, so the result is bit-identical — then allgathers the ``world`` reduced
    slabs (another ``≈ state_bytes``). Total received ``≈ 2×state`` instead of the
    allgather's ``world × state``; ``SyncedState.bytes_shipped/bytes_received`` and the
    ``sync.bytes_*`` counters carry the accounting. A gather without the shard contract
    (the stock ``process_allgather`` path) falls back to the full gather unchanged.

    ``options.compression`` (docs/distributed.md "Compressed collectives") turns on the
    wire codec layer (:mod:`torchmetrics_tpu.parallel.compress`): float32 sum/mean
    payloads ship as block-scaled bf16/int8 blobs — sums with host-side error-feedback
    residuals (``residuals``, one dict per metric, so repeated syncs never drift) —
    and sketch states named in ``sketch_wire`` (``{state: SketchSpec.kind}``) ship as
    LOSSLESS packed blobs decoded and merged on the receiver. Every exactness-promising
    reduction (min/max/count/int dtypes, cat/None/callable, sketch merges) stays
    bit-identical to the uncompressed sync by construction; quorum aggregation operates
    on DECODED values, so partial-world rescaling composes with the codec unchanged.
    The codec needs a payload-faithful transport (one that ships what it is handed —
    the stock ``process_allgather``, or the codec-aware :func:`simulate_mesh_world`);
    raw entries from a compression-unaware gather pass through undecoded and simply
    stay uncompressed. ``SyncedState.compression/compressed_states/bytes_saved`` and
    the ``sync.bytes_saved.compression`` counter + ``sync.compression.*`` gauges carry
    the accounting.
    """
    import inspect

    obs.telemetry.counter("sync.process_sync.calls").inc()
    opts = options if options is not None else sync_options_from_env()
    t0 = time.perf_counter() if obs.telemetry.enabled else 0.0
    gather = gather_fn or gather_all_arrays
    takes_name = takes_ranks = takes_shard = False
    try:
        params = inspect.signature(gather).parameters
        var_kw = any(p.kind == inspect.Parameter.VAR_KEYWORD for p in params.values())
        takes_name = var_kw or "name" in params
        takes_ranks = "ranks" in params
        takes_shard = var_kw or ("shard_slice" in params and "shard_assemble" in params)
    except (TypeError, ValueError):
        pass
    shard_set = frozenset(sharded_states or ())
    world = _world_size(opts)
    rank = _local_rank()
    ledger = health_ledger()
    ledger.configure(opts)
    # circuit breakers: evicted ranks leave the gather group until their probe is due
    gather_group: Tuple[int, ...] = tuple(range(world))
    if world > 1 and takes_ranks:
        gather_group, _ = ledger.gather_group(world)
    quorum_k = quorum_threshold(opts.quorum, world)
    # timeout anchor for the bounded-sync deadline: fault-tolerance plumbing (when to
    # give up on a rank), never a value/window boundary
    deadline = time.monotonic() + opts.timeout_s if opts.bounded else 0.0  # jaxlint: disable=TPU017
    degraded: List[str] = []
    quorum_states: List[str] = []
    responding: Dict[str, Tuple[int, ...]] = {}
    ok_ranks: set = set()
    failed_ranks: set = set()
    gather_latency_us: Dict[str, float] = {}
    bytes_shipped = bytes_received = shard_saved = 0
    shard_synced: List[str] = []
    # wire codec (docs/distributed.md "Compressed collectives"): active only at world
    # > 1 — a single-rank "sync" never touches the wire, so mode "none" semantics and
    # the historical byte accounting are preserved exactly there
    mode = _compress.validate_mode(getattr(opts, "compression", "none"))
    compress_active = mode != "none" and world > 1
    sketch_kinds = dict(sketch_wire or {})
    compressed: List[str] = []
    comp_raw_bytes = comp_wire_bytes = 0

    def run_gather(payload: Any, name: str, kw: Dict[str, Any]) -> List[Any]:
        # per-gather wall time on THIS rank: the raw material of the cross-rank skew
        # report (skew_report / obs.summary). A perf_counter pair is noise next to a
        # collective, so the timing is always-on.
        g0 = time.perf_counter()
        try:
            if not opts.bounded:
                return gather(payload, group, **kw)
            return _bounded_gather(gather, payload, group, kw, opts, deadline, name)
        finally:
            dur = time.perf_counter() - g0
            gather_latency_us[name] = round(dur * 1e6, 1)
            _record_gather_latency(dur)

    def note_responders(name: str, ranks_responded: Any) -> None:
        resp = tuple(sorted(int(r) for r in ranks_responded))
        responding[name] = resp
        if world > 1:
            ok_ranks.update(resp)
            failed_ranks.update(r for r in gather_group if r not in resp)

    out: SyncedState = SyncedState()
    for name, value in state.items():
        fx = reductions.get(name, "sum")
        kw: Dict[str, Any] = {}
        if takes_name:
            kw["name"] = name
        if takes_ranks and world > 1:
            kw["ranks"] = gather_group
        is_list = isinstance(value, (list, tuple))
        if (
            name in shard_set and takes_shard and not is_list
            and shardable_state(value, fx, world)
        ):
            # reduce-scatter + slab assembly (docs/distributed.md "Sharded state"): this
            # rank owns rows [rank*rows, (rank+1)*rows) of the state. Phase 1 gathers
            # every rank's copy of the OWNED slab and reduces it (elementwise identical
            # to the full reduction — bit-identical results); phase 2 allgathers the
            # world's reduced slabs and concatenates them back into the full state.
            rows = value.shape[0] // world
            slab_bytes = _nbytes(value) // world
            slab_shape = (rows,) + tuple(value.shape[1:])
            # lossy slab codec: sum/mean f32 slabs quantize on the wire; sketch states
            # and exactness-promising reductions keep raw slabs (min/max stay exact)
            slab_lossy = (
                compress_active and name not in sketch_kinds
                and _compress.plan_state(value, fx, mode) in ("bf16", "int8")
            )
            got_wire = False
            try:
                pieces = run_gather(value, name, {**kw, "shard_slice": (rank * rows, (rank + 1) * rows)})
                recv_b = sum(_nbytes(p) for p in pieces)
                if slab_lossy:
                    got_wire = any(_compress.is_wire(p) for p in pieces)
                    pieces = [_compress.maybe_decode(p, slab_shape, value.dtype) for p in pieces]
                reduced_slab = _reduce_gathered(fx, [jnp.asarray(p) for p in pieces], world, opts)
                slabs = run_gather(reduced_slab, name, {**kw, "shard_assemble": rows})
                recv_b += sum(_nbytes(s) for s in slabs)
                if slab_lossy:
                    got_wire = got_wire or any(_compress.is_wire(s) for s in slabs)
                    slabs = [_compress.maybe_decode(s, slab_shape, value.dtype) for s in slabs]
            except SyncTimeoutError:
                # a missing rank loses rows, which no quorum can reconstruct — the
                # sharded path degrades straight to the local value (or raises)
                if not opts.degraded_mode:
                    # the exception is about to propagate out of the sync layer: land
                    # the post-mortem bundle while this process still can
                    obs.flightrec.open_incident("sync_timeout")
                    obs.flightrec.record("sync.timeout", state=name, world=world, sharded=True)
                    obs.capture_bundle("sync_timeout")
                    raise
                degraded.append(name)
                out[name] = value
                note_responders(name, (rank,))
                continue
            ship_b = 2 * slab_bytes
            if slab_lossy and got_wire:
                # the transport really spoke the codec: what we shipped was the same
                # encoding of our own slab, once per phase
                own = _compress.encode_array(
                    np.asarray(value[rank * rows:(rank + 1) * rows]), mode
                )
                if own is not None and own.nbytes < slab_bytes:
                    ship_b = 2 * int(own.nbytes)
                raw_total = (2 + len(pieces) + len(slabs)) * slab_bytes
                wire_total = ship_b + recv_b
                if wire_total < raw_total:
                    compressed.append(name)
                    comp_raw_bytes += raw_total
                    comp_wire_bytes += wire_total
            bytes_shipped += ship_b
            bytes_received += recv_b
            shard_saved += max(0, world * _nbytes(value) - recv_b)
            out[name] = jnp.concatenate([jnp.asarray(s) for s in slabs], axis=0)
            shard_synced.append(name)
            note_responders(name, range(world))
            continue
        if is_list and len(value) == 0 and jax.process_count() == 1 and world == 1:
            out[name] = list(value)
            continue
        if is_list:
            payload = jnp.concatenate([jnp.atleast_1d(v) for v in value], axis=0) if len(value) else _empty_payload()
        else:
            payload = value
        # wire codec seam: cat/list payloads always ship raw (sample streams must stay
        # exact); everything else goes through the shared shipping policy — packed
        # sketch blobs, error-feedback quantized sums, plain-quantized means, raw for
        # every exactness-promising reduction and for blobs that would not shrink
        plan = "raw"
        enc_payload = payload
        if compress_active and not is_list:
            enc_payload, plan = _compress.encode_for_wire(
                payload, fx, mode,
                sketch_kind=sketch_kinds.get(name),
                residuals=residuals if fx == "sum" else None,
                key=name,
            )
        try:
            gathered = run_gather(enc_payload, name, kw)
        except SyncTimeoutError as err:
            partial = dict(getattr(err, "responses", None) or {})
            # this rank's own contribution always "responds" — k >= 1, so the quorum
            # mean/rescale arithmetic can never divide by zero
            partial.setdefault(rank, enc_payload)
            if quorum_k and len(partial) >= quorum_k:
                vals = [partial[r] for r in sorted(partial)]
                if plan != "raw" or compress_active:
                    # quorum aggregation (incl. the sum rescale over responders)
                    # operates on DECODED values — the codec never changes the
                    # partial-world arithmetic
                    vals = [
                        _compress.maybe_decode(v, tuple(payload.shape), payload.dtype)
                        for v in vals
                    ] if not is_list else vals
                out[name] = list(vals) if is_list else _reduce_gathered(fx, vals, world, opts)
                quorum_states.append(name)
                note_responders(name, partial.keys())
                continue
            if not opts.degraded_mode:
                obs.flightrec.open_incident("sync_timeout")
                obs.flightrec.record(
                    "sync.timeout", state=name, world=world,
                    responded=sorted(int(r) for r in partial),
                )
                obs.capture_bundle("sync_timeout")
                raise
            degraded.append(name)
            out[name] = list(value) if is_list else value
            note_responders(name, partial.keys())
            continue
        wire_ship = _nbytes(enc_payload)
        wire_recv = sum(_nbytes(g) for g in gathered)
        bytes_shipped += wire_ship
        bytes_received += wire_recv
        if compress_active and not is_list:
            if plan != "raw" or any(_compress.is_wire(g) for g in gathered):
                raw_total = _nbytes(payload) * (1 + len(gathered))
                if wire_ship + wire_recv < raw_total:
                    compressed.append(name)
                    comp_raw_bytes += raw_total
                    comp_wire_bytes += wire_ship + wire_recv
                # the wire is self-identifying, so decode opportunistically: a transport
                # that encoded MORE than this rank planned (e.g. a codec-aware simulated
                # world given sketch descriptors this caller lacked) still round-trips
                gathered = [
                    _compress.maybe_decode(g, tuple(payload.shape), payload.dtype)
                    for g in gathered
                ]
        # successful gather: attribute the entries to ranks where the layout allows
        resp: Optional[Tuple[int, ...]] = None
        if takes_ranks and world > 1 and len(gathered) == len(gather_group):
            resp = gather_group
        elif len(gathered) == world:
            resp = tuple(range(world))
        if resp is not None:
            note_responders(name, resp)
            if len(resp) < world:
                quorum_states.append(name)  # subgroup gather: evicted ranks not covered
        if is_list:
            out[name] = [g for g in gathered]
            continue
        if len(gathered) == 1 and world == 1:
            out[name] = gathered[0]
            continue
        out[name] = _reduce_gathered(fx, list(gathered), world, opts)

    # one health mark per rank per sync: any missed state counts as a miss
    readmitted: List[int] = []
    if world > 1 and (ok_ranks or failed_ranks):
        latencies = list(gather_latency_us.values())
        mean_lat = (sum(latencies) / len(latencies)) if latencies else None
        for r in sorted(ok_ranks - failed_ranks):
            if ledger.record_success(r, mean_lat):
                readmitted.append(r)
        for r in sorted(failed_ranks):
            ledger.record_failure(r)

    level = LOCAL if degraded else (QUORUM if quorum_states else FULL)
    # flight ring (docs/observability.md "Flight recorder"): one always-on outcome
    # event per multi-rank sync, plus an explicit downgrade record whenever the
    # ConsistencyLevel left "full" — the trail a post-mortem bundle reconstructs
    if world > 1:
        obs.flightrec.record(
            "sync.outcome", level=str(level), world=world, states=len(state)
        )
    if level != FULL:
        obs.flightrec.record(
            "sync.downgrade", level=str(level),
            degraded=tuple(degraded), quorum=tuple(dict.fromkeys(quorum_states)),
        )
    out.world_consistent = level
    out.degraded_states = tuple(degraded)
    out.quorum_states = tuple(dict.fromkeys(quorum_states))
    out.responding_ranks = dict(responding)
    out.readmitted_ranks = tuple(readmitted)
    out.gather_latency_us = gather_latency_us
    out.bytes_shipped = bytes_shipped
    out.bytes_received = bytes_received
    out.sharded_states = tuple(shard_synced)
    comp_saved = max(0, comp_raw_bytes - comp_wire_bytes)
    out.compression = mode
    out.compressed_states = tuple(dict.fromkeys(compressed))
    out.bytes_saved = shard_saved + comp_saved
    if bytes_shipped or bytes_received:
        obs.telemetry.counter("sync.bytes_shipped").inc(bytes_shipped)
        obs.telemetry.counter("sync.bytes_received").inc(bytes_received)
    if shard_synced:
        obs.telemetry.counter("sync.bytes_saved").inc(shard_saved)
        obs.telemetry.event(
            "sync.sharded", cat="sync",
            args={"states": shard_synced, "world": world,
                  "bytes_received": bytes_received, "bytes_saved": shard_saved},
        )
    if compressed:
        # the codec's own ledger rows: cumulative bytes avoided vs the full-precision
        # allgather, plus per-sync compressed-vs-raw gauges for the OpenMetrics scrape
        obs.telemetry.counter("sync.compressed_syncs").inc()
        obs.telemetry.counter("sync.bytes_saved.compression").inc(comp_saved)
        obs.telemetry.gauge("sync.compression.wire_bytes").set(comp_wire_bytes)
        obs.telemetry.gauge("sync.compression.raw_bytes").set(comp_raw_bytes)
        obs.telemetry.event(
            "sync.compressed", cat="sync",
            args={"mode": mode, "states": out.compressed_states, "world": world,
                  "wire_bytes": comp_wire_bytes, "raw_bytes": comp_raw_bytes,
                  "bytes_saved": comp_saved},
        )
    if quorum_states and not degraded:
        obs.telemetry.counter("sync.quorum_syncs").inc()
        obs.telemetry.event(
            "sync.quorum", cat="sync",
            args={"states": out.quorum_states, "responding_ranks": {k: list(v) for k, v in responding.items()},
                  "world": world, "quorum_k": quorum_k},
        )
        covered = sorted({r for v in responding.values() for r in v})
        rank_zero_warn(
            f"process_sync degraded to QUORUM: state(s) {sorted(out.quorum_states)} aggregated"
            f" over responding rank(s) {covered} of a {world}-rank world. Sum-reduced values"
            f" are {'rescaled full-world estimates' if opts.quorum_rescale else 'exact partial sums'};"
            " min/max/cat cover the responding subset only (docs/robustness.md).",
            UserWarning,
        )
    if degraded:
        obs.telemetry.counter("robust.degraded_syncs").inc()
        obs.telemetry.event(
            "sync.degraded", cat="sync",
            args={"states": degraded, "timeout_s": opts.timeout_s, "retries": opts.retries},
        )
        rank_zero_warn(
            f"process_sync degraded: state(s) {sorted(degraded)} could not be gathered within"
            f" the {opts.timeout_s:g}s deadline ({opts.retries} retr{'y' if opts.retries == 1 else 'ies'});"
            " falling back to LOCAL state. The next compute() reflects this process only"
            " (non-world-consistent).",
            UserWarning,
        )
    if obs.telemetry.enabled:
        dur_us = (time.perf_counter() - t0) * 1e6
        obs.telemetry.histogram("sync.process_sync.latency_us").record(dur_us)
        obs.telemetry.event(
            "sync.process_sync", ph="X", cat="sync",
            ts_us=obs.telemetry.now_us() - dur_us, dur_us=dur_us,
            args={"world": world, "states": sorted(state), "bytes": obs.tree_bytes(state),
                  "consistency": str(level)},
        )
    return out


def shard_map_unchecked(mesh, in_specs, out_specs):
    """``shard_map`` with the output-replication check disabled, across JAX versions.

    all_gather(tiled) outputs ARE replicated over the gathered axis, but the varying-axes
    inference is conservative about gathers (psum is recognised, gathers are not); the disabling
    flag is ``check_vma`` on current JAX and ``check_rep`` on older releases.
    """
    import functools
    import inspect

    try:
        from jax import shard_map
    except ImportError:  # pre-0.8 JAX
        from jax.experimental.shard_map import shard_map

    flag = "check_vma" if "check_vma" in inspect.signature(shard_map).parameters else "check_rep"
    return functools.partial(shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **{flag: False})
