"""Reduce-fx → XLA-collective mapping.

Parity map (reference ``src/torchmetrics/utilities/distributed.py`` + ``metric.py:426-456``):

==================  =========================================  =============================
reference            semantics                                  TPU-native lowering
==================  =========================================  =============================
gather+``sum``       all_gather → stack → sum                   ``lax.psum`` (fused all-reduce)
gather+``mean``      all_gather → stack → mean                  ``lax.pmean``
gather+``max/min``   all_gather → stack → max/min               ``lax.pmax/pmin``
gather+``cat``        all_gather → concat dim0                  ``lax.all_gather(tiled=True)``
``None``             all_gather → list of replicas              ``lax.all_gather`` (new axis)
uneven shapes        gather sizes → pad → gather → trim         static pad-to-capacity + mask
==================  =========================================  =============================
"""
from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array, lax

from torchmetrics_tpu import obs
from torchmetrics_tpu.utils.exceptions import SyncTimeoutError
from torchmetrics_tpu.utils.prints import rank_zero_warn

ReduceFx = Union[str, Callable, None]

# ------------------------------------------------------------------ bounded-sync options
ENV_SYNC_TIMEOUT = "TM_TPU_SYNC_TIMEOUT_S"
ENV_SYNC_RETRIES = "TM_TPU_SYNC_RETRIES"
ENV_SYNC_BACKOFF = "TM_TPU_SYNC_BACKOFF_S"
ENV_SYNC_DEGRADED = "TM_TPU_SYNC_DEGRADED"


@dataclasses.dataclass(frozen=True)
class SyncOptions:
    """Bounding policy for the eager multi-process sync path (``process_sync``).

    ``timeout_s == 0`` (the default) disables bounding entirely — gathers run inline on
    the calling thread with zero added overhead, exactly the pre-PR-4 behaviour. With a
    positive timeout each gather runs on a worker thread against a *whole-sync* deadline;
    a timed-out or crashed gather is retried up to ``retries`` times with exponential
    backoff (``backoff_s * 2**attempt``), and on exhaustion the sync either falls back to
    the local state (``degraded_mode=True``: result marked non-world-consistent, rank-zero
    warning, ``robust.degraded_syncs`` counter) or raises :class:`SyncTimeoutError`.
    """

    timeout_s: float = 0.0
    retries: int = 2
    backoff_s: float = 0.05
    degraded_mode: bool = True

    @property
    def bounded(self) -> bool:
        return self.timeout_s > 0


def sync_options_from_env() -> SyncOptions:
    """Build :class:`SyncOptions` from the ``TM_TPU_SYNC_*`` environment knobs."""

    def _f(name: str, default: float) -> float:
        try:
            return float(os.environ.get(name, default))
        except (TypeError, ValueError):
            return default

    return SyncOptions(
        timeout_s=_f(ENV_SYNC_TIMEOUT, 0.0),
        retries=int(_f(ENV_SYNC_RETRIES, 2)),
        backoff_s=_f(ENV_SYNC_BACKOFF, 0.05),
        degraded_mode=str(os.environ.get(ENV_SYNC_DEGRADED, "1")).strip().lower()
        not in ("0", "false", "no", "off"),
    )


class SyncedState(dict):
    """``process_sync`` result: a plain state dict plus world-consistency metadata.

    ``world_consistent`` is False when any state fell back to its local value because the
    collective could not complete within its deadline; ``degraded_states`` names them.
    ``gather_latency_us`` maps each state name to the wall time its gather took on THIS
    rank — the raw material of the cross-rank skew report (:func:`skew_report`).
    """

    world_consistent: bool = True
    degraded_states: Tuple[str, ...] = ()
    gather_latency_us: Dict[str, float] = {}


# ------------------------------------------------------------------ cross-rank skew report
#: recent per-gather latencies on this rank (bounded; feeds skew_report / obs.summary)
_GATHER_LATENCIES_US: "deque" = deque(maxlen=1024)
_LAST_SKEW: Optional[Dict[str, Any]] = None


def _record_gather_latency(dur_s: float) -> None:
    us = dur_s * 1e6
    _GATHER_LATENCIES_US.append(us)
    obs.telemetry.histogram("sync.gather.latency_us").record(us)


def local_gather_stats() -> Optional[Dict[str, Any]]:
    """Mean/p50/max of this rank's recent gather latencies; None before any sync ran."""
    if not _GATHER_LATENCIES_US:
        return None
    vals = sorted(_GATHER_LATENCIES_US)
    n = len(vals)
    return {
        "count": n,
        "mean_us": round(sum(vals) / n, 1),
        "p50_us": round(vals[n // 2], 1),
        "max_us": round(vals[-1], 1),
    }


def skew_report(gather_fn: Optional[Callable] = None) -> Optional[Dict[str, Any]]:
    """Cross-rank gather-latency skew: per-rank mean latencies → a straggler index.

    Each rank contributes the mean of its recent gather latencies; the report gathers
    them (ONE tiny extra collective at world > 1 — or ``gather_fn`` injected for tests)
    and computes ``straggler_index = max / median`` with the offending rank named. An
    index near 1.0 means the mesh gathers in lockstep; a rank whose collectives
    consistently take N× the median holds every sync back by the same factor. The result
    is cached module-wide and surfaced by ``obs.summary()`` and ``Metric.telemetry``.
    Returns None when no gather latency has been recorded yet.
    """
    global _LAST_SKEW
    local = local_gather_stats()
    if local is None:
        return None
    try:
        world = jax.process_count()
        rank = jax.process_index()
    except Exception:
        world, rank = 1, 0
    payload = np.asarray([local["mean_us"]], np.float32)
    if gather_fn is not None:
        gathered = [np.asarray(g).reshape(-1) for g in gather_fn(payload, None)]
    elif world > 1:
        gathered = [np.asarray(g).reshape(-1) for g in gather_all_arrays(jnp.asarray(payload))]
    else:
        gathered = [payload]
    per_rank = [round(float(g[0]), 1) for g in gathered]
    ranked = sorted(per_rank)
    median = ranked[len(ranked) // 2] or 1.0
    worst = max(per_rank)
    report = {
        "world": len(per_rank),
        "rank": rank,
        "per_rank_mean_us": per_rank,
        "straggler_rank": int(per_rank.index(worst)),
        "straggler_index": round(worst / median, 3) if median else 1.0,
        "local": local,
    }
    _LAST_SKEW = report
    obs.telemetry.event("sync.skew_report", cat="sync", args=report)
    return report


def last_skew_report() -> Optional[Dict[str, Any]]:
    """The most recent :func:`skew_report` result (no collective); None if never run."""
    return _LAST_SKEW


def reset_skew_state() -> None:
    """Drop recorded gather latencies and the cached skew report (tests)."""
    global _LAST_SKEW
    _GATHER_LATENCIES_US.clear()
    _LAST_SKEW = None


def _bounded_gather(
    gather: Callable, value: Any, group: Optional[str], kw: Dict[str, Any],
    opts: SyncOptions, deadline: float, state_name: str,
) -> List[Any]:
    """Run one gather against the sync deadline, retrying with exponential backoff.

    The gather runs on a daemon worker thread so a peer that never answers cannot wedge
    the training process — the thread is abandoned at timeout (there is no portable way
    to cancel a blocked collective; abandonment + retry/degrade is the honest contract).
    Raises :class:`SyncTimeoutError` when the deadline/retry budget is exhausted.
    """
    attempt = 0
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise SyncTimeoutError(
                f"sync of state {state_name!r} exhausted its {opts.timeout_s:g}s deadline"
                f" after {attempt} attempt(s)"
            )
        result: List[Any] = []
        error: List[BaseException] = []
        done = threading.Event()

        def _work() -> None:
            try:
                result.append(gather(value, group, **kw))
            except BaseException as err:  # noqa: BLE001 - must cross the thread boundary
                error.append(err)
            finally:
                done.set()

        worker = threading.Thread(target=_work, daemon=True, name="tm-tpu-sync-gather")
        worker.start()
        finished = done.wait(remaining)
        if finished and result:
            return result[0]
        attempt += 1
        obs.telemetry.counter("robust.sync_retries").inc()
        if attempt > opts.retries:
            detail = f"last error: {error[0]!r}" if (finished and error) else "gather hung past the deadline"
            raise SyncTimeoutError(
                f"sync of state {state_name!r} failed after {attempt} attempt(s)"
                f" within its {opts.timeout_s:g}s deadline ({detail})"
            )
        # exponential backoff, capped so the sleep never outlives the deadline
        pause = min(opts.backoff_s * (2 ** (attempt - 1)), max(0.0, deadline - time.monotonic()))
        if pause > 0:
            time.sleep(pause)


def _axis_size(axis_name: str) -> Optional[int]:
    """Static size of a mesh axis from inside a traced computation; None if unresolvable.

    ``lax.axis_size`` only exists on newer JAX; ``psum(1, axis)`` constant-folds to the axis
    size as a concrete int on every release this repo supports.
    """
    try:
        # static mesh metadata, constant-folds at trace time — no runtime sync
        return int(lax.axis_size(axis_name))  # jaxlint: disable=TPU001
    except Exception:
        pass
    try:
        size = lax.psum(1, axis_name)
        # the isinstance guard admits only the constant-folded (host int) case
        return int(size) if isinstance(size, int) else None  # jaxlint: disable=TPU001
    except Exception:
        return None


def _reduce_one(value: Array, reduce_fx: ReduceFx, axis_name: str) -> Array:
    """Synchronise a single tensor state across ``axis_name`` inside jit/shard_map/pmap."""
    if reduce_fx == "sum":
        return lax.psum(value, axis_name)
    if reduce_fx == "mean":
        return lax.pmean(value, axis_name)
    if reduce_fx == "max":
        return lax.pmax(value, axis_name)
    if reduce_fx == "min":
        return lax.pmin(value, axis_name)
    if reduce_fx == "cat":
        return lax.all_gather(value, axis_name, axis=0, tiled=True)
    if reduce_fx is None:
        # gather replicas along a fresh leading axis (caller applies its own reduction)
        return lax.all_gather(value, axis_name, axis=0, tiled=False)
    if callable(reduce_fx):
        gathered = lax.all_gather(value, axis_name, axis=0, tiled=False)
        return reduce_fx(gathered)
    raise ValueError(f"Unsupported dist_reduce_fx: {reduce_fx!r}")


def sync_state(
    state: Dict[str, Any],
    reductions: Dict[str, ReduceFx],
    axis_name: str,
) -> Dict[str, Any]:
    """Synchronise a metric state pytree across a mesh axis, inside a compiled computation.

    List states (Python lists of arrays) are pre-concatenated along dim 0 — mirroring
    ``metric.py:431-432`` — then treated as ``cat``.

    Telemetry: this body runs at TRACE time (the collectives execute inside the compiled
    program, where wall-clock timing is impossible), so the recorded event carries what IS
    known at trace time — state names, reduce-fx, payload bytes, and mesh-axis size. Executed
    latency is measured by the eager paths (``process_sync``) and the bench sync probes.
    """
    obs.telemetry.counter("sync.sync_state.traces").inc()
    obs.telemetry.event(
        "sync.sync_state", cat="sync",
        args={
            "axis": axis_name,
            "mesh_size": _axis_size(axis_name),
            "states": sorted(state),
            "bytes": obs.tree_bytes(state),
            "reductions": {k: getattr(v, "__name__", str(v)) for k, v in reductions.items()},
        },
    )
    out: Dict[str, Any] = {}
    for name, value in state.items():
        fx = reductions.get(name, "sum")
        if isinstance(value, (list, tuple)):
            if len(value) == 0:
                out[name] = value
                continue
            cat = jnp.concatenate([jnp.atleast_1d(v) for v in value], axis=0)
            out[name] = [_reduce_one(cat, "cat" if fx in (None, "cat") else fx, axis_name)]
        else:
            out[name] = _reduce_one(value, fx, axis_name)
    return out


# ---------------------------------------------------------------------------
# Multi-process eager path (one metric replica per host process, à la DDP)
# ---------------------------------------------------------------------------

def all_gather_object_shapes(local_shape: tuple) -> List[tuple]:
    """Gather dim-0 sizes from every process (reference ``distributed.py:118-127``)."""
    from jax.experimental import multihost_utils

    sizes = multihost_utils.process_allgather(jnp.asarray(local_shape, jnp.int32))
    return [tuple(int(d) for d in row) for row in np.asarray(sizes)]


def gather_all_arrays(value: Array, group: Optional[str] = None) -> List[Array]:
    """All-gather an array from every process, handling uneven dim-0 sizes by pad+trim.

    Returns a list of per-process arrays (reference ``gather_all_tensors``,
    ``distributed.py:97-147``). No-op single-element list when world size is 1.
    """
    del group
    obs.telemetry.counter("sync.gather.calls").inc()
    if jax.process_count() == 1:
        return [value]
    from jax.experimental import multihost_utils

    shapes = all_gather_object_shapes(tuple(value.shape))
    max_dim0 = max((s[0] if s else 0) for s in shapes)
    pad = max_dim0 - (value.shape[0] if value.ndim else 0)
    padded = jnp.pad(value, [(0, pad)] + [(0, 0)] * (value.ndim - 1)) if value.ndim else value
    gathered = multihost_utils.process_allgather(padded)  # (world, max_dim0, ...)
    return [jnp.asarray(gathered[i][: shapes[i][0]] if value.ndim else gathered[i]) for i in range(len(shapes))]


def process_sync(
    state: Dict[str, Any],
    reductions: Dict[str, ReduceFx],
    gather_fn: Optional[Callable] = None,
    group: Optional[str] = None,
    options: Optional[SyncOptions] = None,
) -> "SyncedState":
    """Eager cross-process sync of a state dict; identity when world size is 1.

    A ``gather_fn`` that accepts a ``name`` keyword receives the state's name — gathers are then
    keyed by identity instead of having to match tensors by value (the reference's injected
    test gathers need this; value matching can mis-map states that happen to be equal).

    With a bounded :class:`SyncOptions` (explicit argument, or the ``TM_TPU_SYNC_*`` env
    knobs) each gather races a deadline with retry+backoff; exhausted states fall back to
    their LOCAL value under degraded mode — the returned :class:`SyncedState` then has
    ``world_consistent=False`` and lists them in ``degraded_states`` — or raise
    :class:`SyncTimeoutError` when degraded mode is off. See ``docs/robustness.md``.
    """
    import inspect

    obs.telemetry.counter("sync.process_sync.calls").inc()
    opts = options if options is not None else sync_options_from_env()
    t0 = time.perf_counter() if obs.telemetry.enabled else 0.0
    gather = gather_fn or gather_all_arrays
    takes_name = False
    try:
        takes_name = "name" in inspect.signature(gather).parameters
    except (TypeError, ValueError):
        pass
    deadline = time.monotonic() + opts.timeout_s if opts.bounded else 0.0
    degraded: List[str] = []

    gather_latency_us: Dict[str, float] = {}

    def run_gather(payload: Any, name: str, kw: Dict[str, Any]) -> List[Any]:
        # per-gather wall time on THIS rank: the raw material of the cross-rank skew
        # report (skew_report / obs.summary). A perf_counter pair is noise next to a
        # collective, so the timing is always-on.
        g0 = time.perf_counter()
        try:
            if not opts.bounded:
                return gather(payload, group, **kw)
            return _bounded_gather(gather, payload, group, kw, opts, deadline, name)
        finally:
            dur = time.perf_counter() - g0
            gather_latency_us[name] = round(dur * 1e6, 1)
            _record_gather_latency(dur)

    out: SyncedState = SyncedState()
    for name, value in state.items():
        fx = reductions.get(name, "sum")
        kw = {"name": name} if takes_name else {}
        if isinstance(value, (list, tuple)):
            if len(value) == 0 and jax.process_count() == 1:
                out[name] = list(value)
                continue
            cat = jnp.concatenate([jnp.atleast_1d(v) for v in value], axis=0) if len(value) else jnp.zeros((0,))
            try:
                gathered = run_gather(cat, name, kw)
            except SyncTimeoutError:
                if not opts.degraded_mode:
                    raise
                degraded.append(name)
                out[name] = list(value)
                continue
            out[name] = [g for g in gathered]
        else:
            try:
                gathered = run_gather(value, name, kw)
            except SyncTimeoutError:
                if not opts.degraded_mode:
                    raise
                degraded.append(name)
                out[name] = value
                continue
            if len(gathered) == 1:
                out[name] = gathered[0]
                continue
            stacked = jnp.stack(gathered) if fx in ("sum", "mean", "max", "min") else None
            if fx == "sum":
                out[name] = jnp.sum(stacked, axis=0)
            elif fx == "mean":
                out[name] = jnp.mean(stacked, axis=0)
            elif fx == "max":
                out[name] = jnp.max(stacked, axis=0)
            elif fx == "min":
                out[name] = jnp.min(stacked, axis=0)
            elif fx == "cat":
                out[name] = jnp.concatenate(gathered, axis=0)
            elif fx is None:
                out[name] = jnp.stack(gathered)
            elif callable(fx):
                out[name] = fx(jnp.stack(gathered))
            else:
                raise ValueError(f"Unsupported dist_reduce_fx: {fx!r}")
    out.gather_latency_us = gather_latency_us
    if degraded:
        out.world_consistent = False
        out.degraded_states = tuple(degraded)
        obs.telemetry.counter("robust.degraded_syncs").inc()
        obs.telemetry.event(
            "sync.degraded", cat="sync",
            args={"states": degraded, "timeout_s": opts.timeout_s, "retries": opts.retries},
        )
        rank_zero_warn(
            f"process_sync degraded: state(s) {sorted(degraded)} could not be gathered within"
            f" the {opts.timeout_s:g}s deadline ({opts.retries} retr{'y' if opts.retries == 1 else 'ies'});"
            " falling back to LOCAL state. The next compute() reflects this process only"
            " (non-world-consistent).",
            UserWarning,
        )
    if obs.telemetry.enabled:
        dur_us = (time.perf_counter() - t0) * 1e6
        try:
            world = jax.process_count()
        except Exception:
            world = 1
        obs.telemetry.histogram("sync.process_sync.latency_us").record(dur_us)
        obs.telemetry.event(
            "sync.process_sync", ph="X", cat="sync",
            ts_us=obs.telemetry.now_us() - dur_us, dur_us=dur_us,
            args={"world": world, "states": sorted(state), "bytes": obs.tree_bytes(state)},
        )
    return out


def shard_map_unchecked(mesh, in_specs, out_specs):
    """``shard_map`` with the output-replication check disabled, across JAX versions.

    all_gather(tiled) outputs ARE replicated over the gathered axis, but the varying-axes
    inference is conservative about gathers (psum is recognised, gathers are not); the disabling
    flag is ``check_vma`` on current JAX and ``check_rep`` on older releases.
    """
    import functools
    import inspect

    try:
        from jax import shard_map
    except ImportError:  # pre-0.8 JAX
        from jax.experimental.shard_map import shard_map

    flag = "check_vma" if "check_vma" in inspect.signature(shard_map).parameters else "check_rep"
    return functools.partial(shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **{flag: False})
