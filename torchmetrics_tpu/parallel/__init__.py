"""Distributed state synchronisation over JAX device meshes.

This replaces the reference's gather-then-host-reduce backend
(``src/torchmetrics/utilities/distributed.py:97-147`` + ``metric.py:426-456``) with XLA
collectives that run *inside* the compiled computation, riding ICI/DCN:

- ``dist_reduce_fx="sum"/"mean"/"max"/"min"`` → ``lax.psum``/``pmean``/``pmax``/``pmin`` — one
  fused all-reduce instead of all-gather + local reduce.
- ``dist_reduce_fx="cat"``/``None`` → ``lax.all_gather`` (+ static pad-and-mask for uneven
  shapes, since XLA requires static shapes).
- The reference's ``process_group`` becomes a mesh **axis name**; ``distributed_available_fn``
  becomes "is there a mesh axis in scope".

Three sync contexts are supported:

1. **Sharded-inputs (zero-collective) mode** — the idiomatic TPU path: hand ``metric.update`` a
   ``jax.Array`` sharded over a ``Mesh``; the jitted update's reductions are global, so XLA
   inserts the ICI collectives itself and the accumulated state is already world-consistent.
2. **In-jit collectives** — ``sync_state(state, reductions, axis_name=...)`` inside
   ``shard_map``/``pmap`` training steps that keep per-device state.
3. **Multi-process eager** — ``process_sync`` over ``jax.process_count()`` hosts for the
   torch.distributed-style one-replica-per-process layout.

Large states additionally support **sharded placement** (``Metric.shard(mesh)``,
``parallel/mesh.py`` + docs/distributed.md "Sharded state"): per-state ``NamedSharding``
specs derived from shape + reduce fx, shard-local accumulation through every dispatch
tier, and a lazy reduce-scatter sync (``process_sync(..., sharded_states=...)``) that
replaces the ``world × state`` allgather with ``≈ 2 × state`` received bytes, cached per
update epoch.
"""
from torchmetrics_tpu.parallel import compress
from torchmetrics_tpu.parallel.sync import (
    FULL,
    LOCAL,
    QUORUM,
    ConsistencyLevel,
    HealthLedger,
    RankHealth,
    SyncedState,
    SyncOptions,
    all_gather_object_shapes,
    as_consistency,
    gather_all_arrays,
    health_ledger,
    process_sync,
    quorum_threshold,
    reset_health_state,
    shardable_state,
    simulate_mesh_world,
    skew_report,
    sync_options_from_env,
    sync_state,
)
from torchmetrics_tpu.parallel.mesh import MeshContext, is_partitioned, local_mesh, reset_mesh_cache

__all__ = [
    "compress",
    "FULL",
    "LOCAL",
    "QUORUM",
    "ConsistencyLevel",
    "HealthLedger",
    "RankHealth",
    "SyncOptions",
    "SyncedState",
    "as_consistency",
    "sync_state",
    "gather_all_arrays",
    "health_ledger",
    "process_sync",
    "quorum_threshold",
    "reset_health_state",
    "skew_report",
    "sync_options_from_env",
    "all_gather_object_shapes",
    "shardable_state",
    "simulate_mesh_world",
    "MeshContext",
    "is_partitioned",
    "local_mesh",
    "reset_mesh_cache",
]
