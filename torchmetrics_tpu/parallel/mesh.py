"""Mesh construction helpers."""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def local_mesh(axis_names: Sequence[str] = ("data",), shape: Optional[Tuple[int, ...]] = None) -> Mesh:
    """Build a mesh over all visible devices.

    Default: a 1-D ``("data",)`` mesh — metric state is replicated per data shard exactly like the
    reference's DDP layout (SURVEY §2.2: data-parallel metric-state replication only).
    """
    devices = jax.devices()
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names)
