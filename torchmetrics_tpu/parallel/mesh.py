"""Device-mesh construction and per-state partition-spec derivation.

The reference (SURVEY §2.2) only knows data-parallel metric-state *replication*: every
process holds a full copy of every accumulator and ``gather_all_tensors`` ships all of it
on every sync. Large states — confusion matrices, retrieval cat-buffers, histogram/curve
buffers, the keyed engine's ``[N, ...]`` tenant tables — waste both memory and interconnect
that way (*Automatic Cross-Replica Sharding of Weight Update in Data-Parallel Training*,
PAPERS.md). This module is the placement layer of the sharded alternative
(``Metric.shard(mesh)``, docs/distributed.md "Sharded state"):

- :func:`local_mesh` builds (and caches) a validated ``jax.sharding.Mesh`` over the
  visible devices, including named multi-axis meshes (``("data", "model")``).
- :class:`MeshContext` wraps a mesh and derives a ``NamedSharding`` per metric state from
  its registered shape and ``dist_reduce_fx``: states with a large, evenly divisible
  leading axis (keyed tenant tables, per-class count vectors) shard that axis across the
  primary mesh axis; scalar/small states stay replicated (replication of a scalar is
  free — sharding it would only add layout churn); host-side list ("cat") states are
  placed entry-by-entry round-robin across the mesh devices so an unbounded concat buffer
  occupies every device's memory evenly instead of one device's.

Placement never changes values: a sharded metric is bit-identical to its replicated twin
by construction, across every dispatch tier. The communication win lives in the sync
layer (``parallel/sync.py``): partitioned states sync by reduce-scatter + slab assembly
(received bytes ``≈ 2×state``) instead of a full allgather (``world × state``).
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError

#: process-level mesh cache: meshes are immutable device layouts, and rebuilding one per
#: ``Metric.shard()`` call would re-hash the device array every time
_MESH_CACHE: Dict[Tuple, Mesh] = {}


def reset_mesh_cache() -> None:
    """Drop all cached meshes (tests)."""
    _MESH_CACHE.clear()


def local_mesh(
    axis_names: Sequence[str] = ("data",),
    shape: Optional[Tuple[int, ...]] = None,
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    """Build a validated, cached mesh over the visible devices.

    Default: a 1-D ``("data",)`` mesh over every device. Multi-axis named meshes are
    supported by passing matching ``axis_names`` and ``shape`` — e.g.
    ``local_mesh(("data", "model"), (4, 2))`` on 8 devices. The shape is validated
    against the device count up front: a shape the devices don't factor into raises a
    clear :class:`TorchMetricsUserError` instead of an opaque numpy reshape error.

    Meshes are cached per ``(axis_names, shape, devices)`` — repeated calls (one per
    ``Metric.shard()``) return the same ``Mesh`` object.
    """
    axis_names = tuple(str(a) for a in axis_names)
    if not axis_names:
        raise TorchMetricsUserError("local_mesh needs at least one axis name, got ()")
    if len(set(axis_names)) != len(axis_names):
        raise TorchMetricsUserError(f"local_mesh axis names must be unique, got {axis_names}")
    devs = tuple(jax.devices()) if devices is None else tuple(devices)
    if not devs:
        raise TorchMetricsUserError("local_mesh: no devices visible to build a mesh over")
    if shape is not None:
        shape = tuple(int(s) for s in shape)
    key = (axis_names, shape, devs)
    cached = _MESH_CACHE.get(key)
    if cached is not None:
        return cached
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    if len(shape) != len(axis_names):
        raise TorchMetricsUserError(
            f"local_mesh: {len(axis_names)} axis name(s) {axis_names} need a {len(axis_names)}-D"
            f" shape, got shape {shape} with {len(shape)} dim(s)"
        )
    if any(s < 1 for s in shape):
        raise TorchMetricsUserError(f"local_mesh: mesh shape {shape} has a non-positive dimension")
    need = math.prod(shape)
    if need != len(devs):
        raise TorchMetricsUserError(
            f"local_mesh: mesh shape {shape} covers {need} device(s) but {len(devs)} are"
            f" visible — pick a shape whose product is exactly the device count"
            f" (e.g. ({len(devs)},){' or a matching factorisation' if len(axis_names) > 1 else ''})."
        )
    dev_array = np.asarray(devs, dtype=object).reshape(shape)
    mesh = Mesh(dev_array, axis_names)
    _MESH_CACHE[key] = mesh
    return mesh


def is_partitioned(sharding: Any) -> bool:
    """True when a ``NamedSharding`` actually splits data (any non-None spec entry)."""
    spec = getattr(sharding, "spec", None)
    return spec is not None and any(p is not None for p in spec)


class MeshContext:
    """A mesh plus the policy mapping metric states to ``NamedSharding`` placements.

    ``mesh`` is a ``jax.sharding.Mesh`` (default: :func:`local_mesh` over every visible
    device) and ``axis`` names the mesh axis states shard over — by default the first
    axis with size > 1 (on a ``("data", "model")`` mesh, ``"data"``).

    Example:
        >>> from torchmetrics_tpu.parallel.mesh import MeshContext
        >>> ctx = MeshContext()
        >>> ctx.size >= 1
        True
    """

    def __init__(self, mesh: Optional[Union[Mesh, "MeshContext"]] = None, axis: Optional[str] = None) -> None:
        if isinstance(mesh, MeshContext):
            self.mesh = mesh.mesh
            self.axis = axis or mesh.axis
        else:
            self.mesh = mesh if mesh is not None else local_mesh()
            if axis is None:
                sized = [a for a in self.mesh.axis_names if self.mesh.shape[a] > 1]
                axis = sized[0] if sized else self.mesh.axis_names[0]
            self.axis = axis
        if self.axis not in self.mesh.axis_names:
            raise TorchMetricsUserError(
                f"MeshContext axis {self.axis!r} is not a mesh axis (mesh has {self.mesh.axis_names})"
            )
        self._devices_flat = tuple(np.asarray(self.mesh.devices).reshape(-1))

    @property
    def size(self) -> int:
        """Number of shards along the primary sharding axis."""
        return int(self.mesh.shape[self.axis])

    # ----------------------------------------------------------------- placements
    def replicated(self) -> NamedSharding:
        """Full replication over the mesh (every device holds the whole array)."""
        return NamedSharding(self.mesh, PartitionSpec())

    def shard_leading(self, ndim: int = 1) -> NamedSharding:
        """Leading axis split over the primary mesh axis, remaining dims replicated."""
        return NamedSharding(self.mesh, PartitionSpec(self.axis, *(None,) * max(0, ndim - 1)))

    def spec_for_value(self, value: Any) -> NamedSharding:
        """Placement for an ad-hoc array (cat assembly): leading-sharded when divisible."""
        shape = tuple(np.shape(value))
        if len(shape) >= 1 and self.size > 1 and shape[0] >= self.size and shape[0] % self.size == 0:
            return self.shard_leading(len(shape))
        return self.replicated()

    def spec_for_state(
        self,
        name: str,
        default: Any,
        reduce_fx: Any,
        override: Optional[Union[PartitionSpec, NamedSharding]] = None,
    ) -> Optional[NamedSharding]:
        """Derive one state's ``NamedSharding`` from its registered default and reduce fx.

        ``override`` (a ``PartitionSpec`` or full ``NamedSharding``) wins unconditionally.
        List ("cat") states return None — they live as host-side lists whose entries are
        placed round-robin (:meth:`device_for_entry`), not as one partitioned array.
        Tensor states shard their leading axis when it is at least the mesh-axis size and
        evenly divisible by it (keyed ``[N, ...]`` tenant tables, per-class vectors);
        everything else — scalars, small accumulators, custom/callable reductions —
        stays replicated, which for sum/max/min-reduced scalars is exactly the
        "replicated-small" regime the sync layer reduces in one collective.
        """
        if override is not None:
            if isinstance(override, NamedSharding):
                return override
            if isinstance(override, PartitionSpec):
                return NamedSharding(self.mesh, override)
            raise TorchMetricsUserError(
                f"shard spec override for state {name!r} must be a PartitionSpec or"
                f" NamedSharding, got {type(override).__name__}"
            )
        if isinstance(default, list):
            return None
        shape = tuple(np.shape(default))
        if (
            self.size > 1
            and len(shape) >= 1
            and shape[0] >= self.size
            and shape[0] % self.size == 0
            and (reduce_fx in ("sum", "mean", "max", "min", "cat") or reduce_fx is None)
        ):
            return self.shard_leading(len(shape))
        return self.replicated()

    def device_for_entry(self, index: int) -> Any:
        """Round-robin device for the ``index``-th appended cat-state entry.

        Distributes an unbounded concat buffer's memory evenly across the mesh — the
        shard-local-accumulate story for list states, whose entries have no static shape
        to partition as one array.
        """
        return self._devices_flat[index % len(self._devices_flat)]

    def describe(self) -> Dict[str, Any]:
        """Telemetry/snapshot descriptor: axis sizes, primary axis, device count."""
        return {
            "axes": {a: int(self.mesh.shape[a]) for a in self.mesh.axis_names},
            "axis": self.axis,
            "devices": len(self._devices_flat),
        }

    def __repr__(self) -> str:
        axes = ", ".join(f"{a}={self.mesh.shape[a]}" for a in self.mesh.axis_names)
        return f"MeshContext({axes}; axis={self.axis!r})"
