"""Concrete retrieval metrics (reference ``src/torchmetrics/retrieval/*.py``)."""
from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.retrieval._kernels import (
    average_precision_kernel,
    fall_out_kernel,
    hit_rate_kernel,
    ndcg_kernel,
    precision_kernel,
    r_precision_kernel,
    recall_kernel,
    reciprocal_rank_kernel,
)
from torchmetrics_tpu.functional.retrieval import _flat
from torchmetrics_tpu.retrieval.base import (
    RetrievalMetric,
    _masked_aggregate,
    _next_pow2,
    _retrieval_aggregate,
)


def _agg_columns(values: Array, include: Array, aggregation: str) -> Array:
    """Per-column (k-axis) masked aggregation of per-query curve values: one vmap of the
    scalar ``base._masked_aggregate`` over the K axis (single source of the masking math)."""
    return jax.vmap(lambda col: _masked_aggregate(col, include, aggregation), in_axes=1)(values)


def _validate_top_k(top_k: Optional[int]) -> None:
    if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")


class RetrievalMAP(RetrievalMetric):
    """Mean average precision (reference ``retrieval/average_precision.py``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.retrieval import RetrievalMAP
        >>> metric = RetrievalMAP()
        >>> metric.update(np.array([0.2, 0.3, 0.5], np.float32), np.array([0, 1, 1]),
        ...               indexes=np.array([0, 0, 0]))
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation="mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_kernel(self, preds, target, mask):
        return average_precision_kernel(preds, target, mask, self.top_k)

    def _flat_values(self, ctx):
        return _flat.average_precision_flat(ctx)


class RetrievalMRR(RetrievalMetric):
    """Mean reciprocal rank (reference ``retrieval/reciprocal_rank.py``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([0, 1, 1])
        >>> indexes = np.array([0, 0, 0])
        >>> from torchmetrics_tpu.retrieval import RetrievalMRR
        >>> metric = RetrievalMRR()
        >>> metric.update(preds, target, indexes=indexes)
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation="mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_kernel(self, preds, target, mask):
        return reciprocal_rank_kernel(preds, target, mask, self.top_k)

    def _flat_values(self, ctx):
        return _flat.reciprocal_rank_flat(ctx)


class RetrievalPrecision(RetrievalMetric):
    """precision@k (reference ``retrieval/precision.py``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([0, 1, 1])
        >>> indexes = np.array([0, 0, 0])
        >>> from torchmetrics_tpu.retrieval import RetrievalPrecision
        >>> metric = RetrievalPrecision()
        >>> metric.update(preds, target, indexes=indexes)
        >>> print(f"{float(metric.compute()):.4f}")
        0.6667
    """

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, adaptive_k: bool = False, aggregation="mean",
                 **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _validate_top_k(top_k)
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.top_k = top_k
        self.adaptive_k = adaptive_k

    def _metric_kernel(self, preds, target, mask):
        return precision_kernel(preds, target, mask, self.top_k, self.adaptive_k)

    def _flat_values(self, ctx):
        return _flat.make_precision_flat(self.top_k, self.adaptive_k)(ctx)


class RetrievalRecall(RetrievalMetric):
    """recall@k (reference ``retrieval/recall.py``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([0, 1, 1])
        >>> indexes = np.array([0, 0, 0])
        >>> from torchmetrics_tpu.retrieval import RetrievalRecall
        >>> metric = RetrievalRecall()
        >>> metric.update(preds, target, indexes=indexes)
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation="mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_kernel(self, preds, target, mask):
        return recall_kernel(preds, target, mask, self.top_k)

    def _flat_values(self, ctx):
        return _flat.recall_flat(ctx)


class RetrievalFallOut(RetrievalMetric):
    """fall-out@k (reference ``retrieval/fall_out.py``); empty-*positive* queries handled on the
    negative-target axis: `empty_target_action` applies to queries with no NEGATIVE targets.

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([0, 1, 1])
        >>> indexes = np.array([0, 0, 0])
        >>> from torchmetrics_tpu.retrieval import RetrievalFallOut
        >>> metric = RetrievalFallOut()
        >>> metric.update(preds, target, indexes=indexes)
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    higher_is_better = False

    def __init__(self, empty_target_action: str = "pos", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation="mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_kernel(self, preds, target, mask):
        return fall_out_kernel(preds, target, mask, self.top_k)

    def _flat_values(self, ctx):
        return _flat.fall_out_flat(ctx)

    _sketch_empty_from = "neg"  # sketch mode inherits the negative-target empty axis

    def _compute(self, state):
        # like base, but "empty" = no negative targets (reference fall_out.py:126)
        if self.approx == "sketch":
            return self._sketch_compute(state)
        arrays = self._state_arrays(state)
        if arrays is None:
            return jnp.zeros(())
        indexes, preds, target, valid = arrays
        msg = "`compute` method was provided with a query with no negative target."
        if callable(self.aggregation):
            values, _pos, neg_count, valid_count = self._grouped_values(
                indexes, preds, target, valid=valid
            )
            values_np = self._select_values(values, neg_count == 0, valid_count > 0, msg)
            return _retrieval_aggregate(jnp.asarray(values_np), self.aggregation)
        return self._flat_aggregate(indexes, preds, target, valid, "neg", msg)


class RetrievalHitRate(RetrievalMetric):
    """hit-rate@k (reference ``retrieval/hit_rate.py``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([0, 1, 1])
        >>> indexes = np.array([0, 0, 0])
        >>> from torchmetrics_tpu.retrieval import RetrievalHitRate
        >>> metric = RetrievalHitRate()
        >>> metric.update(preds, target, indexes=indexes)
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation="mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_kernel(self, preds, target, mask):
        return hit_rate_kernel(preds, target, mask, self.top_k)

    def _flat_values(self, ctx):
        return _flat.hit_rate_flat(ctx)


class RetrievalRPrecision(RetrievalMetric):
    """R-precision (reference ``retrieval/r_precision.py``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([0, 1, 1])
        >>> indexes = np.array([0, 0, 0])
        >>> from torchmetrics_tpu.retrieval import RetrievalRPrecision
        >>> metric = RetrievalRPrecision()
        >>> metric.update(preds, target, indexes=indexes)
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    def _metric_kernel(self, preds, target, mask):
        return r_precision_kernel(preds, target, mask)

    def _flat_values(self, ctx):
        return _flat.r_precision_flat(ctx)


class RetrievalNormalizedDCG(RetrievalMetric):
    """NDCG@k with graded relevance (reference ``retrieval/ndcg.py``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.retrieval import RetrievalNormalizedDCG
        >>> metric = RetrievalNormalizedDCG()
        >>> metric.update(np.array([0.2, 0.3, 0.5], np.float32), np.array([0, 1, 1]),
        ...               indexes=np.array([0, 0, 0]))
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    allow_non_binary_target = True
    _flat_needs_ideal_perm = True  # ideal-DCG re-sort precomputed eagerly on the CPU backend

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation="mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_kernel(self, preds, target, mask):
        return ndcg_kernel(preds, target, mask, self.top_k)

    def _flat_values(self, ctx):
        return _flat.ndcg_flat(ctx)


class RetrievalPrecisionRecallCurve(RetrievalMetric):
    """Averaged precision/recall at k=1..max_k (reference ``retrieval/precision_recall_curve.py``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([0, 1, 1])
        >>> indexes = np.array([0, 0, 0])
        >>> from torchmetrics_tpu.retrieval import RetrievalPrecisionRecallCurve
        >>> metric = RetrievalPrecisionRecallCurve(max_k=3)
        >>> metric.update(preds, target, indexes=indexes)
        >>> precision, recall, top_k = metric.compute()
        >>> np.asarray(top_k).tolist()
        [1, 2, 3]
    """

    # restates the flag RetrievalMetric.__init__ sets on every instance: the curve compute is
    # eager (host max_k sizes the result), and the class attribute makes that visible to
    # static analysis (jaxlint's per-file pass cannot see the cross-module instance assignment)
    jit_compute = False

    def __init__(self, max_k: Optional[int] = None, adaptive_k: bool = False,
                 empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 aggregation="mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        if max_k is not None and not (isinstance(max_k, int) and max_k > 0):
            raise ValueError('`max_k` must be a positive integer or None')
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.max_k = max_k
        self.adaptive_k = adaptive_k

    def _compute(self, state) -> Tuple[Array, Array, Array]:
        arrays = self._state_arrays(state)
        if arrays is None:
            return jnp.zeros(()), jnp.zeros(()), jnp.zeros((), jnp.int32)
        indexes, preds, target, valid = arrays
        from torchmetrics_tpu.retrieval.base import _max_valid_per_query

        if self.max_k is not None:
            max_k = self.max_k
        else:
            # count only non-ignored docs (the old host path filtered before grouping). This is
            # the ONE host round-trip of the curve compute: max_k sizes the returned curves.
            max_k = int(jax.device_get(_max_valid_per_query(indexes, valid)))
        precisions, recalls = self._curve_flat(indexes, preds, target, valid, max_k)
        return precisions, recalls, jnp.arange(1, max_k + 1)

    def _curve_flat(self, indexes, preds, target, valid, max_k: int):
        """All k=1..max_k precision/recall means in ONE fused launch over the flat context.

        The compiled program is sized to the next power of two above ``max_k`` (and the result
        sliced back) so a data-dependent longest-query length growing by one between computes
        does not recompile the whole unrolled k-sweep."""
        requested_k = max_k
        max_k = _next_pow2(max_k)
        indexes, preds, target, valid = self._pad_flat(indexes, preds, target, valid)
        # eager host sort permutation on the CPU backend (see base._flat_aggregate)
        perm = _flat.host_sort_perm(indexes, preds, valid)
        cache_key = f"curve_flat@{max_k}" + ("@perm" if perm is not None else "")
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            action = self.empty_target_action
            adaptive = self.adaptive_k
            aggregation = self.aggregation
            device_agg = aggregation if isinstance(aggregation, str) else None

            def run(indexes, preds, target, valid, perm=None):
                ctx = _flat.build_context(indexes, preds, target, valid, None, perm=perm)
                has_valid = ctx["n_valid_seg"] > 0
                empty = (ctx["pos_seg"] == 0) & has_valid
                include = has_valid & ~empty if action == "skip" else has_valid
                impute = 1.0 if action == "pos" else 0.0
                pv, rv = _flat.curve_counts(ctx, max_k, adaptive)  # (N, K) each
                if action != "skip":
                    pv = jnp.where(empty[:, None], impute, pv)
                    rv = jnp.where(empty[:, None], impute, rv)
                if device_agg is None:  # custom callable: per-query columns go back to the host
                    return pv, rv, include, jnp.any(empty)
                ps = _agg_columns(pv, include, device_agg)
                rs = _agg_columns(rv, include, device_agg)
                return ps, rs, jnp.any(empty)

            fn = jax.jit(run)
            self._jit_cache[cache_key] = fn
        args = (indexes, preds, target, valid) + ((perm,) if perm is not None else ())
        if isinstance(self.aggregation, str):
            p, r, any_empty = fn(*args)
        else:
            pv, rv, include, any_empty = fn(*args)
            keep = np.asarray(include)
            pv_np, rv_np = np.asarray(pv)[keep], np.asarray(rv)[keep]  # ONE transfer each
            p = jnp.stack([jnp.asarray(self.aggregation(jnp.asarray(pv_np[:, k])))
                           for k in range(requested_k)])
            r = jnp.stack([jnp.asarray(self.aggregation(jnp.asarray(rv_np[:, k])))
                           for k in range(requested_k)])
        if self.empty_target_action == "error" and bool(jax.device_get(any_empty)):
            # explicit one-shot D2H read (TPU001): only the "error" action needs this flag on host
            raise ValueError("`compute` method was provided with a query with no positive target.")
        return p[:requested_k], r[:requested_k]


class RetrievalRecallAtFixedPrecision(RetrievalPrecisionRecallCurve):
    """(max recall, best k) such that precision >= min_precision (reference
    ``retrieval/recall_fixed_precision.py``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.2, 0.3, 0.5], np.float32)
        >>> target = np.array([0, 1, 1])
        >>> indexes = np.array([0, 0, 0])
        >>> from torchmetrics_tpu.retrieval import RetrievalRecallAtFixedPrecision
        >>> metric = RetrievalRecallAtFixedPrecision(min_precision=0.5)
        >>> metric.update(preds, target, indexes=indexes)
        >>> [round(float(v), 4) for v in metric.compute()]  # (recall, top_k)
        [1.0, 2.0]
    """

    def __init__(self, min_precision: float = 0.0, max_k: Optional[int] = None,
                 adaptive_k: bool = False, empty_target_action: str = "neg",
                 ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(max_k, adaptive_k, empty_target_action, ignore_index, **kwargs)
        if not (isinstance(min_precision, float) and 0.0 <= min_precision <= 1.0):
            raise ValueError('`min_precision` must be a positive float between 0 and 1')
        self.min_precision = min_precision

    def _compute(self, state):
        precisions, recalls, ks = super()._compute(state)
        p = np.asarray(precisions)
        r = np.asarray(recalls)
        k = np.asarray(ks)
        mask = p >= self.min_precision
        if not mask.any():
            return jnp.zeros(()), jnp.asarray(int(k.max()))
        best = np.argmax(np.where(mask, r, -1.0))
        return jnp.asarray(r[best]), jnp.asarray(int(k[best]))
