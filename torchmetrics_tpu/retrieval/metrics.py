"""Concrete retrieval metrics (reference ``src/torchmetrics/retrieval/*.py``)."""
from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.retrieval._kernels import (
    average_precision_kernel,
    fall_out_kernel,
    hit_rate_kernel,
    ndcg_kernel,
    precision_kernel,
    r_precision_kernel,
    recall_kernel,
    reciprocal_rank_kernel,
)
from torchmetrics_tpu.retrieval.base import RetrievalMetric, _retrieval_aggregate


def _validate_top_k(top_k: Optional[int]) -> None:
    if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")


class RetrievalMAP(RetrievalMetric):
    """Mean average precision (reference ``retrieval/average_precision.py``)."""

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation="mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_kernel(self, preds, target, mask):
        return average_precision_kernel(preds, target, mask, self.top_k)


class RetrievalMRR(RetrievalMetric):
    """Mean reciprocal rank (reference ``retrieval/reciprocal_rank.py``)."""

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation="mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_kernel(self, preds, target, mask):
        return reciprocal_rank_kernel(preds, target, mask, self.top_k)


class RetrievalPrecision(RetrievalMetric):
    """precision@k (reference ``retrieval/precision.py``)."""

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, adaptive_k: bool = False, aggregation="mean",
                 **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _validate_top_k(top_k)
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.top_k = top_k
        self.adaptive_k = adaptive_k

    def _metric_kernel(self, preds, target, mask):
        return precision_kernel(preds, target, mask, self.top_k, self.adaptive_k)


class RetrievalRecall(RetrievalMetric):
    """recall@k (reference ``retrieval/recall.py``)."""

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation="mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_kernel(self, preds, target, mask):
        return recall_kernel(preds, target, mask, self.top_k)


class RetrievalFallOut(RetrievalMetric):
    """fall-out@k (reference ``retrieval/fall_out.py``); empty-*positive* queries handled on the
    negative-target axis: `empty_target_action` applies to queries with no NEGATIVE targets."""

    higher_is_better = False

    def __init__(self, empty_target_action: str = "pos", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation="mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_kernel(self, preds, target, mask):
        return fall_out_kernel(preds, target, mask, self.top_k)

    def _compute(self, state):
        # like base, but "empty" = no negative targets (reference fall_out.py:126)
        arrays = self._state_arrays(state)
        if arrays is None:
            return jnp.zeros(())
        indexes, preds, target, valid = arrays
        msg = "`compute` method was provided with a query with no negative target."
        if callable(self.aggregation):
            values, _pos, neg_count, valid_count = self._grouped_values(
                indexes, preds, target, valid=valid
            )
            values_np = self._select_values(values, neg_count == 0, valid_count > 0, msg)
            return _retrieval_aggregate(jnp.asarray(values_np), self.aggregation)
        return self._grouped_aggregate(indexes, preds, target, valid, "neg", msg)


class RetrievalHitRate(RetrievalMetric):
    """hit-rate@k (reference ``retrieval/hit_rate.py``)."""

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation="mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_kernel(self, preds, target, mask):
        return hit_rate_kernel(preds, target, mask, self.top_k)


class RetrievalRPrecision(RetrievalMetric):
    """R-precision (reference ``retrieval/r_precision.py``)."""

    def _metric_kernel(self, preds, target, mask):
        return r_precision_kernel(preds, target, mask)


class RetrievalNormalizedDCG(RetrievalMetric):
    """NDCG@k with graded relevance (reference ``retrieval/ndcg.py``)."""

    allow_non_binary_target = True

    def __init__(self, empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 top_k: Optional[int] = None, aggregation="mean", **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, aggregation, **kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_kernel(self, preds, target, mask):
        return ndcg_kernel(preds, target, mask, self.top_k)


class RetrievalPrecisionRecallCurve(RetrievalMetric):
    """Averaged precision/recall at k=1..max_k (reference ``retrieval/precision_recall_curve.py``)."""

    def __init__(self, max_k: Optional[int] = None, adaptive_k: bool = False,
                 empty_target_action: str = "neg", ignore_index: Optional[int] = None,
                 **kwargs: Any) -> None:
        super().__init__(empty_target_action, ignore_index, "mean", **kwargs)
        if max_k is not None and not (isinstance(max_k, int) and max_k > 0):
            raise ValueError('`max_k` must be a positive integer or None')
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.max_k = max_k
        self.adaptive_k = adaptive_k

    def _compute(self, state) -> Tuple[Array, Array, Array]:
        arrays = self._state_arrays(state)
        if arrays is None:
            return jnp.zeros(()), jnp.zeros(()), jnp.zeros((), jnp.int32)
        indexes, preds, target, valid = arrays
        from torchmetrics_tpu.retrieval.base import _max_valid_per_query

        if self.max_k is not None:
            max_k = self.max_k
        else:
            # count only non-ignored docs (the old host path filtered before grouping)
            max_k = int(jax.device_get(_max_valid_per_query(indexes, valid)))
        precisions, recalls = [], []
        for k in range(1, max_k + 1):
            def kernel_p(p, t, m, k=k):
                return precision_kernel(p, t, m, k, self.adaptive_k)

            def kernel_r(p, t, m, k=k):
                return recall_kernel(p, t, m, k)

            precisions.append(self._curve_values(indexes, preds, target, valid, kernel_p, f"prec@{k}"))
            recalls.append(self._curve_values(indexes, preds, target, valid, kernel_r, f"rec@{k}"))
        return jnp.stack(precisions), jnp.stack(recalls), jnp.arange(1, max_k + 1)

    def _curve_values(self, indexes, preds, target, valid, kernel, cache_key):
        values, pos_count, _neg, valid_count = self._grouped_values(
            indexes, preds, target, kernel, cache_key, valid=valid
        )
        values_np = self._select_values(
            values, pos_count == 0, valid_count > 0,
            "`compute` method was provided with a query with no positive target.",
        )
        return jnp.mean(jnp.asarray(values_np)) if values_np.size else jnp.zeros(())


class RetrievalRecallAtFixedPrecision(RetrievalPrecisionRecallCurve):
    """(max recall, best k) such that precision >= min_precision (reference
    ``retrieval/recall_fixed_precision.py``)."""

    def __init__(self, min_precision: float = 0.0, max_k: Optional[int] = None,
                 adaptive_k: bool = False, empty_target_action: str = "neg",
                 ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(max_k, adaptive_k, empty_target_action, ignore_index, **kwargs)
        if not (isinstance(min_precision, float) and 0.0 <= min_precision <= 1.0):
            raise ValueError('`min_precision` must be a positive float between 0 and 1')
        self.min_precision = min_precision

    def _compute(self, state):
        precisions, recalls, ks = super()._compute(state)
        p = np.asarray(precisions)
        r = np.asarray(recalls)
        k = np.asarray(ks)
        mask = p >= self.min_precision
        if not mask.any():
            return jnp.zeros(()), jnp.asarray(int(k.max()))
        best = np.argmax(np.where(mask, r, -1.0))
        return jnp.asarray(r[best]), jnp.asarray(int(k[best]))
